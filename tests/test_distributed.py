"""Distributed tests on the virtual 8-device CPU mesh (SURVEY §4: the
reference tests collectives with multi-process + Gloo; here multi-device
CPU + XLA collectives — same golden-comparison idea, numpy as oracle).
Covers: topology/mesh, collective API parity (≈ unittests/collective/),
TP layers == sliced matmuls (≈ hybrid_parallel_mp_layers.py), ZeRO
sharded step == replicated step (≈ dygraph_group_sharded_stage2/3),
recompute == no-recompute grads."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed import fleet
import paddle_tpu.distributed as dist

import jax
from jax.sharding import PartitionSpec as P


@pytest.fixture
def mesh_dp8():
    hcg = fleet.init(strategy=fleet.DistributedStrategy(
        hybrid_configs={"dp_degree": 8}))
    yield hcg
    dist.set_hybrid_communicate_group(None)


@pytest.fixture
def mesh_dp2_mp4():
    hcg = fleet.init(strategy=fleet.DistributedStrategy(
        hybrid_configs={"dp_degree": 2, "mp_degree": 4}))
    yield hcg
    dist.set_hybrid_communicate_group(None)


@pytest.fixture
def mesh_sharding8():
    hcg = fleet.init(strategy=fleet.DistributedStrategy(
        hybrid_configs={"dp_degree": 1, "sharding_degree": 8}))
    yield hcg
    dist.set_hybrid_communicate_group(None)


def test_topology_mesh_shape(mesh_dp2_mp4):
    hcg = mesh_dp2_mp4
    assert hcg.get_data_parallel_world_size() == 2
    assert hcg.get_model_parallel_world_size() == 4
    assert hcg.mesh.shape["dp"] == 2 and hcg.mesh.shape["mp"] == 4
    assert hcg.nranks == 8


def test_all_reduce_matches_numpy(mesh_dp8):
    x = np.arange(16, dtype=np.float32).reshape(8, 2)
    t = paddle.to_tensor(x.copy())
    dist.all_reduce(t, axis="dp")
    # every dp shard (row block) gets the sum of all blocks
    expected = np.tile(x.reshape(8, 2).sum(axis=0, keepdims=True) * 0 +
                       x.sum(axis=0), (8, 1))
    np.testing.assert_allclose(t.numpy(), expected)


def test_all_reduce_max(mesh_dp8):
    x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    t = paddle.to_tensor(x.copy())
    dist.all_reduce(t, op=dist.ReduceOp.MAX, axis="dp")
    np.testing.assert_allclose(t.numpy(), np.tile(x.max(0), (8, 1)),
                               rtol=1e-6)


def test_all_gather(mesh_dp8):
    x = np.arange(8, dtype=np.float32).reshape(8, 1)
    out = []
    dist.all_gather(out, paddle.to_tensor(x), axis="dp")
    assert len(out) == 8
    np.testing.assert_allclose(out[3].numpy(), x[3:4])


def test_broadcast(mesh_dp8):
    x = np.arange(8, dtype=np.float32).reshape(8, 1)
    t = paddle.to_tensor(x.copy())
    dist.broadcast(t, src=2, axis="dp")
    np.testing.assert_allclose(t.numpy(), np.tile(x[2:3], (8, 1)))


def test_reduce_scatter(mesh_dp8):
    x = np.ones((64, 2), np.float32)  # each of 8 shards holds 8 rows
    out = dist.reduce_scatter(None, paddle.to_tensor(x), axis="dp")
    # each shard ends with 1/8 of the reduced rows: all values = 8
    assert out.shape == [8, 2]
    np.testing.assert_allclose(out.numpy(), 8 * np.ones((8, 2)))


def test_alltoall_single(mesh_dp8):
    # 8 shards x 8 sub-blocks: value encodes (src, dst)
    x = np.zeros((64, 1), np.float32)
    for src in range(8):
        for dst in range(8):
            x[src * 8 + dst] = src * 10 + dst
    out = dist.alltoall_single(paddle.to_tensor(x), axis="dp").numpy()
    for dst in range(8):
        for src in range(8):
            assert out[dst * 8 + src, 0] == src * 10 + dst


def test_column_parallel_linear_matches_dense(mesh_dp2_mp4):
    np.random.seed(0)
    layer = dist.ColumnParallelLinear(16, 32, gather_output=True)
    x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32))
    out = layer(x)
    ref = x.numpy() @ layer.weight.numpy() + layer.bias.numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


def test_row_parallel_linear_matches_dense(mesh_dp2_mp4):
    np.random.seed(1)
    layer = dist.RowParallelLinear(16, 8)
    x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32))
    out = layer(x)
    ref = x.numpy() @ layer.weight.numpy() + layer.bias.numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


def test_mp_mlp_sharded_jit_matches_single(mesh_dp2_mp4):
    """Column->Row MLP under jit with the mesh == dense reference
    (≈ hybrid_parallel_mp_layers.py)."""

    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = dist.ColumnParallelLinear(16, 64,
                                                 gather_output=False)
            self.fc2 = dist.RowParallelLinear(64, 16,
                                              input_is_parallel=True)

        def forward(self, x):
            return self.fc2(nn.functional.relu(self.fc1(x)))

    m = MLP()
    fleet.shard_model(m)
    x = paddle.to_tensor(np.random.randn(8, 16).astype(np.float32))
    out = m(x)  # eager (sharded params, constraints active)
    ref = np.maximum(x.numpy() @ m.fc1.weight.numpy() +
                     m.fc1.bias.numpy(), 0) @ m.fc2.weight.numpy() + \
        m.fc2.bias.numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)
    # param placement: fc1 weight sharded over mp on out dim
    shard_shape = m.fc1.weight.data.sharding.shard_shape(
        m.fc1.weight.data.shape)
    assert shard_shape == (16, 16)  # 64/4 on out dim


def _train_ref_and_dist(stage, steps=5):
    """Train the same model replicated-eager vs DistributedTrainStep with
    ZeRO stage N; compare losses (≈ dygraph_group_sharded_stage2/3 tests
    asserting stage2/3 == DP baseline)."""
    np.random.seed(0)
    paddle.seed(0)
    xs = np.random.randn(16, 32).astype(np.float32)
    ys = np.random.randint(0, 4, 16)

    def make_model():
        paddle.seed(42)
        return nn.Sequential(nn.Linear(32, 64), nn.ReLU(),
                             nn.Linear(64, 4))

    # reference: plain eager on replicated weights
    ref_model = make_model()
    ref_opt = optimizer.Adam(learning_rate=0.01,
                             parameters=ref_model.parameters())
    ref_losses = []
    for _ in range(steps):
        loss = nn.functional.cross_entropy(
            ref_model(paddle.to_tensor(xs)), paddle.to_tensor(ys))
        loss.backward()
        ref_opt.step()
        ref_opt.clear_grad()
        ref_losses.append(float(loss))

    # distributed: sharded fused step
    model = make_model()
    opt = optimizer.Adam(learning_rate=0.01, parameters=model.parameters())
    model, opt, _ = dist.group_sharded_parallel(
        model, opt, level={1: "os", 2: "os_g", 3: "p_g_os"}[stage])
    step = fleet.DistributedTrainStep(
        model, opt, nn.functional.cross_entropy)
    dist_losses = [float(step(paddle.to_tensor(xs), paddle.to_tensor(ys)))
                   for _ in range(steps)]
    np.testing.assert_allclose(dist_losses, ref_losses, rtol=2e-3,
                               atol=2e-4)


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_zero_stages_match_replicated(mesh_sharding8, stage):
    _train_ref_and_dist(stage)


def test_dp_distributed_step_matches_serial(mesh_dp8):
    np.random.seed(0)
    xs = np.random.randn(16, 8).astype(np.float32)
    ys = np.random.randn(16, 2).astype(np.float32)

    def make():
        paddle.seed(7)
        return nn.Linear(8, 2)

    ref = make()
    ropt = optimizer.SGD(learning_rate=0.1, parameters=ref.parameters())
    loss = nn.functional.mse_loss(ref(paddle.to_tensor(xs)),
                                  paddle.to_tensor(ys))
    loss.backward()
    ropt.step()

    m = make()
    opt = optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    m = fleet.distributed_model(m)
    opt = fleet.distributed_optimizer(opt)
    step = fleet.DistributedTrainStep(m, opt, nn.functional.mse_loss)
    step(paddle.to_tensor(xs), paddle.to_tensor(ys))
    np.testing.assert_allclose(m.weight.numpy(), ref.weight.numpy(),
                               rtol=1e-4, atol=1e-5)


def test_gradient_accumulation_matches_full_batch(mesh_dp8):
    np.random.seed(3)
    xs = np.random.randn(16, 8).astype(np.float32)
    ys = np.random.randn(16, 2).astype(np.float32)

    def make():
        paddle.seed(5)
        return nn.Linear(8, 2)

    full = make()
    fopt = optimizer.SGD(learning_rate=0.1, parameters=full.parameters())
    fstep = fleet.DistributedTrainStep(full, fopt,
                                       nn.functional.mse_loss)
    fstep(paddle.to_tensor(xs), paddle.to_tensor(ys))

    acc = make()
    aopt = optimizer.SGD(learning_rate=0.1, parameters=acc.parameters())
    astep = fleet.DistributedTrainStep(acc, aopt, nn.functional.mse_loss,
                                       accumulate_steps=2)
    astep(paddle.to_tensor(xs.reshape(2, 8, 8)),
          paddle.to_tensor(ys.reshape(2, 8, 2)))
    np.testing.assert_allclose(acc.weight.numpy(), full.weight.numpy(),
                               rtol=1e-4, atol=1e-5)


def test_recompute_grads_match(mesh_dp8):
    np.random.seed(2)
    m = nn.Sequential(nn.Linear(8, 32), nn.Tanh(), nn.Linear(32, 8))
    x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))

    loss1 = (m(x) ** 2).mean()
    loss1.backward()
    g_plain = [p.grad.numpy().copy() for p in m.parameters()]
    m.clear_gradients()

    loss2 = (dist.recompute(m, x) ** 2).mean()
    loss2.backward()
    g_rc = [p.grad.numpy().copy() for p in m.parameters()]
    for a, b in zip(g_plain, g_rc):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_rng_tracker_differs_across_folds():
    tracker = dist.get_rng_state_tracker()
    tracker.reset()
    tracker.add("global_seed", 1)
    tracker.add("model_parallel_rng", 2)
    with tracker.rng_state("model_parallel_rng") as k1:
        pass
    with tracker.rng_state("model_parallel_rng") as k2:
        pass
    assert not np.array_equal(np.asarray(k1), np.asarray(k2))


def test_hybrid_dcn_mesh_dp_outermost_over_slices():
    """create_hybrid_device_mesh: only the dcn axis (dp) crosses slice
    boundaries — every other axis's hyperplanes are intra-slice (the
    ProcessGroupHeter property, ProcessGroupHeter.h:128-134)."""
    from paddle_tpu.distributed.topology import create_hybrid_device_mesh
    devs = jax.devices()[:8]
    slices = [devs[:4], devs[4:]]  # simulate a 2-slice pod
    slice_of = {id(d): s for s, grp in enumerate(slices) for d in grp}
    mesh = create_hybrid_device_mesh(
        {"dp": 4, "mp": 2}, devices=devs, slices=slices)
    arr = mesh.devices  # [dp=4, mp=2]
    assert arr.shape == (4, 2)
    # each mp row (fixed dp index) stays inside ONE slice
    for i in range(4):
        row_slices = {slice_of[id(d)] for d in arr[i]}
        assert len(row_slices) == 1
    # dp spans both slices
    assert {slice_of[id(d)] for d in arr[:, 0]} == {0, 1}
    # slice-major along dp: first half of dp rows = slice 0
    assert all(slice_of[id(d)] == 0 for d in arr[:2].ravel())
    assert all(slice_of[id(d)] == 1 for d in arr[2:].ravel())


def test_hybrid_dcn_mesh_rejects_non_dp_span():
    from paddle_tpu.distributed.topology import create_hybrid_device_mesh
    devs = jax.devices()[:8]
    slices = [devs[:4], devs[4:]]
    # mp=8 would have to cross DCN -> explicit error, not silent layout
    with pytest.raises(ValueError, match="multiple of the slice count"):
        create_hybrid_device_mesh({"dp": 1, "mp": 8},
                                  devices=devs, slices=slices)


def test_hcg_builds_through_dcn_builder():
    from paddle_tpu.distributed.topology import HybridCommunicateGroup
    devs = jax.devices()[:8]
    hcg = HybridCommunicateGroup(dp_degree=2, mp_degree=4, devices=devs,
                                 slices=[devs[:4], devs[4:]])
    assert hcg.mesh.shape["dp"] == 2 and hcg.mesh.shape["mp"] == 4


def test_ulysses_gqa_kv_head_validation():
    from paddle_tpu.distributed.parallel.context_parallel import (
        ulysses_attention)
    from paddle_tpu.distributed.topology import (
        HybridCommunicateGroup, set_hybrid_communicate_group)
    hcg = HybridCommunicateGroup(sp_degree=8)
    set_hybrid_communicate_group(hcg)
    try:
        import jax.numpy as jnp
        q = jnp.zeros((1, 16, 8, 4))
        kv = jnp.zeros((1, 16, 2, 4))  # 2 kv heads < sp=8
        with pytest.raises(ValueError, match="key heads 2"):
            ulysses_attention(q, kv, kv, axis_name="sp")
    finally:
        set_hybrid_communicate_group(None)
