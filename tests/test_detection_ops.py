"""Golden tests for the round-3 detection op set (VERDICT r2 Next #3):
yolo_loss, deform_conv2d, matrix_nms, distribute_fpn_proposals,
generate_proposals, read_file/decode_jpeg."""
import os
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.vision.ops import (DeformConv2D, decode_jpeg,
                                   deform_conv2d, distribute_fpn_proposals,
                                   generate_proposals, matrix_nms,
                                   read_file, yolo_loss)

sys.path.insert(0, os.path.dirname(__file__))
from _yolo_ref import yolo_loss_ref  # noqa: E402


ANCHORS9 = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45,
            59, 119, 116, 90, 156, 198, 373, 326]


class TestYoloLoss:
    def _data(self, seed, n=2, b=5, h=8, w=8, cls=4, mask_num=3):
        rng = np.random.RandomState(seed)
        x = rng.randn(n, mask_num * (5 + cls), h, w).astype(np.float32) * 0.5
        gt = rng.rand(n, b, 4).astype(np.float32)
        gt[..., 2:] = gt[..., 2:] * 0.5 + 0.05
        gt[..., :2] = gt[..., :2] * 0.8 + 0.1
        gt[0, -1] = 0.0  # invalid box
        lab = rng.randint(0, cls, (n, b)).astype(np.int32)
        return x, gt, lab

    @pytest.mark.parametrize("mask", [[0, 1, 2], [6, 7, 8], [3, 4, 5]])
    def test_matches_reference_kernel(self, mask):
        x, gt, lab = self._data(0)
        ref = yolo_loss_ref(x.astype(np.float64), gt.astype(np.float64),
                            lab, ANCHORS9, mask, 4, 0.7, 32)
        got = np.asarray(yolo_loss(
            paddle.to_tensor(x), paddle.to_tensor(gt),
            paddle.to_tensor(lab), ANCHORS9, mask, 4, 0.7, 32).data)
        np.testing.assert_allclose(got, ref, rtol=1e-4)

    def test_gt_score_and_no_smooth(self):
        x, gt, lab = self._data(1)
        rng = np.random.RandomState(9)
        score = rng.rand(2, 5).astype(np.float32)
        ref = yolo_loss_ref(x.astype(np.float64), gt.astype(np.float64),
                            lab, ANCHORS9, [0, 1, 2], 4, 0.5, 32,
                            gt_score=score.astype(np.float64),
                            use_label_smooth=False)
        got = np.asarray(yolo_loss(
            paddle.to_tensor(x), paddle.to_tensor(gt),
            paddle.to_tensor(lab), ANCHORS9, [0, 1, 2], 4, 0.5, 32,
            gt_score=paddle.to_tensor(score),
            use_label_smooth=False).data)
        np.testing.assert_allclose(got, ref, rtol=1e-4)

    def test_scale_x_y(self):
        x, gt, lab = self._data(2)
        ref = yolo_loss_ref(x.astype(np.float64), gt.astype(np.float64),
                            lab, ANCHORS9, [1, 2, 3], 4, 0.7, 32,
                            scale_x_y=1.05)
        got = np.asarray(yolo_loss(
            paddle.to_tensor(x), paddle.to_tensor(gt),
            paddle.to_tensor(lab), ANCHORS9, [1, 2, 3], 4, 0.7, 32,
            scale_x_y=1.05).data)
        np.testing.assert_allclose(got, ref, rtol=1e-4)

    def test_gradients_finite_difference(self):
        x, gt, lab = self._data(3, n=1, b=3, h=4, w=4, cls=3)
        anchors = ANCHORS9[:6]
        mask = [0, 1, 2]
        xt = paddle.to_tensor(x)
        xt.stop_gradient = False
        loss = yolo_loss(xt, paddle.to_tensor(gt), paddle.to_tensor(lab),
                         anchors, mask, 3, 0.7, 32).sum()
        loss.backward()
        g = np.asarray(xt.grad.data)
        rng = np.random.RandomState(0)
        eps = 1e-3
        checked = 0
        for _ in range(12):
            idx = tuple(rng.randint(0, s) for s in x.shape)
            xp, xm = x.copy(), x.copy()
            xp[idx] += eps
            xm[idx] -= eps
            fp = yolo_loss_ref(xp.astype(np.float64),
                               gt.astype(np.float64), lab, anchors, mask,
                               3, 0.7, 32).sum()
            fm = yolo_loss_ref(xm.astype(np.float64),
                               gt.astype(np.float64), lab, anchors, mask,
                               3, 0.7, 32).sum()
            fd = (fp - fm) / (2 * eps)
            assert abs(fd - g[idx]) < 2e-2 + 0.02 * abs(fd), (idx, fd, g[idx])
            checked += abs(fd) > 1e-6
        assert checked >= 3  # at least some non-zero-grad entries hit

    @pytest.mark.slow  # ~7s train loop; FD-gradient test stays tier-1
    def test_trains_down(self):
        paddle.seed(0)
        head = nn.Conv2D(8, 3 * 9, 1)
        opt = paddle.optimizer.Adam(learning_rate=0.05,
                                    parameters=head.parameters())
        rng = np.random.RandomState(2)
        feat = paddle.to_tensor(rng.randn(2, 8, 8, 8).astype(np.float32))
        gtb = paddle.to_tensor(np.asarray(
            [[[0.4, 0.4, 0.3, 0.35]], [[0.6, 0.5, 0.2, 0.2]]], np.float32))
        gtl = paddle.to_tensor(np.zeros((2, 1), np.int32))
        first = last = None
        for _ in range(12):
            loss = yolo_loss(head(feat), gtb, gtl, ANCHORS9[:6], [0, 1, 2],
                             4, 0.7, 32).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
            first = first if first is not None else float(loss)
            last = float(loss)
        assert last < first * 0.8


class TestDeformConv2D:
    def _oracle(self, x, off, wt, msk, stride, pad, dil, groups, dg):
        n, cin, h, w = x.shape
        cout, _, kh, kw = wt.shape
        hout = (h + 2 * pad[0] - (dil[0] * (kh - 1) + 1)) // stride[0] + 1
        wout = (w + 2 * pad[1] - (dil[1] * (kw - 1) + 1)) // stride[1] + 1
        out = np.zeros((n, cout, hout, wout))
        cg, cpg = cin // groups, cin // dg
        for b in range(n):
            for co in range(cout):
                g = co // (cout // groups)
                for ho in range(hout):
                    for wo in range(wout):
                        acc = 0.0
                        for ci in range(cg):
                            cif = g * cg + ci
                            d = cif // cpg
                            for i in range(kh):
                                for j in range(kw):
                                    p = i * kw + j
                                    py = ho * stride[0] - pad[0] \
                                        + i * dil[0] \
                                        + off[b, d * 2 * kh * kw + 2 * p,
                                              ho, wo]
                                    px = wo * stride[1] - pad[1] \
                                        + j * dil[1] \
                                        + off[b, d * 2 * kh * kw + 2 * p + 1,
                                              ho, wo]
                                    y0 = int(np.floor(py))
                                    x0 = int(np.floor(px))
                                    v = 0.0
                                    for yi, wy in ((y0, 1 - (py - y0)),
                                                   (y0 + 1, py - y0)):
                                        for xi, wx in ((x0, 1 - (px - x0)),
                                                       (x0 + 1, px - x0)):
                                            if 0 <= yi < h and 0 <= xi < w:
                                                v += x[b, cif, yi, xi] \
                                                    * wy * wx
                                    if msk is not None:
                                        v *= msk[b, d * kh * kw + p, ho, wo]
                                    acc += v * wt[co, ci, i, j]
                        out[b, co, ho, wo] = acc
        return out

    @pytest.mark.parametrize("groups,dg,use_mask", [
        (1, 1, False), (1, 1, True), (2, 1, False), (1, 2, True),
        (2, 2, True)])
    def test_matches_naive_oracle(self, groups, dg, use_mask):
        rng = np.random.RandomState(groups * 7 + dg)
        n, cin, h, w = 2, 4, 7, 6
        cout, kh, kw = 6, 3, 3
        stride, pad, dil = (2, 1), (1, 2), (1, 1)
        hout = (h + 2 * pad[0] - (dil[0] * (kh - 1) + 1)) // stride[0] + 1
        wout = (w + 2 * pad[1] - (dil[1] * (kw - 1) + 1)) // stride[1] + 1
        x = rng.randn(n, cin, h, w).astype(np.float32)
        off = (rng.randn(n, 2 * dg * kh * kw, hout, wout) * 1.5) \
            .astype(np.float32)
        msk = rng.rand(n, dg * kh * kw, hout, wout).astype(np.float32) \
            if use_mask else None
        wt = rng.randn(cout, cin // groups, kh, kw).astype(np.float32)
        ref = self._oracle(x.astype(np.float64), off.astype(np.float64),
                           wt.astype(np.float64),
                           None if msk is None else msk.astype(np.float64),
                           stride, pad, dil, groups, dg)
        got = np.asarray(deform_conv2d(
            paddle.to_tensor(x), paddle.to_tensor(off),
            paddle.to_tensor(wt), stride=stride, padding=pad, dilation=dil,
            deformable_groups=dg, groups=groups,
            mask=None if msk is None else paddle.to_tensor(msk)).data)
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)

    def test_zero_offset_equals_conv(self):
        rng = np.random.RandomState(0)
        x = rng.randn(1, 3, 8, 8).astype(np.float32)
        wt = rng.randn(5, 3, 3, 3).astype(np.float32)
        off = np.zeros((1, 18, 8, 8), np.float32)
        got = np.asarray(deform_conv2d(
            paddle.to_tensor(x), paddle.to_tensor(off),
            paddle.to_tensor(wt), padding=1).data)
        conv = nn.Conv2D(3, 5, 3, padding=1, bias_attr=False)
        conv.weight.set_value(paddle.to_tensor(wt))
        ref = np.asarray(conv(paddle.to_tensor(x)).data)
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)

    def test_layer_trains(self):
        paddle.seed(0)
        dc = DeformConv2D(4, 6, 3, padding=1)
        offp = nn.Conv2D(4, 18, 3, padding=1)
        opt = paddle.optimizer.Adam(
            learning_rate=0.01,
            parameters=dc.parameters() + offp.parameters())
        x = paddle.to_tensor(
            np.random.RandomState(3).randn(2, 4, 10, 10).astype(np.float32))
        first = last = None
        for _ in range(8):
            loss = ((dc(x, offp(x)) - 1.0) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            first = first if first is not None else float(loss)
            last = float(loss)
        assert last < first

    def test_static_nn_wrapper(self):
        from paddle_tpu.static import nn as snn
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 4, 6, 6).astype(np.float32))
        off = paddle.zeros([2, 18, 6, 6])
        out = snn.deform_conv2d(x, off, None, 8, 3, padding=1)
        assert tuple(out.shape) == (2, 8, 6, 6)


class TestMatrixNMS:
    def test_single_survivor(self):
        # two heavily-overlapping boxes, one distinct: decay kills none
        # outright but scales scores; check the hand-computed decays
        boxes = np.asarray([[[0, 0, 10, 10], [0, 0, 10, 9],
                             [50, 50, 60, 60]]], np.float32)
        scores = np.asarray([[[0.9, 0.8, 0.7]]], np.float32)  # 1 class
        out, idx, num = matrix_nms(
            paddle.to_tensor(boxes), paddle.to_tensor(scores),
            score_threshold=0.1, post_threshold=0.0, nms_top_k=-1,
            keep_top_k=-1, background_label=-1, return_index=True)
        o = np.asarray(out.data)
        assert o.shape == (3, 6)
        assert int(np.asarray(num.data)[0]) == 3
        # rows sorted by decayed score: 0.9, then the distinct box
        # (undecayed 0.7), then box1 decayed by (1 - iou)
        iou = (10 * 9) / (100 + 90 - 90)
        np.testing.assert_allclose(
            o[:, 1], [0.9, 0.7, 0.8 * (1 - iou)], rtol=1e-5)
        # index points back into the flattened [N*M] box array
        np.testing.assert_array_equal(
            np.asarray(idx.data).ravel(), [0, 2, 1])

    def test_post_threshold_and_background(self):
        boxes = np.asarray([[[0, 0, 10, 10], [0, 0, 10, 10]]], np.float32)
        scores = np.asarray([[[0.9, 0.85], [0.5, 0.4]]], np.float32)
        out, num = matrix_nms(
            paddle.to_tensor(boxes), paddle.to_tensor(scores),
            score_threshold=0.3, post_threshold=0.3, nms_top_k=-1,
            keep_top_k=-1, background_label=0)
        o = np.asarray(out.data)
        # class 0 is background; class 1: second box decays to 0 (iou=1)
        assert o.shape[0] == 1
        assert o[0, 0] == 1.0 and abs(o[0, 1] - 0.5) < 1e-6

    def test_gaussian_decay(self):
        boxes = np.asarray([[[0, 0, 10, 10], [0, 0, 10, 9]]], np.float32)
        scores = np.asarray([[[0.9, 0.8]]], np.float32)
        out = matrix_nms(paddle.to_tensor(boxes), paddle.to_tensor(scores),
                         0.1, 0.0, -1, -1, use_gaussian=True,
                         gaussian_sigma=2.0, background_label=-1,
                         return_rois_num=False)
        o = np.asarray(out.data)
        iou = 90 / 100
        np.testing.assert_allclose(
            o[1, 1], 0.8 * np.exp(-(iou ** 2) * 2.0), rtol=1e-5)


class TestDistributeFpnProposals:
    def test_level_assignment_and_restore(self):
        rois = np.asarray([
            [0, 0, 16, 16],      # sqrt(256)=16 -> low level
            [0, 0, 224, 224],    # refer_scale -> refer_level
            [0, 0, 448, 448],    # 2x refer -> refer_level+1
            [0, 0, 896, 896],    # clipped at max_level
            [0, 0, 60, 60],
        ], np.float32)
        rois_num = np.asarray([3, 2], np.int32)
        multi, restore, per_level = distribute_fpn_proposals(
            paddle.to_tensor(rois), 2, 5, 4, 224,
            rois_num=paddle.to_tensor(rois_num))
        assert len(multi) == 4 and len(per_level) == 4
        sizes = [np.asarray(m.data).shape[0] for m in multi]
        assert sum(sizes) == 5
        # level of roi 1 (area 224^2) = floor(log2(1+eps)+4) = 4
        lv = {}
        for li, m in enumerate(multi):
            for r in np.asarray(m.data):
                lv[int(r[2])] = li + 2
        assert lv[224] == 4 and lv[448] == 5 and lv[896] == 5 \
            and lv[16] == 2
        # restore index is a permutation that undoes the shuffle
        rest = np.asarray(restore.data).ravel()
        shuffled = np.concatenate([np.asarray(m.data) for m in multi])
        np.testing.assert_allclose(shuffled[rest], rois)
        # per-level counts sum per image
        counts = np.stack([np.asarray(p.data) for p in per_level])
        assert counts.sum() == 5
        np.testing.assert_array_equal(counts.sum(axis=0), rois_num)


class TestGenerateProposals:
    def test_decode_clip_filter_nms(self):
        rng = np.random.RandomState(0)
        n, a, h, w = 2, 3, 4, 4
        scores = rng.rand(n, a, h, w).astype(np.float32)
        deltas = (rng.randn(n, 4 * a, h, w) * 0.1).astype(np.float32)
        img = np.asarray([[64.0, 64.0], [64.0, 64.0]], np.float32)
        base = np.stack(np.meshgrid(np.arange(w) * 16, np.arange(h) * 16,
                                    indexing="xy"), -1).astype(np.float32)
        anchors = np.zeros((h, w, a, 4), np.float32)
        for k, sz in enumerate([16, 32, 48]):
            anchors[..., k, 0] = base[..., 0]
            anchors[..., k, 1] = base[..., 1]
            anchors[..., k, 2] = base[..., 0] + sz
            anchors[..., k, 3] = base[..., 1] + sz
        var = np.ones((h, w, a, 4), np.float32)
        rois, probs, num = generate_proposals(
            paddle.to_tensor(scores), paddle.to_tensor(deltas),
            paddle.to_tensor(img), paddle.to_tensor(anchors),
            paddle.to_tensor(var), pre_nms_top_n=30, post_nms_top_n=10,
            nms_thresh=0.5, min_size=4.0, return_rois_num=True)
        r = np.asarray(rois.data)
        p = np.asarray(probs.data)
        nm = np.asarray(num.data)
        assert r.shape[1] == 4 and p.shape[1] == 1
        assert nm.sum() == r.shape[0] and len(nm) == n
        assert (nm <= 10).all()
        # all inside image, min size respected
        assert (r >= 0).all() and (r[:, 2] <= 64).all() \
            and (r[:, 3] <= 64).all()
        assert ((r[:, 2] - r[:, 0]) >= 4 - 1e-4).all()
        # probs sorted descending within each image
        o = 0
        for c in nm:
            seg = p[o:o + c, 0]
            assert (np.diff(seg) <= 1e-6).all()
            o += c


class TestReadDecode:
    def test_read_file_and_decode_jpeg(self, tmp_path):
        from PIL import Image
        arr = (np.random.RandomState(0).rand(12, 16, 3) * 255) \
            .astype(np.uint8)
        fp = str(tmp_path / "t.jpg")
        Image.fromarray(arr).save(fp, quality=95)
        raw = read_file(fp)
        assert raw.dtype == paddle.uint8 and raw.ndim == 1
        img = decode_jpeg(raw)
        got = np.asarray(img.data)
        assert got.shape == (3, 12, 16)
        # exact match vs PIL's own decode of the same bytes
        ref = np.asarray(Image.open(fp)).transpose(2, 0, 1)
        np.testing.assert_array_equal(got, ref)
        gray = decode_jpeg(raw, mode="gray")
        assert np.asarray(gray.data).shape == (1, 12, 16)


class TestReferenceStyleDetectorTraining:
    """VERDICT r2 Missing #1 closure: a reference-style YOLOv3 detector
    — multi-scale heads + per-scale yolo_loss (downsample 32/16/8) —
    trains end to end on the in-tree CSPResNet backbone."""

    @pytest.mark.slow  # ~27s compile on CPU: tier-2
    def test_multiscale_yolov3_trains(self):
        from paddle_tpu.models.ppyoloe import CSPResNet
        paddle.seed(0)
        num_classes = 4
        mask_num, per_scale = 3, 5 + 4
        backbone = CSPResNet(widths=(16, 32, 64, 128))
        heads = [nn.Conv2D(c, mask_num * per_scale, 1)
                 for c in (32, 64, 128)]
        params = backbone.parameters()
        for h in heads:
            params += h.parameters()
        opt = paddle.optimizer.Adam(learning_rate=2e-3,
                                    parameters=params)
        rng = np.random.RandomState(0)
        img = paddle.to_tensor(
            rng.randn(2, 3, 64, 64).astype(np.float32))
        gt = paddle.to_tensor(np.asarray(
            [[[0.3, 0.4, 0.4, 0.5], [0.7, 0.6, 0.2, 0.25]],
             [[0.5, 0.5, 0.6, 0.6], [0.0, 0.0, 0.0, 0.0]]],
            np.float32))
        lab = paddle.to_tensor(
            rng.randint(0, num_classes, (2, 2)).astype(np.int32))
        masks = [[6, 7, 8], [3, 4, 5], [0, 1, 2]]
        downs = [32, 16, 8]

        first = last = None
        for _ in range(6):
            feats = backbone(img)[-3:]  # strides 8/16/32 pyramid
            total = None
            for feat, m, d, head in zip(feats[::-1], masks, downs,
                                        heads[::-1]):
                l = yolo_loss(head(feat), gt, lab, ANCHORS9, m,
                              num_classes, 0.7, d).sum()
                total = l if total is None else total + l
            total.backward()
            opt.step()
            opt.clear_grad()
            first = first if first is not None else float(total)
            last = float(total)
        assert np.isfinite(last)
        assert last < first * 0.9, (first, last)
