"""Golden tests for the Pallas fused linear+CE kernel
(paddle_tpu/kernels/fused_ce.py) in interpret mode: forward and both
operand gradients vs a dense jax reference, both weight layouts,
ignored labels, block-ragged shapes, and jit.

The kernel is a measured NEGATIVE for the bench configs (BASELINE.md
r4 loss-head attack: the twice-recomputed vocab matmul in backward
costs more than the save-logits / remat-scan paths it replaces) but
stays in-tree as a correct, available op — these tests pin it.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.kernels.fused_ce import fused_linear_ce

N, H, V = 70, 32, 150  # deliberately not multiples of the blocks


def _dense_ce(h, w_vh, y):
    logits = h @ w_vh.T
    lse = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(
        logits, jnp.maximum(y, 0)[:, None], axis=-1)[:, 0]
    return jnp.where(y >= 0, lse - gold, 0.0)


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(0)
    h = jnp.asarray(rng.randn(N, H), jnp.float32)
    w = jnp.asarray(rng.randn(V, H) * 0.1, jnp.float32)
    y_np = rng.randint(0, V, (N,))
    y_np[::7] = -1  # deterministic ignored rows
    y = jnp.asarray(y_np, jnp.int32)
    return h, w, y


def test_forward_vocab_major(data):
    h, w, y = data
    ce = fused_linear_ce(h, w, y, True, 32, 64)
    np.testing.assert_allclose(np.asarray(ce), np.asarray(_dense_ce(h, w, y)),
                               rtol=1e-5, atol=1e-5)


def test_forward_hidden_major(data):
    h, w, y = data
    ce = fused_linear_ce(h, w.T, y, False, 32, 64)
    np.testing.assert_allclose(np.asarray(ce), np.asarray(_dense_ce(h, w, y)),
                               rtol=1e-5, atol=1e-5)


def test_ignored_rows_are_zero_and_gradless(data):
    h, w, y = data
    ce = fused_linear_ce(h, w, y, True, 32, 64)
    ignored = np.asarray(y) < 0
    assert ignored.any()
    assert np.all(np.asarray(ce)[ignored] == 0.0)
    dh = jax.grad(lambda h: jnp.sum(
        fused_linear_ce(h, w, y, True, 32, 64)))(h)
    assert np.all(np.asarray(dh)[ignored] == 0.0)


def test_grads_match_dense_both_layouts(data):
    h, w, y = data
    rng = np.random.RandomState(1)
    wvec = jnp.asarray(rng.rand(N), jnp.float32)  # non-trivial cotangent

    gd = jax.grad(lambda h, w: jnp.sum(_dense_ce(h, w, y) * wvec),
                  argnums=(0, 1))(h, w)
    gk = jax.grad(lambda h, w: jnp.sum(
        fused_linear_ce(h, w, y, True, 32, 64) * wvec),
        argnums=(0, 1))(h, w)
    np.testing.assert_allclose(np.asarray(gk[0]), np.asarray(gd[0]),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gk[1]), np.asarray(gd[1]),
                               rtol=1e-4, atol=1e-5)

    gk2 = jax.grad(lambda h, wt: jnp.sum(
        fused_linear_ce(h, wt, y, False, 32, 64) * wvec),
        argnums=(0, 1))(h, w.T)
    np.testing.assert_allclose(np.asarray(gk2[0]), np.asarray(gd[0]),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gk2[1]), np.asarray(gd[1].T),
                               rtol=1e-4, atol=1e-5)


def test_jit_and_mean_loss(data):
    h, w, y = data

    @jax.jit
    def mean_ce(h, w, y):
        ce = fused_linear_ce(h, w, y, True, 32, 64)
        valid = (y >= 0).astype(jnp.float32)
        return jnp.sum(ce) / jnp.maximum(jnp.sum(valid), 1.0)

    got = float(mean_ce(h, w, y))
    valid = np.asarray(y) >= 0
    want = float(np.asarray(_dense_ce(h, w, y))[valid].mean())
    assert abs(got - want) < 1e-5
