"""Op golden tests via the OpTest harness (≈ unittests/test_*_op.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F
from op_test import check_grad, check_output

rng = np.random.RandomState(0)


class TestMath:
    def test_add(self):
        a = rng.randn(3, 4).astype("float32")
        b = rng.randn(3, 4).astype("float32")
        check_output(paddle.add, np.add, [a, b])
        check_grad(paddle.add, [a, b], grad_idx=0)

    def test_broadcast_add(self):
        a = rng.randn(3, 4).astype("float32")
        b = rng.randn(4).astype("float32")
        check_output(paddle.add, np.add, [a, b])
        check_grad(paddle.add, [a, b], grad_idx=1)

    def test_mul_grad(self):
        a = rng.randn(2, 3).astype("float32")
        b = rng.randn(2, 3).astype("float32")
        check_grad(paddle.multiply, [a, b], grad_idx=0)

    def test_exp_log(self):
        a = rng.rand(3, 4).astype("float32") + 0.5
        check_output(paddle.exp, np.exp, [a])
        check_output(paddle.log, np.log, [a], rtol=1e-5)
        check_grad(paddle.log, [a])

    def test_tanh_grad(self):
        a = rng.randn(5).astype("float32")
        check_grad(paddle.tanh, [a])

    def test_reductions(self):
        a = rng.randn(3, 4, 5).astype("float32")
        check_output(paddle.sum, np.sum, [a])
        check_output(lambda x: paddle.sum(x, axis=1),
                     lambda x: np.sum(x, axis=1), [a])
        check_output(lambda x: paddle.mean(x, axis=[0, 2], keepdim=True),
                     lambda x: np.mean(x, axis=(0, 2), keepdims=True), [a])
        check_output(paddle.max, np.max, [a])
        check_grad(lambda x: paddle.mean(x, axis=1), [a])

    def test_clip(self):
        a = rng.randn(4, 4).astype("float32")
        check_output(lambda x: paddle.clip(x, min=-0.5, max=0.5),
                     lambda x: np.clip(x, -0.5, 0.5), [a])

    def test_cumsum(self):
        a = rng.randn(3, 4).astype("float32")
        check_output(lambda x: paddle.cumsum(x, axis=1),
                     lambda x: np.cumsum(x, axis=1), [a])

    def test_comparison(self):
        a = rng.randn(3, 4).astype("float32")
        b = rng.randn(3, 4).astype("float32")
        assert np.array_equal((paddle.to_tensor(a) < paddle.to_tensor(b)).numpy(),
                              a < b)

    def test_logsumexp(self):
        a = rng.randn(3, 4).astype("float32")
        from scipy.special import logsumexp as sls
        check_output(lambda x: paddle.logsumexp(x, axis=1),
                     lambda x: sls(x, axis=1), [a], rtol=1e-5)


class TestLinalg:
    def test_matmul(self):
        a = rng.randn(3, 4).astype("float32")
        b = rng.randn(4, 5).astype("float32")
        check_output(paddle.matmul, np.matmul, [a, b], rtol=1e-4)
        check_grad(paddle.matmul, [a, b], grad_idx=0)
        check_grad(paddle.matmul, [a, b], grad_idx=1)

    def test_matmul_transpose(self):
        a = rng.randn(4, 3).astype("float32")
        b = rng.randn(4, 5).astype("float32")
        check_output(lambda x, y: paddle.matmul(x, y, transpose_x=True),
                     lambda x, y: x.T @ y, [a, b], rtol=1e-4)

    def test_bmm(self):
        a = rng.randn(2, 3, 4).astype("float32")
        b = rng.randn(2, 4, 5).astype("float32")
        check_output(paddle.bmm, np.matmul, [a, b], rtol=1e-4)

    def test_einsum(self):
        a = rng.randn(3, 4).astype("float32")
        b = rng.randn(4, 5).astype("float32")
        out = paddle.einsum("ij,jk->ik", paddle.to_tensor(a),
                            paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-4)

    def test_norm(self):
        a = rng.randn(3, 4).astype("float32")
        check_output(lambda x: paddle.ops.linalg.norm(x),
                     lambda x: np.linalg.norm(x), [a], rtol=1e-5)

    def test_solve_inverse(self):
        a = (rng.randn(4, 4) + 4 * np.eye(4)).astype("float32")
        b = rng.randn(4, 2).astype("float32")
        check_output(paddle.ops.linalg.solve, np.linalg.solve, [a, b],
                     rtol=1e-3, atol=1e-4)
        check_output(paddle.ops.linalg.inv, np.linalg.inv, [a],
                     rtol=1e-3, atol=1e-4)


class TestManipulation:
    def test_reshape_flatten(self):
        a = rng.randn(2, 3, 4).astype("float32")
        check_output(lambda x: paddle.reshape(x, [6, 4]),
                     lambda x: x.reshape(6, 4), [a])
        check_output(lambda x: paddle.flatten(x, 1),
                     lambda x: x.reshape(2, 12), [a])
        check_grad(lambda x: paddle.reshape(x, [24]), [a])

    def test_concat_split(self):
        a = rng.randn(2, 3).astype("float32")
        b = rng.randn(2, 5).astype("float32")
        out = paddle.concat([paddle.to_tensor(a), paddle.to_tensor(b)],
                            axis=1)
        np.testing.assert_allclose(out.numpy(),
                                   np.concatenate([a, b], axis=1))
        parts = paddle.split(out, [3, 5], axis=1)
        np.testing.assert_allclose(parts[0].numpy(), a)
        np.testing.assert_allclose(parts[1].numpy(), b)

    def test_split_grad(self):
        a = paddle.to_tensor(rng.randn(4, 6).astype("float32"),
                             stop_gradient=False)
        p1, p2 = paddle.split(a, 2, axis=1)
        loss = p1.sum() + (2 * p2).sum()
        loss.backward()
        expected = np.concatenate([np.ones((4, 3)), 2 * np.ones((4, 3))], 1)
        np.testing.assert_allclose(a.grad.numpy(), expected)

    def test_transpose(self):
        a = rng.randn(2, 3, 4).astype("float32")
        check_output(lambda x: paddle.transpose(x, [2, 0, 1]),
                     lambda x: x.transpose(2, 0, 1), [a])

    def test_gather_scatter(self):
        a = rng.randn(5, 3).astype("float32")
        idx = np.array([0, 2, 4])
        out = paddle.gather(paddle.to_tensor(a), paddle.to_tensor(idx))
        np.testing.assert_allclose(out.numpy(), a[idx])

    def test_where(self):
        c = rng.rand(3, 4) > 0.5
        a = rng.randn(3, 4).astype("float32")
        b = rng.randn(3, 4).astype("float32")
        out = paddle.where(paddle.to_tensor(c), paddle.to_tensor(a),
                           paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), np.where(c, a, b))

    def test_topk(self):
        a = rng.randn(3, 10).astype("float32")
        vals, idx = paddle.topk(paddle.to_tensor(a), k=3)
        ref = np.sort(a, axis=1)[:, ::-1][:, :3]
        np.testing.assert_allclose(vals.numpy(), ref, rtol=1e-6)

    def test_getitem_setitem(self):
        a = paddle.to_tensor(rng.randn(4, 5).astype("float32"))
        np.testing.assert_allclose(a[1:3].numpy(), a.numpy()[1:3])
        np.testing.assert_allclose(a[:, ::2].numpy(), a.numpy()[:, ::2])
        a2 = a.numpy().copy()
        a[0] = 7.0
        a2[0] = 7.0
        np.testing.assert_allclose(a.numpy(), a2)

    def test_getitem_grad(self):
        x = paddle.to_tensor(rng.randn(4, 5).astype("float32"),
                             stop_gradient=False)
        y = x[1:3, :2].sum()
        y.backward()
        g = np.zeros((4, 5), np.float32)
        g[1:3, :2] = 1
        np.testing.assert_allclose(x.grad.numpy(), g)

    def test_pad(self):
        a = rng.randn(2, 3).astype("float32")
        out = paddle.ops.manipulation.pad(paddle.to_tensor(a),
                                          [1, 1, 2, 2])
        assert list(out.shape) == [4, 7]


class TestActivation:
    @pytest.mark.parametrize("fn,ref", [
        (F.relu, lambda x: np.maximum(x, 0)),
        (F.sigmoid, lambda x: 1 / (1 + np.exp(-x))),
        (F.softplus, lambda x: np.log1p(np.exp(x))),
        (F.silu, lambda x: x / (1 + np.exp(-x))),
    ])
    def test_forward(self, fn, ref):
        a = rng.randn(3, 4).astype("float32")
        check_output(fn, ref, [a], rtol=1e-5)

    def test_softmax(self):
        a = rng.randn(3, 4).astype("float32")

        def ref(x):
            e = np.exp(x - x.max(-1, keepdims=True))
            return e / e.sum(-1, keepdims=True)

        check_output(F.softmax, ref, [a], rtol=1e-5)
        check_grad(F.softmax, [a])

    def test_gelu_grad(self):
        a = rng.randn(6).astype("float32")
        check_grad(F.gelu, [a])


class TestLoss:
    def test_cross_entropy(self):
        logits = rng.randn(4, 10).astype("float32")
        labels = rng.randint(0, 10, (4,))

        def ref(lg, lb):
            e = np.exp(lg - lg.max(-1, keepdims=True))
            p = e / e.sum(-1, keepdims=True)
            return -np.log(p[np.arange(4), lb]).mean()

        out = F.cross_entropy(paddle.to_tensor(logits),
                              paddle.to_tensor(labels))
        np.testing.assert_allclose(float(out), ref(logits, labels),
                                   rtol=1e-5)

    def test_cross_entropy_grad(self):
        logits = rng.randn(4, 6).astype("float32")
        labels = rng.randint(0, 6, (4,))
        check_grad(lambda x: F.cross_entropy(x, paddle.to_tensor(labels)),
                   [logits])

    def test_cross_entropy_ignore_index(self):
        logits = rng.randn(4, 6).astype("float32")
        labels = np.array([1, -100, 3, -100])
        out = F.cross_entropy(paddle.to_tensor(logits),
                              paddle.to_tensor(labels), ignore_index=-100)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        expected = -np.log(p[[0, 2], [1, 3]]).mean()
        np.testing.assert_allclose(float(out), expected, rtol=1e-5)

    def test_mse(self):
        a = rng.randn(3, 4).astype("float32")
        b = rng.randn(3, 4).astype("float32")
        check_output(F.mse_loss, lambda x, y: ((x - y) ** 2).mean(), [a, b],
                     rtol=1e-5)

    def test_bce_with_logits(self):
        lg = rng.randn(8).astype("float32")
        lb = (rng.rand(8) > 0.5).astype("float32")
        out = F.binary_cross_entropy_with_logits(paddle.to_tensor(lg),
                                                 paddle.to_tensor(lb))
        p = 1 / (1 + np.exp(-lg))
        ref = -(lb * np.log(p) + (1 - lb) * np.log(1 - p)).mean()
        np.testing.assert_allclose(float(out), ref, rtol=1e-4)


class TestConvPool:
    def test_conv2d_identity(self):
        x = rng.randn(1, 1, 5, 5).astype("float32")
        w = np.zeros((1, 1, 3, 3), np.float32)
        w[0, 0, 1, 1] = 1.0  # identity kernel
        out = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w), padding=1)
        np.testing.assert_allclose(out.numpy(), x, atol=1e-6)

    def test_conv2d_vs_manual(self):
        x = rng.randn(2, 3, 8, 8).astype("float32")
        w = rng.randn(4, 3, 3, 3).astype("float32")
        out = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w), stride=2,
                       padding=1)
        assert list(out.shape) == [2, 4, 4, 4]
        # spot-check one output element
        xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
        ref = (xp[0, :, 0:3, 0:3] * w[1]).sum()
        np.testing.assert_allclose(float(out.numpy()[0, 1, 0, 0]), ref,
                                   rtol=1e-4)

    def test_conv_grad(self):
        x = rng.randn(1, 2, 5, 5).astype("float32")
        w = rng.randn(3, 2, 3, 3).astype("float32")
        check_grad(lambda a, b: F.conv2d(a, b, padding=1), [x, w],
                   grad_idx=1, rtol=2e-2, atol=2e-3)

    def test_max_pool(self):
        x = rng.randn(1, 2, 4, 4).astype("float32")
        out = F.max_pool2d(paddle.to_tensor(x), 2, 2)
        ref = x.reshape(1, 2, 2, 2, 2, 2).max(axis=(3, 5))
        np.testing.assert_allclose(out.numpy(), ref)

    def test_avg_pool(self):
        x = rng.randn(1, 2, 4, 4).astype("float32")
        out = F.avg_pool2d(paddle.to_tensor(x), 2, 2)
        ref = x.reshape(1, 2, 2, 2, 2, 2).mean(axis=(3, 5))
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-6)

    def test_adaptive_avg_pool(self):
        x = rng.randn(1, 3, 8, 8).astype("float32")
        out = F.adaptive_avg_pool2d(paddle.to_tensor(x), 1)
        np.testing.assert_allclose(out.numpy()[..., 0, 0],
                                   x.mean(axis=(2, 3)), rtol=1e-5)


class TestNorm:
    def test_layer_norm(self):
        x = rng.randn(2, 3, 8).astype("float32")

        def ref(a):
            m = a.mean(-1, keepdims=True)
            v = a.var(-1, keepdims=True)
            return (a - m) / np.sqrt(v + 1e-5)

        check_output(lambda a: F.layer_norm(a, 8), ref, [x], rtol=1e-4,
                     atol=1e-5)
        check_grad(lambda a: F.layer_norm(a, 8), [x], rtol=3e-2, atol=3e-3)

    def test_batch_norm_train_stats(self):
        x = rng.randn(4, 3, 5, 5).astype("float32")
        out, mean, var = F.batch_norm_train(paddle.to_tensor(x))
        np.testing.assert_allclose(mean.numpy(), x.mean(axis=(0, 2, 3)),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(out.numpy().mean(axis=(0, 2, 3)),
                                   np.zeros(3), atol=1e-5)
