"""Real ONNX emission (VERDICT r2 Next #9): hand-encoded protobuf for
the Linear/Conv/Norm subset, validated structurally with the in-tree
wire parser and (when available) `protoc --decode_raw`."""
import shutil
import subprocess

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.onnx_proto import DT_FLOAT, export_onnx, parse_wire


def _model():
    paddle.seed(0)
    return nn.Sequential(
        nn.Conv2D(3, 8, 3, padding=1), nn.BatchNorm2D(8), nn.ReLU(),
        nn.MaxPool2D(2), nn.AdaptiveAvgPool2D(1), nn.Flatten(),
        nn.Linear(8, 4), nn.Softmax())


def _graph_fields(path):
    model_fields = parse_wire(open(path, "rb").read())
    by = {}
    for f, w, v in model_fields:
        by.setdefault(f, []).append(v)
    assert by[1] == [8]          # ir_version
    graph = parse_wire(by[7][0])
    return by, graph


def test_structure_and_ops(tmp_path):
    m = _model()
    m.eval()
    p = export_onnx(m, str(tmp_path / "m"), [1, 3, 16, 16])
    assert p.endswith(".onnx")
    _, graph = _graph_fields(p)
    nodes = [parse_wire(v) for f, w, v in graph if f == 1]
    op_types = [next(v for ff, ww, v in n if ff == 4).decode()
                for n in nodes]
    assert op_types == ["Conv", "BatchNormalization", "Relu",
                        "MaxPool", "GlobalAveragePool", "Flatten",
                        "Gemm", "Softmax"]
    # graph inputs/outputs present
    assert any(f == 11 for f, w, v in graph)
    assert any(f == 12 for f, w, v in graph)


def test_initializers_round_trip(tmp_path):
    paddle.seed(1)
    m = nn.Sequential(nn.Linear(4, 3))
    m.eval()
    p = export_onnx(m, str(tmp_path / "lin"), [2, 4])
    _, graph = _graph_fields(p)
    inits = [parse_wire(v) for f, w, v in graph if f == 5]
    tensors = {}
    for t in inits:
        fields = {f: v for f, w, v in t}
        dims = [v for f, w, v in t if f == 1]
        assert fields[2] == DT_FLOAT
        tensors[fields[8].decode()] = np.frombuffer(
            fields[9], np.float32).reshape(dims)
    w_name = [n for n in tensors if n.startswith("W")][0]
    b_name = [n for n in tensors if n.startswith("B")][0]
    np.testing.assert_allclose(tensors[w_name],
                               np.asarray(m[0].weight.numpy()))
    # poor-man's runtime: Gemm(input, W) + B == model output
    x = np.random.RandomState(0).randn(2, 4).astype(np.float32)
    ref = np.asarray(m(paddle.to_tensor(x)).data)
    np.testing.assert_allclose(x @ tensors[w_name] + tensors[b_name],
                               ref, rtol=1e-5, atol=1e-6)


@pytest.mark.skipif(shutil.which("protoc") is None,
                    reason="protoc unavailable")
def test_protoc_decodes(tmp_path):
    m = _model()
    m.eval()
    p = export_onnx(m, str(tmp_path / "m"), [1, 3, 16, 16])
    r = subprocess.run(["protoc", "--decode_raw"],
                       stdin=open(p, "rb"), capture_output=True,
                       text=True)
    assert r.returncode == 0, r.stderr
    for op in ("Conv", "Gemm", "BatchNormalization", "Softmax"):
        assert op in r.stdout


def test_export_entrypoint_and_fallback(tmp_path):
    m = _model()
    m.eval()
    out = paddle.onnx.export(
        m, str(tmp_path / "art"),
        input_spec=[paddle.to_tensor(
            np.zeros((1, 3, 16, 16), np.float32))],
        format="onnx")
    assert out.endswith(".onnx")
    # outside-subset models raise with a pointer to StableHLO
    class Odd(nn.Layer):
        def forward(self, x):
            return x * 2
    with pytest.raises(NotImplementedError, match="StableHLO"):
        export_onnx(Odd(), str(tmp_path / "odd"), [1, 4])
    # layernorm bumps the opset to 17
    m2 = nn.Sequential(nn.Linear(4, 4), nn.LayerNorm(4))
    m2.eval()
    p2 = export_onnx(m2, str(tmp_path / "ln"), [2, 4])
    by, _ = _graph_fields(p2)
    opset = parse_wire(by[8][0])
    assert {f: v for f, w, v in opset}[2] == 17
