"""Sharded checkpoint tests (≈ the reference's save/load +
hybrid_parallel_pp_save_load + converter.py resharding coverage), on the
8-device CPU mesh."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed import fleet, topology
from paddle_tpu.distributed.checkpoint import (CheckpointManager,
                                               load_sharded, save_sharded,
                                               shardings_for_model)


@pytest.fixture(autouse=True)
def _restore_mesh():
    prev = topology.get_hybrid_communicate_group()
    yield
    topology.set_hybrid_communicate_group(prev)


def _small_model():
    paddle.seed(0)
    return nn.Sequential(nn.Linear(16, 64), nn.ReLU(), nn.Linear(64, 8))


class TestOneShot:
    def test_roundtrip_plain(self, tmp_path):
        model = _small_model()
        path = str(tmp_path / "ckpt1")
        save_sharded({"model": model.state_dict()}, path)
        state = load_sharded(path)
        sd = state["model"]
        for name, t in model.state_dict().items():
            np.testing.assert_allclose(np.asarray(sd[name].data),
                                       np.asarray(t.data))

    def test_restore_resharded_onto_mesh(self, tmp_path):
        """Save unsharded, restore placed onto a dp x mp mesh — the
        cross-strategy conversion path."""
        model = _small_model()
        # give a weight an mp spec so shardings_for_model uses it
        from jax.sharding import PartitionSpec as P
        model[0].weight.spec = P(None, "mp")
        path = str(tmp_path / "ckpt2")
        save_sharded({"model": model.state_dict()}, path)

        fleet.init(strategy=fleet.DistributedStrategy(
            hybrid_configs={"dp_degree": 4, "mp_degree": 2}))
        sh = shardings_for_model(model)
        state = load_sharded(path, shardings={"model": sh})
        w = state["model"]["0.weight"]
        assert tuple(w.shape) == (16, 64)
        import jax
        arr = w.data
        # placed on all 8 devices, sharded over mp on dim 1
        assert len(arr.sharding.device_set) == 8
        np.testing.assert_allclose(np.asarray(arr),
                                   np.asarray(model[0].weight.data))

    def test_zero3_shardings(self, tmp_path):
        from paddle_tpu.distributed.parallel.sharding import \
            ShardingStrategy
        model = _small_model()
        path = str(tmp_path / "ckpt3")
        save_sharded({"model": model.state_dict()}, path)
        fleet.init(strategy=fleet.DistributedStrategy(
            hybrid_configs={"sharding_degree": 8}))
        sh = shardings_for_model(
            model, strategy=ShardingStrategy(stage=3, min_size_to_shard=1))
        state = load_sharded(path, shardings={"model": sh})
        w = state["model"]["0.weight"].data
        # ZeRO-3: weight sharded over the sharding axis
        assert len(w.sharding.device_set) == 8
        spec = w.sharding.spec
        assert "sharding" in str(spec)


class TestManager:
    def test_save_restore_latest_and_retention(self, tmp_path):
        model = _small_model()
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
        mgr = CheckpointManager(str(tmp_path / "mgr"), max_to_keep=2,
                                async_save=False)
        for step in range(4):
            mgr.save(step, {"model": model.state_dict(),
                            "step": step})
        mgr.wait()
        assert mgr.latest_step() == 3
        assert mgr.all_steps() == [2, 3]  # retention pruned 0, 1
        state = mgr.restore()
        assert state["step"] == 3
        for name, t in model.state_dict().items():
            np.testing.assert_allclose(
                np.asarray(state["model"][name].data),
                np.asarray(t.data))
        mgr.close()

    def test_async_save_completes(self, tmp_path):
        model = _small_model()
        mgr = CheckpointManager(str(tmp_path / "amgr"), async_save=True)
        mgr.save(0, {"model": model.state_dict()})
        mgr.wait()
        assert mgr.latest_step() == 0
        state = mgr.restore(0)
        assert "model" in state
        mgr.close()

    def test_resume_after_restart(self, tmp_path):
        """Auto-checkpoint tier: new manager over the same dir resumes
        from the last saved epoch."""
        d = str(tmp_path / "resume")
        model = _small_model()
        mgr = CheckpointManager(d, async_save=False)
        mgr.save(5, {"model": model.state_dict(), "epoch": 5})
        mgr.close()

        mgr2 = CheckpointManager(d, async_save=False)
        assert mgr2.latest_step() == 5
        state = mgr2.restore()
        assert state["epoch"] == 5
        mgr2.close()

    def test_training_resume_equivalence(self, tmp_path):
        """Train 2 steps, checkpoint, train 2 more; vs restore at 2 and
        train the same 2 — parameters must match (the elastic resume
        guarantee)."""
        def make():
            paddle.seed(7)
            model = nn.Sequential(nn.Linear(8, 8), nn.ReLU(),
                                  nn.Linear(8, 1))
            opt = optimizer.AdamW(learning_rate=0.01,
                                  parameters=model.parameters())
            step = paddle.jit.TrainStep(
                model, opt, lambda p, t: ((p - t) ** 2).mean())
            return model, opt, step

        rng = np.random.RandomState(0)
        xs = [rng.standard_normal((8, 8)).astype(np.float32)
              for _ in range(4)]
        ys = [rng.standard_normal((8, 1)).astype(np.float32)
              for _ in range(4)]

        model, opt, step = make()
        for i in range(2):
            step(paddle.to_tensor(xs[i]), paddle.to_tensor(ys[i]))
        d = str(tmp_path / "train")
        mgr = CheckpointManager(d, async_save=False)
        mgr.save(2, {"model": model.state_dict(),
                     "opt": opt.state_dict()})
        mgr.close()
        for i in range(2, 4):
            step(paddle.to_tensor(xs[i]), paddle.to_tensor(ys[i]))
        want = {n: np.asarray(t.data)
                for n, t in model.state_dict().items()}

        model2, opt2, step2 = make()
        mgr2 = CheckpointManager(d, async_save=False)
        state = mgr2.restore()
        mgr2.close()
        model2.set_state_dict(state["model"])
        opt2.set_state_dict(state["opt"])
        for i in range(2, 4):
            step2(paddle.to_tensor(xs[i]), paddle.to_tensor(ys[i]))
        got = {n: np.asarray(t.data)
               for n, t in model2.state_dict().items()}
        for name in want:
            np.testing.assert_allclose(got[name], want[name], atol=1e-6,
                                       err_msg=name)


class TestCorruptionFallback:
    """Commit markers + restore fallback (resilience layer)."""

    def _mgr(self, tmp_path, name="cf"):
        return CheckpointManager(str(tmp_path / name), async_save=False)

    @pytest.mark.chaos
    def test_truncated_latest_restores_previous_and_counts(self, tmp_path):
        from paddle_tpu.profiler import metrics
        from paddle_tpu.utils import fault_injection as fi
        mgr = self._mgr(tmp_path)
        a = np.arange(16.0, dtype=np.float32)
        mgr.save(0, {"w": a})
        mgr.save(1, {"w": a * 2})
        fi.truncate_checkpoint(mgr.directory)  # newest step (1)

        was = metrics.is_enabled()
        metrics.enable()
        try:
            before = metrics.snapshot().get("resilience.ckpt.fallback")
            before = int(before["value"]) if before else 0
            state = mgr.restore()  # latest -> corrupt -> previous
            after = int(metrics.snapshot()
                        ["resilience.ckpt.fallback"]["value"])
        finally:
            if not was:
                metrics.disable()
        np.testing.assert_allclose(np.asarray(state["w"].data), a)
        assert mgr.last_restored_step == 0
        assert after == before + 1
        mgr.close()

    def test_commit_marker_written_and_validated(self, tmp_path):
        import json
        import os
        from paddle_tpu.distributed.checkpoint import COMMIT_MARKER
        mgr = self._mgr(tmp_path)
        mgr.save(0, {"w": np.zeros((4, 2), np.float32)})
        marker = os.path.join(mgr.directory, "0", COMMIT_MARKER)
        assert os.path.exists(marker)
        with open(marker) as f:
            rec = json.load(f)
        assert rec["leaves"]["w"]["shape"] == [4, 2]
        assert mgr.validate(0)
        # a lying marker (wrong shape) fails validation
        rec["leaves"]["w"]["shape"] = [999]
        with open(marker, "w") as f:
            json.dump(rec, f)
        assert not mgr.validate(0)
        mgr.close()

    def test_async_save_marker_flushes_on_wait(self, tmp_path):
        import os
        from paddle_tpu.distributed.checkpoint import COMMIT_MARKER
        mgr = CheckpointManager(str(tmp_path / "as"), async_save=True)
        mgr.save(0, {"w": np.ones(8, np.float32)})
        mgr.wait()
        assert os.path.exists(
            os.path.join(mgr.directory, "0", COMMIT_MARKER))
        mgr.close()

    @pytest.mark.chaos
    def test_all_steps_corrupt_raises(self, tmp_path):
        from paddle_tpu.distributed.checkpoint import CheckpointCorruption
        from paddle_tpu.utils import fault_injection as fi
        mgr = self._mgr(tmp_path)
        mgr.save(0, {"w": np.ones(64, np.float32)})
        mgr.save(1, {"w": np.ones(64, np.float32)})
        fi.truncate_checkpoint(mgr.directory, step=0)
        fi.truncate_checkpoint(mgr.directory, step=1)
        with pytest.raises(CheckpointCorruption):
            mgr.restore()
        mgr.close()

    def test_forced_resave_of_existing_step_is_success(self, tmp_path):
        # emergency save racing the periodic save of the same step: the
        # state is already on disk — success, not an error to swallow
        mgr = self._mgr(tmp_path, "dup")
        a = np.ones(8, np.float32)
        assert mgr.save(0, {"w": a}) is True
        assert mgr.save(0, {"w": a}, force=True) is True
        # unforced duplicate: skipped by the interval policy, no error
        assert mgr.save(0, {"w": a}) is False
        state = mgr.restore()
        np.testing.assert_allclose(np.asarray(state["w"].data), a)
        mgr.close()
