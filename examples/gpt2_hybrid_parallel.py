"""GPT-2 hybrid parallelism on a device mesh: Fleet strategy config ->
named mesh axes -> ONE compiled SPMD step (XLA inserts + overlaps the
collectives). The same script runs on real chips or on a virtual
8-device CPU mesh (no hardware needed) — sharding correctness does not
depend on which.

Usage:
  python examples/gpt2_hybrid_parallel.py --smoke      # 8 virtual CPUs
  python examples/gpt2_hybrid_parallel.py              # real devices
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="force a virtual 8-device CPU mesh")
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--mp", type=int, default=2)
    ap.add_argument("--sharding", type=int, default=2)
    args = ap.parse_args()

    if args.smoke:  # must happen before jax initializes any backend
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu import distributed as dist
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models.gpt import gpt

    import jax
    ndev = len(jax.devices())
    need = args.dp * args.mp * args.sharding
    if ndev < need:
        sys.exit(f"need {need} devices, have {ndev} — run with --smoke")

    hcg = fleet.init(strategy=fleet.DistributedStrategy(hybrid_configs={
        "dp_degree": args.dp, "mp_degree": args.mp,
        "sharding_degree": args.sharding}))
    print("mesh:", dict(hcg.mesh.shape))

    paddle.seed(0)
    batch, seq = 8, 128
    model = gpt("test-tiny" if args.smoke else "gpt2-small",
                max_position_embeddings=seq, fused_lm_loss=True)
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=model.parameters())
    step = fleet.DistributedTrainStep(
        model, opt, lambda out, labels: model.loss(out, labels))

    rng = np.random.RandomState(0)
    ids = rng.randint(0, model.cfg.vocab_size, (batch, seq)).astype(np.int32)
    x = paddle.to_tensor(ids)
    y = paddle.to_tensor(ids.astype(np.int64))
    losses = [float(step(x, y)) for _ in range(4)]
    print("losses:", [round(v, 4) for v in losses])
    assert losses[-1] < losses[0]
    dist.set_hybrid_communicate_group(None)
    print("hybrid SPMD step ok")


if __name__ == "__main__":
    main()
