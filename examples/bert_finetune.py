"""BERT/ERNIE fine-tuning for sequence classification through the
high-level paddle.Model (hapi) API: prepare / fit / evaluate, with
Accuracy metric and a checkpoint callback.

Usage: python examples/bert_finetune.py [--smoke]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import dataclasses

import numpy as np


def synthetic_pairs(n, vocab, seq):
    """Synthetic 2-class task: class 1 sequences are drawn from the top
    half of the vocab, class 0 from the bottom half."""
    rng = np.random.RandomState(0)
    y = rng.randint(0, 2, n)
    lo = rng.randint(1, vocab // 2, (n, seq))
    hi = rng.randint(vocab // 2, vocab, (n, seq))
    x = np.where(y[:, None] == 1, hi, lo).astype(np.int32)
    return x, y.astype(np.int64)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    if args.smoke:  # force CPU before any jax backend init (hermetic)
        import jax
        jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.hapi.model import Model
    from paddle_tpu.io import DataLoader, TensorDataset
    from paddle_tpu.metric import Accuracy
    from paddle_tpu.models.ernie import (CONFIGS,
                                         ErnieForSequenceClassification)
    name, n, seq, epochs = ("test-tiny", 256, 16, 3) if args.smoke \
        else ("ernie-3.0-medium", 2048, 128, 2)

    paddle.seed(0)
    cfg = dataclasses.replace(CONFIGS[name])
    net = ErnieForSequenceClassification(cfg, num_classes=2)
    x, y = synthetic_pairs(n, cfg.vocab_size, seq)
    train = DataLoader(TensorDataset([x[: n // 2], y[: n // 2]]),
                       batch_size=16, shuffle=True)
    val = DataLoader(TensorDataset([x[n // 2:], y[n // 2:]]),
                     batch_size=16)

    model = Model(net)
    model.prepare(
        optimizer=optimizer.AdamW(learning_rate=1e-3,
                                  parameters=net.parameters(),
                                  weight_decay=0.01),
        loss=nn.functional.cross_entropy,
        metrics=Accuracy())
    model.fit(train, epochs=epochs, verbose=1)
    result = model.evaluate(val, verbose=0)
    print("eval:", result)
    acc = result.get("acc", result.get("Accuracy", 0.0))
    assert acc > 0.7, f"expected the separable task to be learned: {result}"
    print("fine-tune ok")


if __name__ == "__main__":
    main()
