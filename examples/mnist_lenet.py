"""MNIST LeNet — the minimum end-to-end slice (BASELINE config #1).

Shows the two training styles side by side:
  1. eager dygraph: forward / loss.backward() / opt.step()
  2. paddle.jit.TrainStep: the whole step as ONE compiled XLA program

Usage: python examples/mnist_lenet.py [--smoke]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def lenet(num_classes=10):
    from paddle_tpu import nn
    return nn.Sequential(
        nn.Conv2D(1, 6, 3, padding=1), nn.ReLU(), nn.MaxPool2D(2, 2),
        nn.Conv2D(6, 16, 5), nn.ReLU(), nn.MaxPool2D(2, 2),
        nn.Flatten(),
        nn.Linear(400, 120), nn.ReLU(),
        nn.Linear(120, 84), nn.ReLU(),
        nn.Linear(84, num_classes))


def synthetic_mnist(n):
    """Separable synthetic digits (class-dependent blob position) so the
    example converges without downloading MNIST."""
    rng = np.random.RandomState(42)
    labels = rng.randint(0, 10, n)
    imgs = rng.randn(n, 1, 28, 28).astype(np.float32) * 0.1
    for i, lab in enumerate(labels):
        imgs[i, 0, 2 + 2 * (lab // 5): 10 + 2 * (lab // 5),
             2 + 2 * (lab % 5): 10 + 2 * (lab % 5)] += 1.0
    return imgs, labels.astype(np.int64)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="tiny/CPU-fast run")
    args = ap.parse_args()
    if args.smoke:  # force CPU before any jax backend init (hermetic)
        import jax
        jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.io import DataLoader, TensorDataset
    from paddle_tpu.nn import functional as F
    n, epochs = (128, 2) if args.smoke else (4096, 3)

    paddle.seed(0)
    imgs, labels = synthetic_mnist(n)
    loader = DataLoader(TensorDataset([imgs, labels]), batch_size=32,
                        shuffle=True)

    # ---- style 1: eager dygraph loop
    model = lenet()
    opt = optimizer.Adam(learning_rate=1e-3, parameters=model.parameters())
    first = last = None
    for _ in range(epochs):
        for x, y in loader:
            loss = F.cross_entropy(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            first = float(loss) if first is None else first
            last = float(loss)
    print(f"eager:     loss {first:.3f} -> {last:.3f}")
    assert last < first

    # ---- style 2: one compiled train step (the performance path)
    paddle.seed(0)
    model = lenet()
    opt = optimizer.Adam(learning_rate=1e-3, parameters=model.parameters())
    step = paddle.jit.TrainStep(model, opt, F.cross_entropy)
    first = last = None
    for _ in range(epochs):
        for x, y in loader:
            loss = step(x, y)
            first = float(loss) if first is None else first
            last = float(loss)
    print(f"TrainStep: loss {first:.3f} -> {last:.3f}")
    assert last < first

    # checkpoint round-trip
    paddle.save(model.state_dict(), "/tmp/lenet.pdparams")
    model2 = lenet()
    model2.set_state_dict(paddle.load("/tmp/lenet.pdparams"))
    x, y = next(iter(loader))
    a, b = float(F.cross_entropy(model(x), y)), \
        float(F.cross_entropy(model2(x), y))
    assert abs(a - b) < 1e-5
    print("checkpoint round-trip ok")


if __name__ == "__main__":
    main()
