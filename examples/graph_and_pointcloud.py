"""Graph learning + sparse 3-D point clouds — the paddle.geometric and
paddle.sparse.nn surfaces end to end.

Two mini-workloads:

1. A GraphSAGE-style node classifier on a synthetic citation graph:
   `sample_neighbors` (CSC sampling) -> `reindex_graph` -> two rounds
   of `send_u_recv` mean aggregation -> linear head, trained with the
   eager tape.
2. A submanifold sparse 3-D CNN over synthetic point-cloud voxels:
   SubmConv3D -> BatchNorm -> ReLU -> Conv3D(stride 2) -> MaxPool3D ->
   global pool -> classify occupancy class.

Run: python examples/graph_and_pointcloud.py [--smoke]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import geometric, nn, optimizer, sparse


def run_gnn(smoke: bool) -> float:
    """2-hop sampled-neighborhood mean-aggregation classifier."""
    paddle.seed(0)
    rs = np.random.RandomState(0)
    n_nodes, feat, n_cls = (60, 16, 3) if smoke else (600, 64, 5)
    # synthetic graph in CSC: each node cites ~5 earlier nodes; label
    # follows the majority community of its neighborhood
    comm = rs.randint(0, n_cls, n_nodes)
    rows, colptr = [], [0]
    for v in range(n_nodes):
        cands = np.where(comm == comm[v])[0]
        nbrs = rs.choice(cands, min(5, len(cands)), replace=False)
        rows.extend(nbrs)
        colptr.append(len(rows))
    row = paddle.to_tensor(np.asarray(rows, np.int64))
    cp = paddle.to_tensor(np.asarray(colptr, np.int64))
    feats = rs.standard_normal((n_nodes, feat)).astype(np.float32)
    feats[:, :n_cls] += 2.0 * np.eye(n_cls)[comm]  # separable signal

    w1 = nn.Linear(feat, 32)
    head = nn.Linear(32, n_cls)
    opt = optimizer.Adam(learning_rate=5e-3,
                         parameters=w1.parameters() + head.parameters())
    import paddle_tpu.nn.functional as F

    losses = []
    for step in range(10 if smoke else 60):
        batch_nodes = rs.choice(n_nodes, 16, replace=False).astype(
            np.int64)
        nb, ct = geometric.sample_neighbors(
            paddle.to_tensor(row), cp, paddle.to_tensor(batch_nodes),
            sample_size=3)
        src, dst, out_nodes = geometric.reindex_graph(
            paddle.to_tensor(batch_nodes), nb, ct)
        h = paddle.to_tensor(feats[out_nodes.numpy()])
        h = F.relu(w1(h))
        agg = geometric.send_u_recv(h, src, dst, reduce_op="mean")
        logits = head(agg[: len(batch_nodes)])
        loss = F.cross_entropy(
            logits, paddle.to_tensor(comm[batch_nodes].astype(np.int64)))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    print(f"[gnn] loss {losses[0]:.3f} -> {losses[-1]:.3f}")
    assert losses[-1] < losses[0], "GNN did not learn"
    return losses[-1]


def run_pointcloud(smoke: bool) -> float:
    """Sparse 3-D CNN over voxelized point clouds (eager tape)."""
    paddle.seed(1)
    rs = np.random.RandomState(1)
    grid, n_pts = (8, 24) if smoke else (16, 120)

    def make_cloud(cls):
        # class 0: axis-aligned plane; class 1: diagonal line cluster
        if cls == 0:
            d = rs.randint(grid)
            pts = np.stack([np.full(n_pts, d), rs.randint(0, grid, n_pts),
                            rs.randint(0, grid, n_pts)], 1)
        else:
            t = rs.randint(0, grid, n_pts)
            pts = np.stack([t, t, (t + rs.randint(0, 2, n_pts)) % grid], 1)
        return pts

    convs = [sparse.nn.SubmConv3D(4, 16, 3, padding=1),
             sparse.nn.BatchNorm(16),
             sparse.nn.ReLU(),
             sparse.nn.Conv3D(16, 32, 2, stride=2),
             sparse.nn.MaxPool3D(2, 2)]
    head = nn.Linear(32, 2)
    params = [p for c in convs for p in getattr(c, "parameters",
                                                lambda: [])()]
    opt = optimizer.Adam(learning_rate=1e-2,
                         parameters=params + head.parameters())
    import paddle_tpu.nn.functional as F

    losses = []
    for step in range(8 if smoke else 40):
        labels, mats = [], []
        for b in range(4):
            cls = rs.randint(2)
            labels.append(cls)
            pts = make_cloud(cls)
            coords = np.concatenate(
                [np.full((len(pts), 1), b), pts], 1).astype(np.int32)
            coords = np.unique(coords, axis=0)
            mats.append(coords)
        allc = np.concatenate(mats, 0)
        vals = np.concatenate(
            [allc[:, 1:].astype(np.float32) / grid,
             np.ones((len(allc), 1), np.float32)], 1)
        x = sparse.sparse_coo_tensor(
            allc.T, vals, shape=[4, grid, grid, grid, 4])
        h = x
        for layer in convs:
            h = layer(h)
        # global mean pool per batch element over active sites
        dense = h.to_dense()  # [4, g', g', g', 32]
        pooled = dense.reshape([4, -1, 32]).mean(axis=1)
        logits = head(pooled)
        loss = F.cross_entropy(
            logits, paddle.to_tensor(np.asarray(labels, np.int64)))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    print(f"[pointcloud] loss {losses[0]:.3f} -> {losses[-1]:.3f}")
    assert losses[-1] < losses[0], "sparse CNN did not learn"
    return losses[-1]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU-fast configuration")
    args = ap.parse_args()
    if args.smoke:
        import jax
        jax.config.update("jax_platforms", "cpu")
    run_gnn(args.smoke)
    run_pointcloud(args.smoke)
    print("graph_and_pointcloud: OK")


if __name__ == "__main__":
    main()
