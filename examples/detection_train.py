"""Object-detection training — the vision/detection path (BASELINE
config #5 family): a CSPResNet backbone with YOLOv3-style multi-scale
heads trained with `yolo_loss` on synthetic boxes, then post-processed
with `matrix_nms`. Exercises the detection op set end-to-end
(reference analog: the PP-YOLOE/YOLOv3 training pipelines).

Usage: python examples/detection_train.py [--smoke]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

ANCHORS9 = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45,
            59, 119, 116, 90, 156, 198, 373, 326]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    # always CPU: this example demonstrates the detection op set and
    # the eager tape (per-op dispatch), not device throughput — eager
    # round-trips every op, which is exactly what the jitted TrainStep
    # examples exist to avoid on the TPU
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.models.ppyoloe import CSPResNet
    from paddle_tpu.vision.ops import yolo_loss, matrix_nms

    steps, hw = (6, 64) if args.smoke else (12, 96)
    paddle.seed(0)
    num_classes = 4
    mask_num, per_scale = 3, 5 + num_classes
    backbone = CSPResNet(widths=(16, 32, 64, 128))
    heads = [nn.Conv2D(c, mask_num * per_scale, 1)
             for c in (32, 64, 128)]
    params = backbone.parameters()
    for h in heads:
        params += h.parameters()
    opt = paddle.optimizer.Adam(learning_rate=2e-3, parameters=params)

    rng = np.random.RandomState(0)
    img = paddle.to_tensor(rng.randn(2, 3, hw, hw).astype(np.float32))
    gt = paddle.to_tensor(np.asarray(
        [[[0.3, 0.4, 0.4, 0.5], [0.7, 0.6, 0.2, 0.25]],
         [[0.5, 0.5, 0.6, 0.6], [0.0, 0.0, 0.0, 0.0]]], np.float32))
    lab = paddle.to_tensor(
        rng.randint(0, num_classes, (2, 2)).astype(np.int32))
    masks = [[6, 7, 8], [3, 4, 5], [0, 1, 2]]
    downs = [32, 16, 8]

    first = last = None
    for step_no in range(steps):
        feats = backbone(img)[-3:]          # strides 8/16/32 pyramid
        total = None
        for feat, m, d, head in zip(feats[::-1], masks, downs,
                                    heads[::-1]):
            loss = yolo_loss(head(feat), gt, lab, ANCHORS9, m,
                             num_classes, 0.7, d).sum()
            total = loss if total is None else total + loss
        total.backward()
        opt.step()
        opt.clear_grad()
        first = first if first is not None else float(total)
        last = float(total)
    print(f"detection loss {first:.2f} -> {last:.2f} over {steps} steps")
    assert last < first * 0.9, (first, last)

    # post-processing path: score some synthetic boxes through matrix_nms
    boxes = paddle.to_tensor(np.asarray(
        [[[10, 10, 50, 50], [12, 12, 52, 52], [100, 100, 150, 150]]],
        np.float32))
    scores = paddle.to_tensor(np.asarray(
        [[[0.9, 0.85, 0.7], [0.1, 0.2, 0.6]]], np.float32))
    out, index, rois_num = matrix_nms(
        boxes, scores, score_threshold=0.3, post_threshold=0.0,
        nms_top_k=10, keep_top_k=5, return_index=True,
        return_rois_num=True)
    print("matrix_nms kept", int(np.asarray(rois_num.numpy())[0]),
          "boxes")
    print("detection ok")


if __name__ == "__main__":
    main()
