"""ResNet-50 training step — the conv/vision path (BASELINE config #2:
2082 img/s at the memory roofline on one v5e). NHWC trunk, bf16 with
fp32-master Momentum + L2 weight decay, space-to-depth stem.

Usage: python examples/resnet_train.py [--smoke] [--batch 128]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args()

    if args.smoke:  # force CPU before any jax backend init (hermetic)
        import jax
        jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.nn import functional as F

    import jax
    on_tpu = jax.devices()[0].platform == "tpu"
    if args.smoke:
        from paddle_tpu.models.resnet import resnet18
        batch, hw, steps = 4, 32, 2
        model = resnet18(num_classes=10)
    else:
        from paddle_tpu.models.resnet import resnet50
        batch, hw, steps = args.batch, 224, args.steps
        model = resnet50(data_format="NHWC", stem_space_to_depth=True)
    paddle.seed(0)
    if on_tpu and not args.smoke:
        model.bfloat16()

    opt = optimizer.Momentum(
        learning_rate=0.1, momentum=0.9,
        parameters=model.parameters(),
        weight_decay=1e-4, multi_precision=on_tpu)
    step = paddle.jit.TrainStep(
        model, opt,
        lambda logits, lab: F.cross_entropy(logits.astype("float32"), lab))

    rng = np.random.RandomState(0)
    imgs = rng.randn(batch, 3, hw, hw).astype(np.float32)
    labels = rng.randint(0, 10 if args.smoke else 1000,
                         (batch,)).astype(np.int64)
    x = paddle.to_tensor(imgs)
    if on_tpu and not args.smoke:
        x = x.astype("bfloat16")  # bf16 model wants bf16 activations
    y = paddle.to_tensor(labels)

    loss = step(x, y)
    print(f"step 0 loss {float(loss):.3f} (compiled)")
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(x, y)
    final = float(loss)
    dt = time.perf_counter() - t0
    print(f"loss {final:.3f} | {batch * steps / dt:,.0f} images/sec "
          f"({dt / steps * 1e3:.1f} ms/step)")


if __name__ == "__main__":
    main()
