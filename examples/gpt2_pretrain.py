"""GPT-2 pretraining step — the flagship single-chip configuration
(BASELINE.md: 121.5k tokens/sec/chip, MFU 0.531 on one v5e).

bf16 weights + fp32 masters, flash attention (engages at seq >= 512),
fused LM-head+CE loss (save-logits or chunked-remat by HBM budget),
donated TrainStep.

Usage: python examples/gpt2_pretrain.py [--smoke] [--batch 16] [--seq 1024]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args()

    if args.smoke:  # force CPU before any jax backend init (hermetic)
        import jax
        jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.models.gpt import gpt

    import jax
    on_tpu = jax.devices()[0].platform == "tpu"
    if args.smoke:
        name, batch, seq, steps = "test-tiny", 2, 64, 3
    else:
        name, batch, seq, steps = "gpt2-small", args.batch, args.seq, \
            args.steps

    paddle.seed(0)
    model = gpt(name, max_position_embeddings=seq, fused_lm_loss=True,
                lm_loss_chunk=seq)
    if on_tpu:
        model.bfloat16()
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=model.parameters(),
                          weight_decay=0.01, multi_precision=on_tpu)
    step = paddle.jit.TrainStep(
        model, opt, lambda out, labels: model.loss(out, labels))

    rng = np.random.RandomState(0)
    ids = rng.randint(0, model.cfg.vocab_size, (batch, seq)).astype(np.int32)
    x = paddle.to_tensor(ids)
    y = paddle.to_tensor(ids.astype(np.int64))

    loss = step(x, y)           # compile + warmup
    print(f"step 0 loss {float(loss):.3f} (compiled)")
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(x, y)
    final = float(loss)         # host fence
    dt = time.perf_counter() - t0
    tok_s = batch * seq * steps / dt
    print(f"loss {final:.3f} | {tok_s:,.0f} tokens/sec "
          f"({dt / steps * 1e3:.1f} ms/step)")


if __name__ == "__main__":
    main()
