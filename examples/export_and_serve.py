"""Deployment path: train a small model, export it three ways, serve it.

  1. paddle.jit.save         -> StableHLO artifact + params
  2. inference.Config/Predictor -> AOT-cached serving (fp32 / bf16 /
     int8 MXU compute), ZeroCopy handles, clone()
  3. paddle.onnx.export      -> real ONNX protobuf, executed by the
     in-repo numpy evaluator to prove the artifact

Usage: python examples/export_and_serve.py [--smoke]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import tempfile

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    if args.smoke:  # force CPU before any jax backend init (hermetic)
        import jax
        jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.inference import Config, PrecisionType, create_predictor
    hidden = 16 if args.smoke else 256

    paddle.seed(0)
    model = nn.Sequential(
        nn.Linear(8, hidden), nn.ReLU(),
        nn.Linear(hidden, hidden), nn.ReLU(),
        nn.Linear(hidden, 4))
    model.eval()
    x = paddle.randn([2, 8])
    ref = model(x).numpy()
    workdir = tempfile.mkdtemp(prefix="serve_demo_")

    # 1. StableHLO artifact (the save_inference_model analog)
    path = os.path.join(workdir, "model")
    paddle.jit.save(model, path, input_spec=[x])
    print("saved:", sorted(os.listdir(workdir)))

    # 2. predictor from the artifact — fp32, then bf16, then clone
    pred = create_predictor(Config(path))
    out = pred.run([x.numpy()])[0]
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
    print("fp32 serving ok")

    cfg16 = Config().from_layer(model, input_spec=[x])
    cfg16.enable_tpu(precision=PrecisionType.Bfloat16)
    out16 = create_predictor(cfg16).run([x.numpy()])[0]
    np.testing.assert_allclose(out16.astype(np.float32), ref,
                               rtol=0.1, atol=0.1)
    print("bf16 serving ok")

    clone = pred.clone()  # shares the compiled program, fresh feeds
    np.testing.assert_allclose(clone.run([x.numpy()])[0], ref,
                               rtol=1e-5, atol=1e-5)
    print("clone ok")

    # 3. ONNX export, proven by executing the artifact
    onnx_path = paddle.onnx.export(
        model, os.path.join(workdir, "model_onnx"),
        input_spec=[x], format="onnx")
    from paddle_tpu.onnx_eval import run_onnx
    got = run_onnx(onnx_path, {"input": x.numpy()})[0]
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
    print("onnx export + numpy-evaluator parity ok:",
          os.path.getsize(onnx_path), "bytes")


if __name__ == "__main__":
    main()
