"""Flagship benchmark: GPT pretraining step throughput + MFU on the local
chip. Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
vs_baseline = achieved MFU / 0.40 (the north-star ERNIE-3.0 target from
BASELINE.md; >1.0 beats the target)."""
from __future__ import annotations

import json
import time

import numpy as np

PEAKS_BF16 = {  # dense bf16 TFLOP/s per chip
    "TPU v5 lite": 197e12, "TPU v5e": 197e12, "TPU v4": 275e12,
    "TPU v6 lite": 918e12, "TPU v6e": 918e12, "TPU v5p": 459e12,
    "cpu": 1e12,  # nominal, so CPU smoke runs produce a number
}


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "") or ""
    for name, val in PEAKS_BF16.items():
        if kind.lower().startswith(name.lower()) or name.lower() in kind.lower():
            return val
    return 197e12 if device.platform == "tpu" else 1e12


def main():
    import os
    import jax
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_cache")
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.models.gpt import gpt

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    # sized to fit one v5e chip (16GB HBM) in bf16 with fp32 masters
    if on_tpu:
        name, batch, seq = "gpt2-small", 16, 1024
    else:  # CPU smoke config
        name, batch, seq = "test-tiny", 2, 64

    paddle.seed(0)
    model = gpt(name, max_position_embeddings=seq)
    model.bfloat16() if on_tpu else None
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=model.parameters(),
                          multi_precision=on_tpu)
    step = paddle.jit.TrainStep(
        model, opt, lambda logits, labels: model.loss(logits, labels))

    rng = np.random.RandomState(0)
    ids = rng.randint(0, model.cfg.vocab_size, (batch, seq)).astype(np.int32)
    x = paddle.to_tensor(ids)
    y = paddle.to_tensor(ids.astype(np.int64))

    # warmup (compile). Sync via host transfer of the loss: on the axon
    # remote tunnel block_until_ready can acknowledge before execution
    # completes, and donated param buffers alias inputs — float() is the
    # only reliable fence.
    loss = step(x, y)
    float(loss)

    iters = 20 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(x, y)
    float(loss)
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * iters / dt
    flops_per_token = model.flops_per_token(seq)
    achieved = tokens_per_sec * flops_per_token
    mfu = achieved / peak_flops(dev)

    print(json.dumps({
        "metric": f"{name} train tokens/sec/chip (b{batch} s{seq}, "
                  f"MFU={mfu:.3f}, loss={float(loss):.3f}, "
                  f"device={dev.device_kind})",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(mfu / 0.40, 4),
    }))


if __name__ == "__main__":
    main()
