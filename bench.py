"""Benchmarks for the BASELINE.md progression configs.

Default (`python bench.py`): the flagship GPT-2 small pretraining step —
prints ONE JSON line {"metric", "value", "unit", "vs_baseline"} with
vs_baseline = achieved MFU / 0.40 (the ERNIE-3.0 north-star target).

Other configs (BASELINE configs #2-#5; `python bench.py <name>`):
  resnet50      ResNet-50 train step, images/sec (conv/layout path)
  ernie-base    ERNIE-3.0-Base masked-LM step (sharding-family model)
  bert-large    BERT-large masked-LM step
  gpt6.7b-layer one GPT-3-6.7B transformer block (single-chip microbench
                of the hybrid config; full model needs the 8-way mesh —
                see __graft_entry__.dryrun_multichip)
  vit-l         ViT-L/16 train step
  warmstart     relaunch-to-first-token / relaunch-to-first-step, cold
                vs warm through the jit.compile_cache executable store
                (ISSUE-9 gate: warm >= 5x faster on test-tiny)
  all           every config; one JSON line each on stderr, flagship on
                stdout last

MFU for the non-GPT configs uses XLA's own cost model for the compiled
step (TrainStep.cost_analysis) instead of hand formulas.

Shape overrides reproduce the BASELINE.md sweep rows on the flagship,
e.g. the long-context sweep: BENCH_SEQ=4096 BENCH_BATCH=4,
BENCH_SEQ=8192 BENCH_BATCH=2, BENCH_SEQ=16384 BENCH_BATCH=1.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

PEAKS_BF16 = {  # dense bf16 TFLOP/s per chip
    "TPU v5 lite": 197e12, "TPU v5e": 197e12, "TPU v4": 275e12,
    "TPU v6 lite": 918e12, "TPU v6e": 918e12, "TPU v5p": 459e12,
    "cpu": 1e12,  # nominal, so CPU smoke runs produce a number
}


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "") or ""
    for name, val in PEAKS_BF16.items():
        if kind.lower().startswith(name.lower()) or name.lower() in kind.lower():
            return val
    return 197e12 if device.platform == "tpu" else 1e12


def _setup(configure_cache: bool = True):
    import os
    import jax
    if configure_cache:
        # the shared process-global setup (jit/compile_cache.py owns the
        # jax cache dir — the compile-cache-dir lint forbids touching it
        # directly); warmstart mode skips this so its COLD phase really
        # is cold
        from paddle_tpu.jit import enable_compile_cache
        cache_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
        enable_compile_cache(cache_dir, min_compile_time_secs=1.0)
    dev = jax.devices()[0]
    return dev, dev.platform == "tpu"


def _time_steps(step, x, y, iters, profile_dir=None):
    # warmup (compile). Sync via host transfer of the loss: on the axon
    # remote tunnel block_until_ready can acknowledge before execution
    # completes, and donated param buffers alias inputs — float() is the
    # only reliable fence.
    loss = step(x, y)
    float(loss)
    prof = None
    if profile_dir:
        # BENCH_PROFILE=1: drop ONE Perfetto trace of a few mid-run
        # steps so host/device overlap is visually auditable (host spans
        # + metric counter tracks; open in ui.perfetto.dev). The
        # recording window adds host overhead — the tokens/sec printed
        # from a profiled run is NOT a benchmark number.
        from paddle_tpu import profiler as _profiler
        prof = _profiler.Profiler(
            scheduler=(1, min(1 + 4, iters)),
            on_trace_ready=_profiler.export_chrome_tracing(
                profile_dir, "bench"))
        prof.start()
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(x, y)
        if prof is not None:
            prof.step()
    final = float(loss)
    if prof is not None:
        prof.stop()
        print(f"BENCH_PROFILE: Perfetto trace in {profile_dir}/",
              file=sys.stderr)
    return time.perf_counter() - t0, final


def bench_gpt2(dev, on_tpu):
    import os
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.models.gpt import gpt

    if on_tpu:
        name, batch, seq = "gpt2-small", 16, 1024
    else:  # CPU smoke config
        name, batch, seq = "test-tiny", 2, 64
    # HBM-pressure sweeps (BASELINE.md): override shape/remat/offload
    batch = int(os.environ.get("BENCH_BATCH", batch))
    seq = int(os.environ.get("BENCH_SEQ", seq))
    remat = os.environ.get("BENCH_REMAT", "")  # ""/selective/full
    offload = os.environ.get("BENCH_OFFLOAD", "") == "1"
    # chunked fused LM-head+CE is the default: it never materializes
    # the [B, S, vocab] logits and wins ~10% MFU at s1024, ~16% at
    # s2048 (see BASELINE.md sweeps). BENCH_FUSED=0 opts out.
    fused = os.environ.get("BENCH_FUSED", "1") == "1"
    # fused-loss chunk: when the whole fp32 [B, S, vocab] logits fit in
    # ~4 GB HBM alongside the step, a single un-rematerialized chunk is
    # fastest (b16-s1024: MFU 0.499 -> 0.529 measured r4 — saving the
    # logits beats recomputing the vocab matmul); beyond that, scan
    # chunks of ~8192 logit rows with per-chunk remat (b32 chunk 256,
    # s2048 chunk 512 — the [batch*chunk, vocab] live buffer matters)
    from paddle_tpu.models.gpt import CONFIGS
    base_cfg = CONFIGS[name]
    logit_bytes = batch * (seq - 1) * base_cfg.vocab_size * 4
    chunk = int(os.environ.get("BENCH_CHUNK", 0)) or \
        (seq if logit_bytes <= base_cfg.lm_loss_save_logits_budget
         else max(8192 // batch, 128))

    paddle.seed(0)
    model = gpt(name, max_position_embeddings=seq,
                use_recompute=bool(remat),
                recompute_granularity=remat or "selective",
                fused_lm_loss=fused, lm_loss_chunk=chunk)
    model.bfloat16() if on_tpu else None
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=model.parameters(),
                          multi_precision=on_tpu)
    step = paddle.jit.TrainStep(
        model, opt, lambda logits, labels: model.loss(logits, labels),
        offload_opt_state=offload)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, model.cfg.vocab_size, (batch, seq)).astype(np.int32)
    x = paddle.to_tensor(ids)
    y = paddle.to_tensor(ids.astype(np.int64))

    iters = 20 if on_tpu else 3
    profile_dir = "bench_trace" \
        if os.environ.get("BENCH_PROFILE", "") == "1" else None
    dt, loss = _time_steps(step, x, y, iters, profile_dir=profile_dir)

    tokens_per_sec = batch * seq * iters / dt
    mfu = tokens_per_sec * model.flops_per_token(seq) / peak_flops(dev)
    extra = (f", remat={remat}" if remat else "") + \
        (", offload" if offload else "") + \
        (", fused_loss" if fused else "")
    return {
        "metric": f"{name} train tokens/sec/chip (b{batch} s{seq}, "
                  f"MFU={mfu:.3f}, loss={loss:.3f}{extra}, "
                  f"device={dev.device_kind})",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(mfu / 0.40, 4),
    }


def _mlm_bench(dev, on_tpu, cfg_name, batch, seq, iters=20):
    """ERNIE/BERT masked-LM + sentence-order pretraining step."""
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.models.ernie import ernie

    import os
    fused = os.environ.get("BENCH_FUSED", "1") == "1"
    paddle.seed(0)
    # fused MLM loss: only the (<= max_predictions) masked positions
    # run the vocab projection — the dense [B, S, vocab] logits never
    # materialize (BENCH_FUSED=0 opts out)
    model = ernie(cfg_name if on_tpu else "test-tiny",
                  fused_mlm_loss=fused,
                  max_predictions=max(int(seq * 0.19), 8))
    model.bfloat16() if on_tpu else None
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=model.parameters(),
                          multi_precision=on_tpu)
    step = paddle.jit.TrainStep(
        model, opt, lambda out, labels: model.loss(out, labels))
    rng = np.random.RandomState(0)
    ids = rng.randint(0, model.cfg.vocab_size,
                      (batch, seq)).astype(np.int32)
    x = paddle.to_tensor(ids)
    mlm = ids.astype(np.int64)
    mlm[rng.rand(*mlm.shape) > 0.15] = -100  # only masked positions score
    y = (paddle.to_tensor(mlm),
         paddle.to_tensor(rng.randint(0, 2, (batch,)).astype(np.int64)))
    xla_flops = float(step.cost_analysis(x, y).get("flops", 0.0))
    n = iters if on_tpu else 2
    dt, loss = _time_steps(step, x, y, n)
    tokens_per_sec = batch * seq * n / dt
    mfu = (xla_flops * n / dt) / peak_flops(dev)
    return {
        "metric": f"{cfg_name} train tokens/sec/chip (b{batch} "
                  f"s{seq}, MFU={mfu:.3f}, loss={loss:.3f}, "
                  f"device={dev.device_kind})",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(mfu / 0.40, 4),
    }


def bench_ernie_base(dev, on_tpu):
    b, s = (32, 512) if on_tpu else (2, 32)
    return _mlm_bench(dev, on_tpu, "ernie-3.0-base", b, s)


def bench_bert_large(dev, on_tpu):
    b, s = (16, 512) if on_tpu else (2, 32)
    return _mlm_bench(dev, on_tpu, "bert-large", b, s)


def bench_gpt67_layer(dev, on_tpu):
    """One transformer block of the GPT-3-6.7B config (BASELINE #4's
    building block; the full model runs on the 8-way mesh in
    dryrun_multichip)."""
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.models.gpt import CONFIGS, GPTBlock
    import dataclasses

    cfg = CONFIGS["gpt3-6.7b" if on_tpu else "test-tiny"]
    paddle.seed(0)

    class OneBlock(nn.Layer):
        def __init__(self):
            super().__init__()
            self.block = GPTBlock(cfg)

        def forward(self, x):
            return self.block(x)

    model = OneBlock()
    model.bfloat16() if on_tpu else None
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=model.parameters(),
                          multi_precision=on_tpu)
    loss_fn = lambda out, labels: (out.astype("float32") ** 2).mean()
    step = paddle.jit.TrainStep(model, opt, loss_fn)
    b, s = (8, 2048) if on_tpu else (2, 32)
    rng = np.random.RandomState(0)
    h = rng.randn(b, s, cfg.hidden_size).astype(np.float32)
    x = paddle.to_tensor(h).astype("bfloat16" if on_tpu else "float32")
    y = paddle.zeros([1])
    xla_flops = float(step.cost_analysis(x, y).get("flops", 0.0))
    iters = 30 if on_tpu else 2
    dt, loss = _time_steps(step, x, y, iters)
    tokens_per_sec = b * s * iters / dt
    mfu = (xla_flops * iters / dt) / peak_flops(dev)
    return {
        "metric": f"gpt3-6.7b single-layer train tokens/sec/chip "
                  f"(b{b} s{s}, MFU={mfu:.3f}, "
                  f"device={dev.device_kind})",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(mfu / 0.40, 4),
    }


def bench_resnet50(dev, on_tpu):
    import os
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.models.resnet import resnet50

    layout = os.environ.get("BENCH_LAYOUT", "NHWC")
    s2d = os.environ.get("BENCH_S2D", "1") == "1"
    # fused conv+BN training kernels (kernels/fused_resnet.py) measured
    # SLOWER end-to-end than XLA's own fusion (61.5 -> 103 ms/step, see
    # BASELINE.md r4 negative result): default OFF; BENCH_FUSED_BN=1
    # opts in. NB: MFU from XLA cost analysis is bogus when Pallas
    # custom calls carry the flops.
    fused_bn = os.environ.get("BENCH_FUSED_BN", "0") == "1" and \
        layout == "NHWC"
    paddle.seed(0)
    model = resnet50(num_classes=1000, data_format=layout,
                     stem_space_to_depth=s2d, fused_bn=fused_bn)
    model.bfloat16() if on_tpu else None
    opt = optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                             parameters=model.parameters(),
                             multi_precision=on_tpu)
    ce = nn.CrossEntropyLoss()

    def loss_fn(logits, labels):
        return ce(logits.astype("float32"), labels)

    step = paddle.jit.TrainStep(model, opt, loss_fn)
    b, hw = (128, 224) if on_tpu else (2, 32)
    rng = np.random.RandomState(0)
    img = rng.randn(b, 3, hw, hw).astype(np.float32)
    x = paddle.to_tensor(img).astype("bfloat16" if on_tpu else "float32")
    y = paddle.to_tensor(rng.randint(0, 1000, (b,)).astype(np.int64))
    xla_flops = float(step.cost_analysis(x, y).get("flops", 0.0))
    iters = 20 if on_tpu else 2
    dt, loss = _time_steps(step, x, y, iters)
    imgs_per_sec = b * iters / dt
    mfu = (xla_flops * iters / dt) / peak_flops(dev)
    return {
        "metric": f"resnet50 train images/sec/chip (b{b} {hw}x{hw}, "
                  f"{layout}{', s2d-stem' if s2d else ''}"
                  f"{', fused-bn' if fused_bn else ''}, "
                  f"MFU={mfu:.3f}, loss={loss:.3f}, "
                  f"device={dev.device_kind})",
        "value": round(imgs_per_sec, 1),
        "unit": "images/sec",
        "vs_baseline": round(mfu / 0.40, 4),
    }


def bench_vit_l(dev, on_tpu):
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.models.vit import vit

    paddle.seed(0)
    model = vit("vit-l-16" if on_tpu else "test-tiny")
    model.bfloat16() if on_tpu else None
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=model.parameters(),
                          multi_precision=on_tpu)
    ce = nn.CrossEntropyLoss()

    def loss_fn(logits, labels):
        return ce(logits.astype("float32"), labels)

    step = paddle.jit.TrainStep(model, opt, loss_fn)
    b = 64 if on_tpu else 2
    hw = model.cfg.image_size
    rng = np.random.RandomState(0)
    img = rng.randn(b, 3, hw, hw).astype(np.float32)
    x = paddle.to_tensor(img).astype("bfloat16" if on_tpu else "float32")
    y = paddle.to_tensor(rng.randint(0, model.cfg.num_classes,
                                     (b,)).astype(np.int64))
    xla_flops = float(step.cost_analysis(x, y).get("flops", 0.0))
    iters = 20 if on_tpu else 2
    dt, loss = _time_steps(step, x, y, iters)
    imgs_per_sec = b * iters / dt
    mfu = (xla_flops * iters / dt) / peak_flops(dev)
    return {
        "metric": f"vit-l-16 train images/sec/chip (b{b} {hw}x{hw}, "
                  f"MFU={mfu:.3f}, loss={loss:.3f}, "
                  f"device={dev.device_kind})",
        "value": round(imgs_per_sec, 1),
        "unit": "images/sec",
        "vs_baseline": round(mfu / 0.40, 4),
    }


def bench_moe_block(dev, on_tpu):
    """Single-chip MoE transformer block (EP correctness lives in the
    dryrun/tests; this is the expert-compute perf leg — BASELINE.md
    r3 MoE row). 8 local experts, gshard gate."""
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.models.gpt import CONFIGS, GPTBlock, GPTConfig
    import dataclasses

    base = CONFIGS["gpt2-small" if on_tpu else "test-tiny"]
    cfg = dataclasses.replace(base, moe_num_experts=8,
                              moe_capacity_factor=1.25)
    paddle.seed(0)

    class OneBlock(nn.Layer):
        def __init__(self):
            super().__init__()
            self.block = GPTBlock(cfg)

        def forward(self, x):
            return self.block(x)

    model = OneBlock()
    model.bfloat16() if on_tpu else None
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=model.parameters(),
                          multi_precision=on_tpu)
    from paddle_tpu.distributed.parallel.moe import aux_loss
    loss_fn = lambda out, labels: \
        (out.astype("float32") ** 2).mean() + aux_loss(model)
    step = paddle.jit.TrainStep(model, opt, loss_fn)
    b, s = (16, 1024) if on_tpu else (2, 32)
    rng = np.random.RandomState(0)
    h = rng.randn(b, s, cfg.hidden_size).astype(np.float32)
    x = paddle.to_tensor(h).astype("bfloat16" if on_tpu else "float32")
    y = paddle.zeros([1])
    xla_flops = float(step.cost_analysis(x, y).get("flops", 0.0))
    iters = 30 if on_tpu else 2
    dt, loss = _time_steps(step, x, y, iters)
    tokens_per_sec = b * s * iters / dt
    mfu = (xla_flops * iters / dt) / peak_flops(dev)
    return {
        "metric": f"moe block (8 experts, gshard, h={cfg.hidden_size}) "
                  f"train tokens/sec/chip (b{b} s{s}, MFU={mfu:.3f}, "
                  f"device={dev.device_kind})",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(mfu / 0.40, 4),
    }


def _metric_counter(name):
    """Current value of one registry counter (0 when never recorded) —
    the delta reader behind every PR-10 counters sub-dict."""
    from paddle_tpu.profiler import metrics as _metrics
    snap = _metrics.snapshot().get(name)
    return int(snap["value"]) if snap else 0


def _tree_bytes(tree):
    import jax
    return sum(
        int(np.prod(l.shape, dtype=np.int64)) * np.dtype(l.dtype).itemsize
        for l in jax.tree_util.tree_leaves(tree) if hasattr(l, "shape"))


def _mem_sub_dict(plan, measure_fn, held, pool_bytes):
    """The ISSUE-14 "mem" row: the static planner's predicted peak vs
    a MEASURED peak (live-byte delta around exactly one dispatch of the
    same program, inputs in ``held`` kept referenced) plus the KV pool
    bytes. The plan upper-bounds the resident set, so
    predicted_over_measured >= 1.0 is the healthy regime; the tier-1
    predicted-vs-measured test pins its slack band."""
    import jax
    from paddle_tpu import device
    device.reset_peak_memory_stats()
    m0 = device.memory_allocated()
    out = measure_fn()
    jax.block_until_ready(out)
    measured = _tree_bytes(held) + max(
        0, device.max_memory_allocated() - m0)
    return {
        "predicted_peak_bytes": int(plan.peak_bytes),
        "measured_peak_bytes": int(measured),
        "pool_bytes": int(pool_bytes),
        "predicted_over_measured": round(plan.peak_bytes / measured, 2),
    }


def _bench_spec_rows(model, draft, on_tpu, new_tokens):
    """Speculative-decode comparison rows (ISSUE-11): batch-1 greedy
    decode — the latency-bound regime speculation targets — off vs
    self-speculative (prompt-lookup) vs draft-model, on a prompt with
    the input-grounded repetition prompt-lookup exists for (a repeated
    motif: the summarization/code-edit/RAG shape). Each variant reports
    decode tokens/sec, accept_rate from the gen.spec.* counters, and
    its own post-warmup retrace counters — the PR-10 sub-dict proving
    the timed pass dispatched warm executables only."""
    rng = np.random.RandomState(0)
    motif = rng.randint(0, model.cfg.vocab_size, 16)
    ids = np.tile(motif, 32)[None, :512].astype(np.int32)  # batch 1
    counter = _metric_counter

    def run(label, **kw):
        model.generate(ids, max_new_tokens=new_tokens, **kw)  # warmup
        before = {k: counter(k) for k in
                  ("jit.compile.total", "jit.compile{cause=new_shape}",
                   "gen.spec.proposed", "gen.spec.accepted")}
        t0 = time.perf_counter()
        model.generate(ids, max_new_tokens=new_tokens, **kw)
        dt = time.perf_counter() - t0
        prop = counter("gen.spec.proposed") - before["gen.spec.proposed"]
        acc = counter("gen.spec.accepted") - before["gen.spec.accepted"]
        return {
            "tokens_per_sec": round(new_tokens / dt, 1),
            **({"accept_rate": round(acc / prop, 3)} if prop else {}),
            "counters": {
                "jit.compile.total":
                    counter("jit.compile.total")
                    - before["jit.compile.total"],
                "jit.compile{cause=new_shape}":
                    counter("jit.compile{cause=new_shape}")
                    - before["jit.compile{cause=new_shape}"],
            },
        }

    rows = {"batch": 1, "prompt": "16-token motif x32 (prompt-lookup "
                                  "regime)", "new_tokens": new_tokens}
    rows["off"] = run("off")
    rows["ngram"] = run("ngram", speculative="ngram")
    rows["draft"] = run("draft", speculative="draft", draft_model=draft)
    off = rows["off"]["tokens_per_sec"]
    for v in ("ngram", "draft"):
        rows[v]["speedup_vs_off"] = round(
            rows[v]["tokens_per_sec"] / off, 2)
    return rows


def _bench_precision_rows(model, on_tpu, ids, new_tokens):
    """Per-precision decode rows (ISSUE-13): the same prompt batch
    decoded with the full-width cache, the int8 KV cache (fused
    in-kernel dequant), and the int8-cache + int4-weight serving
    engine (the only surface that owns a weight path). Each row
    carries decode tokens/sec and the PR-10 counters sub-dict proving
    the timed pass dispatched warm programs only."""
    import paddle_tpu as paddle
    from paddle_tpu.inference import Config
    from paddle_tpu.inference.config import PrecisionType
    from paddle_tpu.serving import RequestParams, ServingEngine

    b = ids.shape[0]
    counter = _metric_counter

    def timed(fn, tokens):
        fn()  # warmup (compiles once)
        before = {k: counter(k) for k in
                  ("jit.compile.total", "jit.compile{cause=new_shape}")}
        t0 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t0
        return {
            "tokens_per_sec": round(tokens / dt, 1),
            "counters": {k: counter(k) - before[k] for k in before},
        }

    wide = "bfloat16" if on_tpu else "float32"
    rows = {"batch": b, "new_tokens": new_tokens, "wide_dtype": wide}
    rows[wide] = timed(
        lambda: model.generate(ids, max_new_tokens=new_tokens),
        b * new_tokens)
    rows["int8-kv"] = timed(
        lambda: model.generate(ids, max_new_tokens=new_tokens,
                               kv_cache_dtype="int8"),
        b * new_tokens)

    # int8-kv + int4 weight-only: through the engine (weights pack two
    # nibbles per byte, dequant in-trace; cache int8, dequant in-kernel)
    bucket = ids.shape[1]
    spec = [paddle.to_tensor(np.zeros((b, 64), np.int32))]
    cfg = (Config().from_layer(model, spec)
           .enable_generation(max_new_tokens=new_tokens,
                              prefill_buckets=(bucket,), max_batch=b,
                              kv_cache_dtype="int8")
           .enable_serving(max_queue=2 * b, weight_bits=4))
    cfg.precision = PrecisionType.Int8
    engine = ServingEngine(cfg, poll_every=4)

    def engine_pass():
        hs = [engine.submit(ids[i], RequestParams(
            max_new_tokens=new_tokens)) for i in range(b)]
        while engine.busy:
            engine.step()
        assert all(h.status.value == "completed" for h in hs)

    rows["int8-kv+int4-w"] = timed(engine_pass, b * new_tokens)
    engine.shutdown()
    for label in (wide, "int8-kv", "int8-kv+int4-w"):
        rows[label]["speedup_vs_wide"] = round(
            rows[label]["tokens_per_sec"] / rows[wide]["tokens_per_sec"],
            2)
    return rows


def bench_decode(dev, on_tpu):
    """Serving-trajectory bench: prefill 512 + decode 128 on test-tiny
    GPT (ISSUE-6 decode mode). Reports decode tokens/sec (pipelined
    host loop, no per-token sync) plus p50/p95 per-token latency from a
    second, per-step-synced pass, the ISSUE-11 speculative rows
    (off / self-spec / draft-model at batch 1) as the "spec" sub-dict,
    and the ISSUE-13 per-precision rows (wide / int8-kv /
    int8-kv+int4-w) as the "precision" sub-dict.
    vs_baseline is 1.0 by definition — this row DEFINES the decode
    baseline from this revision on."""
    import os
    import paddle_tpu as paddle
    from paddle_tpu.generation import GenerationConfig, GenerationSession
    from paddle_tpu.generation.api import _round_up
    from paddle_tpu.models.gpt import gpt
    import jax
    import jax.numpy as jnp

    prefill_len, new_tokens = 512, 128
    b = int(os.environ.get("BENCH_DECODE_BATCH", 8 if on_tpu else 2))
    paddle.seed(0)
    model = gpt("test-tiny", max_position_embeddings=1024)
    model.bfloat16() if on_tpu else None
    rng = np.random.RandomState(0)
    ids = rng.randint(0, model.cfg.vocab_size,
                      (b, prefill_len)).astype(np.int32)

    cfg = GenerationConfig()
    cache_len = _round_up(prefill_len + new_tokens)
    sess = GenerationSession(model)
    state = sess.state_values()
    key = jax.random.PRNGKey(0)
    plen = jnp.full((b,), prefill_len, jnp.int32)

    def run(sync_each_step):
        tok, cache, k, fin = sess.prefill(state, jnp.asarray(ids), plen,
                                          key, cfg, cache_len)
        tok.block_until_ready()  # decode timer must NOT include the
        #                          async prefill-512 device time
        times = []
        t0 = time.perf_counter()
        for _ in range(new_tokens - 1):
            s0 = time.perf_counter()
            tok, _, cache, k, fin = sess.decode(state, tok, cache, k,
                                                fin, cfg)
            if sync_each_step:
                tok.block_until_ready()
                times.append(time.perf_counter() - s0)
        tok.block_until_ready()
        return time.perf_counter() - t0, times

    run(False)  # warmup: compiles prefill + decode
    dt, _ = run(False)                          # throughput pass
    _, per_step = run(True)                     # latency pass
    decode_tps = b * (new_tokens - 1) / dt
    p50 = float(np.percentile(per_step, 50) * 1e3)
    p95 = float(np.percentile(per_step, 95) * 1e3)
    paddle.seed(7)
    draft = gpt("test-tiny-draft", max_position_embeddings=1024)
    draft.bfloat16() if on_tpu else None
    spec = _bench_spec_rows(model, draft, on_tpu, new_tokens)
    precision = _bench_precision_rows(model, on_tpu, ids, new_tokens)
    wide = precision["wide_dtype"]

    # ISSUE-14 "mem" sub-dict: the decode program's static MemoryPlan
    # vs one measured dispatch (same donation the backend dispatches)
    from paddle_tpu import analysis
    tok, cache, k2, fin = sess.prefill(state, jnp.asarray(ids), plen,
                                       key, cfg, cache_len)
    tok.block_until_ready()
    margs = (state, tok, cache, k2, fin)
    mem_plan = analysis.plan_memory(
        sess._decode_fn, *margs, cfg, static_argnums=(5,),
        donate=sess._decode_donate, name="bench.decode")
    mem = _mem_sub_dict(mem_plan, lambda: sess.decode(*margs, cfg),
                        margs, _tree_bytes((cache,)))
    return {
        "metric": f"test-tiny decode tokens/sec/chip (b{b} "
                  f"prefill{prefill_len}+decode{new_tokens}, "
                  f"p50={p50:.2f}ms, p95={p95:.2f}ms per token, "
                  f"spec b1 off={spec['off']['tokens_per_sec']} "
                  f"ngram={spec['ngram']['tokens_per_sec']} "
                  f"({spec['ngram']['speedup_vs_off']}x, accept "
                  f"{spec['ngram'].get('accept_rate', 0)}), "
                  f"int8-kv {precision['int8-kv']['speedup_vs_wide']}x "
                  f"vs {wide}, "
                  f"device={dev.device_kind})",
        "value": round(decode_tps, 1),
        "unit": "tokens/sec",
        "vs_baseline": 1.0,
        "spec": spec,
        "precision": precision,
        "mem": mem,
    }


def bench_serve_shared_prefix(dev, on_tpu):
    """`bench.py serve --shared-prefix` (ISSUE-12): the capacity-at-
    equal-HBM gate for the paged KV cache. Poisson arrivals over K
    distinct LONG system prompts x short user suffixes — the traffic
    shape that dominates real fleets — served twice at the SAME cache
    HBM byte budget:

      dense:  max_batch slots x max_len ring rows   (the PR-8 engine)
      paged:  4x the slots over a page pool of the dense cache's exact
              token footprint (shared prefixes are stored once and
              reference-counted; each request's pages cover only ITS
              prompt + budget)

    The row's value is the ratio of peak concurrent in-flight requests
    (paged / dense); the acceptance gate is > 2x, so vs_baseline =
    ratio / 2. prefix_hits > 0 and page conservation at drain are
    asserted, and the PR-10 counters sub-dict rides along to show zero
    post-warmup retraces (`jit.compile{cause=new_shape}` == 0)."""
    import os
    import threading
    import paddle_tpu as paddle
    from paddle_tpu.inference import Config
    from paddle_tpu.models.gpt import gpt
    from paddle_tpu.serving import RequestParams, ServingEngine

    from paddle_tpu.generation.api import _round_up

    n_req = int(os.environ.get("BENCH_SERVE_REQUESTS",
                               96 if on_tpu else 48))
    rate = float(os.environ.get("BENCH_SERVE_RATE", 256.0))  # req/sec
    dense_batch = int(os.environ.get("BENCH_SERVE_BATCH",
                                     8 if on_tpu else 4))
    paged_batch = 4 * dense_batch
    max_new = int(os.environ.get("BENCH_SERVE_NEW_TOKENS", 16))
    page = int(os.environ.get("PADDLE_KV_PAGE_SIZE",
                              128 if on_tpu else 16))
    # system prompts span 6 FULL pages whatever the page size (sharing
    # is page-granular — a sys prompt shorter than one page would never
    # produce a prefix key, and the gate below would be vacuous): 96
    # tokens at the CPU page 16, 768 at the TPU page 128
    sys_len = 6 * page
    bucket = _round_up(sys_len + 32)
    paddle.seed(0)
    model = gpt("test-tiny", max_position_embeddings=1024)
    model.bfloat16() if on_tpu else None
    assert bucket + max_new <= model.cfg.max_position_embeddings

    rng = np.random.RandomState(0)
    n_sys = 4
    sys_prompts = [rng.randint(0, model.cfg.vocab_size, sys_len)
                   .astype(np.int32) for _ in range(n_sys)]
    prompts = [np.concatenate([sys_prompts[i % n_sys],
                               rng.randint(0, model.cfg.vocab_size,
                                           rng.randint(8, 17))
                               .astype(np.int32)])
               for i in range(n_req)]
    budgets = rng.randint(max(4, max_new // 2), max_new + 1, size=n_req)
    gaps = rng.exponential(1.0 / rate, size=n_req)

    def run(paged, kv_dtype=None, slots=None, kv_pages=None):
        spec = [paddle.to_tensor(np.zeros((dense_batch, 64), np.int32))]
        cfg = (Config().from_layer(model, spec)
               .enable_generation(max_new_tokens=max_new,
                                  prefill_buckets=(bucket,),
                                  max_batch=slots if slots else (
                                      paged_batch if paged
                                      else dense_batch),
                                  kv_cache_dtype=kv_dtype))
        if paged:
            # EQUAL cache HBM: the pool holds exactly the dense
            # engine's dense_batch * max_len tokens (plus the reserved
            # null page); 4x the decode slots share it. An int8 run
            # passes its own kv_pages (the same BYTE budget buys ~2x
            # bf16 / ~3.6x fp32 the pages) + a wider slot set.
            max_len = _round_up(bucket + max_new)
            cfg.enable_serving(
                max_queue=n_req, paged=True, kv_page_size=page,
                kv_pages=kv_pages if kv_pages
                else dense_batch * max_len // page + 1)
        else:
            cfg.enable_serving(max_queue=n_req)
        engine = ServingEngine(cfg, poll_every=2)
        handles = []

        def feeder():
            for p, b, g in zip(prompts, budgets, gaps):
                time.sleep(g)
                handles.append(engine.submit(
                    p, RequestParams(max_new_tokens=int(b))))

        peak = 0
        busy_sum = steps = 0
        t0 = time.perf_counter()
        th = threading.Thread(target=feeder, daemon=True)
        th.start()
        while th.is_alive() or engine.busy:
            if engine.busy:
                engine.step()
                n_busy = sum(s is not None for s in engine._slots)
                peak = max(peak, n_busy)
                busy_sum += n_busy
                steps += 1
            else:
                time.sleep(0.0002)
        dt = time.perf_counter() - t0
        th.join()
        assert len(handles) == n_req and \
            all(h.status.value == "completed" for h in handles)
        stats = dict(engine._alloc.stats) if engine._alloc else {}
        if engine._alloc is not None:
            engine.drain()
            engine._alloc.assert_conserved()   # no leaked/double-freed
        return dict(peak=peak, mean_busy=round(busy_sum / max(1, steps), 2),
                    qps=round(n_req / dt, 1), **stats)

    dense = run(paged=False)
    paged_r = run(paged=True)
    assert paged_r["prefix_hits"] > 0, "shared-prefix traffic never hit"
    ratio = paged_r["peak"] / dense["peak"]
    max_len = _round_up(bucket + max_new)

    # ISSUE-13 equal-HBM int8 row: the SAME cache byte budget spent on
    # int8 pages (values 1 byte + bf16 scale per (position, head))
    # instead of wide ones buys ~2x (bf16) / ~3.6x (fp32) the pages —
    # the acceptance gate is >= 1.8x the wide-paged concurrent
    # capacity. Slots widen with the pages so the page capacity, not
    # the lane count, is what saturates first.
    h = model.cfg.num_heads
    d = model.cfg.hidden_size // h
    wide_itemsize = 2 if on_tpu else 4
    tok_wide = 2 * h * d * wide_itemsize          # k+v bytes/token
    tok_int8 = 2 * (h * d + h * 2)                # + bf16 scales
    hbm_budget = dense_batch * max_len * tok_wide
    int8_pages = hbm_budget // (page * tok_int8)
    int8_r = run(paged=True, kv_dtype="int8", slots=2 * paged_batch,
                 kv_pages=int(int8_pages) + 1)
    assert int8_r["prefix_hits"] > 0
    int8_vs_wide = int8_r["peak"] / paged_r["peak"]

    return {
        "metric": f"test-tiny paged-KV capacity at equal HBM "
                  f"({dense_batch * max_len} cache tokens, page {page}, "
                  f"{n_sys} shared {sys_len}-tok system prompts, "
                  f"poisson@{rate:g}/s): peak {paged_r['peak']} vs "
                  f"{dense['peak']} concurrent; int8 pages "
                  f"{int8_r['peak']} = {int8_vs_wide:.2f}x wide pages "
                  f"(device={dev.device_kind})",
        "value": round(ratio, 2),
        "unit": "x concurrent capacity",
        "vs_baseline": round(ratio / 2.0, 2),   # gate: > 2x -> >= 1.0
        "paged": {"dense": dense, "paged": paged_r,
                  "hbm_cache_tokens": dense_batch * max_len,
                  "page_size": page, "conserved": True},
        "int8": {**int8_r, "pages": int(int8_pages),
                 "wide_pages": dense_batch * max_len // page,
                 "vs_wide_pages": round(int8_vs_wide, 2),
                 "gate_1_8x": round(int8_vs_wide / 1.8, 2)},
    }



def bench_serve(dev, on_tpu):
    """Serving-engine bench (ISSUE-8 serve mode): synthetic Poisson
    arrivals of ragged prompts/budgets against the continuous-batching
    ServingEngine on test-tiny GPT. A feeder thread submits with
    exponential inter-arrival gaps (live traffic — requests land
    mid-decode and are admitted into freed slots); the main thread
    pumps the scheduler. Reports sustained QPS plus the SLA percentiles
    the serve.* metrics family tracks — TTFT and per-token latency
    p50/p95/p99 — as the BENCH_r06 row shape (the flat metric/value
    keys stay BENCH-schema compatible; the new "sla" sub-dict carries
    the percentile table). vs_baseline is 1.0 by definition — this row
    DEFINES the serving baseline from this revision on."""
    import os
    import threading
    import paddle_tpu as paddle
    from paddle_tpu.inference import Config
    from paddle_tpu.models.gpt import gpt
    from paddle_tpu.serving import RequestParams, ServingEngine

    from paddle_tpu.inference.config import PrecisionType

    n_req = int(os.environ.get("BENCH_SERVE_REQUESTS",
                               96 if on_tpu else 32))
    rate = float(os.environ.get("BENCH_SERVE_RATE", 64.0))  # req/sec
    max_batch = int(os.environ.get("BENCH_SERVE_BATCH",
                                   8 if on_tpu else 4))
    max_new = int(os.environ.get("BENCH_SERVE_NEW_TOKENS", 32))
    paddle.seed(0)
    model = gpt("test-tiny", max_position_embeddings=1024)
    model.bfloat16() if on_tpu else None
    spec = [paddle.to_tensor(np.zeros((max_batch, 64), np.int32))]

    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, model.cfg.vocab_size,
                           rng.randint(4, 128)).astype(np.int32)
               for _ in range(n_req)]
    budgets = rng.randint(max(4, max_new // 4), max_new + 1,
                          size=n_req)
    gaps = rng.exponential(1.0 / rate, size=n_req)

    counter = _metric_counter

    def traffic(engine):
        """One Poisson pass of the shared request set; returns
        (qps, handles, counters-delta)."""
        handles = []

        def feeder():
            for p, b, g in zip(prompts, budgets, gaps):
                time.sleep(g)
                handles.append(engine.submit(
                    p, RequestParams(max_new_tokens=int(b))))

        before = {k: counter(k) for k in
                  ("jit.compile.total", "jit.compile{cause=new_shape}")}
        t0 = time.perf_counter()
        th = threading.Thread(target=feeder, daemon=True)
        th.start()
        while th.is_alive() or engine.busy:
            if engine.busy:
                engine.step()
            else:
                time.sleep(0.0002)
        dt = time.perf_counter() - t0
        th.join()
        assert len(handles) == n_req and \
            all(h.status.value == "completed" for h in handles)
        return n_req / dt, handles, \
            {k: counter(k) - before[k] for k in before}

    def build(kv_dtype=None, weight_bits=None):
        cfg = (Config().from_layer(model, spec)
               .enable_generation(max_new_tokens=max_new,
                                  prefill_buckets=(32, 64, 128),
                                  max_batch=max_batch,
                                  kv_cache_dtype=kv_dtype)
               .enable_serving(max_queue=n_req,
                               weight_bits=weight_bits))
        if weight_bits:
            cfg.precision = PrecisionType.Int8
        return ServingEngine(cfg, poll_every=2)  # warmup compiles here

    engine = build()
    # ISSUE-17 "slo" sub-dict scaffolding: bracket the flagship pass
    # with two snapshots in a PRIVATE time-series ring, so the default
    # TTFT SLO can be evaluated over exactly that window (the later
    # precision passes re-drive the same metrics and must not leak in)
    from paddle_tpu.core import slo as slo_mod
    from paddle_tpu.core import timeseries as ts_mod
    slo_ring = ts_mod.TimeSeriesRing(period_s=1.0, retention=4)
    slo_ring.sample(now=0.0)
    t_slo0 = time.perf_counter()
    qps, handles, _ = traffic(engine)
    slo_span = time.perf_counter() - t_slo0
    slo_ring.sample(now=slo_span)
    # ISSUE-15 "goodput" sub-dict: the serve-side wall-time ledger
    # after the first (flagship) pass — buckets sum to wall, compute
    # fraction is the replica's goodput under this traffic shape
    gp = engine.goodput()
    goodput_row = {
        "wall_s": round(gp["wall_s"], 3),
        "goodput_fraction": round(gp["goodput_fraction"], 4),
        "buckets_s": {k: round(v, 3)
                      for k, v in gp["buckets"].items() if v > 0},
    }

    # ISSUE-13 per-precision rows: the SAME traffic against the int8-KV
    # engine and the int8-KV + int4-weight engine (counters prove the
    # timed pass ran warm)
    wide = "bfloat16" if on_tpu else "float32"
    precision = {"wide_dtype": wide}
    for label, kw in ((wide, {}),
                      ("int8-kv", dict(kv_dtype="int8")),
                      ("int8-kv+int4-w",
                       dict(kv_dtype="int8", weight_bits=4))):
        eng = engine if not kw else build(**kw)
        q2, _, ctr = traffic(eng)
        precision[label] = {"qps": round(q2, 1), "counters": ctr}
        if kw:
            eng.shutdown()
    for label in (wide, "int8-kv", "int8-kv+int4-w"):
        precision[label]["vs_wide"] = round(
            precision[label]["qps"] / precision[wide]["qps"], 2)
    ttft = np.array([h.ttft for h in handles]) * 1e3        # ms
    per_tok = np.array([h.per_token_latency for h in handles
                        if h.per_token_latency is not None]) * 1e3
    pct = lambda a, q: float(np.percentile(a, q))  # noqa: E731
    sla = {
        "qps": round(qps, 1),
        "requests": n_req,
        "ttft_ms": {q: round(pct(ttft, q), 2) for q in (50, 95, 99)},
        "token_ms": {q: round(pct(per_tok, q), 2)
                     for q in (50, 95, 99)},
        "slots_reused": engine.stats["slots_reused"],
        "decode_steps": engine.stats["decode_steps"],
    }
    # ISSUE-17 "slo" sub-dict: the default serve TTFT SLO evaluated
    # over the flagship pass — objective, measured p99 off the ring's
    # histogram delta, and the burn rate at end of run (burn > 1 means
    # this traffic shape would eat error budget in production)
    ttft_slo = next((s for s in slo_mod.default_slos()
                     if s.name == "serve-ttft-p99"), None)
    if ttft_slo is None:   # PADDLE_SLO_TTFT_P99=off
        ttft_slo = slo_mod.SLO("serve-ttft-p99", "latency",
                               "serve.ttft", 0.5)
    measured = ttft_slo.measure(slo_ring, slo_span)
    slo_row = {"slo": ttft_slo.name,
               "objective_s": ttft_slo.objective,
               "percentile": ttft_slo.percentile,
               "window_s": round(slo_span, 3)}
    if measured is not None:
        m, bad = measured
        slo_row["measured_s"] = round(m, 4)
        slo_row["burn_rate"] = round(ttft_slo.burn(bad), 3)
        slo_row["within_objective"] = bool(m <= ttft_slo.objective)
    # ISSUE-14 "mem" sub-dict: the engine's static HBM plan vs one
    # measured slot-decode dispatch, plus the KV pool bytes. Runs LAST:
    # on TPU the direct _step_jit dispatch donates the engine's state
    # buffers, so the engine serves no traffic after this.
    from paddle_tpu import analysis
    mp = engine.memory_plan()
    margs = (engine._state, engine._tok, engine._cache, engine._key,
             engine._finished, engine._steps, engine._budget,
             engine._out_buf)
    mem_plan = analysis.plan_memory(
        engine._step_fn, *margs, engine._cfg, static_argnums=(8,),
        donate=engine._step_donate, name="bench.serve.decode")
    mem = _mem_sub_dict(
        mem_plan, lambda: engine._step_jit(*margs, engine._cfg),
        margs, mp["kv_cache_bytes"])
    mem["predicted_engine_peak_bytes"] = mp["predicted_peak_bytes"]
    return {
        "metric": f"test-tiny serving QPS (continuous batching b{max_batch} "
                  f"poisson@{rate:g}/s, ttft p50={sla['ttft_ms'][50]}ms "
                  f"p99={sla['ttft_ms'][99]}ms, token p50="
                  f"{sla['token_ms'][50]}ms p99={sla['token_ms'][99]}ms, "
                  f"int8-kv {precision['int8-kv']['vs_wide']}x vs "
                  f"{wide}, device={dev.device_kind})",
        "value": round(qps, 1),
        "unit": "req/sec",
        "vs_baseline": 1.0,
        "sla": sla,
        "slo": slo_row,
        "precision": precision,
        "mem": mem,
        "goodput": goodput_row,
    }


def bench_serve_adversarial(dev, on_tpu):
    """Head-of-line-blocking bench (ISSUE-20 `serve --adversarial`
    mode): Poisson traffic of SHORT, TTFT-sensitive requests with a
    long prompt injected every few arrivals — the adversarial pattern
    where an inline long prefill parks the device for a whole
    monolithic dispatch while every short request behind it eats that
    wall into its TTFT. The same schedule runs twice at equal engine
    HBM (identical buckets/batch/cache; the only delta is the
    ``prefill_chunk_tokens`` knob): INLINE (chunking off) vs CHUNKED
    (page-aligned chunks interleaved with decode). Reports short-
    request TTFT p50/p95/p99 per mode plus each pass's serve.goodput
    compute fraction; the headline value is the p99 ratio
    (inline/chunked — higher is better), vs_baseline = ratio / 3 (the
    ISSUE-20 acceptance floor is 3x, so >= 1.0 means the gate holds)."""
    import os
    import threading
    import paddle_tpu as paddle
    from paddle_tpu.inference import Config
    from paddle_tpu.models.gpt import gpt
    from paddle_tpu.serving import RequestParams, ServingEngine

    n_req = int(os.environ.get("BENCH_ADV_REQUESTS",
                               80 if on_tpu else 40))
    rate = float(os.environ.get("BENCH_ADV_RATE", 64.0))   # req/sec
    every = int(os.environ.get("BENCH_ADV_LONG_EVERY", 4))
    max_batch = int(os.environ.get("BENCH_SERVE_BATCH",
                                   8 if on_tpu else 4))
    max_new = int(os.environ.get("BENCH_ADV_NEW_TOKENS", 16))
    chunk = int(os.environ.get("BENCH_ADV_CHUNK_TOKENS", 32))
    paddle.seed(0)
    model = gpt("test-tiny", max_position_embeddings=1024)
    model.bfloat16() if on_tpu else None
    spec = [paddle.to_tensor(np.zeros((max_batch, 64), np.int32))]

    rng = np.random.RandomState(0)
    is_long = np.array([(i % every) == every - 1 for i in range(n_req)])
    prompts = [rng.randint(0, model.cfg.vocab_size,
                           rng.randint(400, 512) if lng
                           else rng.randint(4, 24)).astype(np.int32)
               for lng in is_long]
    budgets = rng.randint(4, max_new + 1, size=n_req)
    gaps = rng.exponential(1.0 / rate, size=n_req)

    counter = _metric_counter

    def run(prefill_chunk_tokens):
        cfg = (Config().from_layer(model, spec)
               .enable_generation(max_new_tokens=max_new,
                                  prefill_buckets=(32, 512),
                                  max_batch=max_batch)
               .enable_serving(max_queue=n_req,
                               prefill_chunk_tokens=prefill_chunk_tokens))
        engine = ServingEngine(cfg, poll_every=2)  # warmup compiles here
        before = {k: counter(k) for k in
                  ("jit.compile.total", "jit.compile{cause=new_shape}")}
        handles = []

        def feeder():
            for p, b, g in zip(prompts, budgets, gaps):
                time.sleep(g)
                handles.append(engine.submit(
                    p, RequestParams(max_new_tokens=int(b))))

        t0 = time.perf_counter()
        th = threading.Thread(target=feeder, daemon=True)
        th.start()
        while th.is_alive() or engine.busy:
            if engine.busy:
                engine.step()
            else:
                time.sleep(0.0002)
        dt = time.perf_counter() - t0
        th.join()
        assert len(handles) == n_req and \
            all(h.status.value == "completed" for h in handles)
        short_ttft = np.array([h.ttft for h, lng in zip(handles, is_long)
                               if not lng]) * 1e3           # ms
        gp = engine.goodput()
        row = {
            "qps": round(n_req / dt, 1),
            "short_ttft_ms": {q: round(float(np.percentile(short_ttft,
                                                           q)), 2)
                              for q in (50, 95, 99)},
            "long_requests": int(is_long.sum()),
            "goodput_fraction": round(gp["goodput_fraction"], 4),
            "counters": {k: counter(k) - before[k] for k in before},
        }
        if prefill_chunk_tokens:
            row["prefill_chunks"] = engine.stats["prefill_chunks"]
        engine.shutdown()
        return row

    inline = run(None)
    chunked = run(chunk)
    ratio = inline["short_ttft_ms"][99] / \
        max(chunked["short_ttft_ms"][99], 1e-9)
    return {
        "metric": f"test-tiny adversarial serving: short-request TTFT "
                  f"p99 {inline['short_ttft_ms'][99]}ms inline vs "
                  f"{chunked['short_ttft_ms'][99]}ms chunked@{chunk} "
                  f"(1 long per {every} arrivals, poisson@{rate:g}/s "
                  f"b{max_batch}, goodput {inline['goodput_fraction']} "
                  f"vs {chunked['goodput_fraction']}, "
                  f"device={dev.device_kind})",
        "value": round(ratio, 2),
        "unit": "x short-request TTFT p99 (inline/chunked)",
        "vs_baseline": round(ratio / 3.0, 2),   # gate: >= 3x -> >= 1.0
        "inline": inline,
        "chunked": chunked,
        "chunk_tokens": chunk,
    }


def bench_serve_router(dev, on_tpu):
    """Fleet-router bench (ISSUE-19 `serve --router` mode): the SAME
    Poisson traffic shape as the serve row, but fanned over a 3-replica
    in-process fleet behind the FleetRouter — with a zero-drop rolling
    deploy of one replica MID-RUN. Reports routed QPS (the headline:
    what the fleet sustains while losing and regaining a replica),
    the router's re-route/re-home accounting, and the rejoin's
    ExecutableStore counters (hits == program count, misses == 0: the
    relaunch paid zero XLA compiles). vs_baseline is 1.0 — this row
    defines the routed-serving baseline."""
    import os
    import tempfile
    import threading
    import paddle_tpu as paddle
    from paddle_tpu.inference import Config
    from paddle_tpu.jit.compile_cache import ExecutableStore
    from paddle_tpu.models.gpt import gpt
    from paddle_tpu.serving import InProcessFleet, RequestParams

    n_req = int(os.environ.get("BENCH_ROUTER_REQUESTS",
                               96 if on_tpu else 24))
    rate = float(os.environ.get("BENCH_ROUTER_RATE", 64.0))  # req/sec
    n_rep = int(os.environ.get("BENCH_ROUTER_REPLICAS", 3))
    max_batch = int(os.environ.get("BENCH_SERVE_BATCH",
                                   8 if on_tpu else 2))
    max_new = int(os.environ.get("BENCH_SERVE_NEW_TOKENS", 32))
    paddle.seed(0)
    model = gpt("test-tiny", max_position_embeddings=1024)
    model.bfloat16() if on_tpu else None
    spec = [paddle.to_tensor(np.zeros((max_batch, 64), np.int32))]

    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, model.cfg.vocab_size,
                           rng.randint(4, 128)).astype(np.int32)
               for _ in range(n_req)]
    budgets = rng.randint(max(4, max_new // 4), max_new + 1,
                          size=n_req)
    gaps = rng.exponential(1.0 / rate, size=n_req)

    store = ExecutableStore(tempfile.mkdtemp(prefix="bench_router_"))

    def factory(name):
        from paddle_tpu.serving import ServingEngine
        cfg = (Config().from_layer(model, spec)
               .enable_generation(max_new_tokens=max_new,
                                  prefill_buckets=(32, 64, 128),
                                  max_batch=max_batch)
               .enable_serving(max_queue=n_req, drain_timeout_s=120.0))
        return ServingEngine(cfg, poll_every=2, executable_store=store)

    fleet = InProcessFleet(factory, n=n_rep)   # warmup compiles here
    router = fleet.router
    handles = []

    def feeder():
        for p, b, g in zip(prompts, budgets, gaps):
            time.sleep(g)
            handles.append(router.submit(
                p, RequestParams(max_new_tokens=int(b))))

    t0 = time.perf_counter()
    th = threading.Thread(target=feeder, daemon=True)
    th.start()
    deployed = rejoin = None
    while True:
        engines = router.engines()
        busy = [e for e in engines.values() if e.busy]
        if deployed is None and len(handles) >= n_req // 2:
            # the gate move: drain + relaunch one replica while the
            # fleet's queues are live (its queued work re-homes)
            victim = sorted(engines)[-1]
            h0, m0 = store.stats["hits"], store.stats["misses"]
            fresh = fleet.rolling_deploy(victim)
            deployed = victim
            rejoin = {"replica": victim,
                      "programs": len(fresh._exes),
                      "store_hits": store.stats["hits"] - h0,
                      "store_misses": store.stats["misses"] - m0}
            continue
        if not busy and not th.is_alive():
            break
        for e in busy:
            e.step()
        if not busy:
            time.sleep(0.0002)
    outs = [h.result(timeout=600) for h in handles]
    dt = time.perf_counter() - t0
    th.join()
    assert len(outs) == n_req and \
        all(h.status.value == "completed" for h in handles)
    assert rejoin is not None and rejoin["store_misses"] == 0
    qps = n_req / dt
    stats = router.stats
    homes = {}
    for h in handles:
        homes[h.replica] = homes.get(h.replica, 0) + 1
    fleet.shutdown()
    return {
        "metric": f"test-tiny ROUTED serving QPS ({n_rep} replicas b"
                  f"{max_batch} poisson@{rate:g}/s, rolling deploy of "
                  f"{deployed} mid-run: {stats['rehomed']} re-homed, "
                  f"rejoin {rejoin['store_hits']}/{rejoin['programs']} "
                  f"programs warm, device={dev.device_kind})",
        "value": round(qps, 1),
        "unit": "req/sec",
        "vs_baseline": 1.0,
        "router": {
            "replicas": n_rep,
            "requests": n_req,
            "admissions": stats["admissions"],
            "reroutes": stats["reroutes"],
            "rehomed": stats["rehomed"],
            "rejected": stats["rejected"],
            "breaker_trips": stats["breaker_trips"],
            "placements": homes,
        },
        "deploy": rejoin,
    }


def bench_warmstart(dev, on_tpu):
    """Warm-restart bench (ISSUE-9 warmstart mode): relaunch-to-first-
    token (serving engine build + warmup + one request) and relaunch-
    to-first-step (fused TrainStep build + one step) on test-tiny,
    COLD (empty executable store — every program traces and
    XLA-compiles) vs WARM (same store — every program deserializes off
    the traceless manifest; `jax.clear_caches()` between phases drops
    all in-memory trace/compile state, so the warm phase sees exactly
    what a relaunched process sees: only the store persists).

    The timed window starts at MODEL-IN-MEMORY: a relauncher pays
    python import + module construction + checkpoint restore
    identically cold and warm — that cost is what `bench.py gpt2`-style
    rows already track — while THIS row isolates the window the
    executable store actually owns: build-the-programs-and-produce-the-
    first-output. vs_baseline is speedup / 5 (the ISSUE-9 acceptance
    floor is 5x, so >= 1.0 means the gate holds); the "warmstart"
    sub-dict carries cold_s/warm_s/speedup plus the store's hit/miss
    counters per mode."""
    import os
    import shutil
    import tempfile
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.inference import Config
    from paddle_tpu.jit.compile_cache import ExecutableStore
    from paddle_tpu.models.gpt import gpt
    from paddle_tpu.serving import RequestParams, ServingEngine

    root = os.environ.get("BENCH_WARMSTART_DIR", "")
    keep = bool(root)
    root = root or tempfile.mkdtemp(prefix="bench-warmstart-")
    b, s, max_new = 2, 64, 16

    def serve_relaunch(store):
        """One serving relaunch, model already in memory: engine build
        + warmup (compiles or loads every program) + one request to its
        first token."""
        paddle.seed(0)
        model = gpt("test-tiny")
        spec = [paddle.to_tensor(np.zeros((2, 12), np.int32))]
        t0 = time.perf_counter()
        cfg = (Config().from_layer(model, spec)
               .enable_generation(max_new_tokens=max_new,
                                  prefill_buckets=(16, 32, 64),
                                  max_batch=2))
        engine = ServingEngine(cfg, poll_every=1,
                               executable_store=store)
        handle = engine.submit(np.arange(1, 9, dtype=np.int32),
                               RequestParams(max_new_tokens=1))
        toks = handle.result()
        return time.perf_counter() - t0, np.asarray(toks)

    def train_relaunch(store):
        """One training relaunch, model already in memory: warm-started
        TrainStep build + its first completed step."""
        paddle.seed(0)
        model = gpt("test-tiny", max_position_embeddings=s)
        opt = optimizer.AdamW(learning_rate=1e-4,
                              parameters=model.parameters())
        ids = np.random.RandomState(0).randint(
            0, model.cfg.vocab_size, (b, s)).astype(np.int32)
        x = paddle.to_tensor(ids)
        y = paddle.to_tensor(ids.astype(np.int64))
        t0 = time.perf_counter()
        step = paddle.jit.TrainStep(
            model, opt,
            lambda logits, labels: model.loss(logits, labels))
        step.enable_warm_start(store)
        loss = float(step(x, y))
        return time.perf_counter() - t0, loss

    results = {}
    for mode, relaunch in (("serve", serve_relaunch),
                           ("train", train_relaunch)):
        store_root = os.path.join(root, mode)
        cold_store = ExecutableStore(store_root)
        cold_s, cold_out = relaunch(cold_store)
        jax.clear_caches()  # relaunch: no in-memory jit/trace state
        warm_store = ExecutableStore(store_root)
        warm_s, warm_out = relaunch(warm_store)
        assert warm_store.stats["hits"] > 0 and \
            warm_store.stats["misses"] == 0, warm_store.stats
        assert np.array_equal(np.asarray(cold_out),
                              np.asarray(warm_out)), \
            "warm relaunch must reproduce the cold outputs bitwise"
        results[mode] = {
            "cold_s": round(cold_s, 3), "warm_s": round(warm_s, 3),
            "speedup": round(cold_s / max(warm_s, 1e-9), 2),
            "cold_hits": cold_store.stats["hits"],
            "cold_misses": cold_store.stats["misses"],
            "warm_hits": warm_store.stats["hits"],
            "warm_misses": warm_store.stats["misses"],
        }
    if not keep:
        shutil.rmtree(root, ignore_errors=True)
    sv, tr = results["serve"], results["train"]
    speedup = min(sv["speedup"], tr["speedup"])
    return {
        "metric": f"test-tiny warm restart (serve {sv['speedup']}x: "
                  f"{sv['cold_s']}s->{sv['warm_s']}s to first token, "
                  f"train {tr['speedup']}x: {tr['cold_s']}s->"
                  f"{tr['warm_s']}s to first step, "
                  f"device={dev.device_kind})",
        "value": round(speedup, 2),
        "unit": "x cold/warm",
        "vs_baseline": round(speedup / 5.0, 4),
        "warmstart": results,
    }


# counter families attached to every BENCH row (flat keys always
# present so the row schema is stable; the labeled cause/... breakdown
# rides along when nonzero)
_COUNTER_KEYS = ("jit.compile.total", "jit.compile_cache.hits",
                 "jit.compile_cache.misses", "train.host_syncs",
                 "train.loss_fetches")
_COUNTER_PREFIXES = ("jit.compile{", "jit.compile_cache.misses{")


def _counter_values():
    from paddle_tpu.profiler import metrics
    snap = metrics.snapshot()
    out = {k: int(snap[k]["value"]) if k in snap else 0
           for k in _COUNTER_KEYS}
    for name, d in snap.items():
        if d["kind"] == "counter" and \
                any(name.startswith(p) for p in _COUNTER_PREFIXES):
            out[name] = int(d["value"])
    return out


def _with_counters(fn, dev, on_tpu):
    """Run one bench with the metrics registry on and attach the
    counter deltas as the row's "counters" sub-dict — a perf
    regression's first triage question ("did it retrace? miss the
    executable store? stall on host syncs?") answers itself from the
    BENCH json."""
    from paddle_tpu.profiler import metrics
    was = metrics.is_enabled()
    metrics.enable()
    before = _counter_values()
    try:
        row = fn(dev, on_tpu)
    finally:
        if not was:
            metrics.disable()
    after = _counter_values()
    row["counters"] = {k: after[k] - before.get(k, 0)
                       for k in sorted(after)
                       if k in _COUNTER_KEYS
                       or after[k] - before.get(k, 0)}
    return row


BENCHES = {
    "gpt2": bench_gpt2,
    "decode": bench_decode,
    "serve": bench_serve,
    "serve-prefix": bench_serve_shared_prefix,
    "serve-router": bench_serve_router,
    "serve-adversarial": bench_serve_adversarial,
    "warmstart": bench_warmstart,
    "moe-block": bench_moe_block,
    "resnet50": bench_resnet50,
    "ernie-base": bench_ernie_base,
    "bert-large": bench_bert_large,
    "gpt6.7b-layer": bench_gpt67_layer,
    "vit-l": bench_vit_l,
}


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "gpt2"
    # `bench.py serve --shared-prefix`: the paged-KV capacity gate
    # (ISSUE-12) instead of the PR-8 SLA row
    if which == "serve" and "--shared-prefix" in sys.argv[2:]:
        which = "serve-prefix"
    # `bench.py serve --router`: the ISSUE-19 fleet-router row (3
    # replicas + mid-run rolling deploy) instead of the PR-8 SLA row
    if which == "serve" and "--router" in sys.argv[2:]:
        which = "serve-router"
    # `bench.py serve --adversarial`: the ISSUE-20 head-of-line row
    # (short Poisson traffic + long-prompt injections, inline vs
    # chunked prefill at equal HBM) instead of the PR-8 SLA row
    if which == "serve" and "--adversarial" in sys.argv[2:]:
        which = "serve-adversarial"
    # warmstart measures COLD compiles: it must not inherit a populated
    # process-global cache (it anchors its own fresh store per phase)
    dev, on_tpu = _setup(configure_cache=(which != "warmstart"))
    if which == "all":
        for name, fn in BENCHES.items():
            if name == "gpt2":
                continue
            if name == "warmstart":
                # its COLD phase must not inherit the .jax_cache the
                # other benches just configured/populated (clear_caches
                # drops only in-memory state): run it standalone
                print(json.dumps({"metric": "warmstart SKIPPED in "
                                  "'all' (needs a cold process: run "
                                  "`python bench.py warmstart`)"}),
                      file=sys.stderr)
                continue
            try:
                print(json.dumps(_with_counters(fn, dev, on_tpu)),
                      file=sys.stderr)
            except Exception as e:  # one failing config must not
                print(json.dumps({"metric": f"{name} FAILED: {e}"}),
                      file=sys.stderr)  # silence the flagship line
        print(json.dumps(_with_counters(bench_gpt2, dev, on_tpu)))
        return
    if which not in BENCHES:
        raise SystemExit(f"unknown bench {which!r}; one of "
                         f"{sorted(BENCHES)} or 'all'")
    print(json.dumps(_with_counters(BENCHES[which], dev, on_tpu)))


if __name__ == "__main__":
    main()
