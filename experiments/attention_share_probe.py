"""How much of the GPT-2 trunk's 8.3 ms/layer is attention? Time the
full 12-layer step against a variant whose scaled_dot_product_attention
is replaced by an identity (same shapes, no attention math) — the
difference is the true end-to-end attention cost incl. its backward.

Usage: python experiments/attention_share_probe.py
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax

import paddle_tpu as paddle
from paddle_tpu import optimizer
from paddle_tpu.models.gpt import gpt

BATCH, SEQ, ITERS = 16, 1024, 20


def time_step(step, x, y):
    loss = step(x, y)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        loss = step(x, y)
    float(loss)
    return (time.perf_counter() - t0) / ITERS


def build_step():
    paddle.seed(0)
    model = gpt("gpt2-small", max_position_embeddings=SEQ,
                fused_lm_loss=True, lm_loss_chunk=SEQ)
    model.bfloat16()
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=model.parameters(),
                          multi_precision=True)
    return paddle.jit.TrainStep(
        model, opt, lambda out, labels: model.loss(out, labels)), model


def main():
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 50257, (BATCH, SEQ)).astype(np.int32)
    x = paddle.to_tensor(ids)
    y = paddle.to_tensor(ids.astype(np.int64))

    step, _ = build_step()
    t_full = time_step(step, x, y)

    import paddle_tpu.nn.functional.attention as attn_mod

    def identity_sdpa(query, key, value, attn_mask=None, dropout_p=0.0,
                      is_causal=False, training=True, scale=None,
                      dropout_rng=None):
        return query + 0.0 * (key + value)  # keep all grads flowing

    saved = attn_mod.scaled_dot_product_attention
    attn_mod.scaled_dot_product_attention = identity_sdpa
    # the models call F.scaled_dot_product_attention — rebind there too
    import paddle_tpu.nn.functional as F
    saved_f = F.scaled_dot_product_attention
    F.scaled_dot_product_attention = identity_sdpa
    try:
        step2, _ = build_step()
        t_noattn = time_step(step2, x, y)
    finally:
        attn_mod.scaled_dot_product_attention = saved
        F.scaled_dot_product_attention = saved_f

    print(f"full step        : {t_full * 1e3:7.2f} ms")
    print(f"identity attention: {t_noattn * 1e3:7.2f} ms")
    print(f"attention share  : {(t_full - t_noattn) * 1e3:7.2f} ms "
          f"({(t_full - t_noattn) / 12 * 1e3:5.2f} ms/layer)")


if __name__ == "__main__":
    main()
