"""Calibrate the parallel-strategy tuner's cost model against the
measured BASELINE.md rows (VERDICT r3 Next #2).

For each single-chip bench config this script builds the exact
TrainStep bench.py runs, reads XLA's compiled cost analysis
(flops, bytes), measures the real step time on the chip, and records
everything to experiments/tuner_calibration.json. The fit step then
finds the (mxu_eff, hbm_eff) derate pair minimizing worst-case relative
error of
    t_pred = max(flops / (peak * mxu_eff), bytes / (hbm_bw * hbm_eff))
over the rows; those constants ship as the tuner defaults and
tests/test_parallel_tuner.py asserts the stored table stays within the
error bound (pure arithmetic — no chip needed at test time).

Usage (on the real chip):
    python experiments/tuner_calibration.py measure   # writes the json
    python experiments/tuner_calibration.py fit       # prints constants
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "tuner_calibration.json")


def _steps():
    """(name, build() -> (step, (x, y)), batch_tokens_or_imgs)"""
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer

    def gpt2(batch, seq):
        from paddle_tpu.models.gpt import gpt
        paddle.seed(0)
        chunk = max(8192 // batch, 128)
        model = gpt("gpt2-small", max_position_embeddings=seq,
                    fused_lm_loss=True, lm_loss_chunk=chunk)
        model.bfloat16()
        opt = optimizer.AdamW(learning_rate=1e-4,
                              parameters=model.parameters(),
                              multi_precision=True)
        step = paddle.jit.TrainStep(
            model, opt, lambda lg, lb: model.loss(lg, lb))
        rng = np.random.RandomState(0)
        ids = rng.randint(0, model.cfg.vocab_size,
                          (batch, seq)).astype(np.int32)
        return step, (paddle.to_tensor(ids),
                      paddle.to_tensor(ids.astype(np.int64)))

    def mlm(cfg_name, batch, seq):
        from paddle_tpu.models.ernie import ernie
        paddle.seed(0)
        model = ernie(cfg_name, fused_mlm_loss=True,
                      max_predictions=max(int(seq * 0.19), 8))
        model.bfloat16()
        opt = optimizer.AdamW(learning_rate=1e-4,
                              parameters=model.parameters(),
                              multi_precision=True)
        step = paddle.jit.TrainStep(
            model, opt, lambda out, lb: model.loss(out, lb))
        rng = np.random.RandomState(0)
        ids = rng.randint(0, model.cfg.vocab_size,
                          (batch, seq)).astype(np.int32)
        mlmy = ids.astype(np.int64)
        mlmy[rng.rand(*mlmy.shape) > 0.15] = -100
        y = (paddle.to_tensor(mlmy),
             paddle.to_tensor(rng.randint(0, 2, (batch,)).astype(np.int64)))
        return step, (paddle.to_tensor(ids), y)

    def resnet(batch, fused_bn):
        from paddle_tpu.models.resnet import resnet50
        paddle.seed(0)
        model = resnet50(num_classes=1000, data_format="NHWC",
                         stem_space_to_depth=True, fused_bn=fused_bn)
        model.bfloat16()
        opt = optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                 parameters=model.parameters(),
                                 multi_precision=True)
        ce = nn.CrossEntropyLoss()
        step = paddle.jit.TrainStep(
            model, opt, lambda lg, lb: ce(lg.astype("float32"), lb))
        rng = np.random.RandomState(0)
        img = rng.randn(batch, 3, 224, 224).astype(np.float32)
        x = paddle.to_tensor(img).astype("bfloat16")
        y = paddle.to_tensor(rng.randint(0, 1000, (batch,)).astype(np.int64))
        return step, (x, y)

    def vit(batch):
        from paddle_tpu.models.vit import vit as vit_f
        paddle.seed(0)
        model = vit_f("vit-l-16")
        model.bfloat16()
        opt = optimizer.AdamW(learning_rate=1e-4,
                              parameters=model.parameters(),
                              multi_precision=True)
        ce = nn.CrossEntropyLoss()
        step = paddle.jit.TrainStep(
            model, opt, lambda lg, lb: ce(lg.astype("float32"), lb))
        rng = np.random.RandomState(0)
        img = rng.randn(batch, 3, 224, 224).astype(np.float32)
        x = paddle.to_tensor(img).astype("bfloat16")
        y = paddle.to_tensor(
            rng.randint(0, model.cfg.num_classes, (batch,)).astype(np.int64))
        return step, (x, y)

    return [
        ("gpt2-small b16 s1024", lambda: gpt2(16, 1024)),
        ("gpt2-small b16 s2048", lambda: gpt2(16, 2048)),
        ("gpt2-small b32 s1024", lambda: gpt2(32, 1024)),
        ("ernie-base b32 s512", lambda: mlm("ernie-3.0-base", 32, 512)),
        ("bert-large b16 s512", lambda: mlm("bert-large", 16, 512)),
        ("resnet50 b128 fused", lambda: resnet(128, True)),
        ("resnet50 b128 unfused", lambda: resnet(128, False)),
        ("vit-l-16 b64", lambda: vit(64)),
    ]


def measure():
    import jax
    from paddle_tpu.jit import enable_compile_cache
    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    enable_compile_cache(cache, min_compile_time_secs=1.0)
    rows = []
    for name, build in _steps():
        step, (x, y) = build()
        ca = step.cost_analysis(x, y)
        flops = float(ca.get("flops", 0.0))
        hbm = float(ca.get("bytes accessed", 0.0))
        loss = step(x, y)
        float(loss)          # compile + fence
        iters = 20
        t0 = time.perf_counter()
        for _ in range(iters):
            loss = step(x, y)
        float(loss)
        dt = (time.perf_counter() - t0) / iters
        rows.append({"name": name, "flops": flops, "hbm_bytes": hbm,
                     "measured_s": dt})
        print(f"{name}: {dt * 1e3:.2f} ms  flops={flops / 1e12:.2f}T "
              f"bytes={hbm / 1e9:.2f}GB", flush=True)
        del step
    with open(OUT, "w") as f:
        json.dump({"device": str(jax.devices()[0].device_kind),
                   "peak_flops": 197e12, "hbm_bw": 819e9,
                   "rows": rows}, f, indent=1)
    print(f"wrote {OUT}")


def predict(row, mxu_eff, hbm_eff, peak=197e12, hbm_bw=819e9):
    return max(row["flops"] / (peak * mxu_eff),
               row["hbm_bytes"] / (hbm_bw * hbm_eff))


def fit():
    with open(OUT) as f:
        data = json.load(f)
    rows = data["rows"]
    best = None
    for me in np.arange(0.30, 0.95, 0.01):
        for he in np.arange(0.30, 1.01, 0.01):
            errs = [abs(predict(r, me, he) - r["measured_s"])
                    / r["measured_s"] for r in rows]
            worst = max(errs)
            if best is None or worst < best[0]:
                best = (worst, me, he, errs)
    worst, me, he, errs = best
    print(f"best: mxu_eff={me:.2f} hbm_eff={he:.2f} "
          f"worst-rel-err={worst * 100:.1f}%")
    for r, e in zip(rows, errs):
        p = predict(r, me, he)
        bound = ("mxu" if r["flops"] / (197e12 * me)
                 >= r["hbm_bytes"] / (819e9 * he) else "hbm")
        print(f"  {r['name']:28s} meas {r['measured_s'] * 1e3:7.2f} ms  "
              f"pred {p * 1e3:7.2f} ms  err {e * 100:5.1f}%  [{bound}]")


if __name__ == "__main__":
    {"measure": measure, "fit": fit}[sys.argv[1] if len(sys.argv) > 1
                                     else "measure"]()
