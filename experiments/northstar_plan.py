"""ERNIE-3.0-Base v5e-256 north-star plan (VERDICT r3 Next #2).

Compiles the REAL fleet train step for ERNIE-Base (b32/chip, s512,
fused MLM loss — the measured single-chip bench config) over virtual
CPU meshes at dp x sharding candidates for 256 chips and at dp-only
meshes from 8 to 256 chips, and parses per-step collective payload
bytes out of each compiled HLO. Prediction is MEASURED-ANCHORED: the
per-chip compute term is the real single-chip step time (97.91 ms r5,
read from tuner_calibration.json —
the per-chip workload is identical at b32/chip), and the collective
term adds the HLO payloads over the tuner's link model (ICI/DCN
bandwidth + latency, ring factor folded into the constants). The
roofline derates (mxu_eff/hbm_eff) do NOT enter this prediction —
they are the tuner's cross-model constants; anchoring on the measured
row is strictly tighter for a same-workload scaling projection.
Per-chip HBM rows come from the audited step's MemoryPlan
(analysis.memory liveness scan, ISSUE 14) — byte counts are read off
the program, only the partition rule (params replicate, stage-2 opt
state shards, batch/activations shard) is applied as data.
Writes experiments/northstar_plan.json consumed by BASELINE.md and
tests/test_parallel_tuner.py.

Run: python experiments/northstar_plan.py   (CPU, ~minutes)
"""
import json
import os
import subprocess
import sys
import time

_N_DEV = int(os.environ.get("NORTHSTAR_NDEV", "256"))
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "").split(
        " --xla_force_host_platform_device_count")[0]
    + f" --xla_force_host_platform_device_count={_N_DEV}").strip()

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "northstar_plan.json")

# link model only — the compute term is the measured single-chip step
ICI_BW, ICI_LAT = 180e9, 1e-6
DCN_BW, DCN_LAT = 12.5e9, 25e-6
PER_CHIP_B, SEQ = 32, 512
HBM_PER_CHIP = 16 << 30        # v5e: 16 GiB per chip (plan input)


def hbm_plan_row(mem, dp, sharding):
    """Per-chip HBM prediction from the fleet step's MemoryPlan
    (ISSUE-14): every byte count is read OFF the audited program —
    params / optimizer state / batch operand totals and the scan's
    peak — and only the partition rule is applied as data: params
    replicate per chip, stage-2 optimizer state shards across the
    sharding group, batch and activation temporaries shard across the
    whole dp x sharding mesh. Replaces hand-computed parameter
    arithmetic: when the step gains a buffer, the row moves with it.
    NB the CPU trace materializes attention scores the TPU flash
    kernels never form, so — like the cost-analysis absolutes above —
    per_chip_bytes upper-bounds the TPU footprint."""
    n = dp * sharding
    if mem.arg_bytes is None:  # exotic flattening: no per-arg split
        return {"peak_bytes_global": mem.peak_bytes,
                "per_chip_bytes": None}
    params_b, opt_b = mem.arg_bytes[0], mem.arg_bytes[1]
    batch_b = sum(mem.arg_bytes[4:])
    # temporaries at the peak = everything the resident operands and
    # baked consts don't explain; they scale with the per-chip batch
    temps_b = max(0, mem.peak_bytes - mem.args_bytes - mem.consts_bytes)
    per_chip = (params_b + opt_b // sharding + batch_b // n
                + temps_b // n + mem.consts_bytes)
    return {
        "peak_bytes_global": mem.peak_bytes,
        "params_bytes": params_b,
        "opt_state_bytes": opt_b,
        "batch_bytes": batch_b,
        "temps_bytes_global": temps_b,
        "per_chip_bytes": int(per_chip),
        "per_chip_gib": round(per_chip / (1 << 30), 3),
        "fits_v5e_16gib": bool(per_chip < HBM_PER_CHIP),
    }


def compile_candidate(dp, sharding, n_devices):
    """Build + compile the fleet ERNIE step on a dp x sharding virtual
    mesh; return per-chip flops/bytes + collective stats from HLO."""
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models.ernie import ernie
    from paddle_tpu.distributed.auto_parallel.tuner import collective_bytes

    fleet.init(strategy=fleet.DistributedStrategy(
        hybrid_configs={"dp_degree": dp, "sharding_degree": sharding},
        sharding=sharding > 1, sharding_configs={"stage": 2}))
    paddle.seed(0)
    model = ernie("ernie-3.0-base", fused_mlm_loss=True,
                  max_predictions=97)
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=model.parameters())
    opt = fleet.distributed_optimizer(opt)
    # abstract=True: parameters/optimizer/batch stay un-placed — the
    # replicated state of a 256-device mesh would need ~112 GB of host
    # RAM on the virtual CPU backend otherwise
    step = fleet.DistributedTrainStep(
        model, opt, lambda out, lb: model.loss(out, lb), abstract=True)
    b = PER_CHIP_B * dp * sharding
    ids = jax.ShapeDtypeStruct((b, SEQ), np.int32)
    y = (jax.ShapeDtypeStruct((b, SEQ), np.int64),
         jax.ShapeDtypeStruct((b,), np.int64))
    t0 = time.perf_counter()
    comp = step.lower_abstract(ids, y).compile()
    compile_s = time.perf_counter() - t0
    ca = comp.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    # NB: cost analysis of the SPMD module is PER-DEVICE (the partitioned
    # program), and the CPU lowering is fp32 without the flash/fused
    # paths — these absolutes are sanity context only; the prediction
    # anchors compute on the MEASURED single-chip step (97.91 ms for
    # the identical per-chip workload) and takes just the collective
    # payloads from this HLO.
    flops = float(ca.get("flops", 0.0))
    hbm = float(ca.get("bytes accessed", 0.0))
    txt = comp.as_text()
    ici_b, dcn_b, n_ici, n_dcn = collective_bytes(txt, None)
    # ISSUE-14: per-chip HBM from the audited step's MemoryPlan (trace
    # only, memory pass only — the compile above is the slow part)
    mem = step.audit(ids, y, checks=("memory",)).memory
    return {"dp": dp, "sharding": sharding,
            "flops_per_chip_cpu_fp32": flops, "hbm_per_chip_cpu_fp32": hbm,
            "coll_bytes": ici_b + dcn_b, "n_coll": n_ici + n_dcn,
            "hbm_plan": hbm_plan_row(mem, dp, sharding),
            "compile_s": round(compile_s, 1)}


def _measured_anchor() -> float:
    """Single source of truth: the 'ernie-base b32 s512' row of
    experiments/tuner_calibration.json (the same chip run that fit the
    tuner constants)."""
    import json
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tuner_calibration.json")
    if not os.path.exists(path):
        raise RuntimeError(
            f"{path} is missing; run 'python experiments/"
            "tuner_calibration.py measure' on the chip first")
    rows = json.load(open(path))["rows"]
    hits = [r for r in rows if r["name"] == "ernie-base b32 s512"]
    if not hits:  # fail loudly — a silent constant would desync the plan
        raise RuntimeError(
            "tuner_calibration.json has no 'ernie-base b32 s512' row; "
            "run 'python experiments/tuner_calibration.py measure' first")
    return hits[0]["measured_s"]


MEASURED_1CHIP_S = _measured_anchor()  # 97.91 ms r5 (102.95 r4, 109.74 r3)


def predict(row, slices=1, accum=1, ici_bw=None, dcn_bw=None):
    """Measured-anchored prediction: per-chip compute is the REAL
    single-chip step time (identical per-chip workload at b32/chip);
    the collective term adds the HLO-parsed per-device payload over the
    tuner's link model (ring factor folded into the bw constants).
    slices>1 bills the inter-slice leg of the grad all-reduce to DCN
    (hierarchical mesh: dp outermost, crossing rule topology.py:41).
    accum=K models gradient accumulation (fleet train_step gradient
    merge): K forward/backward microsteps per optimizer step, ONE grad
    exchange — compute scales by K, the collective term is paid once,
    so the per-sample efficiency recovers as K grows. Returns the
    PER-MICROBATCH-equivalent step time (total / K) so efficiencies
    stay comparable across K."""
    ici_bw = ICI_BW if ici_bw is None else ici_bw
    dcn_bw = DCN_BW if dcn_bw is None else dcn_bw
    coll = row["coll_bytes"]
    t_coll = coll / ici_bw + row["n_coll"] * ICI_LAT
    if slices > 1:
        # hierarchical all-reduce: intra-slice legs ride ICI; the
        # inter-slice exchange moves payload/slices per chip over DCN
        t_coll += (coll / slices) / dcn_bw + row["n_coll"] * DCN_LAT
    return (accum * MEASURED_1CHIP_S + t_coll) / accum


def run_one(spec):
    """Entry for one (dp, sharding) point inside a subprocess whose
    virtual device count equals dp*sharding."""
    dp, sh = (int(x) for x in spec.split("x"))
    r = compile_candidate(dp, sh, dp * sh)
    print("RESULT " + json.dumps(r), flush=True)


def main():
    rows = []
    here = os.path.abspath(__file__)
    # 256-chip candidates (dp x sharding; mp is cost-pruned for a 110M
    # model — its all-gathers per layer dwarf the one grad all-reduce)
    # + the dp-only scaling curve 8 -> 256. Each point runs in its own
    # subprocess so the virtual device count matches the mesh.
    points = [("candidate-256", 256, 1), ("candidate-256", 128, 2),
              ("candidate-256", 64, 4),
              ("scaling", 8, 1), ("scaling", 32, 1)]
    for kind, dp, sh in points:
        env = dict(os.environ, NORTHSTAR_NDEV=str(dp * sh))
        out = subprocess.run(
            [sys.executable, here, f"{dp}x{sh}"], env=env,
            capture_output=True, text=True, timeout=2400)
        line = [l for l in out.stdout.splitlines()
                if l.startswith("RESULT ")]
        if not line:
            print(f"FAILED {dp}x{sh}:\n{out.stderr[-2000:]}", flush=True)
            continue
        r = json.loads(line[-1][len("RESULT "):])
        r["kind"] = kind if kind != "scaling" else f"scaling-dp{dp}"
        r["pred_ms"] = round(predict(r) * 1e3, 2)
        r["pred_scaling_eff"] = round(MEASURED_1CHIP_S / predict(r), 4)
        if kind == "candidate-256":
            r["pred_ms_2slice"] = round(predict(r, slices=2) * 1e3, 2)
            r["pred_scaling_eff_2slice"] = round(
                MEASURED_1CHIP_S / predict(r, slices=2), 4)
            # gradient-accumulation recovery curve on the 2-slice mesh
            # (VERDICT r4 Weak #5): one DCN grad exchange per K
            # microbatches reamortizes the inter-slice penalty
            r["accum_2slice"] = {
                str(k): round(
                    MEASURED_1CHIP_S / predict(r, slices=2, accum=k), 4)
                for k in (1, 2, 4, 8, 16)}
            # link-constant sensitivity (VERDICT r4 Weak #4): the ICI/
            # DCN constants are unmeasured in this env — publish the
            # efficiency under 0.5x / 2x bandwidth so the claim carries
            # its error bars
            r["sensitivity"] = {
                f"ici_{m}x": round(
                    MEASURED_1CHIP_S / predict(r, ici_bw=ICI_BW * m), 4)
                for m in (0.5, 2)}
            r["sensitivity"].update({
                f"dcn_{m}x_2slice": round(
                    MEASURED_1CHIP_S / predict(r, slices=2,
                                               dcn_bw=DCN_BW * m), 4)
                for m in (0.5, 2)})
        rows.append(r)
        print(r, flush=True)
    with open(OUT, "w") as f:
        json.dump({"model": "ernie-3.0-base b32/chip s512 fused-mlm",
                   "method": "measured-anchored: compute term = real "
                             "single-chip step; collective term = HLO "
                             "payloads over the link model",
                   "link_model": {"ici_bw": ICI_BW, "ici_lat": ICI_LAT,
                                  "dcn_bw": DCN_BW, "dcn_lat": DCN_LAT},
                   "measured_1chip_ms": MEASURED_1CHIP_S * 1e3,
                   "rows": rows}, f, indent=1)
    print(f"wrote {OUT}")


if __name__ == "__main__":
    if len(sys.argv) > 1:
        run_one(sys.argv[1])
    else:
        main()
