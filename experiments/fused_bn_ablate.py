"""Ablate the fused conv+BN ResNet path on the real chip.

Configs: (a) unfused r3 baseline, (b) fused with XLA 3x3 (Pallas 1x1
epilogue/prologue kernels + residual-lean applies only), (c) fused with
the Pallas 3x3 window kernel. Prints img/s for each.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def run(fused_bn, pallas3x3, remat=()):
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.models import resnet as resnet_mod

    resnet_mod._PALLAS3X3 = pallas3x3
    paddle.seed(0)
    model = resnet_mod.resnet50(num_classes=1000, data_format="NHWC",
                                stem_space_to_depth=True, fused_bn=fused_bn,
                                recompute_stages=remat)
    model.bfloat16()
    opt = optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                             parameters=model.parameters(),
                             multi_precision=True)
    ce = nn.CrossEntropyLoss()
    step = paddle.jit.TrainStep(
        model, opt, lambda lg, lb: ce(lg.astype("float32"), lb))
    b = 128
    rng = np.random.RandomState(0)
    img = rng.randn(b, 3, 224, 224).astype(np.float32)
    x = paddle.to_tensor(img).astype("bfloat16")
    y = paddle.to_tensor(rng.randint(0, 1000, (b,)).astype(np.int64))
    loss = step(x, y)
    float(loss)
    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(x, y)
    float(loss)
    dt = time.perf_counter() - t0
    return b * iters / dt, dt / iters * 1e3


def main():
    import jax
    from paddle_tpu.jit import enable_compile_cache
    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    enable_compile_cache(cache, min_compile_time_secs=1.0)
    cfgs = [("unfused (r3 baseline)", False, False, ()),
            ("fused, XLA 3x3", True, False, ()),
            ("fused, Pallas 3x3", True, True, ()),
            ("unfused, remat L1", False, False, (1,)),
            ("unfused, remat L1-2", False, False, (1, 2)),
            ("unfused, remat L1-3", False, False, (1, 2, 3))]
    import sys as _sys
    only = _sys.argv[1] if len(_sys.argv) > 1 else None
    for name, fused, p3, remat in cfgs:
        if only and only not in name:
            continue
        ips, ms = run(fused, p3, remat)
        print(f"{name:24s} {ips:7.1f} img/s   {ms:6.2f} ms/step",
              flush=True)


if __name__ == "__main__":
    main()
