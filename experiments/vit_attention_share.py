"""ViT-L/16 b64: how much of the step is the XLA attention path
(s197 sits below the flash gate)? Identity-attention ablation, same
method as attention_share_probe.py.

Usage: python experiments/vit_attention_share.py
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import optimizer
from paddle_tpu.nn import functional as F

ITERS = 10


def time_step(step, x, y):
    loss = step(x, y)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        loss = step(x, y)
    float(loss)
    return (time.perf_counter() - t0) / ITERS


def build_step():
    from paddle_tpu.models.vit import vit
    paddle.seed(0)
    model = vit("vit-l-16", num_classes=1000)
    model.bfloat16()
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=model.parameters(),
                          multi_precision=True)
    return paddle.jit.TrainStep(
        model, opt,
        lambda logits, lab: F.cross_entropy(
            logits.astype("float32"), lab))


def main():
    rng = np.random.RandomState(0)
    imgs = rng.randn(64, 3, 224, 224).astype(np.float32)
    labels = rng.randint(0, 1000, (64,)).astype(np.int64)
    x = paddle.to_tensor(imgs).astype("bfloat16")
    y = paddle.to_tensor(labels)

    step = build_step()
    t_full = time_step(step, x, y)

    import paddle_tpu.nn.functional.attention as attn_mod
    import paddle_tpu.nn.functional as Fmod

    def identity_sdpa(query, key, value, attn_mask=None, dropout_p=0.0,
                      is_causal=False, training=True, scale=None,
                      dropout_rng=None):
        return query + 0.0 * (key + value)

    saved = attn_mod.scaled_dot_product_attention
    saved_f = Fmod.scaled_dot_product_attention
    attn_mod.scaled_dot_product_attention = identity_sdpa
    Fmod.scaled_dot_product_attention = identity_sdpa
    try:
        step2 = build_step()
        t_noattn = time_step(step2, x, y)
    finally:
        attn_mod.scaled_dot_product_attention = saved
        Fmod.scaled_dot_product_attention = saved_f

    print(f"full step         : {t_full * 1e3:7.2f} ms")
    print(f"identity attention: {t_noattn * 1e3:7.2f} ms")
    print(f"attention share   : {(t_full - t_noattn) * 1e3:7.2f} ms")


if __name__ == "__main__":
    main()
