"""Isolate the fused LM-head+CE loss cost (b16 s1024 gpt2-small shapes):
grad wrt (hidden, tied-W) across chunk sizes, remat on/off, and an
fp32-preferred matmul variant. The step breakdown shows the fixed
embedding+loss cost is ~43 ms of the 143 ms step; ideal-with-remat is
~26 ms — find where the rest goes.

Usage: python experiments/lm_loss_head_probe.py
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

B, S1, H, V = 16, 1023, 768, 50257
ITERS = 10


def make_loss(chunk, remat, pref32):
    n_chunks = -(-S1 // chunk)
    pad = n_chunks * chunk - S1

    def chunk_ce(hc, yc, w):
        wmat = w.T
        if pref32:
            logits = jax.lax.dot_general(
                hc, wmat.astype(hc.dtype), (((2,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        else:
            logits = (hc @ wmat.astype(hc.dtype)).astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        yc_safe = jnp.maximum(yc, 0)
        gold = jnp.take_along_axis(
            logits, yc_safe[..., None], axis=-1)[..., 0]
        valid = (yc >= 0).astype(jnp.float32)
        return jnp.sum((lse - gold) * valid), jnp.sum(valid)

    def loss(hs, ys, w):
        hs = jnp.pad(hs, ((0, 0), (0, pad), (0, 0)))
        ys = jnp.pad(ys, ((0, 0), (0, pad)), constant_values=-1)
        hsc = hs.reshape(B, n_chunks, chunk, H).transpose(1, 0, 2, 3)
        ysc = ys.reshape(B, n_chunks, chunk).transpose(1, 0, 2)
        ce = jax.checkpoint(chunk_ce) if remat else chunk_ce

        def body(carry, xs):
            hc, yc = xs
            ssum, cnt = ce(hc, yc, w)
            return (carry[0] + ssum, carry[1] + cnt), None

        (total, count), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
            (hsc, ysc))
        return total / jnp.maximum(count, 1.0)

    return loss


def bench(loss_fn, hs, ys, w):
    g = jax.jit(jax.value_and_grad(loss_fn, argnums=(0, 2)))
    out = g(hs, ys, w)
    float(out[0])
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = g(hs, ys, w)
    float(out[0])
    return (time.perf_counter() - t0) / ITERS


def main():
    rng = np.random.RandomState(0)
    hs = jnp.asarray(rng.randn(B, S1, H), jnp.bfloat16)
    ys = jnp.asarray(rng.randint(0, V, (B, S1)), jnp.int32)
    w = jnp.asarray(rng.randn(V, H) * 0.02, jnp.bfloat16)

    for chunk in (256, 512, 1024):
        for remat in (True, False):
            for pref32 in (False, True):
                try:
                    t = bench(make_loss(chunk, remat, pref32), hs, ys, w)
                    tag = f"chunk{chunk:5d} remat={int(remat)} p32={int(pref32)}"
                    print(f"{tag}: {t*1e3:7.2f} ms")
                except Exception as e:  # noqa: BLE001
                    print(f"chunk{chunk} remat={remat} p32={pref32} "
                          f"FAILED {type(e).__name__}: {e}")


if __name__ == "__main__":
    main()
