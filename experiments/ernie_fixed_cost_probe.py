"""Ablate ERNIE's fixed (non-trunk) step cost component by component.

r4's 12-vs-6-layer ablation put ~14.6 ms (now ~16 ms post-kernel-wave)
of the b32-s512 step outside the trunk: gathered MLM head, embedding
backward, SOP head, optimizer. This probe stubs one component at a time
on the real chip to price each:

  full          — the bench step as measured
  no_sop        — loss drops the SOP term (head + pooler still run fwd)
  no_mlm        — loss is mean(hidden): no gather/transform/decode
  no_embed_bwd  — stop_gradient around the three embedding lookups
                  (kills the [b*s, h] -> [vocab, h] scatter-add grad;
                  wte still gets grads through the tied MLM decode)
  fwd_bwd_only  — no optimizer update (prices AdamW)
"""
import sys, os, time
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def _sync(out):
    """Force completion: float() the first loss-like leaf (the XLA
    program is atomic, so the whole step is done when it lands)."""
    import jax
    leaves = jax.tree_util.tree_leaves(
        out, is_leaf=lambda t: hasattr(t, "data"))
    first = leaves[0]
    return float(np.asarray(first.data if hasattr(first, "data")
                            else first).ravel()[0])


def time_fn(fn, *args, iters=20):
    out = fn(*args)  # compile
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _sync(out)
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.models.ernie import ernie, ErnieEmbeddings

    b, s = 32, 512
    paddle.seed(0)
    model = ernie("ernie-3.0-base", fused_mlm_loss=True,
                  max_predictions=max(int(s * 0.19), 8))
    model.bfloat16()
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=model.parameters(),
                          multi_precision=True)
    from paddle_tpu.jit import TrainStep

    rng = np.random.RandomState(0)
    ids = rng.randint(0, model.cfg.vocab_size, (b, s)).astype(np.int32)
    mlm_y = np.full((b, s), -100, np.int64)
    for i in range(b):
        pos = rng.choice(s, 76, replace=False)
        mlm_y[i, pos] = ids[i, pos]
    sop_y = rng.randint(0, 2, (b,)).astype(np.int64)
    x = paddle.to_tensor(ids)
    y = (paddle.to_tensor(mlm_y), paddle.to_tensor(sop_y))

    def build_step(loss_fn):
        return TrainStep(model, opt, loss_fn)

    results = {}

    full_loss = lambda out, lab: model.loss(out, lab)
    results["full"] = time_fn(build_step(full_loss), x, y)

    def no_sop(out, lab):
        import paddle_tpu.nn.functional as F
        seq, sop_logits, wp = out
        from paddle_tpu.core.tensor import dispatch
        return dispatch("fused_mlm_loss",
                        lambda h, yy, *w: model._fused_mlm(h, yy, *w),
                        (seq, lab[0]) + tuple(wp), {})
    results["no_sop"] = time_fn(build_step(no_sop), x, y)

    def no_mlm(out, lab):
        import paddle_tpu.nn.functional as F
        seq, sop_logits, wp = out
        sop = F.cross_entropy(sop_logits, lab[1])
        return seq.astype("float32").mean() + sop
    results["no_mlm"] = time_fn(build_step(no_mlm), x, y)

    # stop-grad embedding lookups: patch the forward
    orig_fwd = ErnieEmbeddings.forward

    def sg_forward(self, input_ids, token_type_ids=None):
        out = orig_fwd(self, input_ids, token_type_ids)
        return out  # patched below at the lookup level instead
    from paddle_tpu.core.tensor import Tensor
    import paddle_tpu.ops as ops

    def sg_fwd(self, input_ids, token_type_ids=None):
        bb, ss = input_ids.shape
        pos = ops.creation.arange(ss, dtype="int32")
        x = self.word_embeddings(input_ids) \
            + self.position_embeddings(pos)
        if token_type_ids is None:
            token_type_ids = ops.creation.zeros([bb, ss], dtype="int32")
        x = x + self.token_type_embeddings(token_type_ids)
        x = Tensor(jax.lax.stop_gradient(x._data)) \
            if isinstance(x, Tensor) else jax.lax.stop_gradient(x)
        return self.dropout(self.layer_norm(x))

    ErnieEmbeddings.forward = sg_fwd
    try:
        results["no_embed_bwd"] = time_fn(build_step(full_loss), x, y)
    finally:
        ErnieEmbeddings.forward = orig_fwd

    # fwd+bwd only (no optimizer): grads via jax directly
    step = build_step(full_loss)
    step(x, y)  # init opt state/tree
    import jax as _jax
    # reuse the TrainStep's internals: time a value_and_grad-only jit
    from paddle_tpu.jit.api import functional_call, _wrap, _unwrap
    names = [n for n, _ in model.named_parameters()]
    vals = [p.data for _, p in model.named_parameters()]

    @_jax.jit
    def fwd_bwd(vals, xx, yy):
        def loss_of(vs):
            pdict = dict(zip(names, vs))
            out = functional_call(model, pdict, _wrap(xx))
            return _unwrap(model.loss(out, _jax.tree_util.tree_map(
                _wrap, yy)))
        return _jax.value_and_grad(loss_of)(vals)

    xx = x.data
    yy = (y[0].data, y[1].data)
    results["fwd_bwd_only"] = time_fn(fwd_bwd, vals, xx, yy)

    print()
    for k, v in results.items():
        print(f"{k:>14}: {v:8.2f} ms")
    fullt = results["full"]
    print(f"\n  sop cost       ~ {fullt - results['no_sop']:.2f} ms")
    print(f"  mlm head cost  ~ {fullt - results['no_mlm']:.2f} ms")
    print(f"  embed bwd cost ~ {fullt - results['no_embed_bwd']:.2f} ms")
    print(f"  optimizer cost ~ {fullt - results['fwd_bwd_only']:.2f} ms")


if __name__ == "__main__":
    main()
