"""Measure the serving path (the AnalysisPredictor analog): ResNet-50
eval through inference.Config/create_predictor — fp32 vs bf16 vs
int8-compute, batch 1 and 32.

CAVEAT (measured 2026-07-31): on the axon-TUNNELED chip every
pred.run() is a remote host round-trip (~150 ms floor at b1, input
upload dominating at b32), so WALL-CLOCK numbers measure the tunnel,
not the predictor. The r5 `--device-time` mode sidesteps this with
paddle_tpu.inference.device_time_per_run (scan-slope extraction: the
predict program runs N times inside one dispatch as a dependent chain;
the slope over two N cancels the fixed dispatch cost exactly) — those
ARE honest per-inference device times and feed the BASELINE serving
row. Wall-clock mode stays for real (untunneled) TPU hosts.

Usage: python experiments/predictor_serving_bench.py [--device-time]
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.inference import Config, PrecisionType, create_predictor

ITERS = 30


def bench(pred, x):
    out = pred.run([x])
    np.asarray(out[0]).sum()
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = pred.run([x])
    np.asarray(out[0]).sum()
    return (time.perf_counter() - t0) / ITERS


def main():
    device_time = "--device-time" in sys.argv
    from paddle_tpu.models.resnet import resnet50
    paddle.seed(0)
    model = resnet50(num_classes=1000, data_format="NHWC")
    model.eval()
    rng = np.random.RandomState(0)

    for batch in (1, 32):
        x = rng.randn(batch, 3, 224, 224).astype(np.float32)
        xt = paddle.to_tensor(x)
        results = []
        for tag, setup in (
            ("fp32", lambda c: None),
            ("bf16", lambda c: c.enable_tpu(
                precision=PrecisionType.Bfloat16)),
            ("bf16+int8", lambda c: (c.enable_tpu(
                precision=PrecisionType.Bfloat16),
                c.enable_int8_compute())),
        ):
            cfg = Config().from_layer(model, input_spec=[xt])
            setup(cfg)
            try:
                pred = create_predictor(cfg)
                if device_time:
                    from paddle_tpu.inference import device_time_per_run
                    dt = device_time_per_run(pred, [x])
                else:
                    dt = bench(pred, x)
                results.append(
                    f"{tag} {dt * 1e3:6.2f} ms ({batch / dt:7.1f} img/s)")
            except Exception as e:  # noqa: BLE001
                results.append(f"{tag} FAILED {type(e).__name__}: "
                               f"{str(e)[:60]}")
        print(f"b{batch}: " + " | ".join(results))


if __name__ == "__main__":
    main()
