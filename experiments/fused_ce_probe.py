"""Measure the Pallas fused linear+CE kernel against the save-logits
and chunked-remat loss-head baselines at the bench shapes (grad wrt
hidden + tied W, mean-over-valid loss), real chip, in-program repeats
via the dependent-carry harness.

Usage: python experiments/fused_ce_probe.py
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.kernels.fused_ce import fused_linear_ce

H, V = 768, 50257
ITERS = 10


def save_logits_loss(hs, ys, w):
    logits = (hs @ w.T.astype(hs.dtype)).astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(
        logits, jnp.maximum(ys, 0)[..., None], axis=-1)[..., 0]
    valid = (ys >= 0).astype(jnp.float32)
    return jnp.sum((lse - gold) * valid) / jnp.maximum(jnp.sum(valid), 1.0)


def remat_chunk_loss(chunk):
    def loss(hs, ys, w):
        b, s1, hd = hs.shape
        n_chunks = -(-s1 // chunk)
        pad = n_chunks * chunk - s1
        hs = jnp.pad(hs, ((0, 0), (0, pad), (0, 0)))
        ys = jnp.pad(ys, ((0, 0), (0, pad)), constant_values=-1)
        hsc = hs.reshape(b, n_chunks, chunk, hd).transpose(1, 0, 2, 3)
        ysc = ys.reshape(b, n_chunks, chunk).transpose(1, 0, 2)

        def chunk_ce(hc, yc):
            logits = (hc @ w.T.astype(hc.dtype)).astype(jnp.float32)
            lse = jax.nn.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(
                logits, jnp.maximum(yc, 0)[..., None], axis=-1)[..., 0]
            valid = (yc >= 0).astype(jnp.float32)
            return jnp.sum((lse - gold) * valid), jnp.sum(valid)

        def body(carry, xs):
            ssum, cnt = jax.checkpoint(chunk_ce)(*xs)
            return (carry[0] + ssum, carry[1] + cnt), None

        (t, c), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
            (hsc, ysc))
        return t / jnp.maximum(c, 1.0)
    return loss


def make_bf16_residual_loss():
    """Explicit-residual CE: save ONLY the bf16 logits (+ lse) for
    backward — half the residual memory of fp32 save-logits, XLA-peak
    matmuls in both passes, softmax recomputed elementwise from the
    saved bf16 logits."""

    @jax.custom_vjp
    def ce_rows(hs2, w, y2):
        logits16 = hs2 @ w.T.astype(hs2.dtype)
        lf = logits16.astype(jnp.float32)
        lse = jax.nn.logsumexp(lf, axis=-1)
        gold = jnp.take_along_axis(
            lf, jnp.maximum(y2, 0)[:, None], axis=-1)[:, 0]
        return jnp.where(y2 >= 0, lse - gold, 0.0)

    def fwd(hs2, w, y2):
        logits16 = hs2 @ w.T.astype(hs2.dtype)
        lf = logits16.astype(jnp.float32)
        lse = jax.nn.logsumexp(lf, axis=-1)
        gold = jnp.take_along_axis(
            lf, jnp.maximum(y2, 0)[:, None], axis=-1)[:, 0]
        ce = jnp.where(y2 >= 0, lse - gold, 0.0)
        return ce, (hs2, w, y2, logits16, lse)

    def bwd(res, dce):
        hs2, w, y2, logits16, lse = res
        s = jnp.where(y2 >= 0, dce, 0.0).astype(jnp.float32)
        p = jnp.exp(logits16.astype(jnp.float32) - lse[:, None])
        onehot = (jax.lax.broadcasted_iota(jnp.int32, p.shape, 1)
                  == y2[:, None])
        d16 = ((p - onehot.astype(jnp.float32)) * s[:, None]
               ).astype(hs2.dtype)
        dh = d16 @ w.astype(hs2.dtype)
        dw = jax.lax.dot_general(
            d16, hs2, (((0,), (0,)), ((), ()))).astype(w.dtype)
        return dh, dw, None

    ce_rows.defvjp(fwd, bwd)

    def loss(hs, ys, w):
        b, s1, hd = hs.shape
        ce = ce_rows(hs.reshape(b * s1, hd), w, ys.reshape(-1))
        valid = (ys.reshape(-1) >= 0).astype(jnp.float32)
        return jnp.sum(ce) / jnp.maximum(jnp.sum(valid), 1.0)
    return loss


def kernel_loss(bn, bv):
    def loss(hs, ys, w):
        b, s1, hd = hs.shape
        ce = fused_linear_ce(hs.reshape(b * s1, hd), w,
                             ys.reshape(b * s1), True, bn, bv)
        valid = (ys.reshape(-1) >= 0).astype(jnp.float32)
        return jnp.sum(ce) / jnp.maximum(jnp.sum(valid), 1.0)
    return loss


def bench(loss_fn, hs, ys, w):
    g = jax.value_and_grad(loss_fn, argnums=(0, 2))

    def prog(hs, ys, w):
        def f(carry, _):
            h_c, w_c = carry
            val, (dh, dw) = g(h_c, ys, w_c)
            return (h_c + dh.astype(h_c.dtype) * 1e-6,
                    w_c + dw.astype(w_c.dtype) * 1e-6), val
        (_, _), vals = jax.lax.scan(f, (hs, w), None, length=ITERS)
        return vals[-1]

    fn = jax.jit(prog)
    out = fn(hs, ys, w)
    float(out)
    t0 = time.perf_counter()
    out = fn(hs, ys, w)
    v = float(out)
    return (time.perf_counter() - t0) / ITERS, v


def main():
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(V, H) * 0.02, jnp.bfloat16)
    for tag, b, s1 in (("b16-s1024", 16, 1023), ("b32-s1024", 32, 1023),
                      ("b16-s2048", 16, 2047)):
        hs = jnp.asarray(rng.randn(b, s1, H), jnp.bfloat16)
        ys = jnp.asarray(rng.randint(0, V, (b, s1)), jnp.int32)
        print(tag)
        fits = b * s1 * V * 4 <= 4 << 30
        if fits:
            t, v = bench(save_logits_loss, hs, ys, w)
            print(f"  save-logits      : {t*1e3:7.2f} ms (loss {v:.4f})")
        t, v = bench(remat_chunk_loss(max(8192 // b, 128)), hs, ys, w)
        print(f"  remat-chunk      : {t*1e3:7.2f} ms (loss {v:.4f})")
        t, v = bench(make_bf16_residual_loss(), hs, ys, w)
        print(f"  bf16-residual    : {t*1e3:7.2f} ms (loss {v:.4f})")
        for bn, bv in ((512, 1024),):
            try:
                t, v = bench(kernel_loss(bn, bv), hs, ys, w)
                print(f"  kernel {bn:4d}/{bv:<4d} : {t*1e3:7.2f} ms "
                      f"(loss {v:.4f})")
            except Exception as e:  # noqa: BLE001
                print(f"  kernel {bn}/{bv} FAILED {type(e).__name__}: "
                      f"{str(e)[:100]}")


if __name__ == "__main__":
    main()
