"""Compare the in-tree flash attention kernel vs jax's reference TPU
flash-attention Pallas kernel, fwd+bwd, at the bench model shapes —
in-program scan repeats so the axon tunnel dispatch cost is amortized.

Usage: python experiments/flash_vs_jax.py
"""
from __future__ import annotations

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from paddle_tpu.kernels.flash_attention import flash_attention as ours

from jax.experimental.pallas.ops.tpu.flash_attention import (
    flash_attention as jax_fa, BlockSizes)

REPS = 10


def bench_scan(grad_fn, q, k, v):
    """Chain REPS grad evaluations (dq feeds the next q) so XLA cannot
    hoist them; one device program, one fence."""

    def prog(q, k, v):
        def f(carry, _):
            dq, dk, dv = grad_fn(carry, k, v)
            upd = (dq + dk + dv).astype(carry.dtype)  # keep all 3 live
            return carry + upd * 1e-6, None
        out, _ = jax.lax.scan(f, q, None, length=REPS)
        return out

    fn = jax.jit(prog)
    out = fn(q, k, v)
    float(jnp.sum(out.astype(jnp.float32)))
    t0 = time.perf_counter()
    out = fn(q, k, v)
    float(jnp.sum(out.astype(jnp.float32)))
    return (time.perf_counter() - t0) / REPS


def run(tag, b, h, s, d, causal):
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, h, d), jnp.bfloat16)  # ours layout
    k = jax.random.normal(kk, (b, s, h, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, s, h, d), jnp.bfloat16)
    qt, kt, vt = (jnp.swapaxes(a, 1, 2) for a in (q, k, v))  # jax layout

    def loss_ours(q, k, v):
        return ours(q, k, v, causal=causal).astype(jnp.float32).sum()

    def make_loss_jax(bq, bkmaj, bk):
        bs = BlockSizes(
            block_q=bq, block_k_major=bkmaj, block_k=bk, block_b=1,
            block_q_major_dkv=bq, block_k_major_dkv=bkmaj,
            block_k_dkv=bk, block_q_dkv=bq,
            block_k_major_dq=bkmaj, block_k_dq=bk, block_q_dq=bq)

        def loss(q, k, v):
            return jax_fa(q, k, v, causal=causal, sm_scale=1.0 / d ** 0.5,
                          block_sizes=bs).astype(jnp.float32).sum()
        return loss

    print(f"{tag}: b{b} h{h} s{s} d{d} causal={causal}")
    t = bench_scan(jax.grad(loss_ours, argnums=(0, 1, 2)), q, k, v)
    print(f"  {'ours':>18}: {t * 1e3:8.2f} ms")
    for bq in (256, 512, 1024):
        for bk in (256, 512, 1024):
            if bq > s or bk > s:
                continue
            try:
                t = bench_scan(
                    jax.grad(make_loss_jax(bq, bk, bk), argnums=(0, 1, 2)),
                    qt, kt, vt)
                print(f"  jax({bq}/{bk})".rjust(20) + f": {t * 1e3:8.2f} ms")
            except Exception as e:  # noqa: BLE001
                print(f"  jax {bq}/{bk} failed: {type(e).__name__}: {e}")


if __name__ == "__main__":
    print("devices:", jax.devices())
    run("ernie-s512", 32, 12, 512, 64, False)
    run("gpt2-s1024", 16, 12, 1024, 64, True)
