"""End-to-end flagship bench with the in-tree flash kernel vs jax's
reference TPU flash kernel as the attention backend. Decides whether
the jax kernel's s1024 microbench edge is real in the full program.

Usage: python experiments/bench_attn_backend.py [jax|ours]
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp


def patch_jax_backend():
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as jax_fa, BlockSizes)
    import paddle_tpu.kernels.flash_attention as fa_mod

    def flash_attention(query, key, value, causal=False, scale=None,
                        block_q=1024, block_k=1024):
        b, s, h, d = query.shape
        if scale is None:
            scale = 1.0 / (d ** 0.5)
        bq = min(1024, s)
        bk = min(1024, s)
        bs = BlockSizes(
            block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
            block_q_major_dkv=bq, block_k_major_dkv=bk,
            block_k_dkv=bk, block_q_dkv=bq,
            block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq)
        qt = jnp.swapaxes(query, 1, 2)
        kt = jnp.swapaxes(key, 1, 2)
        vt = jnp.swapaxes(value, 1, 2)
        out = jax_fa(qt, kt, vt, causal=causal, sm_scale=float(scale),
                     block_sizes=bs)
        return jnp.swapaxes(out, 1, 2)

    fa_mod.flash_attention = flash_attention


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "ours"
    if which == "jax":
        patch_jax_backend()
    import bench
    dev, on_tpu = bench._setup()
    res = bench.bench_gpt2(dev, on_tpu)
    res["backend"] = which
    print(json.dumps(res))


if __name__ == "__main__":
    main()
