"""Decompose the flagship GPT-2 b16 s1024 train step on the real chip:
forward-only vs forward+backward vs full step (optimizer cost), and
12- vs 6-layer variants to split per-layer trunk cost from the fixed
embedding + fused-LM-loss cost.

Usage: python experiments/gpt2_step_breakdown.py
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax

import paddle_tpu as paddle
from paddle_tpu import optimizer
from paddle_tpu.jit.api import functional_call, _wrap, _unwrap
from paddle_tpu.models.gpt import gpt

BATCH, SEQ, ITERS = 16, 1024, 20


def time_fn(fn, *args):
    out = fn(*args)
    loss = jax.tree_util.tree_leaves(out)[0]
    float(np.asarray(loss, dtype=np.float32).ravel()[0])
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fn(*args)
    loss = jax.tree_util.tree_leaves(out)[0]
    float(np.asarray(loss, dtype=np.float32).ravel()[0])
    return (time.perf_counter() - t0) / ITERS


def main():
    rng = np.random.RandomState(0)
    for layers in (12, 6):
        paddle.seed(0)
        chunk = max(8192 // BATCH, 128)
        model = gpt("gpt2-small", max_position_embeddings=SEQ,
                    fused_lm_loss=True, lm_loss_chunk=chunk,
                    num_layers=layers)
        model.bfloat16()
        names = [n for n, _ in model.named_parameters()]
        pvals = [p._data for _, p in model.named_parameters()]

        ids = rng.randint(0, model.cfg.vocab_size,
                          (BATCH, SEQ)).astype(np.int32)
        x = np.asarray(ids)
        y = ids.astype(np.int64)

        def loss_of(plist, x, y):
            pdict = dict(zip(names, plist))
            out = functional_call(model, pdict, _wrap(x))
            return _unwrap(model.loss(out, _wrap(y)))

        fwd = jax.jit(loss_of)
        t_fwd = time_fn(fwd, pvals, x, y)

        grad_fn = jax.jit(jax.value_and_grad(loss_of))
        t_grad = time_fn(grad_fn, pvals, x, y)

        opt = optimizer.AdamW(learning_rate=1e-4,
                              parameters=model.parameters(),
                              multi_precision=True)
        step = paddle.jit.TrainStep(
            model, opt, lambda logits, labels: model.loss(logits, labels))
        xt = paddle.to_tensor(ids)
        yt = paddle.to_tensor(y)
        t_step = time_fn(step, xt, yt)
        print(f"layers={layers:2d}: fwd {t_fwd*1e3:7.2f} | fwd+bwd "
              f"{t_grad*1e3:7.2f} | full step {t_step*1e3:7.2f} ms")


if __name__ == "__main__":
    main()
