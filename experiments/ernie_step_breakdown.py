"""Decompose the ERNIE-Base b32 s512 train step (the north-star config):
fwd vs fwd+bwd vs full step, 12- vs 6-layer variants, and flash on/off.

Usage: python experiments/ernie_step_breakdown.py
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax

import paddle_tpu as paddle
from paddle_tpu import optimizer
from paddle_tpu.jit.api import functional_call, _wrap, _unwrap
from paddle_tpu.models.ernie import ernie

BATCH, SEQ, ITERS = 32, 512, 20


def time_fn(fn, *args):
    out = fn(*args)
    loss = jax.tree_util.tree_leaves(out)[0]
    float(np.asarray(loss, dtype=np.float32).ravel()[0])
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fn(*args)
    loss = jax.tree_util.tree_leaves(out)[0]
    float(np.asarray(loss, dtype=np.float32).ravel()[0])
    return (time.perf_counter() - t0) / ITERS


def main():
    rng = np.random.RandomState(0)
    for layers in (12, 6):
        paddle.seed(0)
        model = ernie("ernie-3.0-base", fused_mlm_loss=True,
                      max_predictions=max(int(SEQ * 0.19), 8),
                      num_layers=layers)
        model.bfloat16()
        names = [n for n, _ in model.named_parameters()]
        pvals = [p._data for _, p in model.named_parameters()]

        ids = rng.randint(0, model.cfg.vocab_size,
                          (BATCH, SEQ)).astype(np.int32)
        mlm = ids.astype(np.int64)
        mlm[rng.rand(*mlm.shape) > 0.15] = -100
        sop = rng.randint(0, 2, (BATCH,)).astype(np.int64)

        def loss_of(plist, x, y1, y2):
            pdict = dict(zip(names, plist))
            out = functional_call(model, pdict, _wrap(x))
            return _unwrap(model.loss(out, (_wrap(y1), _wrap(y2))))

        fwd = jax.jit(loss_of)
        t_fwd = time_fn(fwd, pvals, ids, mlm, sop)
        grad_fn = jax.jit(jax.value_and_grad(loss_of))
        t_grad = time_fn(grad_fn, pvals, ids, mlm, sop)

        opt = optimizer.AdamW(learning_rate=1e-4,
                              parameters=model.parameters(),
                              multi_precision=True)
        step = paddle.jit.TrainStep(
            model, opt, lambda out, lab: model.loss(out, lab))
        x = paddle.to_tensor(ids)
        y = (paddle.to_tensor(mlm), paddle.to_tensor(sop))
        t_step = time_fn(step, x, y)
        print(f"layers={layers:2d}: fwd {t_fwd*1e3:7.2f} | fwd+bwd "
              f"{t_grad*1e3:7.2f} | full step {t_step*1e3:7.2f} ms")


if __name__ == "__main__":
    main()
