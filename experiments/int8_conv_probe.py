"""Is the 'XLA:TPU upcasts int8 convolutions' wall real? (VERDICT r3
Weak #7 — the documented limitation in quantization/int8_compute.py
had no in-tree measurement.)

Three timings on the real chip, in-program scan repeats (tunnel
dispatch amortized), device-resident operands:
  1. bf16 conv_general_dilated        (the production path)
  2. int8-input conv_general_dilated with preferred int32 accumulation
     (what XLA does with it is the question)
  3. int8 1x1 conv recast as the known-good int8 MXU matmul
     (the escape hatch: a 1x1 conv IS a matmul)
Shapes: ResNet layer3-ish 1x1 conv (b128 14x14x1024 -> 256) where the
MXU is the binding resource.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

REPS = 30


def timed_chain(step, x0, w):
    """Dependent chain: carry the activation, so no iteration can be
    hoisted/CSE'd out of the scan."""

    def prog(x, wv):
        def f(carry, _):
            return step(carry, wv), None
        out, _ = jax.lax.scan(f, x, None, length=REPS)
        return out

    fn = jax.jit(prog)
    out = fn(x0, w)
    float(jnp.sum(out.astype(jnp.float32)))       # compile + fence
    t0 = time.perf_counter()
    out = fn(x0, w)
    float(jnp.sum(out.astype(jnp.float32)))
    return (time.perf_counter() - t0) / REPS


def main():
    rng = np.random.RandomState(0)
    n, h, w_, c = 128, 14, 14, 1024
    xf = jax.device_put(jnp.asarray(
        rng.randn(n, h, w_, c).astype(np.float32))).astype(jnp.bfloat16)
    wf = jax.device_put(jnp.asarray(
        (rng.randn(1, 1, c, c) * 0.03).astype(np.float32))
    ).astype(jnp.bfloat16)
    xi = jax.device_put(jnp.asarray(
        rng.randint(-127, 127, (n, h, w_, c)).astype(np.int8)))
    wi = jax.device_put(jnp.asarray(
        rng.randint(-127, 127, (1, 1, c, c)).astype(np.int8)))

    def conv_bf16(x, wv):
        y = jax.lax.conv_general_dilated(
            x, wv, (1, 1), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.float32)
        return y.astype(jnp.bfloat16)

    def conv_int8(x, wv):
        y = jax.lax.conv_general_dilated(
            x, wv, (1, 1), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.int32)
        # requantize back to int8 (shift approximates the scale)
        return (y >> 8).astype(jnp.int8)

    def mm_int8(x, wv):
        x2 = x.reshape(-1, c)
        w2 = wv.reshape(c, c)
        y = jax.lax.dot_general(
            x2, w2, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        return ((y >> 8).astype(jnp.int8)).reshape(x.shape)

    flops = 2.0 * n * h * w_ * c * c
    for name, f, a, b in [("conv bf16", conv_bf16, xf, wf),
                          ("conv int8->int32", conv_int8, xi, wi),
                          ("1x1-as-int8-matmul", mm_int8, xi, wi)]:
        try:
            dt = timed_chain(f, a, b)
            print(f"{name:22s} {dt * 1e6:9.1f} us   "
                  f"{flops / dt / 1e12:7.1f} T(op|flop)/s", flush=True)
        except Exception as e:
            print(f"{name:22s} FAILED: {type(e).__name__}: "
                  f"{str(e)[:120]}", flush=True)
    # what does XLA actually emit for the int8 conv? look for
    # a convert before the convolution
    hlo = jax.jit(conv_int8).lower(xi, wi).compile().as_text()
    upcast = "convert" in hlo.split("convolution")[0][-600:] \
        if "convolution" in hlo else None
    print(f"int8 conv HLO: {'upcast convert before conv' if upcast else 'direct int8 convolution'}")


if __name__ == "__main__":
    main()
