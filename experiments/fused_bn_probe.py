"""Probe: where does the fused conv+BN path lose time vs XLA?

Times, on the real chip (host-transfer fenced, in-program scan repeats
to amortize the ~1.3 ms tunnel dispatch):
  1. Pallas matmul_bn_stats vs XLA (1x1 conv + separate stats) — fwd
  2. the same, fwd+bwd through the stats consumers
  3. one layer1 bottleneck block fwd+bwd, fused vs unfused
"""
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

REPS = 10


def timeit(fn, *args):
    fn_j = jax.jit(fn)
    out = fn_j(*args)
    jax.tree_util.tree_map(
        lambda a: np.asarray(jax.device_get(a)).ravel()[:1], out)
    t0 = time.perf_counter()
    out = fn_j(*args)
    s = jax.tree_util.tree_leaves(out)[0]
    float(jnp.sum(s))  # host fence
    return (time.perf_counter() - t0)


def scan_rep(body, x):
    """Run body REPS times inside the program; returns summed output."""
    def f(carry, _):
        return carry, jnp.sum(body(x))
    _, ys = jax.lax.scan(f, 0, None, length=REPS)
    return ys


def main():
    from paddle_tpu.kernels.fused_resnet import (matmul_bn_stats,
                                                 bn_relu_matmul_bn_stats)
    rng = np.random.RandomState(0)
    # layer1 conv3 shape: M=401408, K=64, N=256
    M, K, N = 128 * 56 * 56, 64, 256
    x = jax.device_put(jnp.asarray(
        rng.randn(M, K).astype(np.float32), ), jax.devices()[0]).astype(jnp.bfloat16)
    w = jax.device_put(jnp.asarray(
        rng.randn(K, N).astype(np.float32))).astype(jnp.bfloat16)
    scale = jnp.ones((K,), jnp.float32)
    shift = jnp.zeros((K,), jnp.float32)

    def pallas_fwd(x):
        y, m, v = matmul_bn_stats(x, w)
        return jnp.sum(y.astype(jnp.float32)) + jnp.sum(m) + jnp.sum(v)

    def xla_fwd(x):
        y = jnp.dot(x, w, preferred_element_type=jnp.float32)
        yb = y.astype(jnp.bfloat16)
        yf = yb.astype(jnp.float32)
        m = jnp.mean(yf, axis=0)
        v = jnp.mean(yf * yf, axis=0) - m * m
        return jnp.sum(yf) + jnp.sum(m) + jnp.sum(v)

    def pallas_prologue_fwd(x):
        y, m, v = bn_relu_matmul_bn_stats(x, scale, shift, w)
        return jnp.sum(y.astype(jnp.float32)) + jnp.sum(m) + jnp.sum(v)

    def xla_prologue_fwd(x):
        a = jnp.maximum(x.astype(jnp.float32) * scale + shift, 0.0)
        y = jnp.dot(a.astype(jnp.bfloat16), w,
                    preferred_element_type=jnp.float32)
        yb = y.astype(jnp.bfloat16).astype(jnp.float32)
        m = jnp.mean(yb, axis=0)
        v = jnp.mean(yb * yb, axis=0) - m * m
        return jnp.sum(yb) + jnp.sum(m) + jnp.sum(v)

    for name, f in [("pallas_fwd", pallas_fwd), ("xla_fwd", xla_fwd),
                    ("pallas_pro_fwd", pallas_prologue_fwd),
                    ("xla_pro_fwd", xla_prologue_fwd)]:
        dt = timeit(lambda x: scan_rep(f, x), x)
        print(f"{name:18s} {dt / REPS * 1e3:8.3f} ms")

    for name, f in [("pallas_fwdbwd", pallas_fwd), ("xla_fwdbwd", xla_fwd),
                    ("pallas_pro_fb", pallas_prologue_fwd),
                    ("xla_pro_fb", xla_prologue_fwd)]:
        g = jax.grad(f)
        dt = timeit(lambda x: scan_rep(lambda x: jnp.sum(g(x)), x), x)
        print(f"{name:18s} {dt / REPS * 1e3:8.3f} ms")


if __name__ == "__main__":
    main()
