"""Locate the s1024-causal gap vs jax's reference flash kernel:
time forward-only and fwd+bwd separately, scan-amortized.
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from paddle_tpu.kernels.flash_attention import flash_attention as ours
from jax.experimental.pallas.ops.tpu.flash_attention import (
    flash_attention as jax_fa, BlockSizes)

REPS = 10
B, H, S, D = 16, 12, 1024, 64


def timeit(fn, *args):
    f = jax.jit(fn)
    out = f(*args)
    float(jnp.sum(out.astype(jnp.float32)))
    t0 = time.perf_counter()
    out = f(*args)
    float(jnp.sum(out.astype(jnp.float32)))
    return (time.perf_counter() - t0) / REPS


def main():
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(kk, (B, S, H, D), jnp.bfloat16)
    v = jax.random.normal(kv, (B, S, H, D), jnp.bfloat16)
    qt, kt, vt = (jnp.swapaxes(a, 1, 2) for a in (q, k, v))

    bs = BlockSizes(
        block_q=1024, block_k_major=1024, block_k=1024, block_b=1,
        block_q_major_dkv=1024, block_k_major_dkv=1024,
        block_k_dkv=1024, block_q_dkv=1024,
        block_k_major_dq=1024, block_k_dq=1024, block_q_dq=1024)

    def fwd_ours(q):
        def f(c, _):
            o = ours(c, k, v, causal=True)
            return c + o.astype(c.dtype) * 1e-6, None
        return jax.lax.scan(f, q, None, length=REPS)[0]

    def fwd_jax(q):
        def f(c, _):
            o = jax_fa(c, kt, vt, causal=True, sm_scale=D ** -0.5,
                       block_sizes=bs)
            return c + o.astype(c.dtype) * 1e-6, None
        return jax.lax.scan(f, q, None, length=REPS)[0]

    def g_ours(q):
        gf = jax.grad(lambda q, k, v: ours(
            q, k, v, causal=True).astype(jnp.float32).sum(),
            argnums=(0, 1, 2))

        def f(c, _):
            dq, dk, dv = gf(c, k, v)
            return c + (dq + dk + dv).astype(c.dtype) * 1e-6, None
        return jax.lax.scan(f, q, None, length=REPS)[0]

    def g_jax(q):
        gf = jax.grad(lambda q, k, v: jax_fa(
            q, k, v, causal=True, sm_scale=D ** -0.5,
            block_sizes=bs).astype(jnp.float32).sum(), argnums=(0, 1, 2))

        def f(c, _):
            dq, dk, dv = gf(c, kt, vt)
            return c + (dq + dk + dv).astype(c.dtype) * 1e-6, None
        return jax.lax.scan(f, q, None, length=REPS)[0]

    for _ in range(2):  # two passes to see run variance
        tfo = timeit(fwd_ours, q)
        tfj = timeit(fwd_jax, qt)
        tgo = timeit(g_ours, q)
        tgj = timeit(g_jax, qt)
        print(f"fwd: ours {tfo*1e3:6.2f}  jax {tfj*1e3:6.2f} | "
              f"fwd+bwd: ours {tgo*1e3:6.2f}  jax {tgj*1e3:6.2f} | "
              f"bwd-only est: ours {(tgo-tfo)*1e3:6.2f} jax {(tgj-tfj)*1e3:6.2f}")


if __name__ == "__main__":
    main()
