"""paddle.audio.datasets analog (reference
python/paddle/audio/datasets/{dataset,esc50,tess}.py): audio
classification datasets over local extracted archives (zero-egress —
download=True raises with instructions), items are (feature, label)
with feat_type raw/spectrogram/melspectrogram/logmelspectrogram/mfcc
riding the in-tree feature extractors."""
from __future__ import annotations

import collections
import csv
import os
from typing import List, Optional, Tuple

import numpy as np

from ..core.tensor import Tensor
from ..io.dataset import Dataset
from .backends import load as _load_wav

__all__ = ["AudioClassificationDataset", "ESC50", "TESS"]


def _feat_funcs():
    from .features import (LogMelSpectrogram, MelSpectrogram, MFCC,
                           Spectrogram)
    return {"raw": None, "melspectrogram": MelSpectrogram,
            "mfcc": MFCC, "logmelspectrogram": LogMelSpectrogram,
            "spectrogram": Spectrogram}


class AudioClassificationDataset(Dataset):
    """Base class (reference audio/datasets/dataset.py:32): files +
    int labels; feat_type selects the transform applied per item."""

    def __init__(self, files: List[str], labels: List[int],
                 feat_type: str = "raw", sample_rate: int = None,
                 **kwargs):
        super().__init__()
        funcs = _feat_funcs()
        if feat_type not in funcs:
            raise RuntimeError(
                f"Unknown feat_type: {feat_type}, it must be one in "
                f"{list(funcs)}")
        self.files = files
        self.labels = labels
        self.feat_type = feat_type
        self.sample_rate = sample_rate
        self.feat_config = kwargs
        self._extractor = None  # built lazily ONCE (filterbanks/DCT)

    def _get_extractor(self, sr: int):
        if self._extractor is None:
            import inspect
            func_cls = _feat_funcs()[self.feat_type]
            kwargs = dict(self.feat_config)
            if "sr" in inspect.signature(func_cls.__init__).parameters:
                kwargs.setdefault("sr", self.sample_rate or sr)
            self._extractor = func_cls(**kwargs)
        return self._extractor

    def _convert_to_record(self, idx: int):
        file, label = self.files[idx], self.labels[idx]
        waveform, sr = _load_wav(file)
        w = waveform.data[0]                      # mono channel
        if _feat_funcs()[self.feat_type] is None:
            feat = np.asarray(w, np.float32)
        else:
            extractor = self._get_extractor(sr)
            feat = np.asarray(
                extractor(Tensor(w[None, :])).data[0], np.float32)
        return feat, np.array(label, np.int64)

    def __getitem__(self, idx):
        return self._convert_to_record(idx)

    def __len__(self):
        return len(self.files)


from ..io.dataset import no_download_gate as _no_download  # noqa: E402


class ESC50(AudioClassificationDataset):
    """ESC-50 environmental sounds (reference esc50.py:26): 5-fold
    layout from the ESC-50-master directory (meta/esc50.csv + audio/),
    mode 'train' excludes the split fold, 'dev' keeps it."""

    meta_info = collections.namedtuple(
        "META_INFO", ("filename", "fold", "target", "category",
                      "esc10", "src_file", "take"))

    def __init__(self, mode: str = "train", split: int = 1,
                 feat_type: str = "raw",
                 data_dir: Optional[str] = None, **kwargs):
        if data_dir is None:
            _no_download(type(self).__name__)
        root = os.path.join(data_dir, "ESC-50-master")
        if not os.path.isdir(root):
            root = data_dir
        files, labels = self._get_data(root, mode, split)
        super().__init__(files=files, labels=labels,
                         feat_type=feat_type, **kwargs)

    def _get_data(self, root, mode, split) -> Tuple[List[str],
                                                    List[int]]:
        meta = os.path.join(root, "meta", "esc50.csv")
        files, labels = [], []
        with open(meta) as f:
            rows = list(csv.reader(f))[1:]
        for row in rows:
            info = self.meta_info(*row[:7])
            keep = int(info.fold) != split if mode == "train" \
                else int(info.fold) == split
            if keep:
                files.append(os.path.join(root, "audio", info.filename))
                labels.append(int(info.target))
        return files, labels


class TESS(AudioClassificationDataset):
    """TESS emotional speech (reference tess.py): wav files named
    <speaker>_<word>_<emotion>.wav under the standard extracted dir;
    n_folds cross-validation split as in the reference."""

    archive_dir = "TESS_Toronto_emotional_speech_set"
    emotions = ["angry", "disgust", "fear", "happy", "neutral", "ps",
                "sad"]

    def __init__(self, mode: str = "train", n_folds: int = 5,
                 split: int = 1, feat_type: str = "raw",
                 data_dir: Optional[str] = None, **kwargs):
        assert split <= n_folds, (
            f"The selected split should not be larger than n_fold, "
            f"but got {split} > {n_folds}")
        if data_dir is None:
            _no_download(type(self).__name__)
        root = os.path.join(data_dir, self.archive_dir)
        if not os.path.isdir(root):
            root = data_dir
        files, labels = self._get_data(root, mode, n_folds, split)
        super().__init__(files=files, labels=labels,
                         feat_type=feat_type, **kwargs)

    def _get_data(self, root, mode, n_folds, split):
        wav_files = []
        for r, _, fs in os.walk(root):
            for f in sorted(fs):
                if f.endswith(".wav"):
                    wav_files.append(os.path.join(r, f))
        # filter to known emotions FIRST, then fold over the kept
        # files; clamp so remainder files land in the last fold rather
        # than a phantom fold no split ever selects
        kept = [(p, os.path.basename(p)[:-4].split("_")[-1])
                for p in wav_files]
        kept = [(p, e) for p, e in kept if e in self.emotions]
        files, labels = [], []
        n_per_fold = max(len(kept) // n_folds, 1)
        for idx, (path, emotion) in enumerate(kept):
            fold = min(idx // n_per_fold + 1, n_folds)
            keep = fold != split if mode == "train" else fold == split
            if keep:
                files.append(path)
                labels.append(self.emotions.index(emotion))
        return files, labels
