"""paddle.audio analog — audio feature extraction.

Reference: python/paddle/audio/ (features/layers.py: Spectrogram,
MelSpectrogram, LogMelSpectrogram, MFCC; functional.py: hz_to_mel,
mel_to_hz, compute_fbank_matrix, create_dct, power_to_db). Built on
paddle_tpu.signal.stft; note the tunneled axon backend lacks complex
FFT — run feature extraction on the CPU backend or real TPU.
"""
from . import backends, datasets, functional  # noqa: F401
from .backends import info, load, save  # noqa: F401
from .features import (LogMelSpectrogram, MFCC,  # noqa: F401
                       MelSpectrogram, Spectrogram)
