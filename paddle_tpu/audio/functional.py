"""Audio functional ops (≈ python/paddle/audio/functional/functional.py)."""
from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["hz_to_mel", "mel_to_hz", "compute_fbank_matrix",
           "create_dct", "power_to_db", "get_window"]


def hz_to_mel(freq, htk: bool = False):
    """Slaney (default) or HTK mel scale, scalar or array."""
    f = np.asarray(freq, dtype=np.float64)
    if htk:
        out = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mels = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        mels = np.where(f >= min_log_hz,
                        min_log_mel + np.log(np.maximum(f, 1e-10)
                                             / min_log_hz) / logstep,
                        mels)
        out = mels
    return float(out) if np.isscalar(freq) else out


def mel_to_hz(mel, htk: bool = False):
    m = np.asarray(mel, dtype=np.float64)
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        freqs = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        freqs = np.where(m >= min_log_mel,
                         min_log_hz * np.exp(logstep
                                             * (m - min_log_mel)),
                         freqs)
        out = freqs
    return float(out) if np.isscalar(mel) else out


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0,
                         f_max: Optional[float] = None,
                         htk: bool = False,
                         norm: str = "slaney") -> np.ndarray:
    """[n_mels, n_fft//2 + 1] triangular mel filterbank."""
    f_max = f_max or sr / 2.0
    n_bins = n_fft // 2 + 1
    fft_freqs = np.linspace(0, sr / 2.0, n_bins)
    mel_pts = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk),
                          n_mels + 2)
    hz_pts = mel_to_hz(mel_pts, htk)
    fb = np.zeros((n_mels, n_bins))
    for i in range(n_mels):
        lo, center, hi = hz_pts[i], hz_pts[i + 1], hz_pts[i + 2]
        up = (fft_freqs - lo) / max(center - lo, 1e-10)
        down = (hi - fft_freqs) / max(hi - center, 1e-10)
        fb[i] = np.clip(np.minimum(up, down), 0, None)
    if norm == "slaney":
        enorm = 2.0 / (hz_pts[2:] - hz_pts[:-2])
        fb *= enorm[:, None]
    return fb.astype(np.float32)


def create_dct(n_mfcc: int, n_mels: int,
               norm: Optional[str] = "ortho") -> np.ndarray:
    """[n_mels, n_mfcc] DCT-II matrix."""
    n = np.arange(n_mels, dtype=np.float64)
    k = np.arange(n_mfcc, dtype=np.float64)
    dct = np.cos(math.pi / n_mels * (n[:, None] + 0.5) * k[None, :])
    if norm == "ortho":
        dct[:, 0] *= 1.0 / math.sqrt(2)
        dct *= math.sqrt(2.0 / n_mels)
    else:
        dct *= 2.0
    return dct.astype(np.float32)


def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                top_db: Optional[float] = 80.0):
    x = spect._data if isinstance(spect, Tensor) else jnp.asarray(spect)
    log_spec = 10.0 * jnp.log10(jnp.maximum(x, amin))
    log_spec = log_spec - 10.0 * math.log10(max(ref_value, amin))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
    return Tensor(log_spec) if isinstance(spect, Tensor) else log_spec


def get_window(window: str, win_length: int,
               fftbins: bool = True) -> np.ndarray:
    n = win_length
    if window in ("hann", "hanning"):
        # periodic (fftbins) vs symmetric
        m = n if fftbins else n - 1
        return (0.5 - 0.5 * np.cos(2 * math.pi * np.arange(n) /
                                   max(m, 1))).astype(np.float32)
    if window == "hamming":
        m = n if fftbins else n - 1
        return (0.54 - 0.46 * np.cos(2 * math.pi * np.arange(n) /
                                     max(m, 1))).astype(np.float32)
    if window in ("rect", "rectangular", "boxcar", "ones"):
        return np.ones(n, np.float32)
    if window == "blackman":
        m = n if fftbins else n - 1
        t = 2 * math.pi * np.arange(n) / max(m, 1)
        return (0.42 - 0.5 * np.cos(t) +
                0.08 * np.cos(2 * t)).astype(np.float32)
    raise ValueError(f"unsupported window {window!r}")
