"""Audio feature layers (≈ python/paddle/audio/features/layers.py)."""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer import Layer
from .functional import (compute_fbank_matrix, create_dct, get_window,
                         power_to_db)

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


def _raw(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


class Spectrogram(Layer):
    """|STFT|^power over [..., time] waveforms ->
    [..., n_fft//2+1, num_frames]."""

    def __init__(self, n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.register_buffer(
            "window", Tensor(jnp.asarray(
                get_window(window, self.win_length))))

    def forward(self, x):
        from ..signal import stft
        spec = stft(x, n_fft=self.n_fft, hop_length=self.hop_length,
                    win_length=self.win_length, window=self.window,
                    center=self.center, pad_mode=self.pad_mode)
        mag = jnp.abs(_raw(spec))
        if self.power != 1.0:
            mag = mag ** self.power
        return Tensor(mag)


class MelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: str = "slaney"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                       window, power)
        self.register_buffer(
            "fbank", Tensor(jnp.asarray(compute_fbank_matrix(
                sr, n_fft, n_mels, f_min, f_max, htk, norm))))

    def forward(self, x):
        spec = _raw(self.spectrogram(x))  # [..., bins, frames]
        mel = jnp.einsum("mb,...bt->...mt", _raw(self.fbank), spec)
        return Tensor(mel)


class LogMelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: Optional[float] = None):
        super().__init__()
        self.mel = MelSpectrogram(sr, n_fft, hop_length, win_length,
                                  window, power, n_mels, f_min, f_max)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return power_to_db(self.mel(x), self.ref_value, self.amin,
                           self.top_db)


class MFCC(Layer):
    def __init__(self, sr: int = 22050, n_mfcc: int = 40,
                 n_fft: int = 512, hop_length: Optional[int] = None,
                 n_mels: int = 64, f_min: float = 50.0,
                 f_max: Optional[float] = None,
                 top_db: Optional[float] = None):
        super().__init__()
        self.log_mel = LogMelSpectrogram(
            sr, n_fft, hop_length, n_mels=n_mels, f_min=f_min,
            f_max=f_max, top_db=top_db)
        self.register_buffer(
            "dct", Tensor(jnp.asarray(create_dct(n_mfcc, n_mels))))

    def forward(self, x):
        logmel = _raw(self.log_mel(x))  # [..., mels, frames]
        out = jnp.einsum("mk,...mt->...kt", _raw(self.dct), logmel)
        return Tensor(out)
