"""paddle.audio.backends analog (reference
python/paddle/audio/backends/wave_backend.py): WAV load/info/save over
the stdlib `wave` module — the reference's default backend does exactly
this (PCM_S 16-bit)."""
from __future__ import annotations

import wave
from collections import namedtuple
from typing import Tuple, Union

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["AudioInfo", "info", "load", "save",
           "list_available_backends", "get_current_backend",
           "set_backend"]

AudioInfo = namedtuple("AudioInfo", ["sample_rate", "num_frames",
                                     "num_channels", "bits_per_sample",
                                     "encoding"])


def info(filepath) -> AudioInfo:
    """Signal info of a WAV file (wave_backend.py:36)."""
    f = filepath if hasattr(filepath, "read") else open(filepath, "rb")
    try:
        w = wave.open(f)
    except wave.Error:
        f.close()
        raise NotImplementedError(
            "only WAV (PCM_S) files are supported by the wave backend")
    out = AudioInfo(w.getframerate(), w.getnframes(), w.getnchannels(),
                    w.getsampwidth() * 8, "PCM_S")
    f.close()
    return out


def load(filepath, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True,
         channels_first: bool = True) -> Tuple[Tensor, int]:
    """Read a WAV file (wave_backend.py:88). normalize=True returns
    float32 in (-1, 1); False returns raw int16. channels_first=True
    gives [channels, time]."""
    f = filepath if hasattr(filepath, "read") else open(filepath, "rb")
    try:
        w = wave.open(f)
    except wave.Error:
        f.close()
        raise NotImplementedError(
            "only WAV (PCM_S) files are supported by the wave backend")
    try:
        sr, nch = w.getframerate(), w.getnchannels()
        width = w.getsampwidth()
        if width != 2:
            raise NotImplementedError(
                f"only 16-bit PCM WAV is supported, got {width * 8}-bit")
        if not 0 <= frame_offset <= w.getnframes():
            raise ValueError(
                f"frame_offset {frame_offset} out of range for a "
                f"{w.getnframes()}-frame file")
        w.setpos(frame_offset)
        n = w.getnframes() - frame_offset if num_frames < 0 \
            else num_frames
        raw = w.readframes(n)
    finally:
        f.close()
    data = np.frombuffer(raw, np.int16).reshape(-1, nch)
    if normalize:
        data = (data.astype(np.float32) / (1 << 15))
    arr = data.T if channels_first else data
    return Tensor(jnp.asarray(arr)), sr


def save(filepath: str, src, sample_rate: int,
         channels_first: bool = True, encoding: str = "PCM_S",
         bits_per_sample: int = 16) -> None:
    """Write [channels, time] (or [time, channels]) to 16-bit PCM WAV
    (wave_backend.py:167)."""
    if bits_per_sample != 16 or encoding != "PCM_S":
        raise NotImplementedError(
            "the wave backend writes 16-bit PCM_S only")
    arr = np.asarray(src.numpy() if hasattr(src, "numpy") else src)
    if arr.ndim == 1:
        arr = arr[None, :]
    if not channels_first:
        arr = arr.T
    if np.issubdtype(arr.dtype, np.floating):
        arr = np.clip(arr, -1.0, 1.0 - 1.0 / (1 << 15))
        arr = (arr * (1 << 15)).astype(np.int16)
    elif arr.dtype != np.int16:
        raise TypeError(
            f"save() accepts float (-1, 1) or int16 samples, got "
            f"{arr.dtype} — convert explicitly to avoid wraparound")
    with wave.open(filepath, "wb") as w:
        w.setnchannels(arr.shape[0])
        w.setsampwidth(2)
        w.setframerate(int(sample_rate))
        w.writeframes(arr.T.astype("<i2").tobytes())


def list_available_backends():
    return ["wave_backend"]


def get_current_backend():
    return "wave_backend"


def set_backend(backend_name: str):
    if backend_name != "wave_backend":
        raise NotImplementedError(
            "only the stdlib wave backend is available (the reference's "
            "soundfile backend needs the optional paddleaudio package)")
