"""Top-level framework utilities and parity shims.

Reference: python/paddle/framework/ + assorted top-level exports in
python/paddle/__init__.py (is_tensor/iinfo/set_printoptions/Places/
DataParallel/LazyGuard/batch/...). TPU-native notes inline; CUDA-named
APIs are parity shims that map onto the single-device-family reality.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from .core import dtype as dtype_mod
from .core import random as random_mod
from .core.device import Place
from .core.tensor import Tensor


# -------------------------------------------------------- type predicates
def is_tensor(x) -> bool:
    return isinstance(x, Tensor)


def _dtype_of(x):
    if isinstance(x, Tensor):
        return x.dtype
    return jnp.asarray(x).dtype


def is_complex(x) -> bool:
    return jnp.issubdtype(_dtype_of(x), jnp.complexfloating)


def is_floating_point(x) -> bool:
    return jnp.issubdtype(_dtype_of(x), jnp.floating)


def is_integer(x) -> bool:
    return jnp.issubdtype(_dtype_of(x), jnp.integer)


def rank(x) -> Tensor:
    return Tensor(jnp.asarray(
        x.ndim if isinstance(x, Tensor) else jnp.ndim(x), jnp.int32))


def tolist(x):
    return x.tolist() if isinstance(x, Tensor) else np.asarray(x).tolist()


def is_empty(x) -> Tensor:
    n = x.size if isinstance(x, Tensor) else jnp.size(x)
    return Tensor(jnp.asarray(n == 0))


# --------------------------------------------------------- dtype queries
class iinfo:
    """paddle.iinfo parity (numpy-backed)."""

    def __init__(self, dtype):
        info = np.iinfo(np.dtype(dtype_mod.convert_dtype(dtype)))
        self.min = int(info.min)
        self.max = int(info.max)
        self.bits = int(info.bits)
        self.dtype = str(info.dtype)


class finfo:
    """paddle.finfo parity (ml_dtypes-aware for bfloat16)."""

    def __init__(self, dtype):
        import ml_dtypes
        d = dtype_mod.convert_dtype(dtype)
        info = ml_dtypes.finfo(d) if d == jnp.bfloat16 else np.finfo(d)
        self.min = float(info.min)
        self.max = float(info.max)
        self.eps = float(info.eps)
        self.tiny = float(info.tiny)
        self.smallest_normal = float(getattr(info, "smallest_normal",
                                             info.tiny))
        self.bits = int(info.bits)
        self.dtype = str(d)


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Bridge to numpy printoptions (Tensor repr prints via numpy)."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


# ------------------------------------------------------------ RNG shims
def get_cuda_rng_state():
    """Parity shim: the single accelerator RNG state (jax key)."""
    return random_mod.get_state()


def set_cuda_rng_state(state):
    random_mod.set_state(state)


def disable_signal_handler():
    """No-op parity shim: jax installs no signal handlers to disable."""


# ------------------------------------------------------------ Place shims
class CPUPlace(Place):
    def __init__(self):
        import jax
        cpus = [d for d in jax.devices("cpu")] if _has_platform("cpu") \
            else jax.devices()
        super().__init__(cpus[0])


class CUDAPlace(Place):
    """Parity shim: maps to the accelerator device (TPU here)."""

    def __init__(self, device_id: int = 0):
        import jax
        devs = jax.devices()
        super().__init__(devs[device_id % len(devs)])


class CUDAPinnedPlace(CPUPlace):
    pass


class NPUPlace(CUDAPlace):
    pass


def _has_platform(name: str) -> bool:
    import jax
    try:
        jax.devices(name)
        return True
    except RuntimeError:
        return False


# ------------------------------------------------------------- wrappers
class LazyGuard:
    """Parity shim for paddle.LazyGuard (delayed parameter init). Layers
    here initialize eagerly but cheaply (jax arrays are lazy buffers),
    so the guard is a no-op context."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def batch(reader, batch_size, drop_last=False):
    """paddle.batch (reference python/paddle/batch.py): wrap a sample
    reader into a batch reader."""

    def batch_reader():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batch_reader


def check_shape(shape):
    """paddle.check_shape (reference tensor/random.py): validate a shape
    argument for creation ops."""
    if isinstance(shape, Tensor):
        return
    for s in shape:
        if isinstance(s, Tensor):
            continue
        if int(s) < -1 or int(s) == 0:
            raise ValueError(f"invalid dim {s} in shape {shape}")


class DataParallel:
    """paddle.DataParallel parity (reference
    python/paddle/fluid/dygraph/parallel.py:457). TPU-native data
    parallelism is a sharding annotation, not a wrapper — gradients are
    reduced by XLA when the train step runs under a dp-sharded mesh
    (distributed.fleet.train_step). This wrapper keeps user code
    portable: it delegates everything to the inner layer and exposes the
    reference's no-sync/scale-loss API as no-ops."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        self._layers = layers

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

    def no_sync(self):
        import contextlib
        return contextlib.nullcontext()

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def __getattr__(self, name):
        return getattr(self.__dict__["_layers"], name)
