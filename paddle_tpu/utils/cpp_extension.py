"""C++ extension loader — JIT-compile user C++ into callable ops.

Reference analog: python/paddle/utils/cpp_extension/cpp_extension.py
(load/CppExtension/CUDAExtension + custom_operator.cc .so loading).
TPU-native shape: user C++ runs on the HOST (there is no user ISA on
the TPU core — the reference's CUDA path maps to Pallas kernels, see
paddle_tpu/kernels/). The compiled function is bridged into jax with
jax.pure_callback, so it works both eagerly and inside jit (XLA
round-trips the buffer to the host, like the reference's CPU custom
kernels do from GPU graphs).

C ABI contract (one function per op):
    extern "C" void fn(const float** ins, const int64_t* sizes,
                       int n_ins, float* out, int64_t out_size);
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.op_registry import op as _register_op

__all__ = ["load", "CppExtensionModule", "get_build_directory"]

_BUILD_DIR = os.environ.get(
    "PADDLE_EXTENSION_DIR",
    os.path.join(tempfile.gettempdir(), "paddle_tpu_extensions"))


def get_build_directory() -> str:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    return _BUILD_DIR


def _compile(name: str, sources: Sequence[str],
             extra_cxx_flags: Sequence[str] = (),
             verbose: bool = False) -> str:
    """g++ -shared -fPIC the sources; content-hash keyed cache."""
    build_dir = get_build_directory()
    h = hashlib.sha256()
    for src in sources:
        with open(src, "rb") as f:
            h.update(f.read())
    h.update(" ".join(extra_cxx_flags).encode())
    so_path = os.path.join(build_dir, f"{name}_{h.hexdigest()[:16]}.so")
    if os.path.exists(so_path):
        return so_path
    # build to a temp path + atomic rename: a killed/concurrent build
    # must never leave a truncated .so at the cached path
    tmp_path = f"{so_path}.tmp.{os.getpid()}"
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
           *extra_cxx_flags, *sources, "-o", tmp_path]
    if verbose:
        print("cpp_extension:", " ".join(cmd))
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp_path, so_path)
    except subprocess.CalledProcessError as e:
        raise RuntimeError(
            f"cpp_extension build failed:\n{e.stderr}") from e
    finally:
        if os.path.exists(tmp_path):
            os.unlink(tmp_path)
    return so_path


class CppExtensionModule:
    """Wraps a compiled .so; def_op() turns exported symbols into
    registered framework ops."""

    def __init__(self, name: str, so_path: str):
        self.name = name
        self.so_path = so_path
        self._lib = ctypes.CDLL(so_path)

    def def_op(self, fn_name: str,
               out_shape: Optional[Callable] = None,
               out_dtype=np.float32,
               op_name: Optional[str] = None) -> Callable:
        """Expose `fn_name` (C ABI above) as a framework op.

        out_shape: callable(*input_shapes) -> output shape; defaults to
        the first input's shape (elementwise ops).
        """
        cfn = getattr(self._lib, fn_name)
        cfn.restype = None
        cfn.argtypes = [ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
                        ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
                        ctypes.POINTER(ctypes.c_float), ctypes.c_int64]
        shape_fn = out_shape or (lambda *shapes: shapes[0])

        def host_call(*arrays: np.ndarray) -> np.ndarray:
            arrs = [np.ascontiguousarray(a, dtype=np.float32)
                    for a in arrays]
            n = len(arrs)
            ptrs = (ctypes.POINTER(ctypes.c_float) * n)(*[
                a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
                for a in arrs])
            sizes = (ctypes.c_int64 * n)(*[a.size for a in arrs])
            oshape = shape_fn(*[a.shape for a in arrs])
            out = np.empty(oshape, dtype=np.float32)
            cfn(ptrs, sizes, n,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                out.size)
            return out.astype(out_dtype, copy=False)

        def impl(*xs):
            if not any(isinstance(x, jax.core.Tracer) for x in xs):
                # eager: call the C function directly on host buffers
                # (also sidesteps PJRT backends without host-callback
                # support, e.g. tunneled devices)
                return jnp.asarray(host_call(*[np.asarray(x)
                                               for x in xs]))
            oshape = shape_fn(*[tuple(x.shape) for x in xs])
            result_sds = jax.ShapeDtypeStruct(tuple(oshape),
                                              jnp.dtype(out_dtype))
            return jax.pure_callback(host_call, result_sds, *xs,
                                     vmap_method="sequential")

        impl.__name__ = fn_name
        public = _register_op(op_name or f"{self.name}::{fn_name}",
                              differentiable=False)(impl)
        setattr(self, fn_name, public)
        return public


def load(name: str, sources: Sequence[str],
         extra_cxx_flags: Sequence[str] = (),
         verbose: bool = False) -> CppExtensionModule:
    """Compile + load a C++ extension (reference cpp_extension.load)."""
    so_path = _compile(name, sources, extra_cxx_flags, verbose)
    return CppExtensionModule(name, so_path)
