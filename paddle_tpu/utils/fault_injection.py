"""Deterministic in-process fault injection — the chaos-test harness.

Everything here is process-local and deterministic: faults fire on exact
call counts or exact byte offsets, never on wall-clock races, so a chaos
test that passes once passes always, and no real TPU (or even a second
process) is needed.

Injectable faults:

- ``KillAfter(n)``              — deliver a signal to this process on the
                                  n-th ``step()`` call (preemption).
- ``truncate_checkpoint(...)``  — truncate the largest payload file of a
                                  checkpoint step (torn write).
- ``remove_commit_marker(...)`` — delete a step's commit marker
                                  (writer died between data and commit).
- ``StoreFaults(...)``          — delay or drop TCPStore responses for
                                  chosen ops/keys (network stall, hang).
- ``poison_batch(...)``         — NaN-fill the float leaves of a batch
                                  (numeric anomaly; trace-compatible:
                                  the poison is in the data, so in-jit
                                  non-finite guards see it).
- ``NaNLoss(loss_fn, at_calls)``— eager loss wrapper returning NaN on
                                  chosen calls (host-side loops only;
                                  under jit the call count is a
                                  trace-time constant — use
                                  poison_batch there).
- ``kill_worker(...)``          — SIGKILL one of a DataLoader's worker
                                  processes (crashed/OOM-killed worker;
                                  drives the supervised respawn path).
- ``truncate_executable(...)``  — truncate a serialized-executable
                                  entry of a ``jit.compile_cache``
                                  store (torn write during relaunch).
- ``corrupt_executable(...)``   — flip payload bytes of an entry (bit
                                  rot; the checksum must catch it and
                                  the load must fall back to compile).
- ``suspend_worker(...)``       — SIGSTOP a worker (wedged worker; the
                                  per-fetch deadline must fire).
- ``FlakySamples(ds, ...)``     — dataset wrapper raising / returning
                                  NaN samples at exact indices (drives
                                  error attribution and quarantine).
- ``wedge_replica(engine)``     — suspend a ServingEngine's scheduler
                                  loop until released (wedged replica:
                                  alive, answers health(), makes zero
                                  progress — the serving-side twin of
                                  ``suspend_worker``).
- ``fail_admission(engine, n)`` — inject ``n`` consecutive admission
                                  failures into a ServingEngine
                                  (pre-prefill, so the failed request
                                  is re-routable; drives the router's
                                  circuit breaker).
"""
from __future__ import annotations

import os
import signal
import time
from typing import Iterable, Optional, Sequence

__all__ = [
    "FlakySamples",
    "KillAfter",
    "NaNLoss",
    "StoreFaults",
    "checkpoint_data_files",
    "corrupt_executable",
    "dataloader_workers",
    "executable_entries",
    "fail_admission",
    "kill_worker",
    "poison_batch",
    "remove_commit_marker",
    "resume_worker",
    "suspend_worker",
    "truncate_checkpoint",
    "truncate_executable",
    "wedge_replica",
]


class KillAfter:
    """Preemption injector: ``step()`` each training step; the ``n``-th
    call sends ``sig`` (default SIGTERM) to this very process — exactly
    what a TPU maintenance event looks like from inside the job."""

    def __init__(self, n: int, sig: int = signal.SIGTERM):
        if n < 1:
            raise ValueError("KillAfter fires on the n-th step, n >= 1")
        self.n = int(n)
        self.sig = sig
        self.calls = 0
        self.fired = False

    def step(self) -> bool:
        """Returns True on the call that delivered the signal."""
        self.calls += 1
        if self.calls == self.n and not self.fired:
            self.fired = True
            os.kill(os.getpid(), self.sig)
            return True
        return False


def _step_dirs(directory: str):
    out = []
    for name in os.listdir(directory):
        if name.isdigit() and os.path.isdir(os.path.join(directory, name)):
            out.append(int(name))
    return sorted(out)


def checkpoint_data_files(directory: str,
                          step: Optional[int] = None) -> list:
    """The payload files of a checkpoint step (the latest when ``step``
    is None): every file under the step dir except metadata/marker
    files (leading underscore). Sorted — deterministic for a given
    on-disk state."""
    steps = _step_dirs(directory)
    if not steps:
        raise FileNotFoundError(f"no checkpoint steps under {directory}")
    step = steps[-1] if step is None else int(step)
    root = os.path.join(directory, str(step))
    out = []
    for dirpath, _, files in os.walk(root):
        for f in files:
            # metadata/marker files are covered by remove_commit_marker;
            # a torn write hits the bulk payload
            if not f.startswith("_"):
                out.append(os.path.join(dirpath, f))
    if not out:
        raise FileNotFoundError(f"no data files under {root}")
    return sorted(out)


def truncate_checkpoint(directory: str, step: Optional[int] = None,
                        keep_bytes: int = 0) -> list:
    """Truncate every payload file of a checkpoint step (the latest
    when ``step`` is None) to ``keep_bytes`` — a torn write from a
    preempted saver. All payload files are hit because the storage
    format keeps redundant copies of small trees (OCDBT manifests plus
    per-process blobs): corrupting only one blob may leave the step
    restorable, which would make chaos tests pass or fail on which
    randomly-named file happened to be chosen. Metadata/marker files
    survive, so the step still LOOKS committed — exactly the case the
    restore fallback must catch. Returns the truncated paths."""
    paths = checkpoint_data_files(directory, step)
    for path in paths:
        with open(path, "r+b") as f:
            f.truncate(int(keep_bytes))
    return paths


def remove_commit_marker(directory: str, step: Optional[int] = None) -> str:
    """Delete a step's ``_PADDLE_COMMIT`` marker — the writer died after
    the data landed but before the commit. Returns the removed path."""
    from ..distributed.checkpoint import COMMIT_MARKER
    steps = _step_dirs(directory)
    if not steps:
        raise FileNotFoundError(f"no checkpoint steps under {directory}")
    step = steps[-1] if step is None else int(step)
    path = os.path.join(directory, str(step), COMMIT_MARKER)
    os.remove(path)
    return path


class StoreFaults:
    """Delay or drop TCPStore server responses, deterministically.

    ::

        with StoreFaults(delay=5.0, ops=("get",), count=1):
            store.get("key")          # this one reply stalls 5s

        with StoreFaults(drop=True, ops=("set",), key_prefix="__barrier"):
            ...                       # barrier sets are never answered

    ``count`` bounds how many matching requests fault (None = all while
    installed). Matching is by op name and optional key prefix; the
    fault applies server-side, so every client of the in-process master
    sees it — the chaos-test stand-in for a stalled or partitioned host.
    """

    def __init__(self, delay: float = 0.0, drop: bool = False,
                 ops: Sequence[str] = ("get",),
                 key_prefix: Optional[str] = None,
                 count: Optional[int] = None):
        self.delay = float(delay)
        self.drop = bool(drop)
        self.ops = tuple(ops)
        self.key_prefix = key_prefix
        self.count = count
        self.triggered = 0

    def _matches(self, op: str, args) -> bool:
        if op not in self.ops:
            return False
        if self.key_prefix is not None:
            key = args[0] if args else ""
            if not str(key).startswith(self.key_prefix):
                return False
        return True

    def __call__(self, op: str, args):
        if self.count is not None and self.triggered >= self.count:
            return None
        if not self._matches(op, args):
            return None
        self.triggered += 1
        if self.delay > 0:
            time.sleep(self.delay)
        return "drop" if self.drop else None

    def __enter__(self) -> "StoreFaults":
        from ..distributed import store
        store.set_fault_hook(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        from ..distributed import store
        store.set_fault_hook(None)
        return False


def poison_batch(batch):
    """NaN-fill every float leaf of a (possibly nested) batch — the
    deterministic numeric-anomaly injection. Integer/bool leaves pass
    through (labels stay valid; the NaN reaches the loss through the
    activations)."""
    import numpy as np

    from ..core.tensor import Tensor

    def poison(x):
        if isinstance(x, Tensor):
            return Tensor(poison(x._data))
        arr = np.asarray(x)
        if np.issubdtype(arr.dtype, np.floating):
            return np.full_like(arr, np.nan)
        return x

    def walk(node):
        if isinstance(node, Tensor):
            return poison(node)
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v) for v in node)
        return poison(node)

    return walk(batch)


# -------------------------------------------- executable-store faults

def executable_entries(store_or_root) -> list:
    """The serialized-executable entries of a ``jit.compile_cache``
    store (an :class:`~paddle_tpu.jit.compile_cache.ExecutableStore`
    or its root dir), sorted — deterministic handle for the
    corruptions below."""
    root = getattr(store_or_root, "root", store_or_root)
    from ..jit.compile_cache import ENTRY_SUFFIX
    try:
        names = os.listdir(root)
    except OSError:
        raise FileNotFoundError(f"no executable store at {root}")
    out = sorted(os.path.join(root, n) for n in names
                 if n.endswith(ENTRY_SUFFIX))
    if not out:
        raise FileNotFoundError(f"no executable entries under {root}")
    return out


def truncate_executable(store_or_root, index: int = 0,
                        keep_bytes: int = 0) -> str:
    """Truncate one store entry to ``keep_bytes`` — a torn write from a
    process killed mid-relaunch. The next load of that program must
    fall back to a fresh compile (``jit.compile_cache.misses{cause=
    corrupt}``) and rewrite a good entry. Returns the truncated
    path."""
    path = executable_entries(store_or_root)[index]
    with open(path, "r+b") as f:
        f.truncate(int(keep_bytes))
    return path


def corrupt_executable(store_or_root, index: int = 0,
                       offset: int = -64, n: int = 8) -> str:
    """XOR-flip ``n`` bytes of one store entry at ``offset`` (negative:
    from the end — the payload tail, past the checksum header) — bit
    rot the entry's sha256 must catch. Returns the corrupted path."""
    path = executable_entries(store_or_root)[index]
    with open(path, "r+b") as f:
        f.seek(offset, os.SEEK_END if offset < 0 else os.SEEK_SET)
        pos = f.tell()
        data = f.read(int(n))
        f.seek(pos)
        f.write(bytes(b ^ 0xFF for b in data))
    return path


# ------------------------------------------------- dataloader faults

def dataloader_workers(loader_or_iter) -> list:
    """The live worker processes of a DataLoader (its active iterator)
    or of a ``_PrefetchIterator`` directly. Deterministic handle for
    the kill/suspend injections below."""
    it = loader_or_iter
    active = getattr(it, "_active_iter", None)
    if callable(active):  # a DataLoader: reach through to the iterator
        it = active()
    if it is None:
        raise RuntimeError("DataLoader has no active iterator")
    workers = [w for w in getattr(it, "_workers", []) if w is not None]
    if not workers:
        raise RuntimeError("no worker processes (num_workers=0?)")
    return workers


def kill_worker(loader_or_iter, worker_id: int = 0,
                sig: int = signal.SIGKILL) -> int:
    """Deliver ``sig`` (default SIGKILL — a crash/OOM-kill) to one
    DataLoader worker. The supervisor must respawn it and re-dispatch
    its in-flight batches with no change to the batch stream. Returns
    the killed pid."""
    p = dataloader_workers(loader_or_iter)[worker_id]
    os.kill(p.pid, sig)
    return p.pid


def suspend_worker(loader_or_iter, worker_id: int = 0) -> int:
    """SIGSTOP a worker — the deterministic 'wedged worker' fault: the
    process stays alive (liveness checks pass) but never produces, so
    the per-fetch deadline must surface a WatchdogTimeout. Returns the
    pid (pass to ``resume_worker`` for cleanup, or let the iterator's
    teardown SIGKILL it)."""
    p = dataloader_workers(loader_or_iter)[worker_id]
    os.kill(p.pid, signal.SIGSTOP)
    return p.pid


def resume_worker(pid: int) -> None:
    """SIGCONT a worker suspended by ``suspend_worker``."""
    try:
        os.kill(pid, signal.SIGCONT)
    except ProcessLookupError:
        pass  # teardown already reaped it


class FlakySamples:
    """Map-style dataset wrapper that fails on exact sample indices:
    ``raise_at`` indices raise ValueError, ``nan_at`` indices return
    the sample with every float leaf NaN-filled. Drives the
    DataLoader's error-attribution and quarantine paths without
    touching the wrapped dataset."""

    def __init__(self, dataset, raise_at: Iterable[int] = (),
                 nan_at: Iterable[int] = ()):
        self.dataset = dataset
        self.raise_at = frozenset(int(i) for i in raise_at)
        self.nan_at = frozenset(int(i) for i in nan_at)

    def __len__(self):
        return len(self.dataset)

    def __getitem__(self, idx):
        if int(idx) in self.raise_at:
            raise ValueError(f"FlakySamples: injected failure at "
                             f"sample {int(idx)}")
        sample = self.dataset[idx]
        if int(idx) in self.nan_at:
            return poison_batch(sample)
        return sample


# -------------------------------------------- serving replica faults

class wedge_replica:
    """Suspend a ServingEngine's scheduler until released — the
    deterministic 'wedged replica' fault (the serving-side twin of
    ``suspend_worker``): the engine stays alive and keeps answering
    ``submit()``/``health()``, but ``step()`` and the inline
    ``result()`` pump make zero progress, so its queue only grows. A
    multi-replica router must observe the mounting backpressure
    (``queue_full`` health reasons, falling score) and steer traffic to
    survivors. Context manager, or ``release()`` explicitly::

        with wedge_replica(engine):
            ...                      # engine frozen, deterministically
        # scheduler restored; queued work resumes
    """

    def __init__(self, engine):
        self.engine = engine
        self._saved = None

    def wedge(self) -> "wedge_replica":
        if self._saved is None:
            self._saved = (self.engine.step, self.engine._try_pump)
            self.engine.step = lambda: None
            self.engine._try_pump = lambda: False
        return self

    def release(self):
        if self._saved is not None:
            self.engine.step, self.engine._try_pump = self._saved
            self._saved = None

    def __enter__(self) -> "wedge_replica":
        return self.wedge()

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False


class fail_admission:
    """Inject ``n`` consecutive admission failures into a
    ServingEngine: the next ``n`` requests popped for admission raise
    at the prefill-executable fetch — BEFORE any prefill dispatch or KV
    write, so the failed admission is idempotent and a router may
    re-route the request to another replica. The engine's own handling
    cancels each doomed handle with an ``admission error: ...`` detail
    (its Future never hangs); ``triggered`` counts faults actually
    fired. Composes with ``KillAfter``/``StoreFaults``::

        with fail_admission(engine, n=3):
            ...   # the next 3 admissions on this engine fail
    """

    def __init__(self, engine, n: int = 1):
        if n < 1:
            raise ValueError("fail_admission fires on n >= 1 admissions")
        self.engine = engine
        self.n = int(n)
        self.triggered = 0
        self._orig = None

    def __enter__(self) -> "fail_admission":
        orig = self.engine._exe_prefill

        def flaky(bucket):
            if self.triggered < self.n:
                self.triggered += 1
                raise RuntimeError(
                    f"fail_admission: injected admission failure "
                    f"{self.triggered}/{self.n}")
            return orig(bucket)

        self._orig = orig
        self.engine._exe_prefill = flaky
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._orig is not None:
            self.engine._exe_prefill = self._orig
            self._orig = None
        return False


class NaNLoss:
    """Eager-path loss wrapper: returns NaN on the given (1-based) call
    numbers, delegates otherwise. Host-side loops only — under jit the
    call counter is a trace-time constant (use ``poison_batch``)."""

    def __init__(self, loss_fn, at_calls: Iterable[int]):
        self.loss_fn = loss_fn
        self.at_calls = frozenset(int(i) for i in at_calls)
        self.calls = 0

    def __call__(self, *args, **kwargs):
        self.calls += 1
        out = self.loss_fn(*args, **kwargs)
        if self.calls in self.at_calls:
            import numpy as np

            from ..core.tensor import Tensor
            return Tensor(np.float32(np.nan)) if isinstance(out, Tensor) \
                else float("nan")
        return out
