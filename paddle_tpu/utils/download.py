"""paddle.utils.download analog, gated for zero-egress environments.

Reference: python/paddle/utils/download.py (get_weights_path_from_url /
get_path_from_url: fetch + md5 + cache under ~/.cache/paddle). This
environment has no network egress, so the functions resolve ONLY from
the local cache (or a mirror directory named by PADDLE_TPU_DOWNLOAD_DIR)
and raise with instructions otherwise — the API shape and cache layout
match, so code written against the reference keeps working wherever a
cache has been provisioned.
"""
from __future__ import annotations

import hashlib
import os
import os.path as osp
import shutil
import tarfile
import zipfile

__all__ = ["get_weights_path_from_url", "get_path_from_url"]

WEIGHTS_HOME = osp.expanduser("~/.cache/paddle/hapi/weights")
DATA_HOME = osp.expanduser("~/.cache/paddle/dataset")


def _md5check(fullname: str, md5sum: str = None) -> bool:
    if md5sum is None:
        return True
    md5 = hashlib.md5()
    with open(fullname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            md5.update(chunk)
    return md5.hexdigest() == md5sum


def _decompress(fname: str) -> str:
    d = osp.dirname(fname)
    if tarfile.is_tarfile(fname):
        with tarfile.open(fname) as tf:
            names = tf.getnames()
            root = names[0].split("/")[0] if names else ""
            out = osp.join(d, root)
            if not (root and osp.exists(out)):  # cache hit: no re-IO
                tf.extractall(d)
        return out if root else fname
    if zipfile.is_zipfile(fname):
        with zipfile.ZipFile(fname) as zf:
            names = zf.namelist()
            root = names[0].split("/")[0] if names else ""
            out = osp.join(d, root)
            if not (root and osp.exists(out)):
                zf.extractall(d)
        return out if root else fname
    return fname


def get_path_from_url(url: str, root_dir: str = DATA_HOME,
                      md5sum: str = None, check_exist: bool = True,
                      decompress: bool = True) -> str:
    """Resolve `url` to a local path. Looks in (1) the cache layout the
    reference would have populated, (2) $PADDLE_TPU_DOWNLOAD_DIR acting
    as a pre-provisioned mirror. No network IO ever happens here."""
    fname = osp.split(url)[-1]
    fullname = osp.join(root_dir, fname)
    if osp.exists(fullname) and _md5check(fullname, md5sum):
        return _decompress(fullname) if decompress else fullname
    mirror = os.environ.get("PADDLE_TPU_DOWNLOAD_DIR")
    if mirror:
        cand = osp.join(mirror, fname)
        if osp.exists(cand) and _md5check(cand, md5sum):
            os.makedirs(root_dir, exist_ok=True)
            shutil.copy(cand, fullname)
            return _decompress(fullname) if decompress else fullname
    raise RuntimeError(
        f"cannot fetch {url!r}: this environment has no network egress. "
        f"Provision the file at {fullname!r} (or set "
        f"PADDLE_TPU_DOWNLOAD_DIR to a directory containing {fname!r}) "
        f"and retry.")


def get_weights_path_from_url(url: str, md5sum: str = None) -> str:
    return get_path_from_url(url, WEIGHTS_HOME, md5sum, decompress=False)
