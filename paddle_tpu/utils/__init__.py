"""paddle.utils analog: custom op registration + C++ extensions."""
from . import cpp_extension  # noqa: F401
from .custom_op import register_custom_op  # noqa: F401
