"""paddle.utils analog: custom op registration + C++ extensions +
deterministic fault injection (chaos-test harness)."""
from . import cpp_extension  # noqa: F401
from . import fault_injection  # noqa: F401
from .custom_op import register_custom_op  # noqa: F401


# ---- paddle.utils top-level helpers (reference python/paddle/utils/) ---

def try_import(module_name: str, err_msg: str = None):
    """Import a soft dependency with an actionable error (reference
    utils/lazy_import.py try_import)."""
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(
            err_msg or f"{module_name} is required but not installed; "
            f"this environment cannot pip install — gate the feature")


def require_version(min_version: str, max_version: str = None):
    """Check the installed framework version (reference
    utils/install_check.py require_version)."""
    from .. import __version__

    def parse(v):
        return tuple(int(x) for x in str(v).split(".")[:3])

    cur = parse(__version__)
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {__version__} < required {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed version {__version__} > allowed {max_version}")
    return True


def deprecated(update_to: str = "", since: str = "", reason: str = "",
               level: int = 0):
    """Decorator marking an API deprecated (reference
    utils/deprecated.py): warns on call, raises at level 2."""
    import functools
    import warnings

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = (f"API '{fn.__name__}' is deprecated since {since}; "
                   f"{('use ' + update_to) if update_to else ''} "
                   f"{reason}")
            if level == 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return wrapper

    return deco


def run_check():
    """Smoke-check the installation on the current device (reference
    utils/install_check.py run_check): one tiny matmul + grad."""
    import numpy as np
    from .. import nn, optimizer, randn, to_tensor
    from ..core.device import get_device
    m = nn.Linear(4, 2)
    x = randn([2, 4])
    out = m(x)
    loss = (out * out).mean()
    loss.backward()
    assert m.weight.grad is not None
    print(f"paddle_tpu is installed successfully! device={get_device()}")
