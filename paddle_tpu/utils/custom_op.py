"""Python-level custom op registration.

Reference analog: PD_BUILD_OP / OpMetaInfoBuilder
(paddle/phi/api/lib/op_meta_info.cc, framework/custom_operator.cc) —
user ops registered at runtime become first-class ops with autograd.
TPU-native: the forward is pure jax; an optional backward becomes a
jax.custom_vjp rule; registration lands in the same op registry as
built-ins so the eager tape, jit traces, and the profiler see it like
any other op.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax

from ..ops.op_registry import OPS, op

__all__ = ["register_custom_op"]


def register_custom_op(name: str, forward: Callable,
                       backward: Optional[Callable] = None,
                       num_inputs: Optional[int] = None):
    """Register `forward(*raw_arrays) -> raw_array(s)` as op `name`.

    `backward(grads, *inputs) -> input_grads` (one per differentiable
    input) installs a custom VJP; omit it to use jax autodiff through
    the forward. Returns the Tensor-aware callable.
    """
    if name in OPS:
        raise ValueError(f"op {name!r} is already registered")
    if backward is not None:
        fwd_core = jax.custom_vjp(forward)

        def fwd_rule(*args):
            return forward(*args), args

        def bwd_rule(saved, g):
            grads = backward(g, *saved)
            if not isinstance(grads, (list, tuple)):
                grads = (grads,)
            if len(grads) != len(saved):
                raise ValueError(
                    f"custom op {name!r}: backward returned "
                    f"{len(grads)} grads for {len(saved)} inputs")
            return tuple(grads)

        fwd_core.defvjp(fwd_rule, bwd_rule)
        impl = fwd_core
    else:
        impl = forward
    return op(name)(impl)
