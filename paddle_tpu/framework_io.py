"""paddle.save / paddle.load analogs.

Reference: python/paddle/framework/io.py:640 (save), :870 (load) — pickled
nested state dicts with C++ tensor serialization. Here tensors serialize as
numpy arrays inside a pickle; bfloat16 round-trips via ml_dtypes. Sharded/
async checkpointing for the distributed path lives in
paddle_tpu.distributed.checkpoint (orbax-backed).
"""
from __future__ import annotations

import os
import pickle
from typing import Any

import jax.numpy as jnp
import numpy as np

from .core.tensor import Tensor


def _to_serializable(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj.data))
    if isinstance(obj, jnp.ndarray):
        return _TensorPayload(np.asarray(obj))
    if isinstance(obj, dict):
        return {k: _to_serializable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_serializable(v) for v in obj)
    return obj


class _TensorPayload:
    __slots__ = ("array",)

    def __init__(self, array: np.ndarray):
        self.array = array

    def __reduce__(self):
        # bfloat16 has no native numpy wire format: ship as uint16 + tag
        arr = self.array
        if arr.dtype == jnp.bfloat16:
            return (_restore_bf16, (arr.view(np.uint16), arr.shape))
        return (_restore, (arr,))


def _restore(arr):
    return arr


def _restore_bf16(u16, shape):
    return u16.view(jnp.bfloat16).reshape(shape)


class _TensorRef:
    """Placeholder in the pickled structure pointing into the native
    sidecar blob file ({path}.tensors)."""

    __slots__ = ("key",)

    def __init__(self, key: str):
        self.key = key


def _extract_payloads(obj, out, prefix="t"):
    """Replace _TensorPayload leaves with _TensorRef, collecting arrays."""
    if isinstance(obj, _TensorPayload):
        key = f"{prefix}{len(out)}"
        out[key] = obj.array
        return _TensorRef(key)
    if isinstance(obj, dict):
        return {k: _extract_payloads(v, out) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_extract_payloads(v, out) for v in obj)
    return obj


def _use_native() -> bool:
    from .core import flags
    if not flags.get_flag("use_native_tensor_store"):
        return False
    from .native import tensor_store
    return tensor_store.available()


def save(obj: Any, path: str, protocol: int = 4):
    """paddle.save: pickled structure; tensor payloads go through the
    native parallel CRC-checked store ({path}.tensors sidecar) when the
    toolchain is available (FLAGS_use_native_tensor_store), else they
    inline into the pickle."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    ser = _to_serializable(obj)
    if _use_native():
        import uuid
        from .native import tensor_store
        payloads: dict = {}
        ser = _extract_payloads(ser, payloads)
        # The sidecar is written under a ckpt_id-suffixed name and the
        # pickle (which records the id) is published last — a writer
        # killed at any point leaves the previous pickle + its own
        # sidecar intact, so the last good checkpoint always loads.
        ckpt_id = uuid.uuid4().hex
        blobs = {k: np.ascontiguousarray(
            v.view(np.uint16) if v.dtype == jnp.bfloat16 else v)
            for k, v in payloads.items()}
        tensor_store.save_tensors(f"{path}.tensors.{ckpt_id}", blobs)
        bf16 = sorted(k for k, v in payloads.items()
                      if v.dtype == jnp.bfloat16)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            pickle.dump({"__pt_native__": True, "tree": ser,
                         "bf16_keys": bf16, "ckpt_id": ckpt_id}, f,
                        protocol=protocol)
        os.replace(tmp, path)
        _gc_stale_sidecars(path, keep_id=ckpt_id)
        return
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        pickle.dump(ser, f, protocol=protocol)
    os.replace(tmp, path)


_SIDECAR_GC_GRACE_S = 120.0


def _gc_stale_sidecars(path: str, keep_id: str):
    """Remove sidecars from superseded (or crashed) save() calls.

    Recently-modified sidecars are spared: a concurrent writer to the
    same path may have written its sidecar but not yet published its
    pickle, and deleting it would strand that writer's checkpoint. A
    crash-orphan merely survives until a later save() collects it."""
    import time
    d = os.path.dirname(path) or "."
    base = os.path.basename(path) + ".tensors"
    keep = f"{base}.{keep_id}"
    cutoff = time.time() - _SIDECAR_GC_GRACE_S
    try:
        names = os.listdir(d)
    except OSError:
        return
    for name in names:
        # `base` exactly = pre-suffix shared-sidecar layout, also stale
        if (name.startswith(base + ".") or name == base) and name != keep:
            full = os.path.join(d, name)
            try:
                if os.path.getmtime(full) < cutoff:
                    os.remove(full)
            except OSError:
                pass


def load(path: str, return_numpy: bool = False):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    if isinstance(obj, dict) and obj.get("__pt_native__"):
        from .native import tensor_store
        want_id = obj.get("ckpt_id")
        sidecar = f"{path}.tensors.{want_id}"
        legacy = not os.path.exists(sidecar)
        if legacy:
            # pre-suffix layout: shared sidecar carrying an id blob
            sidecar = path + ".tensors"
        arrays = tensor_store.load_tensors(sidecar)
        have = arrays.pop("__ckpt_id__", None)
        if legacy and want_id is not None:
            # the suffixed filename IS the id; a legacy shared sidecar
            # must prove it belongs to this pickle via its id blob
            have_id = bytes(have.tobytes()).decode() \
                if have is not None else None
            if want_id != have_id:
                raise IOError(
                    f"checkpoint mismatch: {path!r} and its .tensors "
                    "sidecar are from different save() calls (a writer "
                    "was likely killed mid-save); re-save the checkpoint")
        bf16 = set(obj.get("bf16_keys", ()))

        def resolve(o):
            if isinstance(o, _TensorRef):
                arr = arrays[o.key]
                if o.key in bf16:
                    arr = arr.view(jnp.bfloat16)
                return arr
            if isinstance(o, dict):
                return {k: resolve(v) for k, v in o.items()}
            if isinstance(o, (list, tuple)):
                return type(o)(resolve(v) for v in o)
            return o

        obj = resolve(obj["tree"])
    if return_numpy:
        return obj

    def back(o):
        if isinstance(o, np.ndarray):
            return Tensor(o)
        if isinstance(o, dict):
            return {k: back(v) for k, v in o.items()}
        if isinstance(o, (list, tuple)):
            return type(o)(back(v) for v in o)
        return o

    return back(obj)
