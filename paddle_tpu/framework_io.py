"""paddle.save / paddle.load analogs.

Reference: python/paddle/framework/io.py:640 (save), :870 (load) — pickled
nested state dicts with C++ tensor serialization. Here tensors serialize as
numpy arrays inside a pickle; bfloat16 round-trips via ml_dtypes. Sharded/
async checkpointing for the distributed path lives in
paddle_tpu.distributed.checkpoint (orbax-backed).
"""
from __future__ import annotations

import os
import pickle
from typing import Any

import jax.numpy as jnp
import numpy as np

from .core.tensor import Tensor


def _to_serializable(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj.data))
    if isinstance(obj, jnp.ndarray):
        return _TensorPayload(np.asarray(obj))
    if isinstance(obj, dict):
        return {k: _to_serializable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_serializable(v) for v in obj)
    return obj


class _TensorPayload:
    __slots__ = ("array",)

    def __init__(self, array: np.ndarray):
        self.array = array

    def __reduce__(self):
        # bfloat16 has no native numpy wire format: ship as uint16 + tag
        arr = self.array
        if arr.dtype == jnp.bfloat16:
            return (_restore_bf16, (arr.view(np.uint16), arr.shape))
        return (_restore, (arr,))


def _restore(arr):
    return arr


def _restore_bf16(u16, shape):
    return u16.view(jnp.bfloat16).reshape(shape)


def save(obj: Any, path: str, protocol: int = 4):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_serializable(obj), f, protocol=protocol)


def load(path: str, return_numpy: bool = False):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    if return_numpy:
        return obj

    def back(o):
        if isinstance(o, np.ndarray):
            return Tensor(o)
        if isinstance(o, dict):
            return {k: back(v) for k, v in o.items()}
        if isinstance(o, (list, tuple)):
            return type(o)(back(v) for v in o)
        return o

    return back(obj)
