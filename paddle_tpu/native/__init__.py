"""Native runtime components (C++), built on demand with g++ and bound via
ctypes (no pybind11 dependency — SURVEY §2.6: native where the reference is
native: host tracer ≈ host_event_recorder.h, token feeder ≈ data_feed.cc).

`lib()` compiles paddle_tpu/native/*.cc into _native.so on first use
(cached by source mtime) and returns the ctypes handle, or None when no
toolchain is available — callers must degrade to their pure-Python path.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "_native.so")
_SOURCES = ["host_tracer.cc", "token_feeder.cc", "tensor_store.cc"]

_lock = threading.Lock()
_lib = None
_tried = False


def _needs_build() -> bool:
    if not os.path.exists(_SO):
        return True
    so_mtime = os.path.getmtime(_SO)
    return any(os.path.getmtime(os.path.join(_DIR, s)) > so_mtime
               for s in _SOURCES)


def _build() -> bool:
    # compile to a per-pid temp then os.rename: atomic on POSIX, so
    # concurrent dp-rank processes never load a half-written .so
    srcs = [os.path.join(_DIR, s) for s in _SOURCES]
    tmp = f"{_SO}.tmp.{os.getpid()}"
    cmd = ["g++", "-O2", "-std=c++17", "-fPIC", "-shared", "-pthread",
           *srcs, "-o", tmp]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return False
    if proc.returncode != 0:
        import logging
        logging.getLogger(__name__).warning(
            "native build failed; using pure-Python fallbacks:\n%s",
            proc.stderr[-2000:])
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    os.replace(tmp, _SO)
    return True


def _bind(handle: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    # host tracer
    handle.pt_record_begin.argtypes = [c.c_char_p]
    handle.pt_record_instant.argtypes = [c.c_char_p, c.c_int64]
    handle.pt_now_ns.restype = c.c_uint64
    handle.pt_tracer_enabled.restype = c.c_int
    handle.pt_collect.restype = c.c_void_p
    handle.pt_collect.argtypes = [c.POINTER(c.POINTER(CollectedEvent)),
                                  c.POINTER(c.c_uint64)]
    handle.pt_free_events.argtypes = [c.c_void_p]
    # token feeder
    handle.pt_feeder_create.restype = c.c_void_p
    handle.pt_feeder_create.argtypes = [
        c.c_char_p, c.c_int64, c.c_int64, c.c_int64, c.c_uint64,
        c.c_int64, c.c_int64, c.c_int64, c.c_int]
    handle.pt_feeder_num_batches.restype = c.c_int64
    handle.pt_feeder_num_batches.argtypes = [c.c_void_p]
    handle.pt_feeder_samples_total.restype = c.c_int64
    handle.pt_feeder_samples_total.argtypes = [c.c_void_p]
    handle.pt_feeder_next.restype = c.c_int
    handle.pt_feeder_next.argtypes = [c.c_void_p,
                                      c.POINTER(c.c_int32)]
    handle.pt_feeder_next_epoch.argtypes = [c.c_void_p]
    handle.pt_feeder_destroy.argtypes = [c.c_void_p]
    # tensor store (checkpoint blobs)
    handle.pts_writer_open.restype = c.c_void_p
    handle.pts_writer_open.argtypes = [c.c_char_p, c.c_int]
    handle.pts_writer_add.restype = c.c_int
    handle.pts_writer_add.argtypes = [
        c.c_void_p, c.c_char_p, c.c_char_p, c.c_int,
        c.POINTER(c.c_int64), c.c_void_p, c.c_int64]
    handle.pts_writer_close.restype = c.c_int
    handle.pts_writer_close.argtypes = [c.c_void_p]
    handle.pts_reader_open.restype = c.c_void_p
    handle.pts_reader_open.argtypes = [c.c_char_p]
    handle.pts_reader_count.restype = c.c_int64
    handle.pts_reader_count.argtypes = [c.c_void_p]
    handle.pts_reader_error.restype = c.c_char_p
    handle.pts_reader_error.argtypes = [c.c_void_p]
    handle.pts_reader_name.restype = c.c_char_p
    handle.pts_reader_name.argtypes = [c.c_void_p, c.c_int64]
    handle.pts_reader_dtype.restype = c.c_char_p
    handle.pts_reader_dtype.argtypes = [c.c_void_p, c.c_int64]
    handle.pts_reader_ndim.restype = c.c_int
    handle.pts_reader_ndim.argtypes = [c.c_void_p, c.c_int64]
    handle.pts_reader_shape.argtypes = [c.c_void_p, c.c_int64,
                                        c.POINTER(c.c_int64)]
    handle.pts_reader_nbytes.restype = c.c_int64
    handle.pts_reader_nbytes.argtypes = [c.c_void_p, c.c_int64]
    handle.pts_reader_read.restype = c.c_int
    handle.pts_reader_read.argtypes = [c.c_void_p, c.c_int64,
                                       c.c_void_p]
    handle.pts_reader_close.argtypes = [c.c_void_p]
    return handle


class CollectedEvent(ctypes.Structure):
    _fields_ = [
        ("name", ctypes.c_char_p),
        ("start_ns", ctypes.c_uint64),
        ("end_ns", ctypes.c_uint64),
        ("tid", ctypes.c_uint64),
        ("mem_bytes", ctypes.c_int64),
    ]


def lib():
    """The ctypes handle to _native.so, building if needed; None if the
    toolchain or build is unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if _needs_build() and not _build():
            return None
        try:
            _lib = _bind(ctypes.CDLL(_SO))
        except OSError:
            _lib = None
    return _lib


def available() -> bool:
    return lib() is not None
