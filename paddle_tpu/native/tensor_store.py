"""Python wrapper over the native checkpoint tensor store
(tensor_store.cc). Used by paddle.save/load for the tensor payload when
the native toolchain is available; falls back to pure pickle otherwise.
"""
from __future__ import annotations

import ctypes
from typing import Dict, List, Optional, Tuple

import numpy as np

try:  # registers the "bfloat16" dtype name with numpy
    import ml_dtypes  # noqa: F401
except ImportError:
    pass

from . import lib

__all__ = ["save_tensors", "load_tensors", "available"]


def available() -> bool:
    handle = lib()
    return handle is not None and hasattr(handle, "pts_writer_open")


def save_tensors(path: str, tensors: Dict[str, np.ndarray],
                 num_threads: int = 4) -> None:
    """Write named arrays with parallel CRC-checked IO + atomic rename."""
    handle = lib()
    w = handle.pts_writer_open(path.encode(), num_threads)
    keepalive: List[np.ndarray] = []
    for name, arr in tensors.items():
        arr = np.ascontiguousarray(arr)
        keepalive.append(arr)  # must outlive pts_writer_close
        shape = (ctypes.c_int64 * arr.ndim)(*arr.shape)
        rc = handle.pts_writer_add(
            w, name.encode(), str(arr.dtype).encode(), arr.ndim, shape,
            arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes)
        if rc != 0:
            raise IOError(f"tensor_store: add({name!r}) failed")
    if handle.pts_writer_close(w) != 0:
        raise IOError(f"tensor_store: writing {path!r} failed")
    del keepalive


def load_tensors(path: str) -> Dict[str, np.ndarray]:
    """Read all arrays; every payload is CRC-verified."""
    if not available():
        raise RuntimeError(
            f"{path!r} was saved with the native tensor store, but the "
            "C++ toolchain/native build is unavailable here — install "
            "g++ or re-save with FLAGS_use_native_tensor_store=False")
    handle = lib()
    r = handle.pts_reader_open(path.encode())
    try:
        n = handle.pts_reader_count(r)
        if n < 0:
            err = handle.pts_reader_error(r).decode()
            raise IOError(f"tensor_store: {path!r}: {err}")
        out: Dict[str, np.ndarray] = {}
        for i in range(n):
            name = handle.pts_reader_name(r, i).decode()
            dtype = np.dtype(handle.pts_reader_dtype(r, i).decode())
            ndim = handle.pts_reader_ndim(r, i)
            shape = (ctypes.c_int64 * max(ndim, 1))()
            handle.pts_reader_shape(r, i, shape)
            nbytes = handle.pts_reader_nbytes(r, i)
            arr = np.empty(tuple(shape[:ndim]), dtype=dtype)
            if arr.nbytes != nbytes:
                # the index is not CRC-protected; never let a corrupt
                # shape/nbytes pair overflow the destination buffer
                raise IOError(
                    f"tensor_store: {name!r} index inconsistent "
                    f"(shape says {arr.nbytes} bytes, record says "
                    f"{nbytes}) — corrupt checkpoint {path!r}")
            rc = handle.pts_reader_read(
                r, i, arr.ctypes.data_as(ctypes.c_void_p))
            if rc == -2:
                raise IOError(
                    f"tensor_store: CRC mismatch for {name!r} "
                    f"(corrupt checkpoint {path!r})")
            if rc != 0:
                raise IOError(f"tensor_store: read({name!r}) failed")
            out[name] = arr
        return out
    finally:
        handle.pts_reader_close(r)
