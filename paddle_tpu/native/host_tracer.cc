// Host-side event tracer: lock-free per-thread buffers with nanosecond
// timestamps, drained into chrome-trace-ready records.
//
// Reference analog: paddle/fluid/platform/profiler/host_event_recorder.h
// (HostEventRecorder's per-thread lock-free EventContainer feeding
// HostTracer) — rebuilt here as a small C library bound via ctypes (no
// pybind11 in the image). The Python profiler composes this host stream
// with jax.profiler device traces.
//
// Concurrency model: each thread owns a ThreadBuffer (thread_local).
// Registration of a new thread takes the registry mutex once; recording is
// mutex-free. pt_collect() takes the mutex, swaps out completed events and
// returns them in a flat struct array owned by a caller-freed arena.

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

struct Event {
  std::string name;
  uint64_t start_ns;
  uint64_t end_ns;
  uint64_t tid;
  int64_t mem_bytes;  // optional memory-event payload (0 for spans)
};

struct ThreadBuffer {
  std::vector<Event> events;
  std::vector<Event> open;  // stack of in-flight spans
  uint64_t tid = 0;
};

std::mutex g_registry_mu;
std::vector<ThreadBuffer*> g_buffers;
std::atomic<bool> g_enabled{false};
std::atomic<uint64_t> g_next_tid{1};

ThreadBuffer* local_buffer() {
  thread_local ThreadBuffer* buf = nullptr;
  if (buf == nullptr) {
    buf = new ThreadBuffer();
    buf->tid = g_next_tid.fetch_add(1);
    std::lock_guard<std::mutex> lk(g_registry_mu);
    g_buffers.push_back(buf);
  }
  return buf;
}

uint64_t now_ns() {
  return static_cast<uint64_t>(
      std::chrono::duration_cast<std::chrono::nanoseconds>(
          std::chrono::steady_clock::now().time_since_epoch())
          .count());
}

// flat record handed across the C ABI; name is a pointer into the arena
struct CollectedEvent {
  const char* name;
  uint64_t start_ns;
  uint64_t end_ns;
  uint64_t tid;
  int64_t mem_bytes;
};

struct Arena {
  std::vector<Event> events;           // owns strings
  std::vector<CollectedEvent> flat;    // views into events
};

}  // namespace

extern "C" {

void pt_tracer_enable() { g_enabled.store(true); }
void pt_tracer_disable() { g_enabled.store(false); }
int pt_tracer_enabled() { return g_enabled.load() ? 1 : 0; }

uint64_t pt_now_ns() { return now_ns(); }

void pt_record_begin(const char* name) {
  if (!g_enabled.load(std::memory_order_relaxed)) return;
  ThreadBuffer* buf = local_buffer();
  Event ev;
  ev.name = name;
  ev.start_ns = now_ns();
  ev.end_ns = 0;
  ev.tid = buf->tid;
  ev.mem_bytes = 0;
  buf->open.push_back(std::move(ev));
}

void pt_record_end() {
  if (!g_enabled.load(std::memory_order_relaxed)) return;
  ThreadBuffer* buf = local_buffer();
  if (buf->open.empty()) return;
  Event ev = std::move(buf->open.back());
  buf->open.pop_back();
  ev.end_ns = now_ns();
  buf->events.push_back(std::move(ev));
}

// instant event with an explicit payload (e.g. allocator stats)
void pt_record_instant(const char* name, int64_t mem_bytes) {
  if (!g_enabled.load(std::memory_order_relaxed)) return;
  ThreadBuffer* buf = local_buffer();
  Event ev;
  ev.name = name;
  ev.start_ns = now_ns();
  ev.end_ns = ev.start_ns;
  ev.tid = buf->tid;
  ev.mem_bytes = mem_bytes;
  buf->events.push_back(std::move(ev));
}

// Drain all completed events. Returns an opaque arena; *out_events /
// *out_count describe the flat array. Caller must pt_free_events().
void* pt_collect(CollectedEvent** out_events, uint64_t* out_count) {
  Arena* arena = new Arena();
  {
    std::lock_guard<std::mutex> lk(g_registry_mu);
    for (ThreadBuffer* buf : g_buffers) {
      for (Event& ev : buf->events) {
        arena->events.push_back(std::move(ev));
      }
      buf->events.clear();
    }
  }
  arena->flat.reserve(arena->events.size());
  for (const Event& ev : arena->events) {
    CollectedEvent ce;
    ce.name = ev.name.c_str();
    ce.start_ns = ev.start_ns;
    ce.end_ns = ev.end_ns;
    ce.tid = ev.tid;
    ce.mem_bytes = ev.mem_bytes;
    arena->flat.push_back(ce);
  }
  *out_events = arena->flat.data();
  *out_count = arena->flat.size();
  return arena;
}

void pt_free_events(void* arena) { delete static_cast<Arena*>(arena); }

}  // extern "C"
