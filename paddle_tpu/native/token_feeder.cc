// Multithreaded token-batch feeder for LM pretraining.
//
// Reference analog: paddle/fluid/framework/data_feed.cc + the
// multi-process DataLoader workers (imperative/data_loader.cc) — C++
// reader threads assemble batches off the Python thread so the accelerator
// never waits on host IO. TPU-native twist: batches land in a bounded ring
// queue the Python side drains straight into jax.device_put.
//
// The corpus is a flat little-endian int32 token file (memory-mapped,
// read-only). Samples are non-overlapping windows of seq_len+1 tokens
// (inputs + shifted labels share the window). Each epoch is shuffled with
// a splitmix64-seeded Fisher-Yates over the sample index table, sharded
// across dp ranks (rank r takes samples r, r+world, ...), so multi-host
// input pipelines stay disjoint without coordination — the
// DistributedBatchSampler contract.
//
// Concurrency: N worker threads claim sample slots from an atomic cursor
// and write directly into preallocated batch slabs; a mutex+condvar ring
// hands finished slabs to the consumer. pt_feeder_next copies into the
// caller's (numpy) buffer and recycles the slab.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <random>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

struct Feeder {
  const int32_t* tokens = nullptr;
  size_t num_tokens = 0;
  int fd = -1;
  size_t map_len = 0;

  int64_t seq_len = 0;
  int64_t batch_size = 0;
  int64_t rank = 0;
  int64_t world = 1;
  uint64_t seed = 0;
  bool drop_last = true;
  int64_t num_threads = 1;
  int64_t consumed = 0;  // batches handed to the consumer (under mu)

  std::vector<int64_t> order;       // this rank's sample indices (epoch)
  std::atomic<int64_t> cursor{0};   // next batch index to claim
  int64_t num_batches = 0;
  int64_t epoch = 0;

  // ring of finished slabs
  std::mutex mu;
  std::condition_variable ready_cv;
  std::condition_variable space_cv;
  std::deque<int32_t*> ready;
  std::deque<int32_t*> free_slabs;
  size_t capacity = 0;
  bool stopping = false;

  std::vector<std::thread> workers;

  int64_t samples_total() const {
    return static_cast<int64_t>(num_tokens / (seq_len + 1));
  }

  void build_epoch_order() {
    int64_t total = samples_total();
    std::vector<int64_t> all(total);
    for (int64_t i = 0; i < total; ++i) all[i] = i;
    uint64_t s = splitmix64(seed + static_cast<uint64_t>(epoch));
    std::mt19937_64 rng(s);
    for (int64_t i = total - 1; i > 0; --i) {
      int64_t j = static_cast<int64_t>(rng() % (i + 1));
      std::swap(all[i], all[j]);
    }
    order.clear();
    for (int64_t i = rank; i < total; i += world) order.push_back(all[i]);
    int64_t n = static_cast<int64_t>(order.size());
    num_batches = drop_last ? n / batch_size
                            : (n + batch_size - 1) / batch_size;
    cursor.store(0);
  }

  void fill(int32_t* slab, int64_t batch_idx) {
    int64_t stride = seq_len + 1;
    for (int64_t b = 0; b < batch_size; ++b) {
      int64_t k = batch_idx * batch_size + b;
      // pad the (rare) final partial batch by wrapping
      int64_t sample = order[k < (int64_t)order.size()
                                 ? k
                                 : k % order.size()];
      std::memcpy(slab + b * stride, tokens + sample * stride,
                  sizeof(int32_t) * stride);
    }
  }

  void worker_loop() {
    for (;;) {
      int64_t my = cursor.fetch_add(1);
      if (my >= num_batches) return;  // epoch over; thread retires
      int32_t* slab = nullptr;
      {
        std::unique_lock<std::mutex> lk(mu);
        space_cv.wait(lk, [&] { return stopping || !free_slabs.empty(); });
        if (stopping) return;
        slab = free_slabs.front();
        free_slabs.pop_front();
      }
      fill(slab, my);
      {
        std::lock_guard<std::mutex> lk(mu);
        ready.push_back(slab);
      }
      ready_cv.notify_one();
    }
  }
};

}  // namespace

extern "C" {

void* pt_feeder_create(const char* path, int64_t seq_len,
                       int64_t batch_size, int64_t num_threads,
                       uint64_t seed, int64_t capacity, int64_t rank,
                       int64_t world, int drop_last) {
  Feeder* f = new Feeder();
  f->seq_len = seq_len;
  f->batch_size = batch_size;
  f->seed = seed;
  f->rank = rank;
  f->world = world < 1 ? 1 : world;
  f->drop_last = drop_last != 0;
  f->capacity = static_cast<size_t>(capacity < 2 ? 2 : capacity);

  f->fd = open(path, O_RDONLY);
  if (f->fd < 0) {
    delete f;
    return nullptr;
  }
  struct stat st;
  if (fstat(f->fd, &st) != 0 || st.st_size < (seq_len + 1) * 4) {
    close(f->fd);
    delete f;
    return nullptr;
  }
  f->map_len = static_cast<size_t>(st.st_size);
  void* mapped = mmap(nullptr, f->map_len, PROT_READ, MAP_PRIVATE, f->fd, 0);
  if (mapped == MAP_FAILED) {
    close(f->fd);
    delete f;
    return nullptr;
  }
  f->tokens = static_cast<const int32_t*>(mapped);
  f->num_tokens = f->map_len / sizeof(int32_t);

  int64_t stride = seq_len + 1;
  for (size_t i = 0; i < f->capacity; ++i) {
    f->free_slabs.push_back(new int32_t[batch_size * stride]);
  }
  f->build_epoch_order();
  f->num_threads = num_threads < 1 ? 1 : num_threads;
  for (int64_t i = 0; i < f->num_threads; ++i) {
    f->workers.emplace_back([f] { f->worker_loop(); });
  }
  return f;
}

int64_t pt_feeder_num_batches(void* h) {
  return static_cast<Feeder*>(h)->num_batches;
}

int64_t pt_feeder_samples_total(void* h) {
  return static_cast<Feeder*>(h)->samples_total();
}

// Copy the next batch into out (batch_size x (seq_len+1) int32).
// Returns 1 on success, 0 when the epoch is exhausted.
int pt_feeder_next(void* h, int32_t* out) {
  Feeder* f = static_cast<Feeder*>(h);
  int32_t* slab = nullptr;
  {
    std::unique_lock<std::mutex> lk(f->mu);
    // exactly num_batches slabs will be produced per epoch, so the
    // consumed count is the race-free exhaustion signal
    if (f->consumed >= f->num_batches) return 0;
    f->ready_cv.wait(lk, [&] { return !f->ready.empty() || f->stopping; });
    if (f->stopping) return 0;
    slab = f->ready.front();
    f->ready.pop_front();
    f->consumed += 1;
  }
  std::memcpy(out, slab,
              sizeof(int32_t) * f->batch_size * (f->seq_len + 1));
  {
    std::lock_guard<std::mutex> lk(f->mu);
    f->free_slabs.push_back(slab);
  }
  f->space_cv.notify_one();
  return 1;
}

// Start the next epoch (re-shuffle + restart workers). Safe to call with
// the previous epoch only partially consumed: claims are cut off and
// blocked workers are woken BEFORE joining, so they retire instead of
// waiting forever on slabs still parked in the ready ring.
void pt_feeder_next_epoch(void* h) {
  Feeder* f = static_cast<Feeder*>(h);
  {
    std::lock_guard<std::mutex> lk(f->mu);
    f->cursor.store(f->num_batches);
    f->stopping = true;
  }
  f->space_cv.notify_all();
  for (auto& t : f->workers) t.join();
  f->workers.clear();
  {
    std::lock_guard<std::mutex> lk(f->mu);
    f->stopping = false;
    while (!f->ready.empty()) {
      f->free_slabs.push_back(f->ready.front());
      f->ready.pop_front();
    }
  }
  f->epoch += 1;
  f->consumed = 0;
  f->build_epoch_order();
  for (int64_t i = 0; i < f->num_threads; ++i) {
    f->workers.emplace_back([f] { f->worker_loop(); });
  }
}

void pt_feeder_destroy(void* h) {
  Feeder* f = static_cast<Feeder*>(h);
  {
    std::lock_guard<std::mutex> lk(f->mu);
    f->stopping = true;
    f->cursor.store(f->num_batches);
  }
  f->space_cv.notify_all();
  f->ready_cv.notify_all();
  for (auto& t : f->workers) t.join();
  for (int32_t* s : f->free_slabs) delete[] s;
  while (!f->ready.empty()) {
    delete[] f->ready.front();
    f->ready.pop_front();
  }
  munmap(const_cast<int32_t*>(f->tokens), f->map_len);
  close(f->fd);
  delete f;
}

}  // extern "C"
