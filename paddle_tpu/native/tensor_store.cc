// Native checkpoint tensor store: parallel CRC-verified blob IO.
//
// Reference analog: paddle/fluid/framework/save_load_util.cc +
// phi/core/serialization.cc — C++ tensor (de)serialization behind
// paddle.save/load. TPU-native twist: checkpoints of sharded training
// are dominated by big host buffers; this store writes each tensor at
// a precomputed offset with its own worker thread (pwrite, no shared
// file-position contention), CRC32-checks every payload on load, and
// publishes the file with an atomic rename so a killed writer never
// leaves a truncated checkpoint at the final path.
//
// File layout (little endian):
//   "PTCK0001" | u64 index_offset
//   payload blobs ...
//   index at index_offset:
//     u64 count, then per tensor:
//       u32 name_len | name bytes | u32 dtype_len | dtype bytes |
//       u32 ndim | u64 shape[ndim] | u64 offset | u64 nbytes | u32 crc
//
// C ABI (ctypes): pts_writer_* / pts_reader_*.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <string>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

// ---- CRC32 (IEEE, reflected) -------------------------------------------
uint32_t crc_table[256];
bool crc_init_done = []() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k)
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
  return true;
}();

uint32_t crc32(const uint8_t* data, size_t n, uint32_t seed = 0) {
  uint32_t c = seed ^ 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i)
    c = crc_table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Entry {
  std::string name;
  std::string dtype;
  std::vector<uint64_t> shape;
  const uint8_t* data = nullptr;  // writer: caller-owned until close
  uint64_t offset = 0;
  uint64_t nbytes = 0;
  uint32_t crc = 0;
};

struct Writer {
  std::string final_path;
  std::string tmp_path;
  std::vector<Entry> entries;
  std::string error;
  int num_threads = 4;
};

struct Reader {
  int fd = -1;
  std::vector<Entry> entries;
  std::string error;
};

void put_u32(std::string& b, uint32_t v) { b.append((char*)&v, 4); }
void put_u64(std::string& b, uint64_t v) { b.append((char*)&v, 8); }

bool read_exact(int fd, void* dst, size_t n, uint64_t off) {
  uint8_t* p = (uint8_t*)dst;
  while (n) {
    ssize_t r = pread(fd, p, n, off);
    if (r <= 0) return false;
    p += r;
    off += r;
    n -= r;
  }
  return true;
}

bool write_exact(int fd, const void* src, size_t n, uint64_t off) {
  const uint8_t* p = (const uint8_t*)src;
  while (n) {
    ssize_t r = pwrite(fd, p, n, off);
    if (r <= 0) return false;
    p += r;
    off += r;
    n -= r;
  }
  return true;
}

}  // namespace

extern "C" {

void* pts_writer_open(const char* path, int num_threads) {
  auto* w = new Writer();
  w->final_path = path;
  w->tmp_path = std::string(path) + ".tmp." + std::to_string(getpid());
  w->num_threads = num_threads > 0 ? num_threads : 4;
  return w;
}

// Caller must keep `data` alive until pts_writer_close returns.
int pts_writer_add(void* handle, const char* name, const char* dtype,
                   int ndim, const int64_t* shape, const void* data,
                   int64_t nbytes) {
  auto* w = (Writer*)handle;
  Entry e;
  e.name = name;
  e.dtype = dtype;
  for (int i = 0; i < ndim; ++i) e.shape.push_back((uint64_t)shape[i]);
  e.data = (const uint8_t*)data;
  e.nbytes = (uint64_t)nbytes;
  w->entries.push_back(std::move(e));
  return 0;
}

int pts_writer_close(void* handle) {
  auto* w = (Writer*)handle;
  int rc = 0;
  int fd = open(w->tmp_path.c_str(), O_CREAT | O_TRUNC | O_WRONLY, 0644);
  if (fd < 0) {
    w->error = "cannot open " + w->tmp_path;
    rc = -1;
  } else {
    // layout: header(16) then payloads back to back
    uint64_t off = 16;
    for (auto& e : w->entries) {
      e.offset = off;
      off += e.nbytes;
    }
    uint64_t index_offset = off;
    if (ftruncate(fd, (off_t)index_offset) != 0) { /* best effort */ }

    // parallel payload write + crc, one range of tensors per thread
    std::atomic<size_t> cursor{0};
    std::atomic<bool> failed{false};
    auto work = [&]() {
      for (;;) {
        size_t i = cursor.fetch_add(1);
        if (i >= w->entries.size() || failed.load()) return;
        Entry& e = w->entries[i];
        e.crc = crc32(e.data, e.nbytes);
        if (!write_exact(fd, e.data, e.nbytes, e.offset))
          failed.store(true);
      }
    };
    std::vector<std::thread> threads;
    int nt = std::min<int>(w->num_threads, (int)w->entries.size());
    for (int t = 0; t < std::max(nt, 1); ++t)
      threads.emplace_back(work);
    for (auto& t : threads) t.join();

    // index
    std::string idx;
    put_u64(idx, (uint64_t)w->entries.size());
    for (auto& e : w->entries) {
      put_u32(idx, (uint32_t)e.name.size());
      idx += e.name;
      put_u32(idx, (uint32_t)e.dtype.size());
      idx += e.dtype;
      put_u32(idx, (uint32_t)e.shape.size());
      for (uint64_t s : e.shape) put_u64(idx, s);
      put_u64(idx, e.offset);
      put_u64(idx, e.nbytes);
      put_u32(idx, e.crc);
    }
    std::string header = "PTCK0001";
    put_u64(header, index_offset);
    bool ok = !failed.load() &&
              write_exact(fd, idx.data(), idx.size(), index_offset) &&
              write_exact(fd, header.data(), header.size(), 0) &&
              fsync(fd) == 0;
    close(fd);
    if (ok) {
      if (rename(w->tmp_path.c_str(), w->final_path.c_str()) != 0) {
        w->error = "rename failed";
        rc = -1;
      }
    } else {
      w->error = "write failed";
      rc = -1;
    }
    if (rc != 0) unlink(w->tmp_path.c_str());
  }
  delete w;
  return rc;
}

void* pts_reader_open(const char* path) {
  auto* r = new Reader();
  r->fd = open(path, O_RDONLY);
  if (r->fd < 0) {
    r->error = "cannot open";
    return r;
  }
  char header[16];
  if (!read_exact(r->fd, header, 16, 0) ||
      memcmp(header, "PTCK0001", 8) != 0) {
    r->error = "bad magic";
    return r;
  }
  uint64_t index_offset;
  memcpy(&index_offset, header + 8, 8);
  off_t fsize = lseek(r->fd, 0, SEEK_END);
  if (index_offset >= (uint64_t)fsize) {
    r->error = "bad index offset";
    return r;
  }
  std::vector<uint8_t> idx(fsize - index_offset);
  if (!read_exact(r->fd, idx.data(), idx.size(), index_offset)) {
    r->error = "bad index";
    return r;
  }
  size_t p = 0;
  auto get_u32 = [&](uint32_t& v) {
    if (p + 4 > idx.size()) return false;
    memcpy(&v, &idx[p], 4);
    p += 4;
    return true;
  };
  auto get_u64 = [&](uint64_t& v) {
    if (p + 8 > idx.size()) return false;
    memcpy(&v, &idx[p], 8);
    p += 8;
    return true;
  };
  uint64_t count;
  if (!get_u64(count)) {
    r->error = "bad index";
    return r;
  }
  for (uint64_t i = 0; i < count; ++i) {
    Entry e;
    uint32_t nlen, dlen, nd, crc;
    if (!get_u32(nlen) || p + nlen > idx.size()) goto bad;
    e.name.assign((char*)&idx[p], nlen);
    p += nlen;
    if (!get_u32(dlen) || p + dlen > idx.size()) goto bad;
    e.dtype.assign((char*)&idx[p], dlen);
    p += dlen;
    if (!get_u32(nd)) goto bad;
    for (uint32_t d = 0; d < nd; ++d) {
      uint64_t s;
      if (!get_u64(s)) goto bad;
      e.shape.push_back(s);
    }
    if (!get_u64(e.offset) || !get_u64(e.nbytes) || !get_u32(crc))
      goto bad;
    e.crc = crc;
    r->entries.push_back(std::move(e));
  }
  return r;
bad:
  r->error = "corrupt index";
  r->entries.clear();
  return r;
}

int64_t pts_reader_count(void* handle) {
  auto* r = (Reader*)handle;
  return r->error.empty() ? (int64_t)r->entries.size() : -1;
}

const char* pts_reader_error(void* handle) {
  return ((Reader*)handle)->error.c_str();
}

const char* pts_reader_name(void* handle, int64_t i) {
  return ((Reader*)handle)->entries[i].name.c_str();
}

const char* pts_reader_dtype(void* handle, int64_t i) {
  return ((Reader*)handle)->entries[i].dtype.c_str();
}

int pts_reader_ndim(void* handle, int64_t i) {
  return (int)((Reader*)handle)->entries[i].shape.size();
}

void pts_reader_shape(void* handle, int64_t i, int64_t* out) {
  auto& e = ((Reader*)handle)->entries[i];
  for (size_t d = 0; d < e.shape.size(); ++d)
    out[d] = (int64_t)e.shape[d];
}

int64_t pts_reader_nbytes(void* handle, int64_t i) {
  return (int64_t)((Reader*)handle)->entries[i].nbytes;
}

// Returns 0 on success, -2 on CRC mismatch, -1 on IO error.
int pts_reader_read(void* handle, int64_t i, void* dst) {
  auto* r = (Reader*)handle;
  auto& e = r->entries[i];
  if (!read_exact(r->fd, dst, e.nbytes, e.offset)) return -1;
  if (crc32((const uint8_t*)dst, e.nbytes) != e.crc) return -2;
  return 0;
}

void pts_reader_close(void* handle) {
  auto* r = (Reader*)handle;
  if (r->fd >= 0) close(r->fd);
  delete r;
}

}  // extern "C"
