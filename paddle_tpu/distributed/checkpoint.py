"""Sharded, async, topology-aware checkpointing (orbax-backed).

Reference analogs:
- paddle.save/load object tier → framework_io.py (pickle).
- Sharded/async distributed tier (this module): the reference's
  per-stage/per-rank shard saves (group_sharded utils,
  hybrid_parallel_pp_save_load tests) become orbax OCDBT checkpoints of
  the GLOBAL arrays — every host writes only its addressable shards,
  restore re-assembles under ANY new mesh/sharding.
- Cross-strategy resharding (auto_parallel/converter.py: reshard a ckpt
  saved under one parallel strategy into another) → `with_shardings` on
  restore: orbax places each array straight into the requested
  NamedSharding, so dp-saved → tp-restored "conversion" is a placement
  argument, not a data shuffle pass.
- Auto-checkpoint (fluid/incubate/checkpoint/auto_checkpoint.py:72:
  epoch-granular transparent resume) → CheckpointManager(max_to_keep,
  save_interval) + `resume()`.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..core.tensor import Tensor


def _to_raw_tree(obj):
    """Tensors/np → jax arrays; containers preserved; scalars pass."""
    if isinstance(obj, Tensor):
        return obj._data
    if isinstance(obj, (dict,)):
        return {k: _to_raw_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_to_raw_tree(v) for v in obj]  # orbax prefers lists
    return obj


def _wrap_tree(obj):
    if isinstance(obj, (jax.Array, np.ndarray)):
        return Tensor(obj)
    if isinstance(obj, dict):
        return {k: _wrap_tree(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_wrap_tree(v) for v in obj]
    return obj


def _target_from_shardings(metadata, shardings):
    """Abstract restore target: checkpoint metadata supplies shape/dtype,
    the shardings tree supplies placement (the converter.py analog: each
    leaf restores straight into the NEW strategy's sharding). The
    shardings tree must cover the full checkpoint tree."""

    metadata = getattr(metadata, "item_metadata", metadata)  # StepMetadata

    def walk(sh, md_node):
        if isinstance(sh, dict):
            return {k: walk(v, md_node[k]) for k, v in sh.items()}
        if isinstance(sh, (list, tuple)):
            return [walk(v, md_node[i]) for i, v in enumerate(sh)]
        return jax.ShapeDtypeStruct(tuple(md_node.shape), md_node.dtype,
                                    sharding=sh)

    return walk(shardings, metadata)


class CheckpointManager:
    """Epoch/step-granular async sharded checkpoints with retention.

    Usage:
        mgr = CheckpointManager(dir, max_to_keep=3, async_save=True)
        mgr.save(step, {"model": model.state_dict(),
                        "opt": opt.state_dict()})
        ...
        state = mgr.restore()                 # latest
        state = mgr.restore(step=7)
        mgr.wait()                            # block on in-flight saves
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = True,
                 save_interval_steps: int = 1):
        import orbax.checkpoint as ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._ocp = ocp
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_save)
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    def save(self, step: int, state: Dict[str, Any]) -> bool:
        """Queues (async) or writes a checkpoint of the (possibly
        sharded) state tree. Returns False if skipped by
        save_interval_steps."""
        args = self._ocp.args.StandardSave(_to_raw_tree(state))
        return self._mgr.save(step, args=args)

    def restore(self, step: Optional[int] = None, shardings=None):
        """Restore a state tree; `shardings` (same tree structure, leaves
        = NamedSharding) reshards on the fly — the cross-strategy
        converter. Returns Tensors."""
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        if shardings is not None:
            md = self._mgr.item_metadata(step)
            target = _target_from_shardings(md, shardings)
            args = self._ocp.args.StandardRestore(target)
        else:
            args = self._ocp.args.StandardRestore()
        tree = self._mgr.restore(step, args=args)
        return _wrap_tree(tree)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def wait(self):
        self._mgr.wait_until_finished()

    def close(self):
        self.wait()
        self._mgr.close()


# ------------------------------------------------------- one-shot helpers

def save_sharded(state: Dict[str, Any], path: str,
                 async_save: bool = False):
    """One-shot sharded save (paddle.save analog for distributed state:
    every host writes its addressable shards; call from ALL hosts).
    With async_save=True, returns the checkpointer — call its
    wait_until_finished() before exiting."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, _to_raw_tree(state), force=True)
    if not async_save:
        ckptr.wait_until_finished()
    return ckptr


def load_sharded(path: str, shardings=None):
    """One-shot restore; `shardings` reshards onto a new strategy
    (must mirror the full checkpoint tree when given)."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    if shardings is not None:
        target = _target_from_shardings(ckptr.metadata(path), shardings)
        tree = ckptr.restore(path, target)
    else:
        tree = ckptr.restore(path)
    return _wrap_tree(tree)


def shardings_for_model(model, mesh=None, strategy=None):
    """NamedSharding tree matching a model's state_dict under the active
    mesh + ZeRO strategy — feed to restore(shardings=...) to convert a
    checkpoint to this strategy (≈ auto_parallel/converter.py)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from . import topology
    from .parallel.sharding import ShardingStrategy
    mesh = mesh or topology.get_mesh()
    if mesh is None:
        return None
    strategy = strategy or ShardingStrategy(stage=0)
    out = {}
    params = dict(model.named_parameters())
    for name, t in model.state_dict().items():
        base = getattr(t, "spec", None)
        if name in params:
            spec = strategy.param_spec(tuple(t.shape), mesh,
                                       base if base is not None else P())
        else:
            spec = base if base is not None else P()
        out[name] = NamedSharding(mesh, spec)
    return out
