"""Sharded, async, topology-aware checkpointing (orbax-backed).

Reference analogs:
- paddle.save/load object tier → framework_io.py (pickle).
- Sharded/async distributed tier (this module): the reference's
  per-stage/per-rank shard saves (group_sharded utils,
  hybrid_parallel_pp_save_load tests) become orbax OCDBT checkpoints of
  the GLOBAL arrays — every host writes only its addressable shards,
  restore re-assembles under ANY new mesh/sharding.
- Cross-strategy resharding (auto_parallel/converter.py: reshard a ckpt
  saved under one parallel strategy into another) → `with_shardings` on
  restore: orbax places each array straight into the requested
  NamedSharding, so dp-saved → tp-restored "conversion" is a placement
  argument, not a data shuffle pass.
- Auto-checkpoint (fluid/incubate/checkpoint/auto_checkpoint.py:72:
  epoch-granular transparent resume) → CheckpointManager(max_to_keep,
  save_interval) + `resume()`.
- Fault tolerance (this PR's resilience layer): every committed step
  carries a `_PADDLE_COMMIT` marker recording the tree's leaf
  shapes/dtypes; `restore()` validates it and falls back step-by-step
  (latest → previous → ...) past truncated or uncommitted checkpoints,
  reporting every skipped step through `core.monitor`.
  `save_on_preemption()` registers the manager with the active
  `resilience.GracefulShutdown` so a SIGTERM triggers a synchronous
  emergency save before the elastic relaunch.
- Input-pipeline state (this PR): `DataLoader.state_dict()` trees
  (batch cursor + sampler epoch/seed — plain int leaves) ride inside
  the same save/restore trees; orbax round-trips them and
  `DataLoader.load_state_dict` coerces the restored 0-d leaves, so a
  per-step checkpoint pins the exact mid-epoch resume point alongside
  model and optimizer state.
"""
from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

from ..core import flight_recorder, monitor
from ..core.tensor import Tensor

COMMIT_MARKER = "_PADDLE_COMMIT"


class CheckpointCorruption(RuntimeError):
    """No restorable checkpoint: every candidate step failed commit
    validation or raised during restore."""


def _flatten_tree(tree) -> Dict[str, Any]:
    """Flat {'/'-joined path: leaf} view of a dict/list tree — the one
    traversal both the commit-marker writer and validate() key off, so
    their paths can never drift apart."""
    out: Dict[str, Any] = {}

    def walk(node, prefix):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, f"{prefix}/{k}" if prefix else str(k))
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                walk(v, f"{prefix}/{i}" if prefix else str(i))
        else:
            out[prefix] = node

    walk(tree, "")
    return out


def _leaf_metadata(tree) -> Dict[str, Dict[str, Any]]:
    """Flat {path: {shape, dtype}} map of the raw state tree — the
    structural contract a restore validates against."""
    out: Dict[str, Dict[str, Any]] = {}
    for path, leaf in _flatten_tree(tree).items():
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            out[path] = {"shape": list(leaf.shape),
                         "dtype": str(np.dtype(leaf.dtype))}
        else:
            out[path] = {"shape": None, "dtype": type(leaf).__name__}
    return out


def _to_raw_tree(obj):
    """Tensors/np → jax arrays; containers preserved; scalars pass."""
    if isinstance(obj, Tensor):
        return obj._data
    if isinstance(obj, np.generic):
        # orbax StandardSave rejects numpy scalar types; 0-d arrays
        # round-trip fine (restored as shape-() arrays)
        return np.asarray(obj)
    if isinstance(obj, (dict,)):
        return {k: _to_raw_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_to_raw_tree(v) for v in obj]  # orbax prefers lists
    return obj


def _wrap_tree(obj):
    if isinstance(obj, (jax.Array, np.ndarray)):
        return Tensor(obj)
    if isinstance(obj, dict):
        return {k: _wrap_tree(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_wrap_tree(v) for v in obj]
    return obj


def _target_from_shardings(metadata, shardings):
    """Abstract restore target: checkpoint metadata supplies shape/dtype,
    the shardings tree supplies placement (the converter.py analog: each
    leaf restores straight into the NEW strategy's sharding). The
    shardings tree must cover the full checkpoint tree."""

    metadata = getattr(metadata, "item_metadata", metadata)  # StepMetadata

    def walk(sh, md_node):
        if isinstance(sh, dict):
            return {k: walk(v, md_node[k]) for k, v in sh.items()}
        if isinstance(sh, (list, tuple)):
            return [walk(v, md_node[i]) for i, v in enumerate(sh)]
        return jax.ShapeDtypeStruct(tuple(md_node.shape), md_node.dtype,
                                    sharding=sh)

    return walk(shardings, metadata)


class CheckpointManager:
    """Epoch/step-granular async sharded checkpoints with retention.

    Usage:
        mgr = CheckpointManager(dir, max_to_keep=3, async_save=True)
        mgr.save(step, {"model": model.state_dict(),
                        "opt": opt.state_dict()})
        ...
        state = mgr.restore()                 # latest
        state = mgr.restore(step=7)
        mgr.wait()                            # block on in-flight saves
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = True,
                 save_interval_steps: int = 1):
        import orbax.checkpoint as ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._ocp = ocp
        self._async = bool(async_save)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_save)
        self._mgr = ocp.CheckpointManager(self.directory, options=options)
        # commit markers for async saves flush in wait(), when the data
        # they vouch for has actually hit disk
        self._pending_markers: Dict[int, Dict[str, Any]] = {}
        self._unregister_emergency: Optional[Callable[[], None]] = None
        self.last_restored_step: Optional[int] = None

    def save(self, step: int, state: Dict[str, Any],
             force: bool = False) -> bool:
        """Queues (async) or writes a checkpoint of the (possibly
        sharded) state tree. Returns False if skipped by
        save_interval_steps (``force=True`` bypasses the interval — the
        emergency-save path)."""
        raw = _to_raw_tree(state)
        meta = _leaf_metadata(raw)
        args = self._ocp.args.StandardSave(raw)
        try:
            saved = self._mgr.save(step, args=args, force=force)
        except self._ocp.checkpoint_manager.StepAlreadyExistsError:
            if not force:
                raise
            # forced (emergency) save of a step the periodic path just
            # committed: the state is already on disk — that IS success,
            # not a failure to swallow (make sure the marker exists too)
            self._write_marker(int(step), meta)
            return True
        if saved:
            if self._async:
                self._pending_markers[int(step)] = meta
            else:
                self._write_marker(int(step), meta)
        return saved

    # ------------------------------------------------- commit markers
    def _marker_path(self, step: int) -> str:
        return os.path.join(self.directory, str(step), COMMIT_MARKER)

    def _write_marker(self, step: int, meta: Dict[str, Any]) -> None:
        if jax.process_index() != 0:
            return
        step_dir = os.path.join(self.directory, str(step))
        if not os.path.isdir(step_dir):  # e.g. already garbage-collected
            return
        tmp = self._marker_path(step) + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({"step": int(step), "leaves": meta}, f)
            os.replace(tmp, self._marker_path(step))
            # black-box breadcrumb: a post-mortem dump shows which step
            # last committed, next to the preemption/watchdog events
            flight_recorder.record("checkpoint.commit", step=int(step))
        except OSError as e:
            monitor.record_swallowed("checkpoint.commit_marker", e)

    def validate(self, step: int) -> bool:
        """Structural pre-check of a committed step: the commit marker's
        leaf shapes/dtypes must match orbax's on-disk metadata. A step
        with NO marker passes (legacy checkpoints predate markers) — a
        present-but-unreadable or mismatched marker fails."""
        marker = self._marker_path(step)
        if not os.path.exists(marker):
            return True
        try:
            with open(marker) as f:
                recorded = json.load(f)["leaves"]
        except (OSError, ValueError, KeyError):
            return False
        try:
            md = self._mgr.item_metadata(step)
        except Exception:
            md = None
        md = getattr(md, "item_metadata", md)
        if md is None:
            # metadata unavailable (fresh manager without a handler
            # registry): inconclusive, let the restore attempt decide
            return True
        on_disk = _flatten_tree(md)
        if not on_disk:
            return True  # metadata empty/unreconstructable: inconclusive
        for path, leaf in recorded.items():
            if leaf["shape"] is None:
                continue  # non-array leaf: no orbax shape contract
            got = on_disk.get(path)
            if got is None or list(getattr(got, "shape", ())) != \
                    leaf["shape"]:
                return False
            got_dtype = getattr(got, "dtype", None)
            if got_dtype is not None and \
                    str(np.dtype(got_dtype)) != leaf["dtype"]:
                return False
        return True

    # ------------------------------------------------------- restore
    def restore(self, step: Optional[int] = None, shardings=None,
                fallback: Optional[bool] = None):
        """Restore a state tree; `shardings` (same tree structure, leaves
        = NamedSharding) reshards on the fly — the cross-strategy
        converter. Returns Tensors.

        Fallback: when restoring the latest step (``step=None``, or any
        step with ``fallback=True``), a truncated/uncommitted candidate
        is skipped and the next older step is tried, each skip reported
        via ``core.monitor`` (``resilience.ckpt.fallback``). An explicit
        ``step`` with ``fallback=False`` (the default there) raises
        ``CheckpointCorruption`` instead."""
        self.wait()
        steps = self.all_steps()
        if fallback is None:
            fallback = step is None
        if step is None:
            candidates = list(reversed(steps))
        elif fallback:
            candidates = [s for s in reversed(steps) if s <= step]
        else:
            candidates = [step]
        if not candidates:
            return None

        skipped: List[int] = []
        last_err: Optional[BaseException] = None
        for s in candidates:
            if not self.validate(s):
                err = CheckpointCorruption(
                    f"checkpoint step {s} in {self.directory}: commit "
                    f"marker mismatch")
                if not fallback:
                    # explicit step, no fallback: the caller gets the
                    # specific diagnosis, and no fallback metric fires
                    raise err
                monitor.record_ckpt_fallback(s)
                monitor.record_swallowed("checkpoint.restore", err)
                skipped.append(s)
                continue
            try:
                tree = self._restore_step(s, shardings)
            except Exception as e:  # truncated/corrupt payload
                if not fallback:
                    raise CheckpointCorruption(
                        f"checkpoint step {s} in {self.directory} failed "
                        f"to restore: {e}") from e
                monitor.record_ckpt_fallback(s)
                monitor.record_swallowed("checkpoint.restore", e)
                skipped.append(s)
                last_err = e
                continue
            if skipped:
                import sys
                sys.stderr.write(
                    f"CheckpointManager: skipped corrupt/uncommitted "
                    f"step(s) {skipped}, restored step {s} from "
                    f"{self.directory}\n")
            self.last_restored_step = s
            return _wrap_tree(tree)
        raise CheckpointCorruption(
            f"no restorable checkpoint in {self.directory}: tried "
            f"{candidates}, skipped {skipped}"
            + (f"; last error: {last_err}" if last_err else ""))

    def _restore_step(self, step: int, shardings=None):
        if shardings is not None:
            md = self._mgr.item_metadata(step)
            target = _target_from_shardings(md, shardings)
            args = self._ocp.args.StandardRestore(target)
        else:
            args = self._ocp.args.StandardRestore()
        return self._mgr.restore(step, args=args)

    # ------------------------------------------------------ lifecycle
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def wait(self, timeout: Optional[float] = None):
        """Block on in-flight async saves, then publish their commit
        markers. ``timeout`` (or PADDLE_WATCHDOG_CKPT_S) arms the hang
        watchdog around the orbax wait."""
        from . import resilience
        if timeout is None:
            timeout = resilience.env_timeout("PADDLE_WATCHDOG_CKPT_S")
        resilience.guarded_call(self._mgr.wait_until_finished,
                                label="checkpoint.wait", timeout=timeout)
        if self._pending_markers:
            done = set(self._mgr.all_steps())
            for s, meta in list(self._pending_markers.items()):
                if s in done:
                    self._write_marker(s, meta)
                del self._pending_markers[s]

    def save_on_preemption(self, state_fn: Callable[[], Dict[str, Any]]
                           ) -> Callable[[], None]:
        """Register this manager for the resilience layer's emergency
        save: on preemption, ``state_fn()`` is checkpointed synchronously
        at the preempted step (interval bypassed). Returns an unregister
        callable; ``close()`` also unregisters."""
        from . import resilience

        def _emergency(step: int) -> None:
            self.save(step, state_fn(), force=True)
            self.wait()

        if self._unregister_emergency is not None:
            self._unregister_emergency()
        self._unregister_emergency = resilience.register_emergency(
            _emergency)
        return self._unregister_emergency

    def close(self):
        if self._unregister_emergency is not None:
            self._unregister_emergency()
            self._unregister_emergency = None
        self.wait()
        self._mgr.close()


# ------------------------------------------------------- one-shot helpers

def save_sharded(state: Dict[str, Any], path: str,
                 async_save: bool = False):
    """One-shot sharded save (paddle.save analog for distributed state:
    every host writes its addressable shards; call from ALL hosts).
    With async_save=True, returns the checkpointer — call its
    wait_until_finished() before exiting."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, _to_raw_tree(state), force=True)
    if not async_save:
        ckptr.wait_until_finished()
    return ckptr


def load_sharded(path: str, shardings=None):
    """One-shot restore; `shardings` reshards onto a new strategy
    (must mirror the full checkpoint tree when given)."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    if shardings is not None:
        target = _target_from_shardings(ckptr.metadata(path), shardings)
        tree = ckptr.restore(path, target)
    else:
        tree = ckptr.restore(path)
    return _wrap_tree(tree)


def shardings_for_model(model, mesh=None, strategy=None):
    """NamedSharding tree matching a model's state_dict under the active
    mesh + ZeRO strategy — feed to restore(shardings=...) to convert a
    checkpoint to this strategy (≈ auto_parallel/converter.py)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from . import topology
    from .parallel.sharding import ShardingStrategy
    mesh = mesh or topology.get_mesh()
    if mesh is None:
        return None
    strategy = strategy or ShardingStrategy(stage=0)
    out = {}
    params = dict(model.named_parameters())
    for name, t in model.state_dict().items():
        base = getattr(t, "spec", None)
        if name in params:
            spec = strategy.param_spec(tuple(t.shape), mesh,
                                       base if base is not None else P())
        else:
            spec = base if base is not None else P()
        out[name] = NamedSharding(mesh, spec)
    return out
