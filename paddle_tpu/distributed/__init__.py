from . import auto_parallel  # noqa: F401
from . import collective  # noqa: F401
from . import fleet  # noqa: F401
from . import topology  # noqa: F401
from .auto_parallel import (Engine, ProcessMesh, shard_layer,  # noqa: F401
                            shard_op, shard_tensor)
from . import stream  # noqa: F401
from .fleet_dataset import (CountFilterEntry, InMemoryDataset,  # noqa: F401
                            ProbabilityEntry, QueueDataset,
                            ShowClickEntry)
from .comm_extra import (Group, ParallelMode, all_gather_object,  # noqa: F401
                         destroy_process_group, get_group,
                         gloo_barrier, gloo_init_parallel_env,
                         gloo_release, irecv, isend, new_group, recv,
                         reduce, send, split, wait)
from .collective import (ReduceOp, all_gather, all_reduce,  # noqa: F401
                         all_to_all, alltoall_single, broadcast,
                         reduce_scatter, scatter)
from .env import (ParallelEnv, barrier, get_rank, get_world_size,  # noqa: F401
                  init_parallel_env, is_initialized)
from .parallel import (mp_layers, pipeline, random, recompute,  # noqa: F401
                       sharding)
from .parallel.pipeline import (LayerDesc, PipelineLayer,  # noqa: F401
                                PipelineParallel, SharedLayerDesc)
from .parallel.mp_layers import (ColumnParallelLinear,  # noqa: F401
                                 ParallelCrossEntropy, RowParallelLinear,
                                 VocabParallelEmbedding)
from .parallel.random import get_rng_state_tracker  # noqa: F401
from .parallel.recompute import RecomputeWrapper, recompute  # noqa: F401
from .parallel.sharding import (ShardingStrategy,  # noqa: F401
                                group_sharded_parallel)
from .topology import (HybridCommunicateGroup, create_mesh,  # noqa: F401
                       get_hybrid_communicate_group, get_mesh,
                       set_hybrid_communicate_group)
from . import auto_checkpoint  # noqa: F401
from . import elastic  # noqa: F401
from . import launch  # noqa: F401
from . import resilience  # noqa: F401
from . import rpc  # noqa: F401
from .elastic import ElasticManager  # noqa: F401
from .resilience import (AnomalyGuard, GracefulShutdown,  # noqa: F401
                         Watchdog, WatchdogTimeout)
from .spawn import spawn  # noqa: F401
from .store import TCPStore  # noqa: F401

alltoall = all_to_all
