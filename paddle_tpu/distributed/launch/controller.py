"""Pod controller: spawn per-rank worker processes, watch, reap.

Reference analog: CollectiveController.build_pod
(python/paddle/distributed/launch/controllers/collective.py:32,75,154)
— crafts PADDLE_TRAINER_ENDPOINTS/PADDLE_MASTER/rank env per worker and
the watch() poll loop (launch/controllers/controller.py:74).

TPU-native differences: there is no NCCL endpoint list to distribute —
workers rendezvous through jax.distributed's coordinator (the launcher
just points everyone at it) — and on a real pod slice the natural layout
is ONE process per host driving all local chips, so ``nproc_per_node``
defaults to 1 (raise it only for virtual-CPU testing).
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..elastic import ELASTIC_EXIT_CODE, ELASTIC_SCALE_CODE  # noqa: F401
from ..env_contract import build_rank_env


@dataclass
class JobSpec:
    script: str
    script_args: List[str] = field(default_factory=list)
    nnodes: int = 1
    node_rank: int = 0
    nproc_per_node: int = 1
    master: str = "127.0.0.1:0"  # host:port of the coordinator
    job_id: str = "default"
    log_dir: Optional[str] = None
    envs: Dict[str, str] = field(default_factory=dict)
    max_restarts: int = 0
    # fault-tolerant elastic (reference fleet/elastic/manager.py:128):
    # restart the pod on ANY abnormal worker death — including signal
    # kills (preemption) — not just the cooperative 101/102 codes
    elastic_on_failure: bool = False


class Pod:
    """The set of worker processes owned by this node's controller.

    ``restart`` is the elastic incarnation number, exported to workers
    as PADDLE_RESTART_COUNT so per-incarnation state (the resilience
    layer's preemption flag in the TCPStore) can be namespaced — a
    relaunched pod must not see the previous incarnation's flags."""

    def __init__(self, spec: JobSpec, restart: int = 0):
        self.spec = spec
        self.restart = int(restart)
        self.procs: List[subprocess.Popen] = []
        self.logs: List[object] = []

    @property
    def world_size(self) -> int:
        return self.spec.nnodes * self.spec.nproc_per_node

    def rank_env(self, local_rank: int) -> Dict[str, str]:
        spec = self.spec
        rank = spec.node_rank * spec.nproc_per_node + local_rank
        env = dict(os.environ)
        env.update(spec.envs)
        env.update(build_rank_env(rank, self.world_size, local_rank,
                                  spec.master, nnodes=spec.nnodes,
                                  job_id=spec.job_id))
        env["PADDLE_RESTART_COUNT"] = str(self.restart)
        return env

    def start(self) -> None:
        spec = self.spec
        if spec.log_dir:
            os.makedirs(spec.log_dir, exist_ok=True)
        for lr in range(spec.nproc_per_node):
            cmd = [sys.executable, "-u", spec.script, *spec.script_args]
            if spec.log_dir:
                rank = spec.node_rank * spec.nproc_per_node + lr
                log = open(os.path.join(spec.log_dir,
                                        f"workerlog.{rank}"), "ab")
                self.logs.append(log)
                out = log
            else:
                out = None
            self.procs.append(subprocess.Popen(
                cmd, env=self.rank_env(lr), stdout=out,
                stderr=subprocess.STDOUT if out else None))

    def poll(self) -> Optional[int]:
        """None while all run; first non-zero code, or 0 when all done."""
        codes = [p.poll() for p in self.procs]
        for c in codes:
            if c is not None and c != 0:
                return c
        if all(c == 0 for c in codes):
            return 0
        return None

    def stop(self, sig=signal.SIGTERM, grace: float = 10.0) -> None:
        for p in self.procs:
            if p.poll() is None:
                try:
                    p.send_signal(sig)
                except OSError:
                    pass
        deadline = time.monotonic() + grace
        for p in self.procs:
            left = max(0.1, deadline - time.monotonic())
            try:
                p.wait(timeout=left)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in self.logs:
            try:
                log.close()
            except OSError:
                pass
        self.procs, self.logs = [], []


class Controller:
    """watch() loop: run the pod to completion, restarting on elastic
    exit codes up to max_restarts."""

    def __init__(self, spec: JobSpec):
        self.spec = spec
        self.pod = Pod(spec)

    def run(self) -> int:
        restarts = 0
        self.pod.start()
        prev = {signal.SIGTERM: signal.signal(signal.SIGTERM,
                                              self._forward),
                signal.SIGINT: signal.signal(signal.SIGINT,
                                             self._forward)}
        try:
            while True:
                code = self.pod.poll()
                if code is None:
                    time.sleep(0.2)
                    continue
                restartable = code in (ELASTIC_EXIT_CODE,
                                       ELASTIC_SCALE_CODE) or \
                    (self.spec.elastic_on_failure and code != 0)
                if restartable and \
                        restarts < self.spec.max_restarts:
                    restarts += 1
                    self.pod.stop()
                    self.pod = Pod(self.spec, restart=restarts)
                    self.pod.start()
                    continue
                if code != 0:
                    self.pod.stop()
                return code
        finally:
            for sig, h in prev.items():
                signal.signal(sig, h)

    def _forward(self, signum, frame):
        self.pod.stop(sig=signum)
        raise SystemExit(128 + signum)
