"""CLI: python -m paddle_tpu.distributed.launch [opts] script.py [args].

Reference analog: python/paddle/distributed/launch/main.py:18 (argparse
front end over controllers). The multi-node master is just host:port of
node 0; jax.distributed's coordination service plays the role the
reference splits between the HTTP/etcd master (controllers/master.py)
and the NCCL-id TCPStore exchange.
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from .controller import Controller, JobSpec
from ..store import free_port


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="Launch a multi-process paddle_tpu training job.")
    p.add_argument("--nnodes", type=int,
                   default=int(os.environ.get("PADDLE_NNODES", "1")),
                   help="number of nodes (hosts) in the job")
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")),
                   help="rank of this node in [0, nnodes)")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="worker processes on this node (TPU: 1 process "
                        "drives all local chips; raise only for "
                        "virtual-CPU testing)")
    p.add_argument("--master", type=str,
                   default=os.environ.get("PADDLE_MASTER", ""),
                   help="host:port of the coordinator (node 0); "
                        "auto-picked on single-node jobs")
    p.add_argument("--job_id", type=str, default="default")
    p.add_argument("--log_dir", type=str, default=None,
                   help="write per-rank workerlog.N files here")
    p.add_argument("--max_restarts", type=int, default=0,
                   help="restart budget for elastic exits (101/102) "
                        "and, with --elastic_on_failure, any abnormal "
                        "worker death")
    p.add_argument("--elastic_on_failure", action="store_true",
                   help="also restart (up to max_restarts) on ANY "
                        "abnormal worker death, incl. signal kills — "
                        "pair with auto checkpoint for preemption "
                        "recovery")
    p.add_argument("--devices", type=str, default=None,
                   help="visible device ids for this node (TPU chips)")
    p.add_argument("--fleet_store", type=str,
                   default=os.environ.get("PADDLE_FLEET_STORE", ""),
                   help="host:port of the fleet-telemetry TCPStore: "
                        "every worker publishes its metrics registry "
                        "+ health there (PADDLE_FLEET_METRICS_PERIOD_S"
                        " cadence) and rank 0 aggregates them into "
                        "/fleet/metrics + /fleet/healthz on its "
                        "telemetry server — one pane of glass for the "
                        "whole job")
    p.add_argument("script", type=str)
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p


def launch(argv: Optional[List[str]] = None) -> int:
    args = _parser().parse_args(argv)
    master = args.master
    if not master:
        if args.nnodes > 1:
            raise SystemExit("--master host:port is required for "
                             "multi-node jobs")
        master = f"127.0.0.1:{free_port()}"
    envs = {}
    if args.devices is not None:
        envs["TPU_VISIBLE_DEVICES"] = args.devices
    if args.fleet_store:
        envs["PADDLE_FLEET_STORE"] = args.fleet_store
    spec = JobSpec(script=args.script, script_args=args.script_args,
                   nnodes=args.nnodes, node_rank=args.node_rank,
                   nproc_per_node=args.nproc_per_node, master=master,
                   job_id=args.job_id, log_dir=args.log_dir,
                   envs=envs, max_restarts=args.max_restarts,
                   elastic_on_failure=args.elastic_on_failure)
    return Controller(spec).run()


def main() -> int:
    return launch(sys.argv[1:])
