"""paddle_tpu.distributed.launch — multi-process training launcher.

Reference analog: python -m paddle.distributed.launch
(python/paddle/distributed/launch/main.py:18; CollectiveController
launch/controllers/collective.py:21).
"""
from .main import launch, main  # noqa: F401
