"""Collective communication API.

Reference analog: python/paddle/distributed/collective.py:876-1505
(all_reduce/all_gather/alltoall/broadcast/reduce/scatter/send/recv over
ProcessGroup, C++ side ProcessGroup.h:102-234 and the c_* operator set,
paddle/fluid/operators/collective/).

TPU-native: collectives are XLA ops inside shard_map over a named mesh
axis — ICI-routed, fused and scheduled by the compiler. This module gives
them a paddle-shaped eager API for parity tests and host-driven code
(pipeline schedules); inside pjit-traced model code, USE jax.lax.psum etc.
directly or rely on sharding propagation.

Eager semantics note: `tensor` here is a global jax array sharded over
`axis`; all_reduce(x, axis='dp') psums the shards. ReduceOp maps to the
corresponding XLA collective (c_allreduce_{sum,max,min,prod}_op analogs).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import monitor
from ..core.jaxshim import shard_map
from ..core.tensor import Tensor
from . import topology


def _count(op: str, axis: str, x):
    """Collective telemetry: per-axis op/byte counters (the reference's
    per-collective stats in the Fleet executor). No-op unless the
    runtime monitor is enabled."""
    if monitor.enabled:
        monitor.record_collective(op, axis, getattr(x, "nbytes", 0))


def _guard(label: str, fn, *args):
    """Launch an eager collective under the hang watchdog when
    PADDLE_WATCHDOG_COLLECTIVE_S sets a deadline (a re-forming slice or
    dead peer can block a collective launch forever on a real pod):
    past the deadline, thread stacks dump to stderr and WatchdogTimeout
    raises instead of hanging. Plain call when unconfigured."""
    from . import resilience
    t = resilience.env_timeout("PADDLE_WATCHDOG_COLLECTIVE_S")
    if t is None:
        return fn(*args)
    return resilience.Watchdog.run(fn, *args, timeout=t,
                                   label=f"collective.{label}")


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


_REDUCERS = {
    ReduceOp.SUM: jax.lax.psum,
    ReduceOp.MAX: jax.lax.pmax,
    ReduceOp.MIN: jax.lax.pmin,
}


def _mesh(group=None) -> Mesh:
    if group is not None and hasattr(group, "mesh"):
        return group.mesh
    m = topology.get_mesh()
    if m is None:
        # implicit 1-axis mesh over all devices (single-axis "world" group,
        # like paddle's default global group)
        devs = jax.devices()
        m = Mesh(np.array(devs), ("world",))
    return m


def _axis(axis: Optional[str], mesh: Mesh) -> str:
    if axis is not None:
        return axis
    # default: the one non-degenerate axis, else the first
    for name, size in zip(mesh.axis_names, mesh.devices.shape):
        if size > 1:
            return name
    return mesh.axis_names[0]


def _raw(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _spec_on(axis, ndim, shard_dim=0):
    if ndim == 0:
        return P()  # scalars are replicated; collectives act on the value
    parts = [None] * ndim
    parts[shard_dim] = axis
    return P(*parts)


def all_reduce(tensor, op: str = ReduceOp.SUM, group=None,
               axis: Optional[str] = None, sync_op=True):
    """Reduce across `axis` shards; every shard gets the result."""
    mesh = _mesh(group)
    ax = _axis(axis, mesh)
    x = _raw(tensor)
    _count("all_reduce", ax, x)

    if op == ReduceOp.AVG:
        fn = lambda a: jax.lax.psum(a, ax) / mesh.shape[ax]  # noqa: E731
    elif op == ReduceOp.PROD:
        # no native pprod: gather shards and multiply (sign/zero safe)
        fn = lambda a: jnp.prod(  # noqa: E731
            jax.lax.all_gather(a, ax), axis=0)
    else:
        red = _REDUCERS[op]
        fn = lambda a: red(a, ax)  # noqa: E731

    shard = shard_map(fn, mesh=mesh,
                      in_specs=_spec_on(ax, x.ndim),
                      out_specs=_spec_on(ax, x.ndim), check_vma=False)
    out = _guard("all_reduce", shard, _shard_for(x, mesh, ax))
    result = Tensor(out) if isinstance(tensor, Tensor) else out
    if isinstance(tensor, Tensor):
        tensor._replace_data(out)  # paddle all_reduce is in-place
        return tensor
    return result


def all_gather(tensor_list, tensor, group=None, axis: Optional[str] = None,
               sync_op=True):
    """Gather shards along a new leading-dim list (paddle signature:
    results appended to tensor_list)."""
    mesh = _mesh(group)
    ax = _axis(axis, mesh)
    x = _raw(tensor)
    n = mesh.shape[ax]
    _count("all_gather", ax, x)
    fn = shard_map(
        lambda a: jax.lax.all_gather(a, ax),  # [n, ...local shape]
        mesh=mesh, in_specs=_spec_on(ax, x.ndim),
        out_specs=P(*([None] * (x.ndim + 1))),
        check_vma=False)  # all_gather output IS replicated over ax
    gathered = _guard("all_gather", fn, _shard_for(x, mesh, ax))
    if tensor_list is not None:
        tensor_list.extend(Tensor(gathered[i]) for i in range(n))
    return Tensor(gathered)


def broadcast(tensor, src: int = 0, group=None, axis: Optional[str] = None,
              sync_op=True):
    mesh = _mesh(group)
    ax = _axis(axis, mesh)
    x = _raw(tensor)
    n = mesh.shape[ax]
    _count("broadcast", ax, x)

    def fn(a):
        # select src's shard and replicate it
        full = jax.lax.all_gather(a, ax)
        return full[src]

    shard = shard_map(fn, mesh=mesh, in_specs=_spec_on(ax, x.ndim),
                      out_specs=_spec_on(ax, x.ndim), check_vma=False)
    out = _guard("broadcast", shard, _shard_for(x, mesh, ax))
    if isinstance(tensor, Tensor):
        tensor._replace_data(out)
        return tensor
    return out


def reduce_scatter(output, input, op: str = ReduceOp.SUM, group=None,
                   axis: Optional[str] = None, sync_op=True):
    """Reduce then scatter along dim 0 (≈ ProcessGroup::ReduceScatter)."""
    if op != ReduceOp.SUM:
        raise NotImplementedError("reduce_scatter supports SUM")
    mesh = _mesh(group)
    ax = _axis(axis, mesh)
    x = _raw(input)
    _count("reduce_scatter", ax, x)
    out = _guard("reduce_scatter", shard_map(
        lambda a: jax.lax.psum_scatter(a, ax, scatter_dimension=0,
                                       tiled=True),
        mesh=mesh, in_specs=_spec_on(ax, x.ndim),
        out_specs=_spec_on(ax, x.ndim)), _shard_for(x, mesh, ax))
    if output is not None and isinstance(output, Tensor):
        output._replace_data(out)
        return output
    return Tensor(out)


def alltoall_single(tensor, group=None, axis: Optional[str] = None):
    """Block exchange along dim 0: input sharded over `axis` as n blocks of
    n sub-blocks each; sub-block j of shard i lands as sub-block i of shard
    j (the global_scatter/global_gather primitive,
    operators/collective/global_scatter_op.*)."""
    mesh = _mesh(group)
    ax = _axis(axis, mesh)
    x = _raw(tensor)
    _count("alltoall", ax, x)
    out = _guard("alltoall", shard_map(
        lambda a: jax.lax.all_to_all(a, ax, split_axis=0, concat_axis=0,
                                     tiled=True),
        mesh=mesh, in_specs=_spec_on(ax, x.ndim),
        out_specs=_spec_on(ax, x.ndim)), _shard_for(x, mesh, ax))
    return Tensor(out)


def all_to_all(out_tensor_list, in_tensor_list, group=None,
               axis: Optional[str] = None, sync_op=True):
    """List API (≈ paddle.distributed.alltoall): in the single-controller
    SPMD view, in_tensor_list[j] is the global tensor destined for mesh
    position j, each sharded over `axis` on dim 0 by source."""
    mesh = _mesh(group)
    ax = _axis(axis, mesh)
    n = mesh.shape[ax]
    concat = jnp.concatenate([_raw(t) for t in in_tensor_list], axis=0)
    exchanged = alltoall_single(concat, group=group, axis=ax)
    parts = jnp.split(exchanged.data, n, axis=0)
    if out_tensor_list is not None:
        out_tensor_list.extend(Tensor(p) for p in parts)
    return [Tensor(p) for p in parts]


def scatter(tensor, tensor_list=None, src: int = 0, group=None,
            axis: Optional[str] = None):
    mesh = _mesh(group)
    ax = _axis(axis, mesh)
    stacked = jnp.stack([_raw(t) for t in tensor_list]) if tensor_list \
        else _raw(tensor)
    _count("scatter", ax, stacked)
    out = jax.device_put(
        stacked, NamedSharding(mesh, _spec_on(ax, stacked.ndim)))

    def fn(a):
        return a[0]

    res = _guard("scatter", shard_map(
        fn, mesh=mesh, in_specs=_spec_on(ax, stacked.ndim),
        out_specs=_spec_on(ax, stacked.ndim - 1)
        if stacked.ndim > 1 else P(ax)), out)
    if isinstance(tensor, Tensor):
        tensor._replace_data(res)
        return tensor
    return Tensor(res)


def _shard_for(x, mesh, ax):
    """Lay x out sharded on `ax` along dim 0 (replicating over other axes)."""
    if x.shape and x.shape[0] % mesh.shape[ax] == 0:
        return jax.device_put(x, NamedSharding(mesh, _spec_on(ax, x.ndim)))
    return jax.device_put(x, NamedSharding(mesh, P()))


# -------- in-trace helpers (use inside shard_map-ed / pjit code) ----------

def psum(x, axis_name):
    return jax.lax.psum(_raw(x), axis_name)


def pmean(x, axis_name):
    return jax.lax.pmean(_raw(x), axis_name)


def ppermute(x, axis_name, perm):
    return jax.lax.ppermute(_raw(x), axis_name, perm)


def axis_index(axis_name):
    return jax.lax.axis_index(axis_name)
