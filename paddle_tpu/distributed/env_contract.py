"""The PADDLE_*-style env contract shared by every launch path.

Reference analog: the env each worker receives from
CollectiveController.build_pod (launch/controllers/collective.py:75) and
from paddle.distributed.spawn — one definition here so the CLI launcher
and spawn() cannot drift.
"""
from __future__ import annotations

from typing import Dict


def build_rank_env(rank: int, world_size: int, local_rank: int,
                   master: str, nnodes: int = 1,
                   job_id: str = "default") -> Dict[str, str]:
    return {
        # paddle-parity names
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world_size),
        "PADDLE_MASTER": master,
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_NNODES": str(nnodes),
        "PADDLE_JOB_ID": job_id,
        # names env.init_parallel_env also accepts
        "COORDINATOR_ADDRESS": master,
        "NUM_PROCESSES": str(world_size),
        "PROCESS_ID": str(rank),
    }
