"""TCPStore — rendezvous key-value store.

Reference analog: paddle::distributed::TCPStore
(paddle/fluid/distributed/store/tcp_store.cc; bound in
pybind/communication.cc) — the master rank listens on a TCP socket and
every rank set/get/waits keys to bootstrap collectives.

TPU-native role: jax.distributed's coordination service replaces the
NCCL-id exchange, but the launcher, elastic manager and rpc layer still
need a tiny shared KV plane (worker registration, endpoint discovery,
barriers) — this is that plane, pure stdlib, no brpc.
"""
from __future__ import annotations

import pickle
import random
import socket
import socketserver
import struct
import threading
import time
from typing import Callable, Dict, List, Optional

_LEN = struct.Struct("!I")

# chaos-test hook (utils.fault_injection.StoreFaults): called server-side
# with (op, args) before every reply; may sleep (delay) or return "drop"
# (close the connection without answering). None = no faults installed.
_FAULT_HOOK: Optional[Callable[[str, tuple], Optional[str]]] = None


def set_fault_hook(fn: Optional[Callable[[str, tuple], Optional[str]]]
                   ) -> None:
    global _FAULT_HOOK
    _FAULT_HOOK = fn


def _backoff(attempt: int, base: float = 0.05, cap: float = 2.0) -> float:
    """Full-jittered exponential backoff delay for retry ``attempt`` —
    uniform in [0, min(cap, base * 2^attempt)) so a fleet of ranks
    retrying the master after a blip doesn't re-stampede in lockstep."""
    return random.uniform(0.0, min(cap, base * (2 ** attempt)))


def _armed_watchdog():
    """The resilience watchdog armed on this thread, if any (lazy import:
    store is imported during package init, resilience only on use)."""
    try:
        from . import resilience
        return resilience._armed_watchdog()
    except ImportError:
        return None


def _send_msg(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_msg(sock: socket.socket):
    hdr = _recv_exact(sock, _LEN.size)
    (n,) = _LEN.unpack(hdr)
    return pickle.loads(_recv_exact(sock, n))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("TCPStore peer closed")
        buf += chunk
    return buf


class _StoreServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, addr):
        self.kv: Dict[str, object] = {}
        self.cond = threading.Condition()
        super().__init__(addr, _StoreHandler)


class _StoreHandler(socketserver.BaseRequestHandler):
    def handle(self):
        srv: _StoreServer = self.server  # type: ignore[assignment]
        try:
            while True:
                op, *args = _recv_msg(self.request)
                hook = _FAULT_HOOK
                if hook is not None and hook(op, tuple(args)) == "drop":
                    return  # injected fault: vanish without a reply
                if op == "set":
                    key, val = args
                    with srv.cond:
                        srv.kv[key] = val
                        srv.cond.notify_all()
                    _send_msg(self.request, ("ok", None))
                elif op == "get":
                    key, timeout = args
                    deadline = time.monotonic() + timeout
                    # reply OUTSIDE the lock: a stalled client socket
                    # must not block every other handler thread
                    with srv.cond:
                        while key not in srv.kv:
                            left = deadline - time.monotonic()
                            if left <= 0 or not srv.cond.wait(left):
                                break
                        found = key in srv.kv
                        val = srv.kv.get(key)
                    if found:
                        _send_msg(self.request, ("ok", val))
                    else:
                        _send_msg(self.request, ("timeout", key))
                elif op == "add":
                    key, delta = args
                    with srv.cond:
                        srv.kv[key] = int(srv.kv.get(key, 0)) + delta
                        val = srv.kv[key]
                        srv.cond.notify_all()
                    _send_msg(self.request, ("ok", val))
                elif op == "setts":
                    # server-clock timestamp write (elastic heartbeats:
                    # cross-host wall clocks can't be compared)
                    (key,) = args
                    with srv.cond:
                        srv.kv[key] = time.time()
                        srv.cond.notify_all()
                    _send_msg(self.request, ("ok", None))
                elif op == "now":
                    _send_msg(self.request, ("ok", time.time()))
                elif op == "delete":
                    (key,) = args
                    with srv.cond:
                        existed = srv.kv.pop(key, None) is not None
                        srv.cond.notify_all()
                    _send_msg(self.request, ("ok", existed))
                elif op == "keys":
                    prefix = args[0] if args else ""
                    with srv.cond:
                        ks = [k for k in srv.kv if k.startswith(prefix)]
                    _send_msg(self.request, ("ok", ks))
                elif op == "shutdown":
                    _send_msg(self.request, ("ok", None))
                    threading.Thread(target=srv.shutdown,
                                     daemon=True).start()
                    return
                else:
                    _send_msg(self.request, ("error", f"bad op {op}"))
        except (ConnectionError, OSError):
            return


class TCPStore:
    """Client (and, on the master, server) of the rendezvous store.

    ``TCPStore(host, port, is_master=True)`` starts the in-process server
    thread; every participant (master included) talks to it over TCP.
    """

    def __init__(self, host: str, port: int, is_master: bool = False,
                 timeout: float = 300.0):
        self.host, self.port = host, port
        self.timeout = timeout
        self._server: Optional[_StoreServer] = None
        if is_master:
            self._server = _StoreServer((host, port))
            if port == 0:
                self.port = self._server.server_address[1]
            t = threading.Thread(target=self._server.serve_forever,
                                 daemon=True)
            t.start()
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._cancelled = False

    # --------------------------------------------------------------- conn
    def _conn(self) -> socket.socket:
        if self._sock is None:
            deadline = time.monotonic() + self.timeout
            last = None
            attempt = 0
            while time.monotonic() < deadline:
                if self._cancelled:
                    # a watchdog aborted this op: the connect-retry loop
                    # must stop at the deadline it set, not at the (much
                    # larger) client timeout
                    raise ConnectionAbortedError(
                        "TCPStore: connect cancelled by watchdog")
                try:
                    s = socket.create_connection(
                        (self.host, self.port), timeout=self.timeout)
                    self._sock = s
                    return s
                except OSError as e:  # master not up yet / transient
                    last = e
                    time.sleep(_backoff(attempt))
                    attempt += 1
            raise TimeoutError(
                f"TCPStore: cannot reach {self.host}:{self.port}: {last}")
        return self._sock

    # ops safe to re-send after a broken pipe; "add" is NOT (a lost
    # reply would double-count and corrupt barrier generations)
    _IDEMPOTENT = {"set", "get", "delete", "keys", "setts", "now"}
    # bounded retries on transient socket errors (ECONNRESET, broken
    # pipe): a single flaky packet must not kill the rank
    _MAX_RETRIES = 4

    def _call(self, *msg):
        with self._lock:
            # an armed hang watchdog on this thread may force-close our
            # socket to un-block a stalled recv. Registered only once
            # the lock is HELD (a watchdog expiring while we still wait
            # for the lock must not close another thread's in-flight op)
            # and only AFTER the cancelled flag is reset — the reverse
            # order would let an immediate expiry's cancel be erased and
            # the aborted op retried, re-hanging past the deadline
            self._cancelled = False
            wd = _armed_watchdog()
            if wd is not None:
                wd.add_canceller(self.cancel)
            try:
                status, val = self._call_locked(msg)
            finally:
                if wd is not None:
                    wd.remove_canceller(self.cancel)
        if status == "timeout":
            raise TimeoutError(f"TCPStore: wait for key {val!r} timed out")
        if status == "error":
            raise RuntimeError(val)
        return val

    def _call_locked(self, msg):
        # the server replies at most at the per-call wait deadline;
        # pad the socket deadline so the reply always wins the race
        # and TimeoutError comes from the server's "timeout" status,
        # not the socket
        wait = msg[2] if msg[0] == "get" else self.timeout
        retriable = msg[0] in self._IDEMPOTENT
        attempt = 0
        while True:
            sock = self._conn()
            sock.settimeout(float(wait) + 30.0)
            try:
                _send_msg(sock, msg)
                return _recv_msg(sock)
            except TimeoutError:
                self._sock = None
                raise
            except (ConnectionError, OSError) as e:
                self._sock = None
                if self._cancelled:
                    raise  # watchdog aborted us: do NOT retry
                if not retriable or attempt >= self._MAX_RETRIES:
                    raise
                from ..core import monitor
                monitor.record_swallowed(
                    f"tcpstore.retry:{msg[0]}", e)
                time.sleep(_backoff(attempt))
                attempt += 1

    def cancel(self) -> None:
        """Force-close the live client socket WITHOUT taking the call
        lock (the caller of the in-flight op holds it): the blocked
        recv aborts with ConnectionError and, with the cancelled flag
        set, is not retried. The hang watchdog's canceller."""
        self._cancelled = True
        s = self._sock
        self._sock = None  # a later op must reconnect, not reuse EBADF
        if s is not None:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    # ---------------------------------------------------------------- api
    def set(self, key: str, value) -> None:
        self._call("set", key, value)

    def get(self, key: str, timeout: Optional[float] = None):
        return self._call("get", key,
                          self.timeout if timeout is None else timeout)

    def add(self, key: str, delta: int = 1) -> int:
        return self._call("add", key, delta)

    def delete(self, key: str) -> bool:
        return self._call("delete", key)

    def keys(self, prefix: str = "") -> List[str]:
        return self._call("keys", prefix)

    def set_timestamp(self, key: str) -> None:
        """Write the SERVER's clock under key (skew-free heartbeats)."""
        self._call("setts", key)

    def now(self) -> float:
        """The server's current wall clock."""
        return self._call("now")

    def wait(self, keys, timeout: Optional[float] = None) -> None:
        for k in keys:
            self.get(k, timeout)

    def barrier(self, name: str, world_size: int,
                timeout: Optional[float] = None) -> None:
        """All `world_size` callers block until everyone arrived."""
        n = self.add(f"__barrier/{name}/count", 1)
        gen = (n - 1) // world_size  # reusable barrier generations
        if n % world_size == 0:
            self.set(f"__barrier/{name}/release{gen}", True)
        self.get(f"__barrier/{name}/release{gen}", timeout)

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

    def shutdown_server(self) -> None:
        if self._server is not None:
            try:
                self._call("shutdown")
            except (TimeoutError, RuntimeError, OSError):
                pass
            self._server.server_close()
            self._server = None
        self.close()


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
