"""Auto-parallel (semi-automatic SPMD) API.

Reference analog: python/paddle/distributed/auto_parallel/ (~45k LoC):
annotate tensors with ProcessMesh + dims_mapping (interface.py,
process_mesh.py), propagate dist attrs (Completer, completion.py:140),
split the program per rank (Partitioner, partitioner.py:35), insert
communication at mismatches (Resharder, reshard.py:926), then run on the
executor; Engine drives fit/evaluate/predict (engine.py:58).

TPU-native: annotation = jax NamedSharding on a named Mesh; the XLA SPMD
partitioner IS the Completer+Partitioner+Resharder — it propagates
shardings through the whole jaxpr and inserts ICI/DCN collectives
(SURVEY §3.6 maps the pipeline 1:1). The Engine therefore reduces to:
collect annotations -> jit the step with in/out shardings -> run.
"""
from .process_mesh import ProcessMesh, get_current_mesh
from .interface import shard_tensor, shard_op, shard_layer
from .engine import Engine
from .cost import estimate_cost

__all__ = ["ProcessMesh", "get_current_mesh", "shard_tensor", "shard_op",
           "shard_layer", "Engine", "estimate_cost"]
