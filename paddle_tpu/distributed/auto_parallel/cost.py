"""Cost model: FLOPs/bytes/time estimates for a step function.

Reference analog: auto_parallel/cost/ + cost_model.py — measured per-op
latencies (static_op_benchmark.json) summed over the partitioned program
to rank parallel strategies in the tuner.

TPU-native: XLA already computes a cost analysis for every compiled
executable; we surface it. This is strictly better-grounded than the
reference's table: it reflects the post-fusion, post-SPMD program."""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax

__all__ = ["estimate_cost"]


def estimate_cost(fn: Callable, *example_args,
                  peak_flops: Optional[float] = None) -> Dict[str, Any]:
    """Compile `fn` on example args and return XLA's cost analysis:
    flops, bytes accessed, and (if `peak_flops` given) a roofline time
    estimate in seconds."""
    lowered = jax.jit(fn).lower(*example_args)
    compiled = lowered.compile()
    analyses = compiled.cost_analysis()
    ca = analyses[0] if isinstance(analyses, (list, tuple)) else analyses
    flops = float(ca.get("flops", 0.0))
    bytes_accessed = float(ca.get("bytes accessed", 0.0))
    out = {"flops": flops, "bytes_accessed": bytes_accessed,
           "utilization_keys": sorted(k for k in ca if "utilization" in k)}
    if peak_flops:
        out["roofline_time_s"] = flops / peak_flops
    return out
