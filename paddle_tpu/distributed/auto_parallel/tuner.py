"""Parallel-strategy tuner: choose hybrid mesh degrees for a model and
chip count by compiling candidates and ranking with a measured cost
model.

Reference analog: auto_parallel/tuner/parallel_tuner.py (candidate
dist-attr search with pruning) + auto_parallel/cost/ (comm/comp cost
model over measured op latencies, static_op_benchmark.json).

TPU-native: instead of a hand-maintained latency table, every candidate
is actually COMPILED through XLA SPMD on the virtual device mesh and
scored from the compiled program itself —
  t  =  max(flops / peak, hbm_bytes / hbm_bw)          (roofline)
      + ici_bytes / ici_bw + n_ici * ici_latency       (collectives)
      + dcn_bytes / dcn_bw + n_dcn * dcn_latency
where collective bytes are read out of the compiled HLO (all-reduce /
all-gather / reduce-scatter / collective-permute result shapes) and a
collective is billed to DCN when its replica groups span slice
boundaries (devices_per_slice) — the same crossing rule
create_hybrid_device_mesh (topology.py:41) uses to lay the mesh out.
Candidates that cannot hold their parameter + optimizer shard in HBM
are pruned before compiling (the reference tuner's memory check).
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["Candidate", "ParallelTuner", "tune_parallel"]

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2, "s64": 8, "u64": 8,
    "s32": 4, "u32": 4, "s16": 2, "u16": 2, "s8": 1, "u8": 1,
    "pred": 1, "c64": 8, "c128": 16,
}

_COLLECTIVE_RE = re.compile(
    r"=\s*(?:\(([^)]*)\)|(\w+)\[([\d,]*)\][^ ]*)\s*"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute)"
    r"(?:-start)?\(", )
_GROUPS_RE = re.compile(r"replica_groups=\{(\{[^=]*\})\}")
_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?")
_TUPLE_ELEM_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def _shape_bytes(dtype: str, dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


def _crosses_slices(line: str, devices_per_slice: int) -> bool:
    """True when any replica group mixes devices from different slices.
    Handles both HLO forms: explicit brace lists {{0,1},{2,3}} and the
    iota form [rows,cols]<=[dims]T(perm) XLA prints for regular
    meshes."""
    gm = _GROUPS_RE.search(line)
    if gm:
        for grp in re.findall(r"\{([\d,]+)\}", gm.group(1)):
            slices = {int(i) // devices_per_slice
                      for i in grp.split(",")}
            if len(slices) > 1:
                return True
        return False
    im = _IOTA_RE.search(line)
    if im:
        import numpy as _np
        rows, cols = int(im.group(1)), int(im.group(2))
        dims = [int(d) for d in im.group(3).split(",")]
        ids = _np.arange(rows * cols).reshape(dims)
        if im.group(4):
            perm = [int(p) for p in im.group(4).split(",")]
            ids = ids.transpose(perm)
        for grp in ids.reshape(rows, cols):
            if len({int(i) // devices_per_slice for i in grp}) > 1:
                return True
        return False
    # unparseable groups: bill conservatively as DCN-crossing so the
    # tuner never under-costs a slice-spanning collective
    return "replica_groups" in line


def collective_bytes(hlo_text: str, devices_per_slice: Optional[int]
                     ) -> Tuple[float, float]:
    """Parse compiled HLO, return (ici_bytes, dcn_bytes, n_ici, n_dcn)
    for collectives. A collective crosses DCN when any replica group
    holds device ids from more than one slice."""
    ici = dcn = 0.0
    n_ici = n_dcn = 0
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if not m:
            continue
        tuple_body, dtype, dims, kind = m.groups()
        if tuple_body is not None:
            size = sum(_shape_bytes(d, s)
                       for d, s in _TUPLE_ELEM_RE.findall(tuple_body))
        else:
            size = _shape_bytes(dtype, dims)
        crosses = False
        if devices_per_slice:
            crosses = _crosses_slices(line, devices_per_slice)
        # ring cost factor (k-1)/k folded into bw constants; bytes are
        # the payload itself
        if crosses:
            dcn += size
            n_dcn += 1
        else:
            ici += size
            n_ici += 1
    return ici, dcn, n_ici, n_dcn


@dataclass
class Candidate:
    dp: int = 1
    sharding: int = 1
    pp: int = 1
    mp: int = 1
    interleave: int = 1
    cost_s: float = float("inf")
    detail: Dict[str, float] = field(default_factory=dict)
    feasible: bool = True
    reason: str = ""

    @property
    def hybrid_configs(self) -> Dict[str, int]:
        return {"dp_degree": self.dp, "sharding_degree": self.sharding,
                "pp_degree": self.pp, "mp_degree": self.mp}

    def __repr__(self):
        tag = (f"dp{self.dp}xshard{self.sharding}xpp{self.pp}"
               f"xmp{self.mp}")
        if not self.feasible:
            return f"Candidate({tag}, pruned: {self.reason})"
        return f"Candidate({tag}, est {self.cost_s * 1e3:.3f} ms)"


def _factorizations(n: int) -> List[Tuple[int, int, int, int]]:
    out = []
    for dp in _divisors(n):
        for sharding in _divisors(n // dp):
            rem = n // dp // sharding
            for pp in _divisors(rem):
                mp = rem // pp
                out.append((dp, sharding, pp, mp))
    return out


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


class ParallelTuner:
    """Rank hybrid-parallel configs for `n_devices`.

    step_builder(hybrid_configs: dict) -> (step, batch_tuple) must
    build a fleet.DistributedTrainStep (or any object with
    .lower(*batch) returning a jax Lowered) on the CURRENT virtual
    mesh for the given degrees. The tuner compiles each surviving
    candidate and scores it from the compiled program.
    """

    def __init__(self, n_devices: int,
                 step_builder: Callable[[Dict[str, int]], Any],
                 *,
                 num_layers: Optional[int] = None,
                 num_heads: Optional[int] = None,
                 param_bytes: Optional[float] = None,
                 hbm_capacity: float = 16e9,       # v5e chip
                 peak_flops: float = 197e12,       # bf16 v5e
                 hbm_bw: float = 819e9,
                 mxu_eff: float = 0.43,
                 hbm_eff: float = 0.90,
                 ici_bw: float = 180e9,            # ~4 links x 45GB/s
                 dcn_bw: float = 12.5e9,
                 ici_latency: float = 1e-6,        # per-collective floor
                 dcn_latency: float = 25e-6,
                 devices_per_slice: Optional[int] = None,
                 max_mp: int = 8,
                 max_candidates: int = 8,
                 axes: Sequence[str] = ("dp", "sharding", "pp", "mp")):
        self.n = n_devices
        self.step_builder = step_builder
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.param_bytes = param_bytes
        self.hbm_capacity = hbm_capacity
        self.peak_flops = peak_flops
        self.hbm_bw = hbm_bw
        # roofline derates calibrated against the measured BASELINE.md
        # single-chip rows (experiments/tuner_calibration.json, r5
        # post-attention-wave): the global least-max-error pair is
        # (0.43, 0.90), worst rel err 26.6% across model families;
        # per-family calibration via calibrate() reaches <=20%
        # (tests/test_parallel_tuner.py).
        # Residual error structure: attention flops at head_dim 64
        # occupy half the 128-wide MXU (long-seq underprediction), and
        # XLA cost-model bytes overstate real conv-net traffic.
        self.mxu_eff = mxu_eff
        self.hbm_eff = hbm_eff
        self.ici_bw = ici_bw
        self.dcn_bw = dcn_bw
        self.ici_latency = ici_latency
        self.dcn_latency = dcn_latency
        self.devices_per_slice = devices_per_slice
        self.max_mp = max_mp
        self.max_candidates = max_candidates
        self.axes = set(axes)
        self.candidates: List[Candidate] = []

    # ------------------------------------------------------------ pruning
    def _enumerate(self) -> List[Candidate]:
        cands = []
        for dp, sharding, pp, mp in _factorizations(self.n):
            degrees = {"dp": dp, "sharding": sharding, "pp": pp,
                       "mp": mp}
            if any(v > 1 for k, v in degrees.items()
                   if k not in self.axes):
                continue  # axis not being searched stays at degree 1
            c = Candidate(dp, sharding, pp, mp)
            if mp > self.max_mp:
                c.feasible, c.reason = False, f"mp {mp} > {self.max_mp}"
            elif self.num_heads and self.num_heads % mp:
                c.feasible, c.reason = False, \
                    f"mp {mp} does not divide num_heads {self.num_heads}"
            elif self.num_layers and pp > 1 and self.num_layers % pp:
                c.feasible, c.reason = False, \
                    f"pp {pp} does not divide num_layers {self.num_layers}"
            elif self.devices_per_slice and \
                    self.n > self.devices_per_slice and \
                    dp < self.n // self.devices_per_slice:
                # DCN rule: only the outermost (dp) axis may cross
                # slices (create_hybrid_device_mesh layout); dp must
                # cover the slice count
                c.feasible, c.reason = False, \
                    "non-dp axis would cross DCN slices"
            elif self.param_bytes is not None:
                # fp32 master + 2 AdamW moments + bf16 weight ~ 14B per
                # param when param_bytes counts 4B/param
                state = self.param_bytes * 3.5
                shard = state / (sharding * mp * pp)
                if shard > self.hbm_capacity * 0.85:
                    c.feasible = False
                    c.reason = (f"param+opt shard {shard / 1e9:.1f} GB "
                                f"> 85% of {self.hbm_capacity / 1e9:.0f}"
                                f" GB HBM")
            cands.append(c)
        return cands

    def _rank_heuristic(self, c: Candidate) -> Tuple:
        # compile-order heuristic: try likely winners first so the
        # candidate budget is spent well (prefer some sharding for
        # memory, mild mp, low pp)
        return (c.pp, c.mp, -c.sharding)

    # ------------------------------------------------------------ scoring
    def _score(self, cand: Candidate) -> Candidate:
        step, batch = self.step_builder(cand.hybrid_configs)
        lowered = step.lower(*batch)
        compiled = lowered.compile()
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        flops = float(ca.get("flops", 0.0))
        hbm = float(ca.get("bytes accessed", 0.0))
        ici_b, dcn_b, n_ici, n_dcn = collective_bytes(
            compiled.as_text(), self.devices_per_slice)
        comp = max(flops / (self.peak_flops * self.mxu_eff),
                   hbm / (self.hbm_bw * self.hbm_eff))
        cost = comp + ici_b / self.ici_bw + dcn_b / self.dcn_bw \
            + n_ici * self.ici_latency + n_dcn * self.dcn_latency
        cand.cost_s = cost
        cand.detail = {"flops": flops, "hbm_bytes": hbm,
                       "ici_bytes": ici_b, "dcn_bytes": dcn_b,
                       "n_ici": n_ici, "n_dcn": n_dcn, "comp_s": comp}
        return cand

    # ------------------------------------------------------------- search
    def tune(self, verbose: bool = False) -> Candidate:
        cands = self._enumerate()
        feasible = sorted([c for c in cands if c.feasible],
                          key=self._rank_heuristic)
        self.candidates = cands
        budget = feasible[:self.max_candidates]
        if not budget:
            raise ValueError(
                "no feasible parallel config: " +
                "; ".join(f"{c!r}" for c in cands[:6]))
        for c in budget:
            try:
                self._score(c)
            except Exception as e:  # candidate failed to build/compile
                c.feasible = False
                c.reason = f"compile failed: {type(e).__name__}: {e}"
            if verbose:
                print(c)
        scored = [c for c in budget if c.feasible]
        if not scored:
            raise RuntimeError(
                "every candidate failed to compile; first error: "
                + budget[0].reason)
        return min(scored, key=lambda c: c.cost_s)


def tune_parallel(n_devices: int, step_builder, **kwargs) -> Candidate:
    """One-call form: rank configs and return the winner."""
    return ParallelTuner(n_devices, step_builder, **kwargs).tune()


def predict_step_time(flops: float, hbm_bytes: float, *,
                      peak_flops: float = 197e12, hbm_bw: float = 819e9,
                      mxu_eff: float = 0.43, hbm_eff: float = 0.90
                      ) -> float:
    """The tuner's compute roofline on its own (no collectives):
    max(flops / (peak * mxu_eff), bytes / (bw * hbm_eff))."""
    return max(flops / (peak_flops * mxu_eff),
               hbm_bytes / (hbm_bw * hbm_eff))


def calibrate(rows: Sequence[Dict[str, float]], *,
              peak_flops: float = 197e12, hbm_bw: float = 819e9,
              mxu_grid=None, hbm_grid=None) -> Tuple[float, float, float]:
    """Fit (mxu_eff, hbm_eff) minimizing the WORST relative error of
    predict_step_time over measured rows [{flops, hbm_bytes,
    measured_s}, ...] — the reference's measured-latency cost tables
    (static_op_benchmark.json) recast as a 2-parameter roofline fit.
    Returns (mxu_eff, hbm_eff, worst_rel_err)."""
    import numpy as _np
    mxu_grid = mxu_grid if mxu_grid is not None \
        else _np.arange(0.20, 0.96, 0.01)
    hbm_grid = hbm_grid if hbm_grid is not None \
        else _np.arange(0.30, 1.51, 0.01)
    best = None
    for me in mxu_grid:
        for he in hbm_grid:
            worst = max(
                abs(predict_step_time(
                    r["flops"], r["hbm_bytes"], peak_flops=peak_flops,
                    hbm_bw=hbm_bw, mxu_eff=me, hbm_eff=he)
                    - r["measured_s"]) / r["measured_s"]
                for r in rows)
            if best is None or worst < best[2]:
                best = (float(me), float(he), worst)
    return best
