"""Annotation API: shard_tensor / shard_op / shard_layer.

Reference analog: auto_parallel/interface.py — `shard_tensor(x, mesh,
dims_mapping)` attaches a DistTensorSpec consumed by the Completer
(completion.py:140). TPU-native: the annotation IS a NamedSharding;
eagerly it places the array (jax.device_put), under a trace it becomes
`with_sharding_constraint` — both feed XLA's SPMD propagation, which
replaces the reference's completion/partition/reshard passes.

shard_spec format: one entry per tensor dim — a mesh dim name to shard
along, or None to replicate (≈ dims_mapping index -1).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from .process_mesh import ProcessMesh, get_current_mesh

__all__ = ["shard_tensor", "shard_op", "shard_layer", "get_dist_attr"]


def _to_pspec(shard_spec: Sequence[Optional[str]]) -> P:
    return P(*[s if s else None for s in shard_spec])


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def shard_tensor(x, process_mesh: Optional[ProcessMesh] = None,
                 shard_spec: Optional[Sequence[Optional[str]]] = None):
    """Annotate (and place/constrain) `x` with a sharding over the mesh.
    Returns a Tensor carrying `dist_attr` so the Engine can use it as the
    parameter/input sharding."""
    mesh = process_mesh or get_current_mesh()
    if mesh is None:
        raise ValueError("no ProcessMesh: pass one or enter `with mesh:`")
    spec = _to_pspec(shard_spec or [])
    raw = x._data if isinstance(x, Tensor) else x
    sharding = NamedSharding(mesh.jax_mesh, spec)
    if _is_tracer(raw):
        out = jax.lax.with_sharding_constraint(raw, sharding)
    else:
        out = jax.device_put(raw, sharding)
    if isinstance(x, Tensor):
        x._data = out
        t = x
    else:
        t = Tensor(out)
    t.dist_attr = {"process_mesh": mesh, "shard_spec": list(shard_spec or [])}
    return t


def shard_op(op_fn, process_mesh: Optional[ProcessMesh] = None,
             in_shard_specs: Optional[List] = None,
             out_shard_specs: Optional[List] = None):
    """Wrap a callable so its inputs/outputs are sharding-constrained
    (≈ shard_op attaching dist attrs to an op's tensors)."""
    mesh = process_mesh or get_current_mesh()

    def wrapped(*args, **kwargs):
        m = mesh or get_current_mesh()
        if m is None:
            return op_fn(*args, **kwargs)
        if in_shard_specs:
            args = tuple(
                shard_tensor(a, m, s) if s is not None and
                isinstance(a, (Tensor, jax.Array)) else a
                for a, s in zip(args, in_shard_specs)
            ) + tuple(args[len(in_shard_specs):])
        out = op_fn(*args, **kwargs)
        if out_shard_specs:
            if isinstance(out, (list, tuple)):
                out = type(out)(
                    shard_tensor(o, m, s) if s is not None else o
                    for o, s in zip(out, out_shard_specs))
            else:
                out = shard_tensor(out, m, out_shard_specs[0])
        return out

    return wrapped


def shard_layer(layer, process_mesh: ProcessMesh,
                shard_fn=None):
    """Annotate every parameter of `layer`. `shard_fn(name, param, mesh)`
    returns a shard_spec (list of mesh-dim-or-None) per param; default
    replicates everything (pure DP)."""
    for name, p in layer.named_parameters():
        spec = (shard_fn(name, p, process_mesh) if shard_fn
                else [None] * len(p.shape))
        shard_tensor(p, process_mesh, spec)
    return layer


def get_dist_attr(x) -> Optional[dict]:
    return getattr(x, "dist_attr", None)
