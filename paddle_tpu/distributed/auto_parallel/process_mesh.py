"""ProcessMesh: the named logical device grid.

Reference analog: auto_parallel.ProcessMesh (process_mesh.py) — an
N-D array of process ranks with dim names, used as the target of
dims_mapping annotations. TPU-native: it wraps jax.sharding.Mesh directly;
"process" = TPU chip, and multi-host meshes come from jax.devices()
spanning all processes after jax.distributed.initialize.
"""
from __future__ import annotations

import contextlib
import threading
from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["ProcessMesh", "get_current_mesh"]

_STATE = threading.local()


class ProcessMesh:
    def __init__(self, mesh: Sequence, dim_names: Optional[List[str]] = None,
                 process_ids=None):
        """`mesh` is a nested list of process (device) ids, reference
        style. Convenience: a FLAT list is read as a SHAPE exactly when
        `dim_names` names each of its entries (len(dim_names) ==
        len(mesh)) — so ProcessMesh([2, 4], dim_names=["dp", "mp"]) is a
        2x4 grid over devices 0..7 — and as process ids otherwise. The
        rule depends only on the arguments, never on the host's device
        count."""
        arr = np.asarray(mesh)
        if arr.ndim == 1 and arr.dtype.kind in "iu" and \
                process_ids is None and dim_names is not None and \
                len(dim_names) == len(arr) and all(int(s) >= 1 for s in arr):
            shape = tuple(int(s) for s in arr)
            ids = np.arange(int(np.prod(shape))).reshape(shape)
        else:
            ids = arr
            shape = ids.shape
        self.shape = tuple(int(s) for s in shape)
        self.process_ids = ids
        self.dim_names = list(dim_names) if dim_names else \
            [f"d{i}" for i in range(len(self.shape))]
        if len(self.dim_names) != len(self.shape):
            raise ValueError("dim_names must match mesh rank")

        devices = jax.devices()
        flat_ids = [int(i) for i in ids.reshape(-1)]
        if len(set(flat_ids)) != len(flat_ids):
            raise ValueError(
                f"duplicate process ids in mesh: {sorted(flat_ids)}")
        bad = [i for i in flat_ids if i < 0 or i >= len(devices)]
        if bad:
            raise ValueError(
                f"process ids {bad} out of range for {len(devices)} "
                f"devices")
        flat = [devices[i] for i in flat_ids]
        self._jax_mesh = Mesh(np.array(flat).reshape(self.shape),
                              tuple(self.dim_names))

    @property
    def jax_mesh(self) -> Mesh:
        return self._jax_mesh

    @property
    def ndim(self) -> int:
        return len(self.shape)

    def __enter__(self):
        stack = getattr(_STATE, "stack", None)
        if stack is None:
            stack = _STATE.stack = []
        stack.append(self)
        return self

    def __exit__(self, *exc):
        _STATE.stack.pop()
        return False

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dims={self.dim_names})"


def get_current_mesh() -> Optional[ProcessMesh]:
    stack = getattr(_STATE, "stack", None)
    return stack[-1] if stack else None
