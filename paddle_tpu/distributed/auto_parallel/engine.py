"""Auto-parallel Engine: fit/evaluate/predict over an annotated model.

Reference analog: auto_parallel.Engine (engine.py:58,494,749): trace the
model to a serial Program, complete dist attrs, partition per rank,
reshard, then run per-rank programs on the executor — plus dataloader
splitting and checkpoint I/O.

TPU-native: the Engine jits ONE SPMD training step over the ProcessMesh:
parameter shardings come from shard_tensor annotations (default
replicated), batch inputs shard along the mesh's data axis, and XLA SPMD
does completion/partition/reshard in the compiler. fit() then streams
host batches through the compiled step.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from ...jit.api import functional_call, _wrap
from .interface import get_dist_attr, _to_pspec
from .process_mesh import ProcessMesh

__all__ = ["Engine"]


class Engine:
    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 strategy=None, process_mesh: Optional[ProcessMesh] = None,
                 data_axis: Optional[str] = None):
        self.model = model
        self.loss_fn = loss
        self.optimizer = optimizer
        self.metrics = metrics or []
        self.strategy = strategy
        self.mesh = process_mesh
        # axis batch data shards along; default: first mesh dim
        self._data_axis = data_axis
        self._train_step = None
        self._eval_fn = None
        self._pred_fn = None
        self._opt_state = None
        self._fleet_step = None  # full-space tune installs a fleet step
        self._history: List[Dict[str, float]] = []

    # ------------------------------------------------------------- plumbing
    def _require_mesh(self) -> ProcessMesh:
        if self.mesh is None:
            from .process_mesh import get_current_mesh
            self.mesh = get_current_mesh()
        if self.mesh is None:
            # fallback: 1-D data-parallel mesh over every device
            self.mesh = ProcessMesh(list(range(len(jax.devices()))),
                                    dim_names=["dp"])
        return self.mesh

    def _param_sharding(self, p, mesh: Mesh):
        attr = get_dist_attr(p)
        if attr is not None:
            return NamedSharding(mesh, _to_pspec(attr["shard_spec"]))
        return NamedSharding(mesh, P())  # replicated

    def _batch_sharding(self, ndim: int, mesh: Mesh):
        axis = self._data_axis or mesh.axis_names[0]
        return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))

    def _names_and_params(self):
        names = [n for n, _ in self.model.named_parameters()]
        params = [p for _, p in self.model.named_parameters()]
        return names, params

    # ------------------------------------------------------------- prepare
    def prepare(self):
        """Build + cache the compiled SPMD train step (lazy otherwise)."""
        self._build_train_step()
        return self

    def tune(self, *example_batch, max_candidates: int = 8,
             verbose: bool = False, model_builder: Optional[Callable] = None,
             **tuner_kwargs):
        """strategy='auto' entry: search mesh degrees for this model on
        the visible devices (reference parallel_tuner.py analog; see
        tuner.py for the compiled-program cost model). Returns the
        winning Candidate and leaves the engine on its mesh.

        Without `model_builder` the search covers dp x (one annotated
        model axis) over the engine's own GSPMD step. With
        `model_builder(hybrid_configs) -> (model, optimizer, loss_fn)`
        the FULL dp x sharding x pp x mp space is searched through the
        fleet hybrid path (reference parallel_tuner.py:33 searches
        pipeline stages too): each candidate gets a fresh fleet.init +
        model (pipeline splitting changes parameter placement), and the
        winner's DistributedTrainStep is installed on the engine —
        fit() then trains through it."""
        if model_builder is not None:
            return _engine_tune_full(self, model_builder, example_batch,
                                     max_candidates=max_candidates,
                                     verbose=verbose, **tuner_kwargs)
        return _engine_tune(self, example_batch,
                            max_candidates=max_candidates,
                            verbose=verbose, **tuner_kwargs)

    def _build_train_step(self):
        if self._train_step is not None:
            return
        pmesh = self._require_mesh()
        mesh = pmesh.jax_mesh
        names, params = self._names_and_params()
        p_shardings = [self._param_sharding(p, mesh) for p in params]
        # place params onto their shardings now (device_put is cheap if
        # the annotation already placed them)
        for p, s in zip(params, p_shardings):
            if isinstance(p._data, jax.core.Tracer):
                continue
            p._data = jax.device_put(p._data, s)

        opt = self.optimizer
        model, loss_fn = self.model, self.loss_fn

        def step(param_vals, opt_state, lr, step_no, *batch):
            def loss_of(pvals):
                out = functional_call(
                    model, dict(zip(names, pvals)),
                    *[jax.tree_util.tree_map(_wrap, b)
                      for b in batch[:-1]])
                loss = loss_fn(out, jax.tree_util.tree_map(_wrap,
                                                           batch[-1]))
                return loss._data if isinstance(loss, Tensor) else loss

            loss, grads = jax.value_and_grad(loss_of)(list(param_vals))
            new_p, new_s = opt.apply_gradients(list(param_vals), grads,
                                               opt_state, lr=lr,
                                               step=step_no)
            return loss, new_p, new_s

        self._p_shardings = p_shardings
        self._jit_step = jax.jit(step, donate_argnums=(0, 1))
        self._train_step = True

    # ------------------------------------------------------------------ fit
    def fit(self, train_data, epochs: int = 1, batch_size: Optional[int] = None,
            steps_per_epoch: Optional[int] = None, log_freq: int = 10,
            verbose: int = 1):
        """`train_data` yields (inputs..., label) numpy/Tensor tuples —
        an iterable/DataLoader — or is a tuple of arrays to be batched."""
        if self._fleet_step is not None:
            return self._fit_fleet(train_data, epochs, batch_size,
                                   steps_per_epoch, log_freq, verbose)
        self._build_train_step()
        mesh = self.mesh.jax_mesh
        names, params = self._names_and_params()
        if self._opt_state is None:
            self._opt_state = [self.optimizer.init_state_for(p._data)
                               for p in params]

        for epoch in range(epochs):
            it = _batches(train_data, batch_size)
            t0 = time.perf_counter()
            n_steps = 0
            last_loss = None
            axis = self._data_axis or mesh.axis_names[0]
            axis_size = mesh.shape[axis]
            for bi, batch in enumerate(it):
                if steps_per_epoch is not None and \
                        n_steps >= steps_per_epoch:
                    break
                leaves = jax.tree_util.tree_leaves(
                    batch, is_leaf=lambda t: isinstance(t, Tensor))
                lead = _to_array(leaves[0]).shape[0] if leaves else 0
                if lead % axis_size != 0:
                    import warnings
                    warnings.warn(
                        f"Engine.fit: skipping batch of {lead} samples "
                        f"not divisible by data axis '{axis}' "
                        f"(size {axis_size})")
                    continue
                def _put(t):
                    arr = _to_array(t)
                    return jax.device_put(
                        arr, self._batch_sharding(arr.ndim, mesh))
                raw = [jax.tree_util.tree_map(
                    _put, b, is_leaf=lambda t: isinstance(t, Tensor))
                    for b in batch]
                lr = np.float32(self.optimizer.get_lr())
                self.optimizer._step_count += 1
                stepno = np.int32(self.optimizer._step_count)
                loss, new_vals, self._opt_state = self._jit_step(
                    [p._data for p in params], self._opt_state, lr,
                    stepno, *raw)
                for p, v in zip(params, new_vals):
                    p._data = v
                last_loss = loss
                n_steps += 1
                if verbose and bi % log_freq == 0:
                    print(f"epoch {epoch} step {bi} "
                          f"loss {float(loss):.4f}")
            dt = time.perf_counter() - t0
            if n_steps == 0:
                import warnings
                warnings.warn(
                    f"Engine.fit epoch {epoch} yielded no batches "
                    f"(batch_size larger than the dataset, or a "
                    f"one-shot iterator already exhausted)")
            rec = {"epoch": epoch,
                   "loss": float(last_loss) if last_loss is not None
                   else None,
                   "steps": n_steps, "time_s": dt}
            self._history.append(rec)
        return self._history

    def _fit_fleet(self, train_data, epochs, batch_size, steps_per_epoch,
                   log_freq, verbose):
        """fit() through the full-space-tuned fleet DistributedTrainStep
        (pp/sharding/mp candidates train here; the GSPMD jit path above
        covers the dp x one-model-axis case)."""
        step = self._fleet_step
        axis_size = 1
        for ax in ("dp", "sharding"):
            if ax in step.mesh.shape:
                axis_size *= step.mesh.shape[ax]
        for epoch in range(epochs):
            t0 = time.perf_counter()
            n_steps = 0
            last_loss = None
            for bi, batch in enumerate(_batches(train_data, batch_size)):
                if steps_per_epoch is not None and \
                        n_steps >= steps_per_epoch:
                    break
                leaves = jax.tree_util.tree_leaves(
                    batch, is_leaf=lambda t: isinstance(t, Tensor))
                lead = _to_array(leaves[0]).shape[0] if leaves else 0
                if lead % axis_size != 0:
                    import warnings
                    warnings.warn(
                        f"Engine.fit: skipping batch of {lead} samples "
                        f"not divisible by the data axes "
                        f"(size {axis_size})")
                    continue
                last_loss = step(*batch)
                n_steps += 1
                if verbose and bi % log_freq == 0:
                    print(f"epoch {epoch} step {bi} "
                          f"loss {float(np.asarray(last_loss.data)):.4f}")
            self._history.append(
                {"epoch": epoch,
                 "loss": float(np.asarray(last_loss.data))
                 if last_loss is not None else None,
                 "steps": n_steps,
                 "time_s": time.perf_counter() - t0})
        return self._history

    # ------------------------------------------------------------ evaluate
    def evaluate(self, eval_data, batch_size: Optional[int] = None):
        self._require_mesh()
        names, params = self._names_and_params()
        model, loss_fn = self.model, self.loss_fn

        if self._eval_fn is None:
            def ev(param_vals, *batch):
                out = functional_call(
                    model, dict(zip(names, param_vals)),
                    *[jax.tree_util.tree_map(_wrap, b)
                      for b in batch[:-1]])
                loss = loss_fn(out, jax.tree_util.tree_map(_wrap,
                                                           batch[-1]))
                return loss._data if isinstance(loss, Tensor) else loss
            self._eval_fn = jax.jit(ev)

        losses, weights = [], []
        for batch in _batches(eval_data, batch_size):
            raw = [jax.tree_util.tree_map(
                _to_array, b, is_leaf=lambda t: isinstance(t, Tensor))
                for b in batch]
            leaves = jax.tree_util.tree_leaves(raw)
            weights.append(int(leaves[0].shape[0]) if leaves
                           and getattr(leaves[0], "ndim", 0) else 1)
            losses.append(float(self._eval_fn(
                [p._data for p in params], *raw)))
        if not losses:
            return {"eval_loss": None}
        # weight per-batch mean losses by batch size so a trailing
        # partial batch doesn't bias the average
        return {"eval_loss": float(np.average(losses, weights=weights))}

    # ------------------------------------------------------------- predict
    def predict(self, test_data, batch_size: Optional[int] = None):
        self._require_mesh()
        names, params = self._names_and_params()
        model = self.model

        if self._pred_fn is None:
            def pd(param_vals, *inputs):
                out = functional_call(
                    model, dict(zip(names, param_vals)),
                    *[jax.tree_util.tree_map(_wrap, b) for b in inputs])
                return jax.tree_util.tree_map(
                    lambda t: t._data if isinstance(t, Tensor) else t, out,
                    is_leaf=lambda x: isinstance(x, Tensor))
            self._pred_fn = jax.jit(pd)

        outs = []
        for batch in _batches(test_data, batch_size):
            raw = [jax.tree_util.tree_map(
                _to_array, b, is_leaf=lambda t: isinstance(t, Tensor))
                for b in batch]
            out = self._pred_fn([p._data for p in params], *raw)
            # model outputs may be a pytree (e.g. ERNIE's (mlm, sop)
            # logits) — convert leaves, keep the structure
            outs.append(jax.tree_util.tree_map(np.asarray, out))
        return outs

    # ----------------------------------------------------------------- io
    def save(self, path: str):
        from ... import framework_io
        framework_io.save(self.model.state_dict(), path + ".pdparams")
        if self._opt_state is not None:
            import pickle
            with open(path + ".pdopt", "wb") as f:
                pickle.dump(jax.tree_util.tree_map(np.asarray,
                                                   self._opt_state), f)

    def load(self, path: str):
        from ... import framework_io
        state = framework_io.load(path + ".pdparams")
        self.model.set_state_dict(state)
        import os
        import pickle
        if os.path.exists(path + ".pdopt"):
            with open(path + ".pdopt", "rb") as f:
                self._opt_state = jax.tree_util.tree_map(
                    jnp.asarray, pickle.load(f))

    @property
    def history(self):
        return self._history


def _to_array(t):
    return t._data if isinstance(t, Tensor) else jnp.asarray(t)


def _batches(data, batch_size: Optional[int]):
    """Normalize data into an iterator of tuples of arrays. The trailing
    partial batch is yielded too (one extra XLA compilation for the
    remainder shape, cached across epochs) — samples are never silently
    dropped."""
    if isinstance(data, tuple) and all(
            isinstance(a, (np.ndarray, jnp.ndarray, Tensor))
            for a in data):
        n = len(data[0])
        bs = batch_size or n
        arrs = [a.numpy() if isinstance(a, Tensor) else np.asarray(a)
                for a in data]
        for i in range(0, n, bs):
            yield tuple(a[i:i + bs] for a in arrs)
    else:
        for batch in data:
            yield tuple(batch) if isinstance(batch, (tuple, list)) \
                else (batch,)


class _LowerAdapter:
    """Minimal .lower(*batch) wrapper so ParallelTuner can score an
    Engine-style GSPMD step the same way it scores a
    fleet.DistributedTrainStep."""

    def __init__(self, jit_step, params, opt_state, lr, batch_shardings):
        self._jit = jit_step
        self._params = params
        self._opt_state = opt_state
        self._lr = lr
        self._bshard = batch_shardings

    def lower(self, *batch):
        raw = [jax.device_put(np.asarray(b), s)
               for b, s in zip(batch, self._bshard)]
        return self._jit.lower(self._params, self._opt_state,
                               np.float32(self._lr), np.int32(1), *raw)


def _engine_tune(engine: "Engine", example_batch, max_candidates=8,
                 verbose=False, **tuner_kwargs):
    """strategy='auto': pick the (data x model) mesh for this Engine by
    compiling candidates and ranking them (tuner.py cost model).
    Model-parallel axis names come from the model's shard_tensor
    annotations; with no annotations only the data axis is searched."""
    from .tuner import ParallelTuner

    names, params = engine._names_and_params()
    model_axes = []
    for p in params:
        attr = get_dist_attr(p)
        if attr:
            for ax in attr["shard_spec"]:
                if ax is not None and ax not in model_axes:
                    model_axes.append(ax)
    if len(model_axes) > 1:
        raise ValueError(
            f"Engine strategy='auto' tunes one model axis; model "
            f"annotations use {model_axes} — pass an explicit "
            f"process_mesh for >2-D meshes")
    model_axis = model_axes[0] if model_axes else None
    n = len(jax.devices())
    data_axis = engine._data_axis or "dp"

    def step_builder(cfg):
        dp, mp = cfg["dp_degree"], cfg["mp_degree"]
        shape = (dp, mp) if model_axis else (dp,)
        axis_names = [data_axis] + ([model_axis] if model_axis else [])
        pm = ProcessMesh(
            np.arange(n).reshape(shape), dim_names=axis_names)
        engine.mesh = pm
        engine._train_step = None  # rebuild on the candidate mesh
        engine._build_train_step()
        mesh = pm.jax_mesh
        pvals = [p._data for p in engine.model.parameters()]
        opt_state = [engine.optimizer.init_state_for(v) for v in pvals]
        bshard = [engine._batch_sharding(np.asarray(b).ndim, mesh)
                  for b in example_batch]
        adapter = _LowerAdapter(engine._jit_step, pvals, opt_state,
                                engine.optimizer.get_lr(), bshard)
        return adapter, tuple(np.asarray(b) for b in example_batch)

    tuner = ParallelTuner(
        n, step_builder, axes=("dp", "mp") if model_axis else ("dp",),
        max_candidates=max_candidates, **tuner_kwargs)
    best = tuner.tune(verbose=verbose)
    # leave the engine on the winning mesh
    step_builder(best.hybrid_configs)
    engine._tuned = best
    return best


def _engine_tune_full(engine: "Engine", model_builder, example_batch, *,
                      max_candidates=8, verbose=False, **tuner_kwargs):
    """Full-space strategy search (dp x sharding x pp x mp) through the
    fleet hybrid path. Per candidate: fleet.init on the candidate
    degrees, a FRESH model from model_builder (pipeline splitting
    changes parameter structure, so the same Layer object cannot be
    re-partitioned in place), then a fleet.DistributedTrainStep is
    lowered/compiled and scored by the tuner cost model. The winning
    candidate is rebuilt and installed: engine.fit() trains through
    its DistributedTrainStep.

    model_builder(hybrid_configs) -> (model, optimizer, loss_fn); it
    reads the active fleet topology (already initialized on the
    candidate degrees when called) to pick e.g. gpt() vs
    gpt_pipe(num_stages=pp). Reference:
    auto_parallel/tuner/parallel_tuner.py:33 (candidates over process
    meshes incl. pipeline stages)."""
    from .tuner import ParallelTuner
    from .. import fleet

    def step_builder(cfg):
        strategy = fleet.DistributedStrategy(
            hybrid_configs=dict(cfg),
            sharding=cfg.get("sharding_degree", 1) > 1,
            sharding_configs={"stage": 2})
        fleet.init(strategy=strategy)
        model, opt, loss_fn = model_builder(dict(cfg))
        model = fleet.distributed_model(model)
        opt = fleet.distributed_optimizer(opt)
        step = fleet.DistributedTrainStep(model, opt, loss_fn)
        return step, tuple(example_batch)

    axes = tuner_kwargs.pop("axes", ("dp", "sharding", "pp", "mp"))
    tuner = ParallelTuner(len(jax.devices()), step_builder, axes=axes,
                          max_candidates=max_candidates, **tuner_kwargs)
    best = tuner.tune(verbose=verbose)
    step, _ = step_builder(best.hybrid_configs)
    engine._fleet_step = step
    engine.model = step.model
    engine.optimizer = step.optimizer
    engine.loss_fn = step.loss_fn
    # expose the winner's hybrid mesh so evaluate()/_require_mesh see
    # the tuned topology, not a fresh 1-D fallback
    dev_ids = np.array([d.id for d in step.mesh.devices.flat]).reshape(
        step.mesh.devices.shape)
    engine.mesh = ProcessMesh(dev_ids,
                              dim_names=list(step.mesh.axis_names))
    engine._tuned = best
    return best
