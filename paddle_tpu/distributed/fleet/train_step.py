"""DistributedTrainStep: the hybrid-parallel fused training step.

This is where the reference's whole distributed runtime collapses into one
XLA program: Reducer grad bucketing+allreduce (imperative/reducer.cc:451),
sharding stage1/2/3 reduce-scatter/all-gather (group_sharded_stage2/3.py),
TP collectives (mp_ops.py), and comm/compute overlap (ProcessGroupNCCL
comm streams) are ALL emitted by XLA's SPMD partitioner + latency-hiding
scheduler from the shardings declared here:

  params:    per-layer spec (mp) composed with ZeRO stage>=3 (sharding)
  grads:     constrained to ZeRO stage>=2 specs (reduce-scatter fusion)
  opt state: ZeRO stage>=1 specs
  batch:     sharded over (dp, sharding) on dim 0
  loss mean: global psum inserted automatically by the partitioner

Gradient accumulation (the reference's gradient_merge /
GradientMergeOptimizer) is a lax.scan over microbatches inside the same
program.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...optimizer.optimizer import opt_key as _opt_key
from ...core.tensor import Tensor
from ...jit.api import functional_call, _unwrap, _wrap
from ...nn.layer import Layer
from .. import topology
from ..parallel.sharding import ShardingStrategy

DATA_AXES = ("dp", "sharding")  # batch dim shards over both (ZeRO axes
# are data-parallel axes too — fleet's sharding group is a dp subgroup)


def _param_base_spec(p) -> P:
    return getattr(p, "spec", P())


def shard_model(model: Layer, mesh: Optional[Mesh] = None,
                strategy: Optional[ShardingStrategy] = None):
    """Place every parameter according to its spec (+ ZeRO stage 3).
    ≈ the initial broadcast/partition pass of DataParallel/stage3."""
    mesh = mesh or topology.get_mesh()
    if mesh is None:
        return model
    strategy = strategy or ShardingStrategy(stage=0)
    for _, p in model.named_parameters():
        spec = strategy.param_spec(tuple(p.data.shape), mesh,
                                   _param_base_spec(p))
        p._data = jax.device_put(p._data, NamedSharding(mesh, spec))
    for _, b in model.named_buffers():
        b._data = jax.device_put(
            b._data, NamedSharding(mesh, getattr(b, "spec", P())))
    return model


class DistributedTrainStep:
    """Sharded, donated, fused train step over the active hybrid mesh.

    loss_fn(outputs, labels) -> scalar mean loss over the GLOBAL batch.
    accumulate_steps>1 runs gradient accumulation as an in-program scan
    over leading-dim microbatches (inputs get an extra leading dim).
    """

    def __init__(self, model: Layer, optimizer, loss_fn: Callable,
                 mesh: Optional[Mesh] = None, donate: bool = True,
                 accumulate_steps: int = 1, abstract: bool = False,
                 recompute=None):
        """abstract=True skips placing parameters on the mesh (and
        lower_abstract() skips optimizer/batch buffers too): the step
        can then only be LOWERED, not executed — compile-planning a
        mesh whose replicated state would not fit host memory (e.g. a
        256-chip plan on a virtual CPU mesh)."""
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh or topology.get_mesh()
        if self.mesh is None:
            raise RuntimeError("No mesh: call fleet.init(strategy) first")
        self.strategy: ShardingStrategy = getattr(
            optimizer, "_sharding_strategy", ShardingStrategy(stage=0))
        self.accumulate_steps = accumulate_steps
        self.abstract = abstract
        # recompute: fleet.utils.RecomputeConfig (or policy name) —
        # wraps the whole per-microbatch forward in jax.checkpoint so
        # long-context configs trade backward FLOPs for activation HBM
        # (and with it, batch size) without editing the model
        if recompute is not None:
            from .utils.recompute import _as_config
            recompute = _as_config(recompute)
        self._recompute = recompute

        if not abstract:
            shard_model(model, self.mesh, self.strategy)
        self._params = [p for _, p in model.named_parameters()]
        self._param_names = [n for n, _ in model.named_parameters()]

        m, s = self.mesh, self.strategy
        self._param_shardings = [
            NamedSharding(m, s.param_spec(tuple(p.data.shape), m,
                                          _param_base_spec(p)))
            for p in self._params]
        self._grad_specs = [
            s.grad_spec(tuple(p.data.shape), m, _param_base_spec(p))
            for p in self._params]
        self._opt_state_tree = None
        self._jitted = None
        self._warm_store = None   # enable_warm_start() opt-in
        self._warm_exe = None

    # ----------------------------------------------------------------- build
    def _build(self, batch_ndims):
        m = self.mesh
        names = self._param_names
        grad_specs = self._grad_specs
        acc = self.accumulate_steps
        loss_fn = self.loss_fn
        model = self.model
        opt = self.optimizer

        def loss_of(pvals, *batch):
            pdict = dict(zip(names, pvals))
            out = functional_call(model, pdict, *[Tensor(b) if
                                                  isinstance(b, jax.Array)
                                                  else b for b in batch[:-1]])
            loss = loss_fn(out, jax.tree_util.tree_map(_wrap, batch[-1]))
            return _unwrap(loss)

        if self._recompute is not None and self._recompute.enabled:
            loss_of = self._recompute.wrap(loss_of)

        def grads_of(pvals, *batch):
            loss, grads = jax.value_and_grad(loss_of)(list(pvals), *batch)
            grads = [
                jax.lax.with_sharding_constraint(
                    g, NamedSharding(m, spec))
                for g, spec in zip(grads, grad_specs)]
            return loss, grads

        def step_fn(param_vals, opt_state, lr, step_no, *batch):
            if acc == 1:
                loss, grads = grads_of(param_vals, *batch)
            else:
                # microbatch scan: batch elems have leading dim acc
                def body(carry, micro):
                    l_acc, g_acc = carry
                    l, g = grads_of(param_vals, *micro)
                    return (l_acc + l,
                            [a + b for a, b in zip(g_acc, g)]), None

                zero_g = [jnp.zeros_like(p) for p in param_vals]
                (loss, grads), _ = jax.lax.scan(
                    body, (jnp.zeros((), jnp.float32), zero_g), batch)
                loss = loss / acc
                grads = [g / acc for g in grads]
            new_params, new_state = opt.apply_gradients(
                list(param_vals), grads, opt_state, lr=lr, step=step_no)
            return loss, new_params, new_state

        from ...core.jaxshim import SHARDING_AWARE_DONATION
        # old jax mispairs donated buffers across the mixed-sharding
        # param/opt trees (aval-only matching): donate only where the
        # matcher is sharding-aware; the fallback costs one transient
        # copy of params+state, it never changes numerics
        donate = (0, 1) if SHARDING_AWARE_DONATION else ()
        self._donate = donate
        self._step_fn = step_fn
        self._jitted = jax.jit(
            step_fn, donate_argnums=donate,
            out_shardings=(NamedSharding(m, P()),
                           self._param_shardings, None))
        # warm/AOT path: donation baked only where the backend
        # implements it — deserialized aliasing double-frees donated
        # buffers on CPU (see TrainStep.__init__); the audit keeps the
        # donation intent regardless
        self._aot_donate = donate if jax.default_backend() == "tpu" \
            else ()
        self._aot_jitted = self._jitted if self._aot_donate == donate \
            else jax.jit(
                step_fn, donate_argnums=self._aot_donate,
                out_shardings=(NamedSharding(m, P()),
                               self._param_shardings, None))

    # ------------------------------------------------------------------ call
    def batch_sharding_for(self, leaf) -> NamedSharding:
        """Target input sharding for one batch leaf (rank-determined:
        the leading data dim shards over the dp+sharding axes). This is
        the contract the sharded device prefetcher
        (``io.device_prefetch.prefetch_to_device(loader, step)``)
        places against, so batches arrive committed on exactly the
        shardings ``_shard_batch`` would apply — which then skips."""
        nd = getattr(leaf, "ndim", None)
        if nd is None:
            nd = np.ndim(leaf)
        return NamedSharding(self.mesh, self._batch_leaf_spec(int(nd)))

    @property
    def batch_shardings(self):
        """Callable ``leaf -> NamedSharding`` (alias of
        batch_sharding_for) for prefetchers/loaders."""
        return self.batch_sharding_for

    def _shard_batch(self, arr):
        # the ONE idempotent-placement implementation (skip test +
        # io.host2device counting) lives in io.device_prefetch; lazy
        # import keeps fleet importable without the io package loaded
        from ...io.device_prefetch import place_batch
        sh = NamedSharding(self.mesh, self._batch_leaf_spec(arr.ndim))
        out = place_batch(arr, sh)
        return out._data if isinstance(out, Tensor) else out

    def _ensure_opt_state(self):
        """Seed (or re-load from a restored optimizer) the sharded
        optimizer-state tree."""
        if self._opt_state_tree is not None:
            return
        m, s = self.mesh, self.strategy
        self._opt_state_tree = []
        for p in self._params:
            st = self.optimizer._state.get(_opt_key(p)) \
                or self.optimizer.init_state_for(p)
            st = {k: (jax.device_put(
                v, NamedSharding(m, s.opt_state_spec(
                    tuple(jnp.shape(v)), m, _param_base_spec(p))))
                if v is not None else None)
                for k, v in st.items()}
            self._opt_state_tree.append(st)

    def _prepare(self, batch):
        """Shared by __call__ and lower(): opt state + jit + sharded
        raw batch."""
        if self.abstract:
            raise RuntimeError(
                "DistributedTrainStep(abstract=True) never placed its "
                "parameters/optimizer state on the mesh — it can only "
                "be lower_abstract()'ed, not executed; rebuild with "
                "abstract=False to run steps")
        self._ensure_opt_state()
        if self._jitted is None:
            self._build(tuple(getattr(b, "ndim", 0) for b in batch))
        return tuple(
            jax.tree_util.tree_map(
                lambda t: self._shard_batch(_unwrap(t)), b,
                is_leaf=lambda t: isinstance(t, Tensor))
            for b in batch)

    def lower(self, *batch):
        """jax Lowered for the step on these example inputs — the
        auto-parallel tuner compiles it per candidate mesh and scores
        the resulting program (tuner.py); also usable for AOT caching."""
        raw_batch = self._prepare(batch)
        return self._jitted.lower(
            [p._data for p in self._params], self._opt_state_tree,
            np.float32(self.optimizer.get_lr()),
            np.int32(self.optimizer._step_count + 1), *raw_batch)

    def _batch_leaf_spec(self, nd: int) -> P:
        lead = 1 if self.accumulate_steps > 1 else 0
        parts = [None] * nd
        if nd > lead:
            parts[lead] = DATA_AXES
        return P(*parts)

    def _abstract_operands(self, *batch):
        """ShapeDtypeStruct operands for step_fn — shapes, dtypes AND
        shardings, exactly what the compiled program runs with. The ONE
        construction shared by lower_abstract() and audit(), so the
        audited program can never drift from the lowered one. `batch`
        leaves may be arrays, Tensors, or ShapeDtypeStructs — only
        shape/dtype are read."""
        m, s = self.mesh, self.strategy
        p_avals = [jax.ShapeDtypeStruct(tuple(p.data.shape), p.data.dtype,
                                        sharding=sh)
                   for p, sh in zip(self._params, self._param_shardings)]
        opt_avals = []
        for p in self._params:
            st = jax.eval_shape(self.optimizer.init_state_for, p._data)
            opt_avals.append({
                k: (jax.ShapeDtypeStruct(
                    tuple(v.shape), v.dtype,
                    sharding=NamedSharding(m, s.opt_state_spec(
                        tuple(v.shape), m, _param_base_spec(p))))
                    if v is not None else None)
                for k, v in st.items()})
        repl = NamedSharding(m, P())
        lr_aval = jax.ShapeDtypeStruct((), np.float32, sharding=repl)
        no_aval = jax.ShapeDtypeStruct((), np.int32, sharding=repl)

        def leaf_aval(t):
            x = _unwrap(t)
            nd = len(x.shape)
            return jax.ShapeDtypeStruct(
                tuple(x.shape), x.dtype,
                sharding=NamedSharding(m, self._batch_leaf_spec(nd)))

        batch_avals = tuple(
            jax.tree_util.tree_map(
                leaf_aval, b, is_leaf=lambda t: isinstance(t, Tensor))
            for b in batch)
        return p_avals, opt_avals, lr_aval, no_aval, batch_avals

    def lower_abstract(self, *batch):
        """jax Lowered built from abstract (ShapeDtypeStruct) operands:
        no parameter, optimizer-state, or batch buffer is ever placed
        on the mesh, so meshes far larger than host memory compile-plan
        fine."""
        if self._jitted is None:
            self._build(None)
        p_avals, opt_avals, lr_aval, no_aval, batch_avals = \
            self._abstract_operands(*batch)
        return self._jitted.lower(p_avals, opt_avals, lr_aval, no_aval,
                                  *batch_avals)

    def cost_analysis(self, *batch):
        """XLA cost analysis of the compiled distributed step."""
        ca = self.lower(*batch).compile().cost_analysis()
        return ca[0] if isinstance(ca, (list, tuple)) else ca

    def audit(self, *batch, donate=(0, 1), **audit_kw):
        """Static audit of the sharded step on abstract operands (works
        for ``abstract=True`` plan-only steps too — nothing is placed
        on the mesh). ``donate`` defaults to the DESIGN intent (params
        + opt state donated) even where the running jax disables
        donation via the SHARDING_AWARE_DONATION shim: the audit checks
        the program we ship on TPU, not the fallback."""
        from ...analysis import audit as _audit
        if self._jitted is None:
            self._build(None)
        p_avals, opt_avals, lr_aval, no_aval, batch_avals = \
            self._abstract_operands(*batch)
        audit_kw.setdefault("name", "DistributedTrainStep.step_fn")
        with self.mesh:
            return _audit(self._step_fn, p_avals, opt_avals, lr_aval,
                          no_aval, *batch_avals, donate=donate,
                          **audit_kw)

    def enable_warm_start(self, store=None):
        """Opt-in executable persistence for the sharded step (same
        contract as ``TrainStep.enable_warm_start``): the first call
        lowers and loads a serialized executable from the store —
        keyed on the mesh axes too, so a resize can never replay the
        wrong program — falling back to (and persisting) a fresh
        compile on a cold store."""
        from ...jit import compile_cache
        self._warm_store = store if store is not None \
            else compile_cache.default_store()
        return self

    def _mesh_signature(self):
        return tuple(zip(self.mesh.axis_names,
                         self.mesh.devices.shape))

    def _warm_signature(self, args):
        """Traceless manifest key for the sharded step (same contract
        as TrainStep._warm_signature) — the mesh axes and sharding
        strategy join the key, so a resized mesh or changed ZeRO stage
        can never resolve to a stale executable."""
        from ...jit import compile_cache
        sig = compile_cache.network_signature(self.model)
        loss_sig = compile_cache.callable_signature(self.loss_fn)
        opt_src = compile_cache.source_hash(type(self.optimizer))
        flags = repr((self.accumulate_steps, self._recompute))
        if sig is None or loss_sig is None or opt_src is None \
                or "0x" in flags:
            return None
        sig.update(
            program=("DistributedTrainStep",), loss=loss_sig,
            opt=(type(self.optimizer).__qualname__, opt_src,
                 compile_cache.scalar_signature(self.optimizer)),
            strategy=(type(self.strategy).__qualname__,
                      compile_cache.scalar_signature(self.strategy)),
            flags=flags, mesh=self._mesh_signature(),
            operands=compile_cache.aval_signature(args))
        return sig

    def __call__(self, *batch):
        params = self._params
        raw_batch = self._prepare(batch)
        lr = self.optimizer.get_lr()
        self.optimizer._step_count += 1
        args = ([p._data for p in params], self._opt_state_tree,
                np.float32(lr), np.int32(self.optimizer._step_count),
                *raw_batch)
        if self._warm_store is not None and self._warm_exe is None:
            from ...core import monitor
            from ...jit import compile_cache
            try:
                self._warm_exe = compile_cache.build_or_load(
                    self._warm_signature(args),
                    lambda: self._aot_jitted.lower(*args),
                    store=self._warm_store,
                    extra=dict(kind="DistributedTrainStep",
                               donation=self._aot_donate,
                               mesh=self._mesh_signature()),
                    label="fleet.train_step")
            except Exception as e:
                # never let persistence break a training step
                monitor.record_swallowed(
                    "jit.compile_cache.fleet_warm", e)
            self._warm_store = None  # warmed once; drift falls back
        if self._warm_exe is not None:
            try:
                loss, new_vals, self._opt_state_tree = \
                    self._warm_exe(*args)
            except (TypeError, ValueError) as e:
                from ...core import monitor
                monitor.record_swallowed(
                    "jit.compile_cache.fleet_warm_step", e)
                self._warm_exe = None
        if self._warm_exe is None:
            loss, new_vals, self._opt_state_tree = self._jitted(*args)
        for p, v in zip(params, new_vals):
            p._data = v
        for p, st in zip(params, self._opt_state_tree):
            self.optimizer._state[_opt_key(p)] = st
        from ...optimizer.lr import LRScheduler
        if isinstance(self.optimizer._lr, LRScheduler) and \
                self.optimizer._lr._step_each_iter:
            self.optimizer._lr.step()
        return _wrap(loss)
