"""Fleet: the distributed orchestration facade.

Reference analog: python/paddle/distributed/fleet/fleet.py:166 (init),
fleet/model.py:30 (distributed_model), fleet.py:1030
(distributed_optimizer); DistributedStrategy over protobuf
(fleet/base/distributed_strategy.py:109, framework/distributed_strategy
.proto:28-117).

TPU-native: `init(strategy)` builds the hybrid mesh (HybridCommunicateGroup
-> jax Mesh) and installs it globally; `distributed_model` returns the
model unchanged (sharding comes from param specs + the mesh — there is no
wrapper class to intercept comm, XLA does it) after tagging dp-replicated
specs; `distributed_optimizer` attaches the ZeRO strategy. The
DistributedTrainStep (train_step.py) is where everything meets.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .. import topology
from ..env import init_parallel_env
from ..parallel.sharding import ShardingStrategy


@dataclass
class HybridConfig:
    dp_degree: int = 1
    mp_degree: int = 1
    pp_degree: int = 1
    sharding_degree: int = 1
    sp_degree: int = 1
    ep_degree: int = 1


@dataclass
class DistributedStrategy:
    """Typed strategy tree (the protobuf analog, distributed_strategy.proto:
    28-117 — sharding/mp/pp degrees, amp, recompute, gradient_merge...)."""
    hybrid_configs: HybridConfig = field(default_factory=HybridConfig)
    sharding: bool = False
    sharding_configs: dict = field(default_factory=dict)
    amp: bool = False
    amp_configs: dict = field(default_factory=dict)
    recompute: bool = False
    recompute_configs: dict = field(default_factory=dict)
    gradient_merge: bool = False
    gradient_merge_configs: dict = field(default_factory=dict)
    find_unused_parameters: bool = False

    def __post_init__(self):
        if isinstance(self.hybrid_configs, dict):
            self.hybrid_configs = HybridConfig(**{
                k: v for k, v in self.hybrid_configs.items()
                if k in HybridConfig.__dataclass_fields__})


_FLEET_STRATEGY: Optional[DistributedStrategy] = None


def init(role_maker=None, is_collective: bool = True,
         strategy: Optional[DistributedStrategy] = None,
         slices=None):
    """≈ fleet.init: rendezvous + build the mesh. `slices` (list of
    device groups) builds a DCN-aware hierarchical mesh where only the
    dp axis crosses slice boundaries (topology.create_hybrid_device_mesh
    — the ProcessGroupHeter analog)."""
    global _FLEET_STRATEGY
    init_parallel_env()
    strategy = strategy or DistributedStrategy()
    _FLEET_STRATEGY = strategy
    hc = strategy.hybrid_configs
    hcg = topology.HybridCommunicateGroup(
        dp_degree=hc.dp_degree, mp_degree=hc.mp_degree,
        pp_degree=hc.pp_degree, sharding_degree=hc.sharding_degree,
        sp_degree=hc.sp_degree, ep_degree=hc.ep_degree, slices=slices)
    topology.set_hybrid_communicate_group(hcg)
    return hcg


def get_hybrid_communicate_group():
    return topology.get_hybrid_communicate_group()


def get_strategy() -> Optional[DistributedStrategy]:
    return _FLEET_STRATEGY


def distributed_model(model):
    """≈ fleet.distributed_model (fleet/model.py:126-165 picks
    DataParallel/TensorParallel/PipelineParallel wrappers). Here sharding
    is declarative: ensure every param has a spec (default replicated) and
    return the model. PipelineParallel models go through
    parallel.pipeline.PipelineLayer instead."""
    from jax.sharding import PartitionSpec as P
    for _, p in model.named_parameters():
        if not hasattr(p, "spec"):
            p.spec = P()  # replicated (dp)
    return model


def distributed_optimizer(optimizer, strategy: Optional[DistributedStrategy] = None):
    """≈ fleet.distributed_optimizer -> HybridParallelOptimizer
    (dygraph_optimizer/hybrid_parallel_optimizer.py:186: TP-aware clip +
    grad sync). Grad sync is XLA's job; we attach the ZeRO strategy."""
    strategy = strategy or _FLEET_STRATEGY or DistributedStrategy()
    if strategy.sharding:
        stage = int(strategy.sharding_configs.get("stage", 2))
        optimizer._sharding_strategy = ShardingStrategy(stage=stage)
    elif not hasattr(optimizer, "_sharding_strategy"):
        optimizer._sharding_strategy = ShardingStrategy(stage=0)
    return optimizer


def worker_index() -> int:
    from ..env import get_rank
    return get_rank()


def worker_num() -> int:
    from ..env import get_world_size
    return get_world_size()
