from .base import (DistributedStrategy, distributed_model,  # noqa: F401
                   distributed_optimizer, get_hybrid_communicate_group,
                   init, worker_index, worker_num)
from .train_step import DistributedTrainStep, shard_model  # noqa: F401
