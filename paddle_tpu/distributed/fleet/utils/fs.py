"""Filesystem clients for checkpoint/elastic storage (reference:
python/paddle/distributed/fleet/utils/fs.py:111 `LocalFS`, :381+
`HDFSClient` — the same FS interface the reference's auto-checkpoint
and fleet save/load paths program against).

`LocalFS` is fully implemented over the local filesystem. `HDFSClient`
shells out to the `hadoop fs` CLI exactly like the reference; when no
hadoop binary is available (this environment) construction fails with
a clear error rather than a broken client.
"""
from __future__ import annotations

import abc
import os
import shutil
import subprocess


class ExecuteError(Exception):
    pass


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class FS(abc.ABC):
    """Abstract FS interface (mirrors the reference's method set)."""

    @abc.abstractmethod
    def ls_dir(self, fs_path):
        ...

    @abc.abstractmethod
    def is_file(self, fs_path):
        ...

    @abc.abstractmethod
    def is_dir(self, fs_path):
        ...

    @abc.abstractmethod
    def is_exist(self, fs_path):
        ...

    @abc.abstractmethod
    def upload(self, local_path, fs_path):
        ...

    @abc.abstractmethod
    def download(self, fs_path, local_path):
        ...

    @abc.abstractmethod
    def mkdirs(self, fs_path):
        ...

    @abc.abstractmethod
    def delete(self, fs_path):
        ...

    @abc.abstractmethod
    def need_upload_download(self):
        ...

    @abc.abstractmethod
    def rename(self, fs_src_path, fs_dst_path):
        ...

    @abc.abstractmethod
    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=False):
        ...

    @abc.abstractmethod
    def list_dirs(self, fs_path):
        ...

    @abc.abstractmethod
    def touch(self, fs_path, exist_ok=True):
        ...


class LocalFS(FS):
    """Local filesystem client (reference fs.py:111)."""

    def ls_dir(self, fs_path):
        """-> ([subdir names], [file names]) under fs_path."""
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for f in os.listdir(fs_path):
            (dirs if os.path.isdir(os.path.join(fs_path, f))
             else files).append(f)
        return dirs, files

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def upload(self, local_path, fs_path):
        # local->local: a copy (parity with the reference's semantics)
        if not os.path.exists(local_path):
            raise FSFileNotExistsError(local_path)
        if os.path.isdir(local_path):
            if os.path.exists(fs_path):
                raise FSFileExistsError(fs_path)
            shutil.copytree(local_path, fs_path)
        else:
            shutil.copy2(local_path, fs_path)

    def download(self, fs_path, local_path):
        self.upload(fs_path, local_path)

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def delete(self, fs_path):
        if not self.is_exist(fs_path):
            return
        if os.path.isdir(fs_path):
            shutil.rmtree(fs_path)
        else:
            os.remove(fs_path)

    def need_upload_download(self):
        return False

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if not self.is_exist(src_path):
            raise FSFileNotExistsError(src_path)
        if self.is_exist(dst_path):
            if not overwrite:
                raise FSFileExistsError(dst_path)
            self.delete(dst_path)
        os.replace(src_path, dst_path) if os.path.isfile(src_path) \
            else shutil.move(src_path, dst_path)

    def list_dirs(self, fs_path):
        """Subdirectory names only."""
        return self.ls_dir(fs_path)[0]

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            return
        with open(fs_path, "a"):
            pass

    def cat(self, fs_path=None):
        with open(fs_path, "rb") as f:
            return f.read().decode()


class HDFSClient(FS):
    """HDFS client over the `hadoop fs` CLI (reference fs.py:381+).
    Requires a hadoop binary; in environments without one (this
    container) construction raises with remediation instead of
    returning a client whose every call would fail."""

    def __init__(self, hadoop_home=None, configs=None,
                 time_out=5 * 60 * 1000, sleep_inter=1000):
        hadoop_home = hadoop_home or os.environ.get("HADOOP_HOME", "")
        cand = os.path.join(hadoop_home, "bin", "hadoop") \
            if hadoop_home else "hadoop"
        if shutil.which(cand) is None:
            raise RuntimeError(
                "HDFSClient needs the hadoop CLI; none found (set "
                "HADOOP_HOME or install hadoop). For local storage use "
                "LocalFS — the checkpoint subsystems accept either.")
        self._bin = cand
        self._configs = configs or {}
        self._time_out = time_out          # total budget, ms
        self._sleep_inter = sleep_inter    # retry sleep, ms

    def _run(self, *args, _retries=True):
        """Run `hadoop fs <args>`, retrying transient failures with
        sleep_inter pauses until the time_out budget is spent (the
        reference's _handle_errors contract). Every failure mode —
        nonzero exit, CLI hang — surfaces as ExecuteError."""
        import time as _time
        cmd = [self._bin, "fs"]
        for k, v in self._configs.items():
            cmd += ["-D", f"{k}={v}"]
        cmd += list(args)
        deadline = _time.monotonic() + self._time_out / 1000
        last = None
        while True:
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                raise ExecuteError(
                    f"{' '.join(cmd)}: timed out after "
                    f"{self._time_out} ms ({last})")
            try:
                r = subprocess.run(cmd, capture_output=True, text=True,
                                   timeout=remaining)
            except subprocess.TimeoutExpired:
                raise ExecuteError(
                    f"{' '.join(cmd)}: hadoop CLI hung past the "
                    f"{self._time_out} ms budget")
            if r.returncode == 0:
                return r.stdout
            last = r.stderr.strip()
            if not _retries or args[0].startswith("-test"):
                # predicates use nonzero exit as their answer
                raise ExecuteError(f"{' '.join(cmd)}: {last}")
            _time.sleep(self._sleep_inter / 1000)

    def ls_dir(self, fs_path):
        try:
            out = self._run("-ls", fs_path)
        except ExecuteError:
            # only after the retry budget: a missing path yields ([], [])
            # per LocalFS.ls_dir and the reference HDFSClient.ls_dir
            # (fs.py:547); anything else (transient cluster failure that
            # outlived the retries) still surfaces as the error
            if not self.is_exist(fs_path):
                return [], []
            raise
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def is_file(self, fs_path):
        try:
            self._run("-test", "-f", fs_path)
            return True
        except ExecuteError:
            return False

    def is_dir(self, fs_path):
        try:
            self._run("-test", "-d", fs_path)
            return True
        except ExecuteError:
            return False

    def is_exist(self, fs_path):
        try:
            self._run("-test", "-e", fs_path)
            return True
        except ExecuteError:
            return False

    def upload(self, local_path, fs_path):
        self._run("-put", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        if self.is_exist(fs_path):
            self._run("-rm", "-r", fs_path)

    def need_upload_download(self):
        return True

    def rename(self, fs_src_path, fs_dst_path):
        self._run("-mv", fs_src_path, fs_dst_path)

    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=True):
        # test_exists defaults True per the reference HDFSClient.mv
        # contract (fs.py:916): missing src / existing dst fail fast with
        # typed errors instead of an ExecuteError after the retry budget
        if test_exists:
            if not self.is_exist(fs_src_path):
                raise FSFileNotExistsError(fs_src_path)
            if self.is_exist(fs_dst_path) and not overwrite:
                # hadoop -mv into an existing dir silently NESTS src
                # inside it — checkpoint renames must fail instead
                raise FSFileExistsError(fs_dst_path)
        if overwrite and self.is_exist(fs_dst_path):
            self.delete(fs_dst_path)
        self._run("-mv", fs_src_path, fs_dst_path)

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            return
        self._run("-touchz", fs_path)

    def cat(self, fs_path=None):
        return self._run("-cat", fs_path)
