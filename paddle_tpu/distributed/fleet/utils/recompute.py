"""Paddle-parity activation recompute: ``fleet.utils.recompute``.

Reference analog: python/paddle/distributed/fleet/utils re-exports
``recompute`` (fleet/recompute/recompute.py:386 — a PyLayer re-running
the forward with RNG state restore). TPU-native the whole mechanism is
``jax.checkpoint``: XLA re-emits the forward inside the backward pass,
RNG is functional so nothing needs restoring, and the *policy* decides
which intermediates are worth keeping.

``RecomputeConfig`` names the policies with their jax names so a config
file can dial the memory/FLOPs trade per run:

    ============================  =========================================
    policy                        saves
    ============================  =========================================
    ``None``                      everything (recompute OFF)
    ``"full"``                    nothing — max HBM relief, ~1.3x trunk
                                  FLOPs (alias ``"nothing_saveable"``,
                                  the literal jax name)
    ``"dots_saveable"``           matmul/einsum outputs — cheap backward,
                                  moderate memory (the reference's
                                  ``core_attn`` granularity)
    ``"dots_with_no_batch_dims_saveable"``  matmuls without batch dims —
                                  the default "selective" granularity
    ============================  =========================================

Long-context configs trade recompute for batch size: at s4096+ the
activations dominate HBM, and ``RecomputeConfig("full")`` buys back
enough to double the per-chip batch (see BASELINE.md sweeps).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

import jax

from ...parallel.recompute import recompute as _parallel_recompute

#: policy name -> jax.checkpoint policy (None = save nothing)
_JAX_POLICIES = {
    "full": None,
    "nothing_saveable": None,
    "dots_saveable": jax.checkpoint_policies.dots_saveable,
    "dots_with_no_batch_dims_saveable":
        jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    # reference-granularity aliases (models/gpt.py vocabulary)
    "selective": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    "core_attn": jax.checkpoint_policies.dots_saveable,
}


@dataclass(frozen=True)
class RecomputeConfig:
    """Declarative remat knob carried by train steps and model configs.

    ``policy=None`` disables recompute entirely (``wrap`` is the
    identity); any named policy wraps a function in ``jax.checkpoint``
    with the corresponding saveable-intermediates rule.
    """

    #: a name from _JAX_POLICIES, a raw ``jax.checkpoint_policies``
    #: callable, or None (recompute OFF)
    policy: Optional[object] = "full"

    def __post_init__(self):
        if self.policy is not None and not callable(self.policy) \
                and self.policy not in _JAX_POLICIES:
            raise ValueError(
                f"unknown recompute policy {self.policy!r}; one of "
                f"{sorted(set(_JAX_POLICIES))}, a jax.checkpoint_policies "
                f"callable, or None")

    @property
    def enabled(self) -> bool:
        return self.policy is not None

    def jax_policy(self):
        """The jax.checkpoint ``policy=`` value (None = save nothing)."""
        if callable(self.policy):
            return self.policy
        return _JAX_POLICIES.get(self.policy)

    def wrap(self, fn: Callable) -> Callable:
        """``jax.checkpoint(fn, policy=...)`` under this config; ``fn``
        unchanged when disabled."""
        if not self.enabled:
            return fn
        return jax.checkpoint(fn, policy=self.jax_policy())


def _as_config(policy) -> Optional[RecomputeConfig]:
    if policy is None or isinstance(policy, RecomputeConfig):
        return policy
    return RecomputeConfig(policy=policy)


def recompute(function: Callable, *args, **kwargs):
    """≈ ``paddle.distributed.fleet.utils.recompute(function, *args)``:
    run ``function`` now, recompute its intermediates in backward.

    Accepts the reference's ``use_reentrant``/``preserve_rng_state``
    kwargs (both meaningless under jax — remat re-traces, RNG is
    functional) and a ``policy=`` extension: a name from
    :class:`RecomputeConfig` or a raw ``jax.checkpoint_policies``
    callable. Layers become functional remat regions (their parameters
    turn into explicit tape inputs), plain callables are wrapped
    directly."""
    kwargs.pop("use_reentrant", None)
    kwargs.pop("preserve_rng_state", None)
    # policy=None here means "full" (calling recompute() at all asks
    # for remat — Paddle's recompute has no policy knob, it always
    # recomputes everything); pass RecomputeConfig(None) to run the
    # function plainly with recompute OFF.
    policy = kwargs.pop("policy", "full")
    cfg = _as_config("full" if policy is None else policy)
    if not cfg.enabled:
        return function(*args, **kwargs)
    return _parallel_recompute(function, *args, policy=cfg.jax_policy(),
                               **kwargs)
