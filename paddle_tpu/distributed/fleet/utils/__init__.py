"""Fleet utilities (reference: python/paddle/distributed/fleet/utils/
— the FS client family used by checkpoint/elastic paths)."""
from .fs import FS, LocalFS, HDFSClient  # noqa: F401

__all__ = ["FS", "LocalFS", "HDFSClient"]
