"""Fleet utilities (reference: python/paddle/distributed/fleet/utils/
— the FS client family used by checkpoint/elastic paths, plus the
``recompute`` activation-checkpointing entry)."""
from .fs import FS, LocalFS, HDFSClient  # noqa: F401
from .recompute import RecomputeConfig, recompute  # noqa: F401

__all__ = ["FS", "LocalFS", "HDFSClient", "RecomputeConfig", "recompute"]
