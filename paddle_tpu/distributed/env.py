"""Process/cluster environment.

Reference analog: paddle.distributed.init_parallel_env
(python/paddle/distributed/parallel.py:98) — TCPStore rendezvous (:264) +
ProcessGroupNCCL per rank (:272), env contract PADDLE_TRAINER_ID/
PADDLE_TRAINERS_NUM/PADDLE_MASTER set by the launcher.

TPU-native: jax.distributed.initialize IS the coordination service
(≈ TCPStore + comm bootstrap in one); on a TPU pod slice every process
sees its slice-local chips and XLA handles cross-chip routing. Single
process = single "rank" regardless of local chip count (SPMD inside).
"""
from __future__ import annotations

import os
from typing import Optional

import jax

from ..core.jaxshim import shard_map

_INITIALIZED = False


def init_parallel_env(strategy=None) -> "ParallelEnv":
    """Initialize multi-host coordination if launcher env is present."""
    global _INITIALIZED
    if _INITIALIZED:
        return ParallelEnv()
    coord = os.environ.get("PADDLE_MASTER") or \
        os.environ.get("COORDINATOR_ADDRESS")
    nproc = int(os.environ.get("PADDLE_TRAINERS_NUM",
                               os.environ.get("NUM_PROCESSES", "1")))
    pid = int(os.environ.get("PADDLE_TRAINER_ID",
                             os.environ.get("PROCESS_ID", "0")))
    if coord and nproc > 1:
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=nproc, process_id=pid)
    _INITIALIZED = True
    return ParallelEnv()


class ParallelEnv:
    """≈ paddle.distributed.ParallelEnv: rank/world info."""

    @property
    def rank(self) -> int:
        return jax.process_index()

    @property
    def world_size(self) -> int:
        return jax.process_count()

    @property
    def device_id(self) -> int:
        return jax.local_devices()[0].id

    @property
    def nranks(self) -> int:
        return self.world_size

    @property
    def local_rank(self) -> int:
        return self.rank


def get_rank() -> int:
    """Process index (≈ paddle.distributed.get_rank). Note: on TPU one
    process drives many chips; per-chip 'rank' only exists inside
    shard_map via jax.lax.axis_index."""
    return jax.process_index()


def get_world_size() -> int:
    return jax.process_count()


def is_initialized() -> bool:
    return _INITIALIZED


def barrier(group=None):
    """Host-level barrier: a tiny psum across all devices."""
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    devs = jax.devices()
    if len(devs) == 1:
        return
    import numpy as np
    mesh = Mesh(np.array(devs), ("all",))
    x = jax.device_put(jnp.zeros(len(devs)),
                       NamedSharding(mesh, P("all")))
    shard_map(lambda a: jax.lax.psum(a, "all"), mesh=mesh,
                  in_specs=P("all"), out_specs=P())(x).block_until_ready()
