"""TP RNG state tracker.

Reference analog: python/paddle/distributed/fleet/layers/mpu/random.py —
RNGStatesTracker keeps per-name generator states so dropout inside
model-parallel regions differs per mp rank while replicated regions match.

Functional jax version: a tracker maps name -> base key; `get_states_
tracker().rng_state('local_seed')` yields a key folded with the mesh
position along the given axes (different per mp shard), while
'global_seed' yields the unfolded key (same everywhere). Inside shard_map
the fold uses jax.lax.axis_index so it traces correctly.
"""
from __future__ import annotations

import contextlib
from typing import Dict, Optional, Sequence

import jax

_MODEL_PARALLEL_RNG = "model_parallel_rng"
_GLOBAL_RNG = "global_seed"


class RNGStatesTracker:
    def __init__(self):
        self.states: Dict[str, jax.Array] = {}

    def reset(self):
        self.states.clear()

    def add(self, name: str, seed: int):
        if name in self.states:
            raise ValueError(f"rng state {name} already exists")
        self.states[name] = jax.random.PRNGKey(seed)

    def get_states(self):
        return dict(self.states)

    def set_states(self, states):
        self.states = dict(states)

    @contextlib.contextmanager
    def rng_state(self, name: str = _MODEL_PARALLEL_RNG,
                  fold_axes: Sequence[str] = ("mp",)):
        """Context yielding a key; folded per mesh position for
        model-parallel names so parallel dropout masks differ per shard."""
        if name not in self.states:
            import zlib
            # stable across processes (hash() is PYTHONHASHSEED-randomized,
            # which would silently desync dp replicas across hosts)
            self.add(name, zlib.crc32(name.encode()) % (2 ** 31))
        key = self.states[name]
        if name != _GLOBAL_RNG:
            for ax in fold_axes:
                try:
                    key = jax.random.fold_in(key, jax.lax.axis_index(ax))
                except NameError:
                    pass  # axis not bound (not inside shard_map) -> global
        # split so repeated entries differ
        self.states[name], sub = jax.random.split(self.states[name])
        yield jax.random.fold_in(sub, 0) if name == _GLOBAL_RNG else \
            jax.random.fold_in(key, 1)


_TRACKER = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _TRACKER


def model_parallel_random_seed(seed: int = 1234):
    """≈ mpu.random.model_parallel_random_seed: seed global + local
    streams."""
    _TRACKER.reset()
    _TRACKER.add(_GLOBAL_RNG, seed)
    _TRACKER.add(_MODEL_PARALLEL_RNG, seed + 1024)
