"""ZeRO sharding stages 1/2/3 as sharding specs.

Reference analog: python/paddle/distributed/fleet/meta_parallel/sharding/
group_sharded_optimizer_stage2.py:52 (opt-state sharding + param broadcast),
group_sharded_stage2.py (grad reduce-scatter), group_sharded_stage3.py:59
(param sharding with gather-on-forward); user API group_sharded_parallel
(distributed/sharding/group_sharded.py:55).

TPU-native: ZeRO is NOT wrapper classes mutating comm hooks — it is a
choice of NamedShardings for (params, grads, opt-state) over the
dp/sharding axis of the mesh; XLA inserts the reduce-scatter/all-gather
the reference implements imperatively:
  stage 1: opt state sharded; params+grads replicated
  stage 2: + grads sharded (reduce-scatter in backward)
  stage 3: + params sharded (all-gather on use)
`ShardingStrategy.specs_for(shape)` picks the largest divisible dim to
shard — the analog of stage3's parameter segmentation (:193).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass
class ShardingStrategy:
    stage: int = 0                  # 0 = pure DP
    axis: str = "sharding"          # mesh axis carrying ZeRO
    min_size_to_shard: int = 2 ** 10  # don't shard tiny tensors

    def _shard_spec(self, shape: Tuple[int, ...], mesh: Mesh,
                    extra_spec: Optional[P] = None) -> P:
        """Shard the largest axis-divisible dim not already taken by
        extra_spec (e.g. an mp sharding on the weight)."""
        n = mesh.shape[self.axis]
        if n <= 1 or int(np.prod(shape or (1,))) < self.min_size_to_shard:
            return extra_spec if extra_spec is not None else P()
        taken = list(extra_spec) if extra_spec is not None else \
            [None] * len(shape)
        taken += [None] * (len(shape) - len(taken))
        best, best_dim = 0, -1
        for i, s in enumerate(shape):
            if taken[i] is None and s % n == 0 and s > best:
                best, best_dim = s, i
        if best_dim < 0:
            return extra_spec if extra_spec is not None else P()
        parts = list(taken)
        parts[best_dim] = self.axis
        return P(*parts)

    def param_spec(self, shape, mesh, base_spec: Optional[P] = None) -> P:
        if self.stage >= 3:
            return self._shard_spec(shape, mesh, base_spec)
        return base_spec if base_spec is not None else P()

    def grad_spec(self, shape, mesh, base_spec: Optional[P] = None) -> P:
        if self.stage >= 2:
            return self._shard_spec(shape, mesh, base_spec)
        return base_spec if base_spec is not None else P()

    def opt_state_spec(self, shape, mesh, base_spec: Optional[P] = None) -> P:
        if self.stage >= 1:
            return self._shard_spec(shape, mesh, base_spec)
        return base_spec if base_spec is not None else P()


def group_sharded_parallel(model, optimizer, level: str = "os_g",
                           scaler=None):
    """≈ paddle.distributed.sharding.group_sharded_parallel: annotate for
    ZeRO. level: 'os' = stage1, 'os_g' = stage2, 'p_g_os' = stage3.
    Returns (model, optimizer, scaler); the sharded TrainStep
    (fleet.distributed_train_step) reads `optimizer._sharding_strategy`."""
    stage = {"os": 1, "os_g": 2, "p_g_os": 3}[level]
    optimizer._sharding_strategy = ShardingStrategy(stage=stage)
    return model, optimizer, scaler
