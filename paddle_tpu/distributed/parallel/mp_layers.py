"""Tensor-parallel layers.

Reference analog: python/paddle/distributed/fleet/layers/mpu/mp_layers.py —
VocabParallelEmbedding(:37), ColumnParallelLinear(:173),
RowParallelLinear(:327), ParallelCrossEntropy(:491), with hand-inserted
collectives from mp_ops.py (_c_identity/_mp_allreduce/_c_split).

TPU-native (GSPMD): layers hold FULL logical weights annotated with a
PartitionSpec over the 'mp' mesh axis; XLA's SPMD partitioner slices the
matmuls and inserts the psum/all_gather the reference writes by hand.
`with_sharding_constraint` pins activation layouts at the seams the
reference's _c_identity/_c_concat mark. The layers therefore run
unchanged on 1 device (specs are no-ops) and partition under a mesh.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor, dispatch
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer import Layer
from .. import topology


def _constraint(x_raw, spec):
    """Apply a sharding constraint if a global mesh is active and the
    shape divides the mesh axes (small debug batches skip the pin rather
    than erroring — XLA still propagates shardings without it)."""
    mesh = topology.get_mesh()
    if mesh is None:
        return x_raw
    for dim, entry in enumerate(spec):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        n = 1
        for a in axes:
            n *= mesh.shape[a]
        if dim >= x_raw.ndim or x_raw.shape[dim] % n != 0:
            return x_raw
    from ...core import jaxshim
    if jaxshim.in_manual_fallback():
        # old-jax full-manual shard_map fallback: these axes are manual
        # in the enclosing region, a constraint on them fails lowering
        return x_raw
    try:
        return jax.lax.with_sharding_constraint(
            x_raw, NamedSharding(mesh, spec))
    except Exception:
        return x_raw


def sharded_constraint(x, spec):
    if isinstance(x, Tensor):
        return dispatch("sharding_constraint",
                        lambda a: _constraint(a, spec), (x,), {})
    return _constraint(x, spec)


class ColumnParallelLinear(Layer):
    """Weight [in, out] sharded on out (mp); output shards over mp unless
    gather_output (≈ mp_layers.py:173)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.spec = P(None, "mp")  # out-dim sharded
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            self.bias.spec = P("mp")
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            out = sharded_constraint(out, P(*([None] * out.ndim)))
        else:
            out = sharded_constraint(
                out, P(*([None] * (out.ndim - 1) + ["mp"])))
        return out


class RowParallelLinear(Layer):
    """Weight [in, out] sharded on in (mp); input expected mp-sharded on its
    last dim; output is psum-reduced by GSPMD (≈ mp_layers.py:327)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.spec = P("mp", None)  # in-dim sharded
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            self.bias.spec = P()
        else:
            self.bias = None

    def forward(self, x):
        x = sharded_constraint(x, P(*([None] * (x.ndim - 1) + ["mp"])))
        out = F.linear(x, self.weight, None)
        out = sharded_constraint(out, P(*([None] * out.ndim)))
        if self.bias is not None:
            out = out + self.bias
        return out


class VocabParallelEmbedding(Layer):
    """Embedding table sharded on the vocab dim (≈ mp_layers.py:37). GSPMD
    turns the gather into a masked local lookup + psum, the same trick the
    reference's c_embedding op implements by hand
    (operators/collective/c_embedding_op.cu)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        self.weight.spec = P("mp", None)

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return sharded_constraint(out, P(*([None] * out.ndim)))


class ParallelCrossEntropy(Layer):
    """Cross entropy over mp-sharded logits (≈ mp_layers.py:491 /
    c_softmax_with_cross_entropy_op). Under GSPMD the plain fused
    cross-entropy partitions correctly when logits are mp-sharded on the
    class dim; we pin that layout and let XLA insert the two psums."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        input = sharded_constraint(
            input, P(*([None] * (input.ndim - 1) + ["mp"])))
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
