"""Context/sequence parallelism: ring attention + Ulysses (all-to-all).

NEW capability relative to the reference snapshot — SURVEY.md §5 verified
(grep) that Paddle has no sequence/context parallelism; its closest assets
are the fused attention CUDA ops. The TPU design reserves the 'sp' mesh
axis (topology.AXIS_ORDER) and implements the two standard long-context
schemes natively:

- **Ring attention** (`ring_attention`): q/k/v sharded on the sequence dim
  over 'sp'; k/v chunks rotate around the ring via `jax.lax.ppermute`
  (XLA lowers to ICI neighbor exchange) while each device accumulates its
  query block's online softmax — O(S/n) activation memory per chip, full
  overlap of the rotation with the local block matmul. Differentiable: AD
  transposes the ppermute automatically, so the backward runs the reverse
  ring without hand-written collectives.

- **Ulysses** (`ulysses_attention`): all_to_all re-shards sequence →
  heads, runs dense local attention (which may itself use the Pallas
  flash kernel), and all_to_alls back. Cheaper at moderate S, requires
  num_heads % sp == 0.

Both run inside `shard_map` islands so they compose with the dp/mp axes of
the surrounding GSPMD program.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from ...core.jaxshim import shard_map
from jax.sharding import PartitionSpec as P

from .. import topology

_NEG_INF = -1e30


def _local_block(q, k, v, scale, causal, q_off, k_off):
    """One [sq_local, sk_local] attention block in fp32 online-softmax
    form. Returns (m, l, acc): row max, row normalizer, unnormalized out.
    q/k/v: [B, S_l, H, D]."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2) + q_off
        cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 3) + k_off
        s = jnp.where(rows >= cols, s, _NEG_INF)
    m = jnp.max(s, axis=-1)                       # [B,H,Q]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)                       # [B,H,Q]
    acc = jnp.einsum("bhqk,bkhd->bhqd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return m, l, acc


def _merge(carry, new):
    """Merge two online-softmax partial results."""
    m0, l0, a0 = carry
    m1, l1, a1 = new
    m = jnp.maximum(m0, m1)
    c0 = jnp.exp(m0 - m)
    c1 = jnp.exp(m1 - m)
    return m, l0 * c0 + l1 * c1, a0 * c0[..., None] + a1 * c1[..., None]


def _ring_attention_local(q, k, v, *, scale, causal, axis_name):
    """Per-device body under shard_map. q/k/v: [B, S_local, H, D] (their
    shard of the global sequence)."""
    n = jax.lax.psum(1, axis_name)
    me = jax.lax.axis_index(axis_name)
    s_local = q.shape[1]
    q_off = me * s_local
    perm = [(i, (i - 1) % n) for i in range(n)]  # kv source idx advances

    m = jnp.full(q.shape[:1] + (q.shape[2], s_local), _NEG_INF, jnp.float32)
    l = jnp.zeros_like(m)
    acc = jnp.zeros((q.shape[0], q.shape[2], s_local, q.shape[3]),
                    jnp.float32)

    def body(step, carry):
        m, l, acc, k, v = carry
        src = (me + step) % n        # rank whose kv chunk we hold now
        k_off = src * s_local
        if causal:
            # skip chunks strictly above the causal diagonal
            needed = k_off <= q_off + s_local - 1

            def do(args):
                m, l, acc, k, v = args
                return _merge((m, l, acc),
                              _local_block(q, k, v, scale, True,
                                           q_off, k_off))

            m, l, acc = jax.lax.cond(
                needed, do, lambda args: (args[0], args[1], args[2]),
                (m, l, acc, k, v))
        else:
            m, l, acc = _merge((m, l, acc),
                               _local_block(q, k, v, scale, False, 0, 0))
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        return m, l, acc, k, v

    m, l, acc, k, v = jax.lax.fori_loop(0, n, body, (m, l, acc, k, v),
                                        unroll=True)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = acc / l_safe[..., None]                 # [B,H,Q,D]
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)  # [B,S_l,H,D]


def _axis_degree(mesh, axis_name) -> int:
    return mesh.shape[axis_name] if axis_name in mesh.shape else 1


def _data_spec_entry(mesh, batch):
    axes = [a for a in ("dp", "sharding")
            if _axis_degree(mesh, a) > 1]
    n = 1
    for a in axes:
        n *= mesh.shape[a]
    return tuple(axes) if axes and batch % n == 0 else None


def ring_attention(q, k, v, causal=False, scale=None,
                   axis_name: str = "sp", mesh=None):
    """Ring attention over [batch, seq, heads, head_dim] GLOBAL arrays
    whose sequence dim is (to be) sharded over `axis_name`. Falls back to
    plain attention when the axis is trivial."""
    mesh = mesh or topology.get_mesh()
    d = q.shape[-1]
    scale = float(scale if scale is not None else 1.0 / (d ** 0.5))
    if mesh is None or _axis_degree(mesh, axis_name) == 1:
        from ...nn.functional.attention import _sdpa_xla
        return _sdpa_xla(q, k, v, is_causal=causal, scale=scale)
    bspec = _data_spec_entry(mesh, q.shape[0])
    hspec = "mp" if (_axis_degree(mesh, "mp") > 1
                     and q.shape[2] % mesh.shape["mp"] == 0) else None
    spec = P(bspec, axis_name, hspec, None)
    fn = shard_map(
        functools.partial(_ring_attention_local, scale=scale,
                          causal=causal, axis_name=axis_name),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def _ulysses_local(q, k, v, *, scale, causal, axis_name, sp):
    """Per-device body: [B, S/sp, H, D] → all_to_all → [B, S, H/sp, D] →
    dense attention → back."""
    from ...nn.functional.attention import _sdpa_xla

    def seq_to_heads(x):
        # split heads into sp groups, exchange so each device holds the
        # full sequence for H/sp heads
        x = jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                               tiled=True)
        return x

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = _sdpa_xla(qh, kh, vh, is_causal=causal, scale=scale)
    return heads_to_seq(out)


def ulysses_attention(q, k, v, causal=False, scale=None,
                      axis_name: str = "sp", mesh=None):
    """DeepSpeed-Ulysses style sequence parallelism: all_to_all seq↔heads.
    Requires num_heads divisible by the sp degree."""
    mesh = mesh or topology.get_mesh()
    d = q.shape[-1]
    scale = float(scale if scale is not None else 1.0 / (d ** 0.5))
    sp = _axis_degree(mesh, axis_name) if mesh is not None else 1
    if mesh is None or sp == 1:
        from ...nn.functional.attention import _sdpa_xla
        return _sdpa_xla(q, k, v, is_causal=causal, scale=scale)
    if q.shape[2] % sp != 0:
        raise ValueError(
            f"ulysses needs heads {q.shape[2]} divisible by sp={sp}")
    # GQA: k/v are all_to_all'd on the head axis too, so the kv-head
    # count must also divide sp — catch it here with a real message
    # instead of a mid-trace reshape failure
    for name, t in (("key", k), ("value", v)):
        if t.shape[2] % sp != 0:
            raise ValueError(
                f"ulysses needs {name} heads {t.shape[2]} divisible by "
                f"sp={sp}; for GQA either repeat kv heads to a multiple "
                f"of sp or use ring_attention (no head-axis exchange)")
    bspec = _data_spec_entry(mesh, q.shape[0])
    spec = P(bspec, axis_name, None, None)
    fn = shard_map(
        functools.partial(_ulysses_local, scale=scale, causal=causal,
                          axis_name=axis_name, sp=sp),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def split_sequence(x, axis: int = 1, axis_name: str = "sp", mesh=None):
    """Pin a sharding constraint placing `axis` over the sp mesh axis
    (the scatter half of the reference-style scatter/gather SP pair).
    Other dims are left UNCONSTRAINED so existing dp/mp placement
    propagates untouched."""
    mesh = mesh or topology.get_mesh()
    if mesh is None or _axis_degree(mesh, axis_name) == 1:
        return x
    from ...core import jaxshim
    if jaxshim.in_manual_fallback():
        return x
    parts = [P.UNCONSTRAINED] * x.ndim
    parts[axis] = axis_name
    from jax.sharding import NamedSharding
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*parts)))


def gather_sequence(x, axis: int = 1, axis_name: str = "sp", mesh=None):
    """Constraint-replicate the sequence dim (gather half); other dims
    stay UNCONSTRAINED."""
    mesh = mesh or topology.get_mesh()
    if mesh is None or _axis_degree(mesh, axis_name) == 1:
        return x
    from ...core import jaxshim
    if jaxshim.in_manual_fallback():
        return x
    parts = [P.UNCONSTRAINED] * x.ndim
    parts[axis] = None
    from jax.sharding import NamedSharding
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*parts)))
