"""Activation recompute (gradient checkpointing).

Reference analog: python/paddle/distributed/fleet/recompute/recompute.py
:224 (RecomputeFunction PyLayer re-running forward with RNG restore),
:386 (recompute entry). TPU-native: jax.checkpoint (remat) — XLA re-emits
the forward in the backward pass; RNG is functional so no state juggling.
Policies map to jax.checkpoint_policies (e.g. save matmul outputs ≈ the
reference's selective offload)."""
from __future__ import annotations

from typing import Callable

import jax

from ...core.tensor import Tensor, dispatch
from ...nn.layer import Layer

_POLICIES = {
    None: None,
    "full": None,  # save nothing, recompute everything
    "save_dots": jax.checkpoint_policies.dots_saveable,
    "save_dots_no_batch": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
}


def recompute(function: Callable, *args, policy=None, **kwargs):
    """≈ fleet.recompute: run `function` without saving intermediates;
    recompute them in backward. When `function` is a Layer its parameters
    become explicit tape inputs (via functional_call) so eager backward
    differentiates through the remat region."""
    if isinstance(function, Layer):
        names = [n for n, _ in function.named_parameters()]
        params = [p for _, p in function.named_parameters()]
        n_args = len(args)

        def raw_fn(*raw):
            raw_args, raw_params = raw[:n_args], raw[n_args:]
            from ...jit.api import functional_call
            out = functional_call(function, dict(zip(names, raw_params)),
                                  *[_maybe_tensor(a) for a in raw_args],
                                  **kwargs)
            return _unwrap_tree(out)

        ckpt_fn = jax.checkpoint(raw_fn,
                                 policy=_POLICIES.get(policy, policy))
        return dispatch("recompute", ckpt_fn, tuple(args) + tuple(params),
                        {})

    ckpt_fn = jax.checkpoint(
        lambda *raw: _raw_call(function, raw, kwargs),
        policy=_POLICIES.get(policy, policy))
    return dispatch("recompute", ckpt_fn, args, {})


def _maybe_tensor(a):
    import jax as _jax
    import numpy as _np
    if isinstance(a, Tensor) or not isinstance(a, (_jax.Array, _np.ndarray)):
        return a
    return Tensor(a)


def _raw_call(function, raw_args, kwargs):
    targs = [_maybe_tensor(a) for a in raw_args]
    out = function(*targs, **kwargs)
    return _unwrap_tree(out)


def _unwrap_tree(out):
    return jax.tree_util.tree_map(
        lambda t: t._data if isinstance(t, Tensor) else t, out,
        is_leaf=lambda x: isinstance(x, Tensor))


class RecomputeWrapper(Layer):
    """Wrap a sublayer so its forward is rematerialized (the PipelineLayer
    per-chunk recompute analog, pp_layers.py:206)."""

    def __init__(self, layer: Layer, policy=None):
        super().__init__()
        self.inner = layer
        self.policy = policy

    def forward(self, *args, **kwargs):
        return recompute(self.inner, *args, policy=self.policy, **kwargs)
