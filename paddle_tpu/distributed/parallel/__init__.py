from . import (context_parallel, mp_layers, pipeline, random,  # noqa: F401
               recompute, sharding)
from .context_parallel import (ring_attention, split_sequence,  # noqa: F401
                               ulysses_attention)
