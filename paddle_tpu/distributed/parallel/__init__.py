from . import mp_layers, pipeline, random, recompute, sharding  # noqa: F401
