from . import mp_layers, random, recompute, sharding  # noqa: F401
