from . import (context_parallel, moe, mp_layers, pipeline,  # noqa: F401
               random, recompute, sharding)
from .context_parallel import (ring_attention, split_sequence,  # noqa: F401
                               ulysses_attention)
from .moe import MoEMLP, aux_loss as moe_aux_loss  # noqa: F401
