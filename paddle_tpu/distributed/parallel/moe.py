"""Mixture-of-Experts with expert parallelism over the 'ep' mesh axis.

Reference analog: python/paddle/incubate/distributed/models/moe/
(moe_layer.py MoELayer, gate/gshard_gate.py, gate/switch_gate.py) dispatching
tokens with the hand-written global_scatter/global_gather collective ops
(paddle/fluid/operators/collective/global_scatter_op.*).

TPU-native (GShard formulation): expert FFN weights are STACKED with a
leading expert dim sharded over 'ep'; routing builds dense dispatch/combine
tensors [tokens, E, capacity] and the dispatch/return become einsums whose
resharding (token-sharded → expert-sharded → token-sharded) XLA lowers to
the same all_to_all pair the reference codes by hand — riding ICI, fused
with the expert matmuls, and differentiable with zero extra code.

Gates: 'naive' (top-k softmax, no aux loss), 'switch' (top-1 + load-balance
loss, Fedus et al.), 'gshard' (top-2 + load-balance loss, Lepikhin et al.).
Auxiliary loss is exposed as `layer.l_aux` (a traced value when called
under jit: read it in the SAME trace, e.g. inside the loss closure —
`aux_loss(model)` sums it over all MoE sublayers).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...core.tensor import Tensor, dispatch as _dispatch
from ...nn import initializer as I
from ...nn.layer import Layer
from .mp_layers import sharded_constraint


def _one_hot(idx, n):
    return jax.nn.one_hot(idx, n, dtype=jnp.float32)


def top_k_routing(gates, top_k: int, capacity: int):
    """Greedy top-k routing with per-expert capacity.

    gates: [T, E] softmax probabilities.
    Returns (combine [T, E, C], dispatch_mask [T, E, C], aux_inputs):
    aux_inputs = (me, ce): mean gate prob and mean top-1 assignment per
    expert, the two factors of the GShard/Switch load-balancing loss.
    """
    t, e = gates.shape
    remaining = gates
    counts = jnp.zeros((e,), jnp.float32)   # tokens already placed / expert
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    me = jnp.mean(gates, axis=0)
    ce = None
    for k in range(top_k):
        idx = jnp.argmax(remaining, axis=1)              # [T]
        mask = _one_hot(idx, e)                          # [T, E]
        if k == 0:
            ce = jnp.mean(mask, axis=0)
        # position of each token within its chosen expert's buffer
        pos_in_expert = (jnp.cumsum(mask, axis=0) - 1.0 + counts) * mask
        kept = mask * (pos_in_expert < capacity)
        counts = counts + jnp.sum(kept, axis=0)
        weight = jnp.sum(gates * kept, axis=1, keepdims=True)  # [T,1]
        pos = jnp.sum(pos_in_expert * kept, axis=1).astype(jnp.int32)
        cap_oh = _one_hot(pos, capacity) * jnp.sum(kept, axis=1,
                                                   keepdims=True)
        combine = combine + weight[..., None] * kept[..., None] * \
            cap_oh[:, None, :]
        remaining = remaining * (1.0 - mask)
    dispatch_mask = (combine > 0.0).astype(gates.dtype)
    return combine.astype(gates.dtype), dispatch_mask, (me, ce)


def load_balance_loss(me, ce):
    """GShard/Switch aux loss: E * sum_e(me_e * ce_e) — minimized when
    routing is uniform (≈ reference's gate/gshard_gate.py loss)."""
    return me.shape[0] * jnp.sum(me * ce)


class MoEMLP(Layer):
    """Expert-parallel FFN bank + gate (the MoELayer analog).

    Holds stacked expert weights [E, ...] sharded over 'ep'; forward
    routes tokens, runs experts, and combines. l_aux is set per call.
    """

    def __init__(self, d_model: int, d_hidden: int, num_experts: int,
                 gate: str = "gshard", top_k: Optional[int] = None,
                 capacity_factor: float = 1.25,
                 activation=None, name=None):
        super().__init__()
        if gate not in ("naive", "switch", "gshard"):
            raise ValueError(f"unknown gate type {gate!r}")
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.gate_type = gate
        self.top_k = top_k if top_k is not None else \
            {"naive": 2, "switch": 1, "gshard": 2}[gate]
        self.capacity_factor = capacity_factor
        # raw (non-Tensor) activation: runs on jax arrays inside the
        # already-dispatched forward
        self.activation = activation or (lambda x: jax.nn.gelu(x))

        self.gate_weight = self.create_parameter(
            (d_model, num_experts),
            default_initializer=I.XavierUniform())
        self.gate_weight.spec = P()
        self.w1 = self.create_parameter(
            (num_experts, d_model, d_hidden),
            default_initializer=I.XavierUniform())
        self.w1.spec = P("ep", None, "mp")
        self.b1 = self.create_parameter((num_experts, d_hidden),
                                        is_bias=True)
        self.b1.spec = P("ep", "mp")
        self.w2 = self.create_parameter(
            (num_experts, d_hidden, d_model),
            default_initializer=I.XavierUniform())
        self.w2.spec = P("ep", "mp", None)
        self.b2 = self.create_parameter((num_experts, d_model),
                                        is_bias=True)
        self.b2.spec = P("ep", None)
        self.l_aux = None

    def capacity(self, num_tokens: int) -> int:
        cap = int(self.capacity_factor * self.top_k * num_tokens /
                  self.num_experts)
        return max(cap, self.top_k)

    def forward(self, x):
        # params go THROUGH dispatch so the eager tape records their
        # grads; aux is an op output so it is differentiable too
        y, aux = _dispatch(
            "moe_mlp", self._impl,
            (x, self.gate_weight, self.w1, self.b1, self.w2, self.b2), {})
        self.l_aux = aux
        return y

    def _impl(self, x, gate_w, w1, b1, w2, b2):
        """Pure-jax body (raw arrays in/out)."""
        shape = x.shape
        m = shape[-1]
        xf = x.reshape(-1, m)                              # [T, M]
        t = xf.shape[0]
        c = self.capacity(t)

        logits = xf.astype(jnp.float32) @ gate_w.astype(jnp.float32)
        gates = jax.nn.softmax(logits, axis=-1)            # [T, E]
        combine, disp, (me, ce) = top_k_routing(gates, self.top_k, c)
        if self.gate_type in ("switch", "gshard"):
            aux = load_balance_loss(me, ce)
        else:
            aux = jnp.zeros((), jnp.float32)
        if self.gate_type == "gshard":
            # GShard normalizes over the selected top-2; Switch keeps the
            # raw top-1 prob (router grad flows through the output scale)
            denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
            combine = combine / jnp.where(denom == 0.0, 1.0, denom)

        xe = jnp.einsum("tec,tm->ecm", disp.astype(xf.dtype), xf)
        xe = sharded_constraint(xe, P("ep", None, None))
        h = jnp.einsum("ecm,emh->ech", xe, w1) + b1[:, None, :]
        h = sharded_constraint(h, P("ep", None, "mp"))
        h = self.activation(h)
        ye = jnp.einsum("ech,ehm->ecm", h, w2) + b2[:, None, :]
        ye = sharded_constraint(ye, P("ep", None, None))
        y = jnp.einsum("tec,ecm->tm", combine.astype(xf.dtype), ye)
        return y.reshape(shape), aux


def aux_loss(model: Layer):
    """Sum of l_aux over every MoE sublayer (call in the same trace as
    the forward — the reference sums gate losses the same way in its
    MoE grad-clip integration). Tensor arithmetic keeps it on the eager
    grad tape."""
    total = None
    for layer in model.sublayers(include_self=True):
        la = getattr(layer, "l_aux", None)
        if la is not None:
            total = la if total is None else total + la
    if total is None:
        return Tensor(jnp.zeros((), jnp.float32))
    return total if isinstance(total, Tensor) else Tensor(total)
