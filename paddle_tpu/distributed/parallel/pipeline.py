"""Pipeline parallelism — SPMD collective pipelining over the 'pp' mesh axis.

Reference analog: python/paddle/distributed/fleet/meta_parallel/
parallel_layers/pp_layers.py:56,76,206 (`LayerDesc`, `PipelineLayer` stage
partitioning with shared-weight groups, seg_method segmentation) and
meta_parallel/pipeline_parallel.py:117-198,457 (`PipelineParallel`
1F1B + `PipelineParallelWithInterleave` virtual stages) with P2P handoff
in pp_utils/p2p_communication.py:344.

TPU-native redesign: instead of per-rank processes exchanging activations
over NCCL P2P with a host-driven 1F1B state machine, the whole pipeline is
ONE SPMD program:

  * the homogeneous trunk's blocks are stacked at BLOCK granularity:
    params live in one array with leading dims [S, v, maxB] (stage,
    virtual chunk, blocks-per-unit) sharded `P('pp')` on the stage dim;
  * a `lax.scan` over the schedule's ticks runs the pipeline: at each
    tick every stage applies its current unit (an inner masked scan over
    its blocks), then activations rotate one hop along the ring via
    `lax.ppermute` (the ICI-neighbor analog of P2P send/recv);
  * **interleaved virtual stages** (`interleave=v`, the
    PipelineParallelWithInterleave analog): each device hosts v chunks;
    virtual microbatches flow chunk-major through the ring v times, so
    the bubble drops from (S-1)/(M+S-1) to (S-1)/(vM+S-1);
  * **unbalanced partition** (`seg_sizes`, the seg_method analog): units
    may hold different numbers of blocks; the inner scan masks the
    padding, so a 7-block trunk on 4 stages is [2,2,2,1] instead of an
    error;
  * `shard_map` is *manual only over 'pp'* (`axis_names={'pp'}`) — dp/
    sharding/mp stay in GSPMD auto mode, so tensor-parallel layers and
    batch sharding inside each stage keep working unchanged;
  * backward is just `jax.grad` through the scan — XLA schedules the
    backward pipeline (the 1F1B memory behaviour is recovered with
    `jax.checkpoint` on the block body instead of a hand-written
    schedule).

The embedding / final-norm / lm-head ("pre"/"post" segments) run
replicated across the pp axis: they are outside the homogeneous trunk, and
on TPU recomputing them on every stage is cheaper than serializing the
mesh (they are a tiny fraction of FLOPs; XLA dedupes the params via
sharding anyway).

Bubble accounting: (S-1)/(vM+S-1) of trunk compute is wasted; choose
num_microbatches >= 4*S (or interleave v) to amortize — the same
guidance as the reference's 1F1B/interleave pair.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ...core.jaxshim import pcast, shard_map
from ...core.tensor import Parameter, Tensor
from ...nn.container import Sequential
from ...nn.layer import Layer
from .. import topology


class LayerDesc:
    """Deferred layer construction (≈ pp_layers.py:56 `LayerDesc`)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build(self) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """≈ pp_layers.py `SharedLayerDesc`: same weights used at several
    pipeline positions (embedding/lm-head tying). In the SPMD design the
    pre/post segments are replicated over pp, so sharing is reusing one
    built Layer at each position; only the FIRST occurrence registers the
    parameters — later ones hold an unregistered reference so state_dict
    stays duplicate-free."""

    def __init__(self, key, layer_cls, *args, forward_func=None, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key
        self.forward_func = forward_func


class _ForwardAdapter(Layer):
    """Run `fn(inner, *args)`. The FIRST occurrence of a shared layer
    registers it (owns its params); later occurrences hold an unregistered
    reference — under functional_call the shared values flow through the
    owning name, so state_dict stays duplicate-free."""

    def __init__(self, inner: Layer, fn: Optional[Callable],
                 owns_inner: bool = False):
        super().__init__()
        if owns_inner:
            self.inner = inner  # registered sublayer: params live here
        self._inner_ref = [inner]  # plain list: not a registered sublayer
        self._fn = fn

    def forward(self, *args, **kwargs):
        inner = self._inner_ref[0]
        if self._fn is None:
            return inner(*args, **kwargs)
        return self._fn(inner, *args, **kwargs)


def _param_shape_tree(layer: Layer):
    return tuple((name, tuple(t.shape), str(t.dtype))
                 for name, t in layer.state_dict().items())


def _find_trunk(layers: List[Layer]):
    """Longest contiguous run of structurally-identical layers = the
    pipeline trunk (the analog of the reference's uniform segmentation,
    pp_layers.py:206 `_segment_network` with seg_method='uniform').
    Identity = (class, param shapes/dtypes, repr) — repr catches
    non-parameter config differences (activation choice, epsilon, dropout
    rate) that shapes alone would miss, since all stages execute through
    the stage-0 template's forward."""
    n = len(layers)
    sigs = [(type(l), _param_shape_tree(l), repr(l)) for l in layers]
    best = (0, 0)  # (start, length)
    i = 0
    while i < n:
        j = i
        while j < n and sigs[j] == sigs[i]:
            j += 1
        if j - i > best[1]:
            best = (i, j - i)
        i = j
    start, length = best
    return start, start + length


def _sanitize(name: str) -> str:
    return name.replace(".", "__")


class PipelineLayer(Layer):
    """Partition a layer list into [pre | homogeneous trunk | post] and run
    the trunk as an SPMD collective pipeline over the 'pp' mesh axis.

    Parameters of the trunk are stored STACKED with a leading
    `num_stages`-dim carrying spec `P('pp', *block_spec)`; pre/post params
    keep their own specs (replicated over pp). The model therefore drops
    straight into `fleet.DistributedTrainStep` — no wrapper classes, no
    P2P plumbing.
    """

    def __init__(self, layers: Sequence, num_stages: Optional[int] = None,
                 loss_fn: Optional[Callable] = None,
                 num_microbatches: Optional[int] = None,
                 use_recompute: bool = False, topology_=None,
                 interleave: int = 1,
                 seg_sizes: Optional[Sequence[int]] = None):
        super().__init__()
        shared: Dict[str, Layer] = {}
        seen: set = set()
        built: List[Layer] = []
        for d in layers:
            if isinstance(d, SharedLayerDesc):
                if d.key not in shared:
                    shared[d.key] = LayerDesc.build(d)
                layer = shared[d.key]
                first = id(layer) not in seen
                if not first or d.forward_func is not None:
                    # first occurrence owns (registers) the shared params
                    layer = _ForwardAdapter(layer, d.forward_func,
                                            owns_inner=first)
                seen.add(id(shared[d.key]))
            elif isinstance(d, LayerDesc):
                layer = d.build()
            else:
                layer = d
                if id(layer) in seen:
                    layer = _ForwardAdapter(layer, None)
                seen.add(id(d))
            built.append(layer)
        if num_stages is None:
            hcg = topology.get_hybrid_communicate_group()
            num_stages = (hcg.get_pipe_parallel_world_size()
                          if hcg is not None else 1)
        self.num_stages = int(num_stages)
        self.interleave = int(interleave)
        if self.interleave < 1:
            raise ValueError(f"interleave must be >= 1, got {interleave}")
        self.loss_fn = loss_fn
        self.num_microbatches = num_microbatches
        self.use_recompute = use_recompute

        t0, t1 = _find_trunk(built)
        trunk = built[t0:t1]
        S, v = self.num_stages, self.interleave
        U = S * v  # virtual units, traversal order u = chunk*S + stage
        if S > 1:
            if seg_sizes is not None:
                seg_sizes = [int(s) for s in seg_sizes]
                if len(seg_sizes) != U or sum(seg_sizes) != len(trunk):
                    raise ValueError(
                        f"seg_sizes {seg_sizes} must have {U} entries "
                        f"summing to the trunk length {len(trunk)}")
                if any(s < 0 for s in seg_sizes):
                    raise ValueError("seg_sizes entries must be >= 0")
            else:
                # uniform with remainder to the FIRST units (the
                # reference's seg_method='uniform' segmentation)
                base_n, rem = divmod(len(trunk), U)
                seg_sizes = [base_n + (1 if u < rem else 0)
                             for u in range(U)]
                if base_n == 0 and rem == 0:
                    raise ValueError("empty trunk cannot be pipelined")
        self.seg_sizes = seg_sizes

        self.pre = Sequential(*built[:t0])
        self.post = Sequential(*built[t1:])

        # template holds the block structure; its param VALUES are never
        # used after stacking. Plain-list stash avoids sublayer
        # registration (stacked Parameters below are the real state).
        self._block_template = [trunk[0] if trunk else Sequential()]
        self._block_state_names = (
            list(trunk[0].state_dict().keys()) if trunk else [])

        # stack every block's params/buffers -> [S, v, maxB, ...] with
        # the stage dim sharded P('pp'); padding blocks (unbalanced
        # units) reuse block 0's values and are masked in the inner scan
        self._stacked_names: Dict[str, str] = {}
        if S > 1:
            maxB = max(seg_sizes) if seg_sizes else 1
            self._max_blocks = maxB
            offs = np.concatenate([[0], np.cumsum(seg_sizes)])
            tmpl_state = trunk[0].state_dict()
            param_names = {n for n, _ in trunk[0].named_parameters()}
            for name in self._block_state_names:
                rows = []
                for s in range(S):
                    chunk_rows = []
                    for c in range(v):
                        u = c * S + s
                        blocks = trunk[offs[u]:offs[u + 1]]
                        vals = [b.state_dict()[name]._data
                                for b in blocks]
                        while len(vals) < maxB:  # padding (masked off)
                            vals.append(tmpl_state[name]._data)
                        chunk_rows.append(jnp.stack(vals, axis=0))
                    rows.append(jnp.stack(chunk_rows, axis=0))
                stacked = jnp.stack(rows, axis=0)  # [S, v, maxB, ...]
                base = getattr(tmpl_state[name], "spec", P())
                spec = P("pp", None, None, *tuple(base))
                reg = _sanitize("block_stack." + name)
                self._stacked_names[name] = reg
                if name in param_names:
                    p = Parameter(stacked)
                    p.spec = spec
                    self.add_parameter(reg, p)
                else:
                    t = Tensor(stacked)
                    t.spec = spec
                    self.register_buffer(reg, t)
            # per-[stage, chunk] real-block counts, rides shard_map
            self._seg_counts = np.array(
                [[seg_sizes[c * S + s] for c in range(v)]
                 for s in range(S)], dtype=np.int32)
        else:
            # degenerate: single stage, keep the trunk as a sublayer
            self.stage0 = Sequential(*trunk)

    # ------------------------------------------------------------------ util
    def _microbatches(self, batch: int) -> int:
        m = self.num_microbatches or max(self.num_stages, 1)
        if batch % m != 0:
            raise ValueError(f"batch {batch} not divisible by "
                             f"num_microbatches {m}")
        return m

    def _unit_call(self, names, pstacks: Sequence[jax.Array], cnt,
                   x: jax.Array):
        """Apply one unit = inner scan over its <= maxB blocks; padding
        blocks (j >= cnt) pass the activation through unchanged."""
        from ...jit.api import functional_call
        block = self._block_template[0]

        def block_body(pvals, arr):
            return functional_call(
                block, {k: v for k, v in zip(names, pvals)},
                Tensor(arr))._data

        if self.use_recompute and self.training:
            block_body = jax.checkpoint(
                block_body,
                policy=jax.checkpoint_policies
                .dots_with_no_batch_dims_saveable)

        def step(arr, sl):
            pvals, j = sl
            out = block_body(pvals, arr)
            return jnp.where(j < cnt, out, arr), None

        x, _ = jax.lax.scan(
            step, x, (list(pstacks), jnp.arange(pstacks[0].shape[0])))
        return x

    @staticmethod
    def _run_segment(seg: Sequential, *inputs):
        """Run a pre/post segment; the FIRST layer receives all inputs
        (e.g. (input_ids, attn_mask)), the rest chain single-activation."""
        layers = list(seg._sub_layers.values())
        if not layers:
            return inputs[0] if len(inputs) == 1 else inputs
        x = layers[0](*inputs)
        for layer in layers[1:]:
            x = layer(x)
        return x

    # --------------------------------------------------------------- forward
    def forward(self, *inputs):
        x = self._run_segment(self.pre, *inputs)
        if self.num_stages <= 1:
            x = self.stage0(x)
            return self.post(x)

        mesh = topology.get_mesh()
        if mesh is None or mesh.shape.get("pp", 1) != self.num_stages:
            raise RuntimeError(
                f"PipelineLayer needs an active mesh with pp="
                f"{self.num_stages}; call fleet.init first")

        raw = x._data if isinstance(x, Tensor) else x
        b = raw.shape[0]
        m = self._microbatches(b)
        if self.interleave > 1 and m < self.num_stages:
            raise ValueError(
                f"interleaved pipeline needs num_microbatches ({m}) >= "
                f"num_stages ({self.num_stages}) so a chunk's output has "
                f"left the ring before its next chunk enters")
        mb = raw.reshape((m, b // m) + raw.shape[1:])

        names = list(self._stacked_names.keys())
        regs = [self._stacked_names[n] for n in names]
        state = self.state_dict()
        stacked_vals = [state[r]._data for r in regs]
        # shard_map specs mention ONLY the manual 'pp' axis (leading stage
        # dim); mp/dp shardings on the other dims remain in auto mode and
        # ride along on the arrays' NamedShardings.
        specs = [P("pp") for _ in regs]

        out = _spmd_pipeline(
            self._unit_call, names, stacked_vals, specs,
            jnp.asarray(self._seg_counts), mb, mesh,
            self.num_stages, self.interleave)
        out = out.reshape((b,) + out.shape[2:])
        return self.post(Tensor(out) if isinstance(x, Tensor) else out)


def _spmd_pipeline(unit_call, names, stacked_vals, specs, seg_counts,
                   mb, mesh, num_stages: int, interleave: int = 1):
    """The collective circular-pipeline loop.

    Schedule (the SPMD form of pipeline_parallel.py:117 1F1B and :457
    interleave): virtual microbatch k = chunk*M + mu flows chunk-major
    through the S-stage ring; device s at tick t works on k = t - s with
    its chunk-(k // M) unit. Chunk c's input for mu is chunk c-1's
    output, which left stage S-1 at tick (k - M) + S - 1 <= t - 1 (needs
    M >= S) and was banked in stage 0's `inter` buffer on arrival.
    Ticks = v*M + S - 1, so the bubble is (S-1)/(vM+S-1)."""
    S = num_stages
    v = interleave
    M = mb.shape[0]
    steps = v * M + S - 1
    ring = [(i, (i + 1) % S) for i in range(S)]

    def per_device(mb_local, cnt_local, *param_slices):
        stage = jax.lax.axis_index("pp")
        # shard_map gives each device a [1, v, maxB, ...] slice
        stacks = [val[0] for val in param_slices]   # [v, maxB, ...]
        cnts = cnt_local[0]                         # [v]

        def tick(carry, t):
            # `inter` (chunk c-1 outputs banked for chunk c's entry) is
            # carried only when interleaving — at v=1 it would be an
            # extra full-microbatch HBM buffer that is provably never
            # read
            if v > 1:
                act, inter, outs = carry
                # bank the ring arrival (stage S-1's tick t-1 output) —
                # only stage 0 ever reads it, as chunk c>0 input
                k_arr = t - S
                mu_arr = jnp.clip(k_arr, 0, v * M - 1) % M
                bank = (k_arr >= 0) & (k_arr // M < v - 1)
                inter = jnp.where(
                    bank,
                    jax.lax.dynamic_update_index_in_dim(inter, act,
                                                        mu_arr, 0),
                    inter)
            else:
                act, outs = carry

            k = t - stage
            valid = (k >= 0) & (k < v * M)
            kc = jnp.clip(k, 0, v * M - 1)
            c = kc // M
            mu = kc % M
            feed = jax.lax.dynamic_index_in_dim(mb_local, mu, 0,
                                                keepdims=False)
            if v > 1:
                feedc = jax.lax.dynamic_index_in_dim(inter, mu, 0,
                                                     keepdims=False)
                feed = jnp.where(c == 0, feed, feedc)
            inp = jnp.where(stage == 0, feed, act)
            pstacks = [jax.lax.dynamic_index_in_dim(sv, c, 0,
                                                    keepdims=False)
                       for sv in stacks]
            out = unit_call(names, pstacks, cnts[c], inp)
            is_final = (stage == S - 1) & valid & (c == v - 1)
            outs = jnp.where(
                is_final,
                jax.lax.dynamic_update_index_in_dim(outs, out, mu, 0),
                outs)
            act = jax.lax.ppermute(out, "pp", ring)
            return ((act, inter, outs) if v > 1 else (act, outs)), None

        carry0 = (jnp.zeros_like(mb_local[0]), jnp.zeros_like(mb_local),
                  jnp.zeros_like(mb_local)) if v > 1 else             (jnp.zeros_like(mb_local[0]), jnp.zeros_like(mb_local))
        init = pcast(carry0, ("pp",), to="varying")
        final_carry, _ = jax.lax.scan(tick, init, jnp.arange(steps))
        outs = final_carry[-1]
        # [1, M, mb, ...] local -> global leading dim S over 'pp'; only
        # stage S-1's slice is real, sliced out by the caller.
        return outs[None]

    fn = shard_map(
        per_device, mesh=mesh,
        in_specs=(P(), P("pp")) + tuple(specs),
        out_specs=P("pp"),
        axis_names={"pp"})
    all_stage_outs = fn(mb, seg_counts, *stacked_vals)
    return all_stage_outs[S - 1]


class PipelineParallel(Layer):
    """API-parity wrapper (≈ meta_parallel/pipeline_parallel.py:117
    `PipelineParallel` with `train_batch`). Thin: scheduling lives in the
    compiled program, so this only carries the train-step plumbing."""

    def __init__(self, layers: PipelineLayer, hcg=None, strategy=None):
        super().__init__()
        self.pipe = layers

    def forward(self, *inputs):
        return self.pipe(*inputs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """One pipelined optimization step; `data=(inputs, labels)`.
        ≈ PipelineParallel.train_batch -> forward_backward_pipeline.
        An *enabled* GradScaler is rejected: on TPU the bf16 path needs no
        loss scaling (pass GradScaler(enable=False) for API parity)."""
        if scaler is not None and scaler.is_enable():
            raise NotImplementedError(
                "PipelineParallel.train_batch does not support an enabled "
                "GradScaler; use bf16 (no scaling) on TPU")
        from ..fleet.train_step import DistributedTrainStep
        if getattr(self, "_step_opt_id", None) != id(optimizer):
            loss_fn = self.pipe.loss_fn or (lambda o, l: o)
            self._step = DistributedTrainStep(self.pipe, optimizer, loss_fn)
            self._step_opt_id = id(optimizer)
        inputs, labels = data
        return self._step(inputs, labels)
