"""Fleet dataset shims: InMemoryDataset / QueueDataset + feature
entries.

Reference: python/paddle/distributed/fleet/dataset/ (C++ DataFeed-based
readers for the parameter-server pipeline, SURVEY §2.2 Dataset/
DataFeed). The PS training path is a declared non-goal on TPU
(SURVEY §2.6 item 10); these classes keep the configuration API
usable and feed standard python pipelines instead of the brpc one.
"""
from __future__ import annotations

from typing import Callable, List, Optional

__all__ = ["InMemoryDataset", "QueueDataset", "CountFilterEntry",
           "ProbabilityEntry", "ShowClickEntry"]


class _Entry:
    def __init__(self, **kw):
        self._cfg = kw

    def __repr__(self):
        return f"{type(self).__name__}({self._cfg})"


class CountFilterEntry(_Entry):
    """Sparse-feature frequency filter config (reference
    entry_attr CountFilterEntry)."""

    def __init__(self, count_filter: int = 0):
        super().__init__(count_filter=count_filter)


class ProbabilityEntry(_Entry):
    def __init__(self, probability: float = 1.0):
        super().__init__(probability=probability)


class ShowClickEntry(_Entry):
    def __init__(self, show_name: str = "", click_name: str = ""):
        super().__init__(show_name=show_name, click_name=click_name)


class _FileDataset:
    def __init__(self):
        self._files: List[str] = []
        self._parse_fn: Optional[Callable] = None
        self._batch_size = 1
        self._thread = 1

    def init(self, batch_size=1, thread_num=1, use_var=None,
             pipe_command=None, input_type=0, fs_name="", fs_ugi="",
             **kwargs):
        self._batch_size = batch_size
        self._thread = thread_num

    def set_filelist(self, filelist: List[str]):
        self._files = list(filelist)

    def set_parse_func(self, fn: Callable):
        """Line -> sample parser (stands in for pipe_command)."""
        self._parse_fn = fn

    def _iter_lines(self):
        for path in self._files:
            with open(path) as f:
                for line in f:
                    line = line.rstrip("\n")
                    yield self._parse_fn(line) if self._parse_fn \
                        else line


class InMemoryDataset(_FileDataset):
    """Load text samples fully into memory, then iterate/shuffle
    (reference fleet InMemoryDataset minus the brpc PS plumbing)."""

    def __init__(self):
        super().__init__()
        self._samples: List = []

    def load_into_memory(self):
        self._samples = list(self._iter_lines())

    def local_shuffle(self, seed: int = 0):
        import random
        random.Random(seed).shuffle(self._samples)

    def global_shuffle(self, fleet=None, thread_num: int = 12):
        self.local_shuffle()

    def get_memory_data_size(self, fleet=None) -> int:
        return len(self._samples)

    def release_memory(self):
        self._samples = []

    def __iter__(self):
        return iter(self._samples)

    def __len__(self):
        return len(self._samples)

    def __getitem__(self, i):
        return self._samples[i]


class QueueDataset(_FileDataset):
    """Streaming file dataset (reference QueueDataset): iterate without
    materializing."""

    def __iter__(self):
        return self._iter_lines()
