"""Remaining paddle.distributed surface: groups, p2p, object
collectives, misc shims.

Reference: python/paddle/distributed/collective.py (new_group:340,
send/recv/isend/irecv, reduce, split, wait, all_gather_object),
parallel.py (ParallelMode, gloo_* helpers). TPU-native notes: a
"process group" here is a VIEW over mesh axes (XLA emits the
collectives), so groups are lightweight descriptors; eager host-side
p2p rides the rendezvous TCPStore (control plane only — bulk tensors
belong in compiled collectives), matching how the reference uses
send/recv for control flow rather than throughput.
"""
from __future__ import annotations

import pickle
from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from ..core import monitor
from ..core.tensor import Tensor
from . import topology

__all__ = ["Group", "ParallelMode", "new_group", "get_group",
           "destroy_process_group", "wait", "all_gather_object",
           "send", "recv", "isend", "irecv", "reduce", "split",
           "gloo_init_parallel_env", "gloo_barrier", "gloo_release"]


class ParallelMode:
    """Training-mode enum (reference parallel.py ParallelMode)."""
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


class Group:
    """A mesh-axis view standing in for ProcessGroup (reference
    collective.py Group): `axis` names the mesh dimension whose
    collectives this group runs over."""

    def __init__(self, gid: int, axis: Optional[str], ranks: List[int]):
        self.id = gid
        self.axis = axis
        self.ranks = list(ranks)
        self.nranks = len(ranks)

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank: int) -> int:
        return self.ranks.index(rank) if rank in self.ranks else -1

    def __repr__(self):
        return f"Group(id={self.id}, axis={self.axis}, " \
               f"ranks={self.ranks})"


_GROUPS = {}
_NEXT_GID = [1]


def new_group(ranks=None, backend=None, axis: Optional[str] = None,
              timeout=None):
    """Create a group over `axis` (or explicit ranks — recorded for
    bookkeeping; XLA partitions by axis name, reference new_group
    collective.py:340)."""
    hcg = topology.get_hybrid_communicate_group()
    if ranks is None:
        n = hcg.nranks if hcg is not None else 1
        ranks = list(range(n))
    gid = _NEXT_GID[0]
    _NEXT_GID[0] += 1
    g = Group(gid, axis, ranks)
    _GROUPS[gid] = g
    return g


def get_group(gid: int = 0) -> Optional[Group]:
    if gid == 0:
        hcg = topology.get_hybrid_communicate_group()
        n = hcg.nranks if hcg is not None else 1
        return Group(0, None, list(range(n)))
    return _GROUPS.get(gid)


def destroy_process_group(group: Optional[Group] = None):
    """Tear down groups (reference destroy_process_group); the global
    mesh itself is owned by fleet/topology."""
    if group is None:
        _GROUPS.clear()
    else:
        _GROUPS.pop(group.id, None)


def wait(tensor, group=None, use_calc_stream: bool = True):
    """Block until `tensor` is materialized (the stream-sync analog —
    XLA has no user-visible streams, so readiness is block_until_ready,
    ≈ c_sync_comm_stream)."""
    arr = tensor.data if isinstance(tensor, Tensor) else tensor
    if hasattr(arr, "block_until_ready"):
        arr.block_until_ready()
    return tensor


# --- store-backed object/p2p plane --------------------------------------

_STORE = [None]


def _store():
    """Shared TCPStore for the object/p2p plane (reference: the
    rendezvous TCPStore created by init_parallel_env). Lazily connects
    using the launcher env (PADDLE_MASTER port + 2, clear of the jax
    coordinator and the rpc store)."""
    if _STORE[0] is None:
        import os
        from .store import TCPStore
        base = os.environ.get("PADDLE_MASTER")
        if base is None:
            raise RuntimeError(
                "no PADDLE_MASTER in the environment — launch via "
                "paddle.distributed.launch for store-backed "
                "collectives")
        host, port = base.rsplit(":", 1)
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        _STORE[0] = TCPStore(host, int(port) + 2,
                             is_master=(rank == 0))
    return _STORE[0]


def _world():
    import os
    return int(os.environ.get("PADDLE_TRAINERS_NUM", "1")), \
        int(os.environ.get("PADDLE_TRAINER_ID", "0"))


def all_gather_object(object_list: list, obj, group=None):
    """Gather arbitrary picklable objects from every rank (reference
    all_gather_object): store-backed exchange; single-process returns
    [obj]."""
    world, rank = _world()
    if world == 1:
        object_list.clear()
        object_list.append(obj)
        return
    store = _store()
    key = f"__ago/{_NEXT_GID[0]}"
    store.set(f"{key}/{rank}", pickle.dumps(obj))
    store.barrier(f"{key}/b", world)
    object_list.clear()
    for r in range(world):
        object_list.append(pickle.loads(store.get(f"{key}/{r}")))
    _NEXT_GID[0] += 1


_P2P_SEQ: dict = {}


def send(tensor, dst: int = 0, group=None, sync_op: bool = True):
    """Host-plane p2p send (reference collective send; control-plane
    semantics — bulk tensors belong in compiled collectives). Each
    (src, dst) channel carries a sequence number so back-to-back sends
    never overwrite an unconsumed message."""
    world, rank = _world()
    if world == 1:
        raise RuntimeError("send needs a multi-process launch")
    arr = np.asarray(tensor.data if isinstance(tensor, Tensor)
                     else tensor)
    if monitor.enabled:
        monitor.record_p2p("send", arr.nbytes)
    store = _store()
    chan = ("s", rank, dst)
    seq = _P2P_SEQ.get(chan, 0)
    _P2P_SEQ[chan] = seq + 1
    store.set(f"__p2p/{rank}->{dst}/{seq}", pickle.dumps(arr))


def recv(tensor, src: int = 0, group=None, sync_op: bool = True):
    world, rank = _world()
    if world == 1:
        raise RuntimeError("recv needs a multi-process launch")
    store = _store()
    chan = ("r", src, rank)
    seq = _P2P_SEQ.get(chan, 0)
    _P2P_SEQ[chan] = seq + 1
    key = f"__p2p/{src}->{rank}/{seq}"
    data = pickle.loads(store.get(key))
    store.delete(key)  # consume
    if monitor.enabled:
        monitor.record_p2p("recv", getattr(data, "nbytes", 0))
    if isinstance(tensor, Tensor):
        tensor.set_value(jnp.asarray(data))
        return tensor
    return Tensor(jnp.asarray(data))


class _DoneTask:
    def __init__(self, value=None):
        self._value = value

    def wait(self):
        return self._value

    def is_completed(self):
        return True


def isend(tensor, dst: int = 0, group=None):
    send(tensor, dst, group)
    return _DoneTask()


def irecv(tensor, src: int = 0, group=None):
    out = recv(tensor, src, group)
    return _DoneTask(out)


def reduce(tensor, dst: int = 0, op=None, group=None,
           axis: Optional[str] = None, sync_op: bool = True):
    """Reduce-to-one (reference c_reduce): on the SPMD mesh a reduce is
    an all_reduce whose non-dst shards are simply unused — XLA's
    partitioner drops dead outputs, so this is not wasteful."""
    from .collective import all_reduce
    return all_reduce(tensor, op=op or "sum", group=group, axis=axis)


def split(x, size, operation: str = "linear", axis: Optional[str] = "mp",
          num_partitions: Optional[int] = None, gather_out: bool = True,
          weight_attr=None, bias_attr=None, name=None):
    """paddle.distributed.split (reference collective.py split): build
    a row/column-parallel linear or parallel embedding over the mp
    axis. Delegates to the mpu layers — on TPU the partitioning is a
    sharding annotation."""
    from .parallel.mp_layers import (ColumnParallelLinear,
                                     RowParallelLinear,
                                     VocabParallelEmbedding)
    in_sz, out_sz = size
    if operation == "embedding":
        return VocabParallelEmbedding(in_sz, out_sz)
    if operation == "linear":
        # reference picks row/column by the axis= argument (0=row)
        if num_partitions is not None and gather_out:
            return RowParallelLinear(in_sz, out_sz)
        return ColumnParallelLinear(in_sz, out_sz,
                                    gather_output=gather_out)
    raise ValueError(f"unknown split operation {operation!r}")


# --- gloo shims (CPU barrier plane) -------------------------------------

def gloo_init_parallel_env(rank_id: int, rank_num: int,
                           server_endpoint: str):
    """CPU rendezvous (reference gloo_init_parallel_env) over the
    TCPStore instead of a gloo ring."""
    from .store import TCPStore
    host, port = server_endpoint.rsplit(":", 1)
    store = TCPStore(host, int(port), is_master=(rank_id == 0))
    _GROUPS["__gloo__"] = (store, rank_id, rank_num)


def gloo_barrier():
    entry = _GROUPS.get("__gloo__")
    if entry is None:
        raise RuntimeError("call gloo_init_parallel_env first")
    store, rank, num = entry
    store.barrier(f"gloo/{_NEXT_GID[0]}", num)
    _NEXT_GID[0] += 1


def gloo_release():
    entry = _GROUPS.pop("__gloo__", None)
    if entry is not None:
        entry[0].close()
