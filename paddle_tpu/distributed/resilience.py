"""Fault-tolerance layer: preemption-safe checkpoints, hang watchdog,
anomaly guard.

Reference analogs: Fleet's ElasticManager treats worker death as a
first-class event (manager.py restarts on exit codes 101/102) and
fluid/incubate/checkpoint/auto_checkpoint.py gives transparent resume —
but both assume the happy path inside one run. On real TPU pods
maintenance events preempt hosts mid-step, collectives hang when a slice
re-forms, and a preempted writer leaves truncated checkpoints. This
module is the glue that turns those into survivable events:

- ``GracefulShutdown``: SIGTERM/SIGINT → cross-host "preempted" flag in
  the TCPStore → synchronous emergency checkpoint of registered state →
  ``sys.exit(ELASTIC_EXIT_CODE)`` so the launcher relaunches and the
  training loop resumes from the emergency step.
- ``Watchdog``: armed around collectives, TCPStore ops and checkpoint
  waits; past the deadline it dumps every thread's stack to stderr,
  bumps the ``resilience.watchdog.timeouts`` counter and raises
  ``WatchdogTimeout`` instead of hanging the pod forever.
- ``AnomalyGuard``: non-finite loss → skip the batch; N consecutive
  anomalies → restore from the last good checkpoint.

The checkpoint-integrity half (commit markers, corruption fallback)
lives in ``distributed.checkpoint``; ``utils.fault_injection`` is the
chaos-test harness that drives all of it deterministically in-process.
"""
from __future__ import annotations

import ctypes
import os
import signal
import sys
import threading
import time
import traceback
from typing import Callable, List, Optional, Tuple

from ..core import flight_recorder, monitor
from .elastic import ELASTIC_EXIT_CODE

__all__ = [
    "AnomalyGuard",
    "GracefulShutdown",
    "Watchdog",
    "WatchdogTimeout",
    "active",
    "dump_stacks",
    "poll",
    "preempted",
    "register_emergency",
    "watchdog",
]

PREEMPT_KEY = "__resilience/preempted"


class WatchdogTimeout(RuntimeError):
    """An armed watchdog expired: the guarded operation overran its
    deadline (thread stacks were dumped to stderr when it fired)."""


# --------------------------------------------------------------- watchdog

def dump_stacks(label: str, timeout: float) -> None:
    """Dump every thread's stack to stderr in the watchdog's format —
    for deadline guards that detect the overrun themselves (the
    DataLoader's per-fetch supervisor) and want the same diagnostics a
    fired ``Watchdog`` produces."""
    _dump_all_stacks(label, timeout)


def _dump_all_stacks(label: str, timeout: float) -> None:
    lines = [f"\n=== Watchdog '{label}' expired after {timeout:.1f}s — "
             f"dumping {threading.active_count()} thread stacks ==="]
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    for tid, frame in frames.items():
        lines.append(f"--- thread {names.get(tid, '?')} ({tid}) ---")
        lines.append("".join(traceback.format_stack(frame)))
    lines.append("=== end watchdog dump ===\n")
    sys.stderr.write("\n".join(lines))
    sys.stderr.flush()


_tls = threading.local()


def _armed_watchdog() -> Optional["Watchdog"]:
    """The innermost watchdog armed on the CURRENT thread (blocking ops
    like TCPStore calls register their cancellers against it)."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


class Watchdog:
    """Deadline monitor for operations that can hang forever.

    Context-manager form — arms a timer around the guarded region::

        with Watchdog(timeout=60, label="allreduce"):
            dist.all_reduce(x)

    On expiry the monitor thread dumps all thread stacks, bumps the
    ``resilience.watchdog.timeouts`` counter, runs any registered
    cancellers (e.g. force-closing a TCPStore socket so its blocked recv
    aborts) and injects ``WatchdogTimeout`` into the armed thread. Pure
    C-level blocks that ignore async exceptions are un-hung only by a
    canceller; ``Watchdog.run`` is the guaranteed form for those::

        Watchdog.run(mgr.wait, timeout=120, label="ckpt.wait")

    runs the callable on a worker thread and abandons it on timeout (the
    daemon worker keeps blocking, the caller gets WatchdogTimeout).
    """

    def __init__(self, timeout: float, label: str = "op",
                 dump_stacks: bool = True):
        self.timeout = float(timeout)
        self.label = label
        self.dump_stacks = dump_stacks
        self.expired = False
        self._timer: Optional[threading.Timer] = None
        self._owner: Optional[int] = None
        self._cancellers: List[Callable[[], None]] = []
        self._lock = threading.Lock()
        self._closed = False  # __exit__ ran: _fire must stand down

    # ------------------------------------------------------------ cancellers
    def add_canceller(self, fn: Callable[[], None]) -> None:
        """Register a callback the expiry path runs to abort the guarded
        op at its source (close a socket, kill a subprocess, ...)."""
        with self._lock:
            self._cancellers.append(fn)

    def remove_canceller(self, fn: Callable[[], None]) -> None:
        with self._lock:
            try:
                self._cancellers.remove(fn)
            except ValueError:
                pass

    # ------------------------------------------------------------- lifecycle
    def _fire(self) -> None:
        with self._lock:
            if self._closed:  # lost the race against __exit__: no-op
                return
            self.expired = True
        if self.dump_stacks:
            _dump_all_stacks(self.label, self.timeout)
        monitor.record_watchdog_timeout(self.label)
        # the black box: record the expiry and dump the ring — a hung
        # process about to be force-killed must leave behind what it
        # was doing (the stalled request's spans, the last compiles)
        flight_recorder.record("watchdog.timeout", label=self.label,
                               timeout_s=self.timeout)
        flight_recorder.auto_dump("watchdog")
        # abort actions run under the lock and re-check _closed, so a
        # region that exited between the dump and here is never hit: no
        # closing a socket some LATER op now owns, no async exception
        # left pending to detonate at an arbitrary later bytecode
        with self._lock:
            if self._closed:
                return
            if self._cancellers:
                # a canceller aborts the guarded op at its source
                # (closed socket -> ConnectionError); __exit__ converts
                # that abort to WatchdogTimeout. Never ALSO inject an
                # async exception: the op unwinds immediately, and a
                # still-pending injection would land later, anywhere.
                for fn in list(self._cancellers):
                    try:
                        fn()
                    except Exception as e:
                        monitor.record_swallowed(
                            f"watchdog.cancel:{self.label}", e)
            elif self._owner is not None:
                # no canceller: best-effort injection, delivered at the
                # thread's next bytecode boundary — un-hangs pure-Python
                # waits; C-level blocks need a canceller or Watchdog.run
                ctypes.pythonapi.PyThreadState_SetAsyncExc(
                    ctypes.c_ulong(self._owner),
                    ctypes.py_object(WatchdogTimeout))

    def __enter__(self) -> "Watchdog":
        self.expired = False
        self._closed = False
        self._owner = threading.get_ident()
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self)
        self._timer = threading.Timer(self.timeout, self._fire)
        self._timer.daemon = True
        self._timer.name = f"watchdog:{self.label}"
        self._timer.start()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._timer is not None:
            self._timer.cancel()
        with self._lock:
            # close under the same lock _fire acts under: either its
            # abort actions already happened (retracted just below) or
            # its _closed re-check makes them a no-op — never a stray
            # injection after this region is gone
            self._closed = True
            if self.expired and self._owner is not None:
                # retract a still-pending async exception so it cannot
                # surface at an arbitrary later point
                ctypes.pythonapi.PyThreadState_SetAsyncExc(
                    ctypes.c_ulong(self._owner), None)
        stack = getattr(_tls, "stack", None)
        if stack and stack[-1] is self:
            stack.pop()
        if self.expired:
            msg = (f"watchdog '{self.label}' expired after "
                   f"{self.timeout:.1f}s")
            if exc is not None and not isinstance(exc, WatchdogTimeout):
                # the canceller aborted the op with its own error
                # (ConnectionError from a closed socket, ...): surface
                # the deadline, keep the abort as the cause
                raise WatchdogTimeout(msg) from exc
            if exc is None:
                raise WatchdogTimeout(msg)
        return False

    # -------------------------------------------------------- threaded form
    @staticmethod
    def run(fn: Callable, *args, timeout: float, label: str = "op",
            dump_stacks: bool = True, **kwargs):
        """Run ``fn`` with a hard deadline: the call happens on a daemon
        worker thread; if it overruns, the worker is abandoned and
        ``WatchdogTimeout`` raises in the caller. Use for blocking calls
        that cannot be cancelled (collective dispatch, orbax waits)."""
        result: list = []
        error: list = []

        def target():
            try:
                result.append(fn(*args, **kwargs))
            except BaseException as e:  # noqa: BLE001 — relayed below
                error.append(e)

        worker = threading.Thread(target=target, daemon=True,
                                  name=f"watchdog.run:{label}")
        worker.start()
        worker.join(timeout)
        if worker.is_alive():
            if dump_stacks:
                _dump_all_stacks(label, timeout)
            monitor.record_watchdog_timeout(label)
            flight_recorder.record("watchdog.timeout", label=label,
                                   timeout_s=float(timeout))
            flight_recorder.auto_dump("watchdog")
            raise WatchdogTimeout(
                f"watchdog '{label}' expired after {timeout:.1f}s "
                f"(worker thread abandoned)")
        if error:
            raise error[0]
        return result[0]


def watchdog(timeout: float, label: str = "op",
             dump_stacks: bool = True) -> Watchdog:
    """`with watchdog(30, "store.get"): ...` — sugar over Watchdog."""
    return Watchdog(timeout, label=label, dump_stacks=dump_stacks)


def env_timeout(var: str) -> Optional[float]:
    """Parse a watchdog deadline from the environment; None/0 = off."""
    raw = os.environ.get(var, "").strip()
    if not raw:
        return None
    try:
        t = float(raw)
    except ValueError:
        return None
    return t if t > 0 else None


# ---------------------------------------------------- emergency checkpoint

# (save_fn(step) -> None) registered process-wide; GracefulShutdown runs
# every entry synchronously when a preemption lands. CheckpointManager.
# save_on_preemption and hapi's ModelCheckpoint both register here.
_EMERGENCY: List[Tuple[int, Callable[[int], None]]] = []
_EMERGENCY_LOCK = threading.Lock()
_EMERGENCY_SEQ = 0


def register_emergency(save_fn: Callable[[int], None]) -> Callable[[], None]:
    """Register ``save_fn(step)`` to run on preemption; returns an
    unregister callable."""
    global _EMERGENCY_SEQ
    with _EMERGENCY_LOCK:
        _EMERGENCY_SEQ += 1
        entry = (_EMERGENCY_SEQ, save_fn)
        _EMERGENCY.append(entry)

    def unregister():
        with _EMERGENCY_LOCK:
            try:
                _EMERGENCY.remove(entry)
            except ValueError:
                pass

    return unregister


def _run_emergency_saves(step: int) -> int:
    with _EMERGENCY_LOCK:
        entries = list(_EMERGENCY)
    done = 0
    for _, fn in entries:
        try:
            fn(step)
            done += 1
        except Exception as e:
            # one broken saver must not stop the others from committing
            monitor.record_swallowed("emergency_save", e)
    if done:
        monitor.record_emergency_save(step)
    return done


# ------------------------------------------------------- graceful shutdown

_ACTIVE: List["GracefulShutdown"] = []


class GracefulShutdown:
    """Preemption-safe shutdown context for a training loop.

    ::

        mgr = CheckpointManager(path)
        mgr.save_on_preemption(lambda: {"model": model.state_dict()})
        with GracefulShutdown(store=store) as gs:
            for step, batch in enumerate(loader):
                train_step(batch)
                gs.check(step)   # preempted? -> emergency save + exit 101

    The signal handler only sets a flag (no locks, no sockets: the
    signal may land while this very thread holds the store's client
    lock). ``check(step)`` at the next step boundary does the real work:
    broadcast the preemption through the TCPStore so every host saves
    the same step, run all registered emergency saves synchronously, and
    ``sys.exit(ELASTIC_EXIT_CODE)`` so the launcher's elastic path
    relaunches the job, which resumes from the emergency checkpoint.
    """

    def __init__(self, store=None,
                 signals=(signal.SIGTERM, signal.SIGINT),
                 exit_code: int = ELASTIC_EXIT_CODE,
                 exit_on_save: bool = True,
                 key: str = PREEMPT_KEY,
                 store_poll_interval: float = 5.0,
                 incarnation: Optional[str] = None):
        self.store = store
        self.signals = tuple(signals)
        self.exit_code = exit_code
        self.exit_on_save = exit_on_save
        # the flag/election keys are namespaced by the elastic restart
        # incarnation (launcher-exported PADDLE_RESTART_COUNT): keys a
        # previous incarnation published survive in the launcher's
        # store, and a relaunched job reading its predecessor's flag
        # would emergency-exit on its very first step — a crash loop
        if incarnation is None:
            incarnation = os.environ.get("PADDLE_RESTART_COUNT", "0")
        self.key = f"{key}/{incarnation}"
        # store polling is a real RPC: throttle it off the per-batch hot
        # path (the local signal flag is still checked on every call)
        self.store_poll_interval = float(store_poll_interval)
        self._last_store_poll = float("-inf")
        self._signaled = threading.Event()
        self._via_store = False   # detected via the store broadcast,
        #                           not a local signal (peer, not victim)
        self._prev_handlers = {}
        self._installed = False

    # --------------------------------------------------------------- signals
    def _handler(self, signum, frame):
        # async-signal-safe by construction: set a flag, nothing else
        self._signaled.set()

    def __enter__(self) -> "GracefulShutdown":
        if threading.current_thread() is threading.main_thread():
            for sig in self.signals:
                self._prev_handlers[sig] = signal.signal(sig, self._handler)
            self._installed = True
        else:
            monitor.record_swallowed(
                "graceful_shutdown.install",
                RuntimeError("signal handlers need the main thread; "
                             "relying on store flag polling only"))
        _ACTIVE.append(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._installed:
            for sig, prev in self._prev_handlers.items():
                try:
                    signal.signal(sig, prev)
                except (ValueError, OSError) as e:
                    monitor.record_swallowed("graceful_shutdown.restore", e)
            self._prev_handlers.clear()
            self._installed = False
        try:
            _ACTIVE.remove(self)
        except ValueError:
            pass
        return False

    # ------------------------------------------------------------- preempted
    @property
    def preempted(self) -> bool:
        """True once this host was signaled OR any host published the
        preemption flag to the store. The local flag costs nothing and
        is read every call; the store check is one keys() RPC, rate-
        limited to ``store_poll_interval`` seconds so per-batch polling
        stays off the hot path."""
        if self._signaled.is_set():
            return True
        if self.store is not None:
            now = time.monotonic()
            if now - self._last_store_poll < self.store_poll_interval:
                return False
            self._last_store_poll = now
            try:
                if self.store.keys(self.key):
                    if not self._signaled.is_set():
                        self._via_store = True
                    self._signaled.set()
                    return True
            except (TimeoutError, RuntimeError, OSError) as e:
                monitor.record_swallowed("graceful_shutdown.poll", e)
        return False

    def trigger(self) -> None:
        """Programmatic preemption (tests, cluster-notice pollers)."""
        self._signaled.set()

    # ----------------------------------------------------------------- check
    def check(self, step: int) -> bool:
        """Call at every step boundary. Returns False in the happy path;
        on preemption: broadcast flag → emergency save → exit."""
        if not self.preempted:
            return False
        from ..core import goodput
        t_recover = time.perf_counter()
        monitor.record_preemption()
        # the preemption dump happens BEFORE the emergency saves: if a
        # save wedges, the black box already shows the step the process
        # reached and everything it was doing when the signal landed.
        # source distinguishes the VICTIM (the signal landed here) from
        # peers that detected it through the store broadcast — the
        # merged fleet trace orders the SIGTERM instant before the
        # detections
        flight_recorder.record("resilience.preemption", step=int(step),
                               source="store" if self._via_store
                               else "signal")
        flight_recorder.auto_dump("preemption")
        save_step = int(step)
        if self.store is not None:
            try:
                # atomic election via the store's add counter: exactly
                # one host (the first) publishes ITS step; everyone
                # else blocks briefly for that value and adopts it, so
                # all hosts checkpoint under the same step id even when
                # simultaneously signaled a boundary apart
                if self.store.add(f"{self.key}/elect", 1) == 1:
                    self.store.set(self.key, save_step)
                else:
                    save_step = int(self.store.get(self.key, timeout=10.0))
            except (TimeoutError, RuntimeError, OSError) as e:
                monitor.record_swallowed("graceful_shutdown.broadcast", e)
        _run_emergency_saves(save_step)
        # the whole detection->broadcast->emergency-save window is
        # preemption recovery in the goodput ledger (ambient no-op
        # outside a ledgered loop)
        goodput.charge("preemption_recovery",
                       time.perf_counter() - t_recover)
        if self.exit_on_save:
            sys.exit(self.exit_code)
        return True


def active() -> Optional[GracefulShutdown]:
    """The innermost live GracefulShutdown context, if any."""
    return _ACTIVE[-1] if _ACTIVE else None


def preempted() -> bool:
    gs = active()
    return gs.preempted if gs is not None else False


def poll(step: int) -> bool:
    """Step-boundary hook for loops that did not create the context
    themselves (hapi Model.fit calls this): delegates to the active
    GracefulShutdown's check(), no-op when none is installed."""
    gs = active()
    return gs.check(step) if gs is not None else False


# ----------------------------------------------------------- anomaly guard

class AnomalyGuard:
    """Skip-and-recover policy for non-finite losses.

    ``observe(loss)`` returns True when the loss is usable. A non-finite
    loss is an anomaly: the batch is reported as skipped, and after
    ``max_consecutive`` anomalies in a row ``restore_fn()`` is invoked
    (restore from the last good checkpoint) and the streak resets.
    ``PADDLE_ANOMALY_MAX_CONSECUTIVE`` overrides the threshold."""

    def __init__(self, max_consecutive: int = 3,
                 restore_fn: Optional[Callable[[], None]] = None):
        env = os.environ.get("PADDLE_ANOMALY_MAX_CONSECUTIVE", "").strip()
        try:
            self.max_consecutive = int(env) if env else int(max_consecutive)
        except ValueError:  # env typo must not kill a training job
            monitor.record_swallowed(
                "anomaly_guard.env",
                ValueError(f"PADDLE_ANOMALY_MAX_CONSECUTIVE={env!r}"))
            self.max_consecutive = int(max_consecutive)
        self.restore_fn = restore_fn
        self.consecutive = 0
        self.total = 0
        self.restores = 0

    @staticmethod
    def _finite(loss) -> bool:
        import numpy as np
        try:
            return bool(np.isfinite(np.asarray(
                getattr(loss, "numpy", lambda: loss)(),
                dtype=np.float64)).all())
        except (TypeError, ValueError):
            return True  # non-numeric "loss": not this guard's business

    def observe(self, loss) -> bool:
        if self._finite(loss):
            self.consecutive = 0
            return True
        self.consecutive += 1
        self.total += 1
        monitor.record_anomaly()
        flight_recorder.record("train.anomaly",
                               consecutive=self.consecutive)
        sys.stderr.write(
            f"AnomalyGuard: non-finite loss "
            f"({self.consecutive}/{self.max_consecutive} consecutive); "
            f"skipping batch\n")
        if self.consecutive >= self.max_consecutive:
            self.consecutive = 0
            if self.restore_fn is not None:
                self.restores += 1
                monitor.record_anomaly_restore()
                # dump before rolling back: the events leading into the
                # anomaly streak are the evidence the restore destroys
                flight_recorder.record("train.anomaly_restore",
                                       total=self.total)
                flight_recorder.auto_dump("anomaly_restore")
                sys.stderr.write(
                    "AnomalyGuard: restoring from last good checkpoint\n")
                self.restore_fn()
        return False


# --------------------------------------------------- watchdogged call sugar

def guarded_call(fn: Callable, *args, label: str,
                 timeout: Optional[float] = None, **kwargs):
    """Run ``fn`` under ``Watchdog.run`` when a deadline is configured
    (argument, else the PADDLE_WATCHDOG_<layer> env the caller resolved),
    plainly otherwise. The single chokepoint collectives and checkpoint
    waits route through."""
    if timeout is None or timeout <= 0:
        return fn(*args, **kwargs)
    return Watchdog.run(fn, *args, timeout=timeout, label=label,
                        dump_stacks=True, **kwargs)
