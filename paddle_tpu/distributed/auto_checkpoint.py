"""Auto checkpoint — transparent epoch-granular train-loop resume.

Reference: python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py:72
(train_epoch_range generator + AutoCheckpointChecker env config,
checkpoint_saver.py) — used with elastic so a preempted/restarted job
resumes at the last completed epoch. Env contract kept:
PADDLE_RUNNING_ENV=PADDLE_EDL_AUTO_CHECKPOINT enables it,
PADDLE_JOB_ID keys the checkpoint, PADDLE_EDL_HDFS_CHECKPOINT_PATH
names the directory (any filesystem path here).

Fault tolerance (resilience layer): the epoch loop runs under a
GracefulShutdown context — SIGTERM/SIGINT lands, the NEXT epoch boundary
writes a synchronous emergency checkpoint of ``status.state`` and exits
with ELASTIC_EXIT_CODE so the elastic launcher relaunches; the restarted
range resumes at the emergency epoch + 1 (at most one epoch redone).
Restores go through the corruption-fallback path: a truncated latest
checkpoint transparently resumes from the previous committed one.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Iterator, Optional

from . import resilience
from .checkpoint import CheckpointManager

__all__ = ["train_epoch_range", "ExeTrainStatus", "AutoCheckpointChecker"]


class AutoCheckpointChecker:
    def __init__(self):
        self.running_env = os.environ.get("PADDLE_RUNNING_ENV", "")
        self.job_id = os.environ.get("PADDLE_JOB_ID", "default")
        self.ckpt_path = os.environ.get(
            "PADDLE_EDL_HDFS_CHECKPOINT_PATH",
            os.environ.get("PADDLE_AUTO_CHECKPOINT_PATH", ""))
        self.save_interval = int(os.environ.get(
            "PADDLE_EDL_SAVE_CHECKPOINT_INTER", "1"))

    def get_job_checkpoint_path(self) -> str:
        return os.path.join(self.ckpt_path, f"job_{self.job_id}")

    @property
    def enabled(self) -> bool:
        return bool(self.ckpt_path) and \
            self.running_env == "PADDLE_EDL_AUTO_CHECKPOINT"


class ExeTrainStatus:
    """Mutable holder the loop body can stash model/opt state into;
    whatever is in `.state` is what gets checkpointed each epoch."""

    def __init__(self):
        self.state: Dict[str, Any] = {}
        self.epoch: int = -1  # the epoch currently running (resilience)

    def update(self, **kwargs):
        self.state.update(kwargs)


def train_epoch_range(max_epoch_num: int,
                      save_checkpoint_inter: Optional[int] = None,
                      checker: Optional[AutoCheckpointChecker] = None,
                      status: Optional[ExeTrainStatus] = None,
                      store=None) -> Iterator[int]:
    """for epoch in train_epoch_range(N): ... — on restart, already
    completed epochs are skipped and `status.state` is restored from
    the last epoch checkpoint before the first yielded epoch.

    ``store`` (a TCPStore, optional): on multi-host jobs, pass the
    launcher's store so a preemption on ANY host is broadcast and every
    host emergency-saves the same epoch; without it the shutdown
    handling is host-local only (fine single-host)."""
    checker = checker or AutoCheckpointChecker()
    if not checker.enabled:
        yield from range(max_epoch_num)
        return

    interval = save_checkpoint_inter if save_checkpoint_inter is not None \
        else checker.save_interval
    status = status or ExeTrainStatus()
    mgr = CheckpointManager(checker.get_job_checkpoint_path(),
                            max_to_keep=2, async_save=False,
                            save_interval_steps=1)

    def _epoch_state() -> Dict[str, Any]:
        return {"user_state": status.state, "epoch": status.epoch}

    mgr.save_on_preemption(_epoch_state)
    try:
        # corruption fallback: a truncated/uncommitted latest epoch
        # transparently resumes from the previous committed one
        from .checkpoint import CheckpointCorruption
        try:
            restored = mgr.restore()
        except CheckpointCorruption as e:
            # every candidate failed: transparent resume means a cold
            # start, not a crash loop — but never a silent one
            from ..core import monitor
            monitor.record_swallowed("auto_checkpoint.restore", e)
            restored = None
        start = 0
        if restored is not None:
            status.state = restored.get("user_state", {})
            start = int(mgr.last_restored_step) + 1
        with resilience.GracefulShutdown(store=store) as gs:
            for epoch in range(start, max_epoch_num):
                status.epoch = epoch
                yield epoch
                # epoch completed -> the emergency state is this epoch
                # from here on, even if the periodic snapshot is skipped
                # by the interval
                if (epoch + 1) % max(interval, 1) == 0 or \
                        epoch == max_epoch_num - 1:
                    mgr.save(epoch, _epoch_state())
                # preempted mid-epoch? -> synchronous emergency save of
                # the just-completed epoch, then exit(ELASTIC_EXIT_CODE)
                # for the launcher's relaunch path
                gs.check(epoch)
        mgr.wait()
    finally:
        mgr.close()
