"""Auto checkpoint — transparent epoch-granular train-loop resume.

Reference: python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py:72
(train_epoch_range generator + AutoCheckpointChecker env config,
checkpoint_saver.py) — used with elastic so a preempted/restarted job
resumes at the last completed epoch. Env contract kept:
PADDLE_RUNNING_ENV=PADDLE_EDL_AUTO_CHECKPOINT enables it,
PADDLE_JOB_ID keys the checkpoint, PADDLE_EDL_HDFS_CHECKPOINT_PATH
names the directory (any filesystem path here).

Fault tolerance (resilience layer): the epoch loop runs under a
GracefulShutdown context — SIGTERM/SIGINT lands, the NEXT epoch boundary
writes a synchronous emergency checkpoint of ``status.state`` and exits
with ELASTIC_EXIT_CODE so the elastic launcher relaunches; the restarted
range resumes at the emergency epoch + 1 (at most one epoch redone).
Restores go through the corruption-fallback path: a truncated latest
checkpoint transparently resumes from the previous committed one.

Exact mid-epoch resume: pass the training ``DataLoader`` as ``loader=``
and every checkpoint (periodic AND emergency — including per-STEP
emergency saves triggered by ``resilience.poll(step)`` from the user's
inner loop) carries ``loader.state_dict()`` (batch cursor + sampler
epoch/RNG state). On restart the loader is rewound to the exact batch:
a job preempted mid-epoch redoes at most one *step*, not one epoch —
the restarted range re-yields the interrupted epoch and the loader
replays only its remaining batches.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Iterator, Optional

from . import resilience
from .checkpoint import CheckpointManager

__all__ = ["train_epoch_range", "ExeTrainStatus", "AutoCheckpointChecker"]


def _scalar(v, default=None):
    """int() a checkpoint-restored leaf (Tensor / 0-d array / scalar)."""
    if v is None:
        return default
    from ..io.dataloader import _state_scalar
    return int(_state_scalar(v))


class AutoCheckpointChecker:
    def __init__(self):
        self.running_env = os.environ.get("PADDLE_RUNNING_ENV", "")
        self.job_id = os.environ.get("PADDLE_JOB_ID", "default")
        self.ckpt_path = os.environ.get(
            "PADDLE_EDL_HDFS_CHECKPOINT_PATH",
            os.environ.get("PADDLE_AUTO_CHECKPOINT_PATH", ""))
        self.save_interval = int(os.environ.get(
            "PADDLE_EDL_SAVE_CHECKPOINT_INTER", "1"))

    def get_job_checkpoint_path(self) -> str:
        return os.path.join(self.ckpt_path, f"job_{self.job_id}")

    @property
    def enabled(self) -> bool:
        return bool(self.ckpt_path) and \
            self.running_env == "PADDLE_EDL_AUTO_CHECKPOINT"


class ExeTrainStatus:
    """Mutable holder the loop body can stash model/opt state into;
    whatever is in `.state` is what gets checkpointed each epoch."""

    def __init__(self):
        self.state: Dict[str, Any] = {}
        self.epoch: int = -1  # the epoch currently running (resilience)

    def update(self, **kwargs):
        self.state.update(kwargs)


def train_epoch_range(max_epoch_num: int,
                      save_checkpoint_inter: Optional[int] = None,
                      checker: Optional[AutoCheckpointChecker] = None,
                      status: Optional[ExeTrainStatus] = None,
                      store=None, loader=None) -> Iterator[int]:
    """for epoch in train_epoch_range(N): ... — on restart, already
    completed epochs are skipped and `status.state` is restored from
    the last epoch checkpoint before the first yielded epoch.

    ``store`` (a TCPStore, optional): on multi-host jobs, pass the
    launcher's store so a preemption on ANY host is broadcast and every
    host emergency-saves the same epoch; without it the shutdown
    handling is host-local only (fine single-host).

    ``loader`` (a DataLoader, optional): checkpoints carry its
    ``state_dict()`` (batch cursor + sampler state), and a restore
    rewinds it — a mid-epoch emergency save (the user's inner loop
    calling ``resilience.poll(step)``) resumes AT the interrupted epoch
    with only the remaining batches replayed."""
    checker = checker or AutoCheckpointChecker()
    if not checker.enabled:
        yield from range(max_epoch_num)
        return

    interval = save_checkpoint_inter if save_checkpoint_inter is not None \
        else checker.save_interval
    status = status or ExeTrainStatus()
    mgr = CheckpointManager(checker.get_job_checkpoint_path(),
                            max_to_keep=2, async_save=False,
                            save_interval_steps=1)

    # completed[0] = the last epoch whose yield has RETURNED (-1 before
    # any). The checkpointed "epoch" record is always this value, so
    # resume is one uniform rule: start = recorded epoch + 1, with the
    # loader cursor (captured live, mid-epoch) rewinding into that
    # epoch's remaining batches.
    completed = [-1]

    def _epoch_state() -> Dict[str, Any]:
        st = {"user_state": status.state, "epoch": completed[0]}
        if loader is not None and hasattr(loader, "state_dict"):
            st["loader"] = loader.state_dict()
        return st

    # orbax keys checkpoints by a monotonic step id, but this loop saves
    # at two granularities: epoch boundaries AND (via resilience.poll in
    # the user's inner loop) arbitrary mid-epoch steps. One id space
    # covers both: (completed+1)*STRIDE + batch_cursor — a boundary save
    # of completed epoch e is (e+1)*STRIDE (the SAME id whether periodic
    # or emergency, so a boundary emergency after a periodic save is the
    # no-op it should be), a mid-epoch save of the next epoch at batch k
    # is (e+1)*STRIDE + k — strictly increasing as training progresses.
    STRIDE = 1 << 20

    def _save_id() -> int:
        cursor = 0
        if loader is not None and hasattr(loader, "state_dict"):
            cursor = min(int(loader.state_dict().get("cursor") or 0),
                         STRIDE - 1)
        gs = resilience.active()
        if gs is not None and getattr(gs, "store", None) is not None:
            # multi-host: orbax saves are collective, so every host must
            # use the SAME id — hosts a boundary apart agree on
            # `completed` but not on a mid-epoch cursor. Drop the cursor
            # from the id (mid-epoch resume granularity stays a
            # single-host refinement; multi-host keeps the <=1-epoch
            # guarantee).
            cursor = 0
        return (completed[0] + 1) * STRIDE + cursor

    def _emergency(step: int) -> None:
        # the elected step number (the caller's inner-loop counter)
        # lives in a different id space: key by epoch+cursor instead
        mgr.save(_save_id(), _epoch_state(), force=True)
        mgr.wait()

    unregister = resilience.register_emergency(_emergency)
    try:
        # corruption fallback: a truncated/uncommitted latest epoch
        # transparently resumes from the previous committed one
        from .checkpoint import CheckpointCorruption
        try:
            restored = mgr.restore()
        except CheckpointCorruption as e:
            # every candidate failed: transparent resume means a cold
            # start, not a crash loop — but never a silent one
            from ..core import monitor
            monitor.record_swallowed("auto_checkpoint.restore", e)
            restored = None
        start = 0
        if restored is not None:
            status.state = restored.get("user_state", {})
            if loader is not None and hasattr(loader, "load_state_dict") \
                    and restored.get("loader") is not None:
                # rewinds mid-epoch (cursor > 0) or restores the next
                # epoch's sampler state (cursor 0) — either way the
                # resumed epoch replays exactly the right batches
                loader.load_state_dict(restored["loader"])
            epoch_rec = _scalar(restored.get("epoch"))
            if epoch_rec is not None:
                # "epoch" records the last COMPLETED epoch (old
                # checkpoints recorded the epoch at a boundary save —
                # same value): resume at the next one; a mid-epoch save
                # re-enters it through the rewound loader
                start = epoch_rec + 1
            else:  # pre-epoch-record checkpoints: step id IS the epoch
                start = int(mgr.last_restored_step) + 1
            completed[0] = start - 1
        with resilience.GracefulShutdown(store=store) as gs:
            for epoch in range(start, max_epoch_num):
                status.epoch = epoch
                yield epoch
                completed[0] = epoch
                # epoch completed -> the emergency state is this epoch
                # from here on, even if the periodic snapshot is skipped
                # by the interval
                if (epoch + 1) % max(interval, 1) == 0 or \
                        epoch == max_epoch_num - 1:
                    mgr.save((epoch + 1) * STRIDE, _epoch_state())
                # preempted mid-epoch? -> synchronous emergency save of
                # the just-completed epoch, then exit(ELASTIC_EXIT_CODE)
                # for the launcher's relaunch path
                gs.check(epoch)
        mgr.wait()
    finally:
        unregister()
        mgr.close()
