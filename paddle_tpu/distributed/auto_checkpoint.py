"""Auto checkpoint — transparent epoch-granular train-loop resume.

Reference: python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py:72
(train_epoch_range generator + AutoCheckpointChecker env config,
checkpoint_saver.py) — used with elastic so a preempted/restarted job
resumes at the last completed epoch. Env contract kept:
PADDLE_RUNNING_ENV=PADDLE_EDL_AUTO_CHECKPOINT enables it,
PADDLE_JOB_ID keys the checkpoint, PADDLE_EDL_HDFS_CHECKPOINT_PATH
names the directory (any filesystem path here).
"""
from __future__ import annotations

import os
from typing import Any, Dict, Iterator, Optional

from .checkpoint import CheckpointManager

__all__ = ["train_epoch_range", "ExeTrainStatus", "AutoCheckpointChecker"]


class AutoCheckpointChecker:
    def __init__(self):
        self.running_env = os.environ.get("PADDLE_RUNNING_ENV", "")
        self.job_id = os.environ.get("PADDLE_JOB_ID", "default")
        self.ckpt_path = os.environ.get(
            "PADDLE_EDL_HDFS_CHECKPOINT_PATH",
            os.environ.get("PADDLE_AUTO_CHECKPOINT_PATH", ""))
        self.save_interval = int(os.environ.get(
            "PADDLE_EDL_SAVE_CHECKPOINT_INTER", "1"))

    def get_job_checkpoint_path(self) -> str:
        return os.path.join(self.ckpt_path, f"job_{self.job_id}")

    @property
    def enabled(self) -> bool:
        return bool(self.ckpt_path) and \
            self.running_env == "PADDLE_EDL_AUTO_CHECKPOINT"


class ExeTrainStatus:
    """Mutable holder the loop body can stash model/opt state into;
    whatever is in `.state` is what gets checkpointed each epoch."""

    def __init__(self):
        self.state: Dict[str, Any] = {}

    def update(self, **kwargs):
        self.state.update(kwargs)


def train_epoch_range(max_epoch_num: int,
                      save_checkpoint_inter: Optional[int] = None,
                      checker: Optional[AutoCheckpointChecker] = None,
                      status: Optional[ExeTrainStatus] = None
                      ) -> Iterator[int]:
    """for epoch in train_epoch_range(N): ... — on restart, already
    completed epochs are skipped and `status.state` is restored from
    the last epoch checkpoint before the first yielded epoch."""
    checker = checker or AutoCheckpointChecker()
    if not checker.enabled:
        yield from range(max_epoch_num)
        return

    interval = save_checkpoint_inter if save_checkpoint_inter is not None \
        else checker.save_interval
    status = status or ExeTrainStatus()
    mgr = CheckpointManager(checker.get_job_checkpoint_path(),
                            max_to_keep=2, async_save=False,
                            save_interval_steps=1)
    try:
        last = mgr.latest_step()
        start = 0
        if last is not None:
            restored = mgr.restore(step=last)
            if restored is not None:
                status.state = restored.get("user_state", {})
            start = int(last) + 1
        for epoch in range(start, max_epoch_num):
            yield epoch
            # epoch completed -> snapshot
            if (epoch + 1) % max(interval, 1) == 0 or \
                    epoch == max_epoch_num - 1:
                mgr.save(epoch, {"user_state": status.state,
                                 "epoch": epoch})
        mgr.wait()
    finally:
        mgr.close()
