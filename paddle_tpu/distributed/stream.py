"""paddle.distributed.stream namespace.

Reference: python/paddle/distributed/communication/stream/ — the
stream-variant collectives taking sync_op/use_calc_stream. XLA owns
stream scheduling (latency-hiding scheduler), so these are the same
compiled collectives; sync_op=False returns a completed task handle
for API parity.
"""
from __future__ import annotations

from .collective import (ReduceOp, all_gather, all_reduce,  # noqa: F401
                         all_to_all, alltoall_single, broadcast,
                         reduce_scatter, scatter)
from .comm_extra import recv, reduce, send  # noqa: F401

__all__ = ["all_gather", "all_reduce", "all_to_all", "alltoall_single",
           "broadcast", "reduce", "reduce_scatter", "scatter", "send",
           "recv"]
