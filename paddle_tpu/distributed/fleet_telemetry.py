"""Fleet observability plane: cross-process metrics aggregation over
the TCPStore — one pane of glass for an N-process job.

Reference analog: the reference's ``paddle/fluid/distributed`` layer
spends much of its bulk on controller-side visibility (fleet metrics
tables, barrier/heartbeat monitors, the PSCore dashboards); every
surface we built so far — the PR-2 registry, PR-10's flight recorder
and ``/metrics`` — describes ONE process in isolation. This module
makes the fleet observable before the fleet runtime itself lands, and
deliberately needs NO jax cross-process collectives (the PR-3
capability gap): it rides the TCPStore the launcher already runs and
plain HTTP, so it works fully in CPU CI.

Three legs on one shared ``(rank, incarnation)`` identity:

- **Publisher** (every rank): periodically pushes a delta-encoded
  snapshot of the local metrics registry (``metrics.snapshot_delta``)
  plus a health dict to the store, and stamps a server-clock heartbeat
  (``setts`` — cross-host wall clocks are never compared). Period:
  ``PADDLE_FLEET_METRICS_PERIOD_S`` (default 2s).
- **Aggregator** (elected: the launch Controller's node, or rank 0):
  merges the per-rank streams into one fleet registry with ``rank=``/
  ``replica=``/``incarnation=`` labels, served by the telemetry
  server at ``/fleet/metrics`` (Prometheus text) and ``/fleet/healthz``
  (per-replica ``ready``/``reason``/``predicted_headroom_bytes``
  rolled up — the ROADMAP item-1 router admission signal). A rank
  that stops publishing within the deadline is marked STALE
  (``fleet.ranks_stale``, ``fleet.rank_up{rank=}`` -> 0) and its last
  series stay visible — never silently dropped: a vanished rank is
  the most important thing on the dashboard.
- **Clock handshake**: each rank estimates its wall-clock offset vs
  the store master via a ping handshake (NTP-style: the minimum-RTT
  sample's midpoint), records it as ``fleet.clock_skew_ns`` and into
  the flight recorder's dump metadata, so ``tools/trace_merge`` can
  align N per-rank post-mortems onto one timeline.

Delta protocol: each publish carries ``seq`` and either a full
snapshot (first publish, or on resync) or per-metric deltas. The
aggregator applies ``seq == last+1`` deltas, ignores re-reads of the
same ``seq``, and on any gap (missed payload, aggregator restart, new
incarnation) writes a resync key the publisher answers with a full
snapshot — the merged view can never silently drift.
"""
from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from ..core import flight_recorder, metrics, monitor, slo, timeseries

__all__ = [
    "FleetAggregator", "FleetIdentity", "FleetMember",
    "MetricsPublisher", "estimate_clock_offset_ns", "local_identity",
    "start", "start_from_env",
]

DEFAULT_PERIOD_S = 2.0
# a rank is stale after this many publish periods without a heartbeat
STALE_PERIODS = 3.0


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        v = float(raw)
        if v > 0:
            return v
        raise ValueError(raw)
    except ValueError as e:
        monitor.record_swallowed(f"fleet.env:{name}", e)
        return default


@dataclass(frozen=True)
class FleetIdentity:
    """The shared identity every leg keys on: launcher rank, elastic
    incarnation (PADDLE_RESTART_COUNT), replica label, pid."""
    rank: int
    world_size: int
    incarnation: int
    replica: str
    pid: int


def local_identity() -> FleetIdentity:
    rank, restart, pid = flight_recorder.identity()
    try:
        world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1") or 1)
    except ValueError:
        world = 1
    replica = os.environ.get("PADDLE_REPLICA_ID", "").strip()
    if replica and "PADDLE_TRAINER_ID" not in os.environ:
        # N replicas joined by hand (a router's serving fleet, no
        # launcher): everyone would read rank 0 and clobber one
        # stream, so a NUMERIC replica id doubles as the fleet rank
        try:
            rank = int(replica)
        except ValueError:
            pass   # non-numeric replica stays a label; the
            #        aggregator reports the pid collision observably
    replica = replica or str(rank)
    return FleetIdentity(rank=rank, world_size=world,
                         incarnation=restart, replica=replica, pid=pid)


def _namespace(namespace: Optional[str]) -> str:
    if namespace:
        return namespace
    job = os.environ.get("PADDLE_JOB_ID", "default").strip() or "default"
    return f"__fleet/{job}"


def _merge_labels(key: str, extra: Dict[str, str]) -> str:
    """``name{a=b}`` + extra labels -> one sorted labeled key (the
    registry's ``_labeled`` format). Existing labels win on collision:
    a published series already carrying ``rank=`` must not be
    re-attributed to the publisher."""
    if key.endswith("}") and "{" in key:
        base, _, rest = key.partition("{")
        labels = {}
        for kv in rest[:-1].split(","):
            k, _, v = kv.partition("=")
            labels[k] = v
    else:
        base, labels = key, {}
    merged = dict(extra)
    merged.update(labels)
    return metrics._labeled(base, merged)


# -------------------------------------------------------- clock handshake

def estimate_clock_offset_ns(store, samples: int = 5):
    """NTP-style offset of THIS host's wall clock vs the store
    master's: ping ``samples`` times, keep the minimum-RTT sample, and
    assume the server read its clock at the round-trip midpoint.
    Returns ``(offset_ns, rtt_ns)`` — local_wall - offset ≈ master
    wall. Accuracy is bounded by rtt/2 (sub-ms on a LAN), plenty for
    ordering SIGTERM-vs-detection events across ranks."""
    best = None
    for _ in range(max(int(samples), 1)):
        t0 = time.time_ns()
        server_s = store.now()
        t1 = time.time_ns()
        rtt = t1 - t0
        offset = (t0 + t1) // 2 - int(server_s * 1e9)
        if best is None or rtt < best[1]:
            best = (offset, rtt)
    return best


# --------------------------------------------------------------- publisher

class MetricsPublisher:
    """One rank's outbound leg: snapshot_delta -> store, heartbeat,
    health. ``start()`` runs a daemon thread at the publish period;
    ``publish_now()`` is the synchronous form tests (and the drain
    path) call directly."""

    def __init__(self, store, identity: Optional[FleetIdentity] = None,
                 period_s: Optional[float] = None,
                 health_fn: Optional[Callable[[], Dict]] = None,
                 namespace: Optional[str] = None,
                 clock_sync: bool = True):
        self.store = store
        self.identity = identity or local_identity()
        self.period_s = float(period_s) if period_s is not None else \
            _env_float("PADDLE_FLEET_METRICS_PERIOD_S", DEFAULT_PERIOD_S)
        self.health_fn = health_fn
        ns = _namespace(namespace)
        self._key = f"{ns}/m/{self.identity.rank}"
        self._ts_key = f"{ns}/ts/{self.identity.rank}"
        self._resync_key = f"{ns}/resync/{self.identity.rank}"
        self._prev: Optional[Dict[str, dict]] = None
        self._seq = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.clock_offset_ns = 0
        self._clock_sync = bool(clock_sync)
        self._clock_synced = False

    # ------------------------------------------------------------ clock
    def sync_clock(self):
        """Run the ping handshake once: record the offset locally
        (``fleet.clock_skew_ns``), stamp it into the flight recorder's
        dump metadata, and leave a ``fleet.clock_sync`` event in the
        ring so a post-mortem shows the alignment term used."""
        offset, rtt = estimate_clock_offset_ns(self.store)
        self.clock_offset_ns = offset
        self._clock_synced = True
        flight_recorder.set_clock_offset_ns(offset)
        flight_recorder.record("fleet.clock_sync", offset_ns=offset,
                               rtt_ns=rtt)
        monitor.record_clock_skew(self.identity.rank, offset)
        return offset, rtt

    # ---------------------------------------------------------- publish
    def publish_now(self) -> dict:
        """One publish: honor any pending resync request, delta-encode
        the registry, write payload then heartbeat (the aggregator
        reads them in that order). Returns the payload (tests)."""
        with self._lock:
            if not self._clock_synced and self._clock_sync:
                self.sync_clock()
            if self._prev is not None and \
                    self.store.keys(self._resync_key):
                self._prev = None    # aggregator asked: go absolute
                self.store.delete(self._resync_key)
            new_prev, delta = metrics.snapshot_delta(self._prev)
            # the fleet meta-plane (fleet.*) is produced by the
            # aggregator; republishing our local copy would collide
            # with its per-rank labels in the merged view
            delta["metrics"] = {
                k: v for k, v in delta["metrics"].items()
                if not k.startswith("fleet.")}
            ident = self.identity
            payload = {
                "seq": self._seq,
                "rank": ident.rank,
                "incarnation": ident.incarnation,
                "replica": ident.replica,
                "pid": ident.pid,
                "clock_offset_ns": self.clock_offset_ns,
                "delta": delta,
                "health": self._health(),
            }
            self.store.set(self._key, payload)
            # the payload is durably in the store: commit the delta
            # baseline + seq NOW, before the heartbeat. Committing
            # earlier would lose this window's increments forever on a
            # failed set (the next delta, sent under the SAME seq,
            # covers only the newer window yet looks contiguous to the
            # aggregator — the exact silent drift the seq protocol
            # exists to prevent); committing later would re-send a
            # WIDER window under the same seq, which the aggregator's
            # idempotent same-seq drop discards. A failed heartbeat
            # after the commit only delays staleness by one period.
            self._prev = new_prev
            self._seq += 1
            self.store.set_timestamp(self._ts_key)
            monitor.record_fleet_publish()
            return payload

    def _health(self) -> Dict:
        if self.health_fn is None:
            return {"ready": True}
        try:
            return dict(self.health_fn())
        except Exception as e:
            monitor.record_swallowed("fleet.health_fn", e)
            return {"ready": False, "reason": "health_fn error"}

    # --------------------------------------------------------- lifecycle
    def start(self) -> "MetricsPublisher":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"fleet-publish:{self.identity.rank}")
        self._thread.start()
        return self

    def _loop(self):
        # first publish immediately: the aggregator should see a new
        # rank within one poll, not one period later
        while True:
            try:
                self.publish_now()
            except Exception as e:  # store blip: keep the loop alive
                monitor.record_swallowed("fleet.publish", e)
            if self._stop.wait(self.period_s):
                return

    def stop(self, final_publish: bool = True):
        """Stop the thread; by default push one last snapshot so the
        aggregator sees the final counters (a drained replica's last
        numbers are the interesting ones)."""
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.period_s + 5.0)
        if final_publish:
            try:
                self.publish_now()
            except Exception as e:
                monitor.record_swallowed("fleet.final_publish", e)


# -------------------------------------------------------------- aggregator

@dataclass
class _RankState:
    incarnation: int
    replica: str
    pid: int = 0
    seq: int = -1
    metrics: Dict[str, dict] = field(default_factory=dict)
    health: Dict = field(default_factory=dict)
    clock_offset_ns: int = 0
    age_s: Optional[float] = None
    stale: bool = False
    resync_pending: bool = False


class FleetAggregator:
    """The elected merge point: polls every rank's published stream,
    maintains the fleet registry, and answers the telemetry server's
    ``/fleet/metrics`` / ``/fleet/healthz``."""

    def __init__(self, store, expected_ranks: Optional[int] = None,
                 period_s: Optional[float] = None,
                 stale_after_s: Optional[float] = None,
                 namespace: Optional[str] = None):
        self.store = store
        self.period_s = float(period_s) if period_s is not None else \
            _env_float("PADDLE_FLEET_METRICS_PERIOD_S", DEFAULT_PERIOD_S)
        self.stale_after_s = float(stale_after_s) \
            if stale_after_s is not None \
            else STALE_PERIODS * self.period_s
        if expected_ranks is None:
            try:
                expected_ranks = int(
                    os.environ.get("PADDLE_TRAINERS_NUM", "") or 0) \
                    or None
            except ValueError:
                expected_ranks = None
        self.expected_ranks = expected_ranks
        self._ns = _namespace(namespace)
        self._ranks: Dict[int, _RankState] = {}
        # fleet-scope SLO watchtower: every poll appends the merged
        # (relabeled, deep-copied) per-rank state to a private
        # time-series ring and evaluates the same default specs over
        # it — the fleet face of core.slo; the straggler detector
        # diffs each rank's cumulative train.step_time between polls
        self._slo_ring = timeseries.TimeSeriesRing(period_s=self.period_s)
        self.slo_evaluator = slo.SLOEvaluator(self._slo_ring,
                                              scope="fleet")
        self.straggler = slo.StragglerDetector()
        # _lock guards only the in-memory merged view (held for
        # microseconds); _poll_lock serializes store I/O rounds.
        # Separate so a store outage mid-poll can NEVER block
        # fleet_registry()/healthz() — the scrape threads keep serving
        # the last merged view while the poll waits on its timeouts
        self._lock = threading.Lock()
        self._poll_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_poll = float("-inf")

    # -------------------------------------------------------------- poll
    def poll(self):
        """One aggregation round: read every published payload, apply
        deltas (resync on gaps), refresh staleness from the store's
        OWN clock (heartbeats are server timestamps — rank clocks are
        never compared to each other)."""
        with self._poll_lock:
            self._poll_inner()

    def _poll_inner(self):
        # ---- store I/O phase: NO view lock held
        self._last_poll = time.monotonic()
        try:
            now = self.store.now()
            keys = self.store.keys(f"{self._ns}/m/")
        except (TimeoutError, RuntimeError, OSError) as e:
            monitor.record_swallowed("fleet.aggregate", e)
            return
        payloads = []
        for key in sorted(keys):
            tail = key.rsplit("/", 1)[1]
            try:
                rank = int(tail)
            except ValueError:
                continue
            try:
                payloads.append(
                    (rank, self.store.get(key, timeout=5.0)))
            except (TimeoutError, RuntimeError, OSError) as e:
                monitor.record_swallowed("fleet.read_rank", e)
        with self._lock:
            known = set(self._ranks) | {r for r, _ in payloads}
        ages: Dict[int, Optional[float]] = {}
        for rank in known:
            try:
                ts = self.store.get(f"{self._ns}/ts/{rank}",
                                    timeout=0.25)
                ages[rank] = max(now - float(ts), 0.0)
            except (TimeoutError, RuntimeError, OSError):
                ages[rank] = None
        # ---- merge phase: view lock held, in-memory only
        resyncs = []
        with self._lock:
            for rank, payload in payloads:
                self._apply(rank, payload, resyncs)
            stale = 0
            for rank, st in self._ranks.items():
                st.age_s = ages.get(rank)
                was = st.stale
                st.stale = st.age_s is None or \
                    st.age_s > self.stale_after_s
                if st.stale:
                    stale += 1
                    if not was:
                        flight_recorder.record(
                            "fleet.rank_stale", rank=rank,
                            incarnation=st.incarnation,
                            age_s=round(st.age_s, 3)
                            if st.age_s is not None else -1.0)
                monitor.record_fleet_rank_up(rank, st.incarnation,
                                             not st.stale)
                monitor.record_clock_skew(rank, st.clock_offset_ns)
            monitor.record_fleet_ranks(len(self._ranks), stale)
            fleet_state, step_totals = self._fleet_snapshot_locked()
        # ---- watchtower phase: own locks only, store lock released
        self.straggler.observe(step_totals)
        self._slo_ring.sample_state(fleet_state)
        self.slo_evaluator.evaluate()
        # ---- resync writes: store I/O again, lock released
        for rank, st in resyncs:
            try:
                self.store.set(f"{self._ns}/resync/{rank}", True)
            except (TimeoutError, RuntimeError, OSError) as e:
                with self._lock:
                    st.resync_pending = False
                monitor.record_swallowed("fleet.resync", e)

    def _fleet_snapshot_locked(self):
        """(relabeled deep-copied mergeable state of every rank's
        series, per-rank cumulative ``train.step_time`` (count, sum))
        — the fleet SLO ring sample and the straggler detector input.
        Caller holds ``self._lock``; records are copied because
        ``apply_delta`` mutates the rank states in place."""
        state: Dict[str, dict] = {}
        totals: Dict[int, tuple] = {}
        for rank, st in self._ranks.items():
            extra = {"rank": str(rank), "replica": st.replica,
                     "incarnation": str(st.incarnation)}
            for key, rec in st.metrics.items():
                out = dict(rec)
                if "counts" in out:
                    out["counts"] = list(out["counts"])
                state[_merge_labels(key, extra)] = out
            rec = st.metrics.get("train.step_time")
            if rec is not None and rec.get("kind") == "histogram":
                totals[rank] = (float(rec.get("count", 0)),
                                float(rec.get("sum", 0.0)))
        return state, totals

    def _apply(self, rank: int, payload: dict, resyncs: list):
        # caller holds self._lock
        inc = int(payload.get("incarnation", 0))
        seq = int(payload.get("seq", 0))
        pid = int(payload.get("pid", 0))
        delta = payload.get("delta") or {"full": True, "metrics": {}}
        st = self._ranks.get(rank)
        if st is not None and st.incarnation == inc \
                and pid and st.pid and pid != st.pid:
            # two live processes publishing one (rank, incarnation)
            # stream: a misconfigured fleet (N hand-joined replicas
            # without distinct PADDLE_REPLICA_IDs). Last writer wins
            # below — but the flapping must be OBSERVABLE, never a
            # silent resync storm
            monitor.record_swallowed(
                "fleet.rank_collision",
                RuntimeError(
                    f"rank {rank} incarnation {inc} published by both "
                    f"pid {st.pid} and pid {pid}: give each replica a "
                    f"distinct PADDLE_REPLICA_ID (or rank)"))
        fresh_stream = st is None or st.incarnation != inc
        if fresh_stream and not delta.get("full"):
            # mid-stream join (aggregator restarted, or a relaunched
            # rank whose first full publish we missed): hold the old
            # view and ask for an absolute snapshot
            self._request_resync(rank, st, inc,
                                 payload.get("replica", str(rank)),
                                 resyncs)
            return
        if fresh_stream:
            st = _RankState(incarnation=inc,
                            replica=str(payload.get("replica", rank)))
            self._ranks[rank] = st
        elif seq == st.seq:
            return                     # same payload re-read: idempotent
        elif not delta.get("full") and seq != st.seq + 1:
            self._request_resync(rank, st, inc, st.replica, resyncs)
            return
        metrics.apply_delta(st.metrics, delta)
        st.seq = seq
        st.incarnation = inc
        st.replica = str(payload.get("replica", st.replica))
        st.pid = pid or st.pid
        st.health = dict(payload.get("health") or {})
        st.clock_offset_ns = int(payload.get("clock_offset_ns", 0))
        st.resync_pending = False

    def _request_resync(self, rank: int, st: Optional[_RankState],
                        inc: int, replica: str, resyncs: list):
        # caller holds self._lock; the store write itself happens
        # after release (resyncs is the poll round's write list)
        if st is not None and st.resync_pending:
            return
        if st is None:
            st = _RankState(incarnation=inc, replica=str(replica))
            self._ranks[rank] = st
        st.resync_pending = True
        resyncs.append((rank, st))

    def refresh(self, min_interval_s: float = 0.2):
        """Rate-limited poll — what the HTTP handlers call, so a
        scrape hammer (N dashboards) doesn't multiply store traffic.
        Non-blocking: when another thread is already mid-poll this
        returns immediately and the caller serves the current view."""
        if time.monotonic() - self._last_poll < min_interval_s:
            return
        if not self._poll_lock.acquire(blocking=False):
            return
        try:
            self._poll_inner()
        finally:
            self._poll_lock.release()

    # ------------------------------------------------------------- reads
    def fleet_registry(self) -> Dict[str, object]:
        """The merged registry: every rank's series relabeled with
        ``rank=``/``replica=``/``incarnation=``, plus the aggregator's
        meta series (rank census, per-rank up/skew) — feed it to
        ``telemetry_server.prometheus_text``."""
        with self._lock:
            out: Dict[str, object] = {}
            stale = 0
            for rank, st in self._ranks.items():
                extra = {"rank": str(rank), "replica": st.replica,
                         "incarnation": str(st.incarnation)}
                for key, rec in st.metrics.items():
                    out[_merge_labels(key, extra)] = \
                        metrics.state_metric(key, rec)
                up = metrics.Gauge(_merge_labels(
                    "fleet.rank_up",
                    {"rank": str(rank),
                     "incarnation": str(st.incarnation)}))
                up._value = up._peak = 0.0 if st.stale else 1.0
                out[up.name] = up
                skew = metrics.Gauge(_merge_labels(
                    "fleet.clock_skew_ns", {"rank": str(rank)}))
                skew._value = skew._peak = float(st.clock_offset_ns)
                out[skew.name] = skew
                stale += st.stale
            total = metrics.Gauge("fleet.ranks_total")
            total._value = total._peak = float(len(self._ranks))
            out[total.name] = total
            g_stale = metrics.Gauge("fleet.ranks_stale")
            g_stale._value = g_stale._peak = float(stale)
            out[g_stale.name] = g_stale
            return out

    def healthz(self) -> Dict:
        """The ``/fleet/healthz`` rollup: per-replica ready/reason/
        headroom plus the fleet verdict — ready iff every known rank
        is ready, none is stale, and (when the world size is known)
        everyone has reported."""
        straggler_ranks = set(self.straggler.straggler_ranks())
        slo_states = self.slo_evaluator.states()
        with self._lock:
            ranks = {}
            stale = 0
            all_ready = True
            for rank, st in sorted(self._ranks.items()):
                h = st.health or {}
                ready = bool(h.get("ready", False)) and not st.stale
                all_ready = all_ready and ready
                stale += st.stale
                entry = {
                    "ready": ready,
                    "reason": "stale" if st.stale
                    else h.get("reason"),
                    "stale": st.stale,
                    "incarnation": st.incarnation,
                    "replica": st.replica,
                    "age_s": round(st.age_s, 3)
                    if st.age_s is not None else None,
                    # marked, never dropped: a straggler stays ready
                    # (it IS serving/stepping) but the router/operator
                    # sees the flag
                    "straggler": rank in straggler_ranks,
                }
                for k in ("predicted_headroom_bytes",
                          "predicted_peak_bytes", "free_tokens",
                          "capacity_tokens", "queue_depth",
                          "pending_prefill_tokens",
                          "prefill_chunks_queued"):
                    if k in h:
                        entry[k] = h[k]
                ranks[str(rank)] = entry
            seen = len(self._ranks)
            missing = max(self.expected_ranks - seen, 0) \
                if self.expected_ranks else 0
            return {
                "ready": all_ready and stale == 0 and missing == 0
                and seen > 0,
                "ranks_total": seen,
                "ranks_stale": stale,
                "ranks_expected": self.expected_ranks,
                "ranks_missing": missing,
                "stale_after_s": self.stale_after_s,
                "stragglers": sorted(straggler_ranks),
                "slo": slo_states,
                "ranks": ranks,
            }

    def slo_report(self) -> Dict:
        """The fleet section of the telemetry server's ``/slo`` body:
        fleet-scope SLO states + alert history + straggler flags."""
        doc = self.slo_evaluator.report()
        doc["stragglers"] = self.straggler.straggler_ranks()
        doc["straggler_flags"] = self.straggler.flags()
        return doc

    # --------------------------------------------------------- lifecycle
    def start(self) -> "FleetAggregator":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="fleet-aggregate")
        self._thread.start()
        return self

    def _loop(self):
        while True:
            try:
                self.poll()
            except Exception as e:
                monitor.record_swallowed("fleet.aggregate_loop", e)
            if self._stop.wait(self.period_s):
                return

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.period_s + 5.0)


# ---------------------------------------------------------------- wiring

class FleetMember:
    """One process's fleet-telemetry handles: always a publisher,
    plus the aggregator on the elected rank."""

    def __init__(self, publisher: MetricsPublisher,
                 aggregator: Optional[FleetAggregator]):
        self.publisher = publisher
        self.aggregator = aggregator

    def stop(self):
        self.publisher.stop()
        if self.aggregator is not None:
            self.aggregator.stop()


def start(store, health_fn: Optional[Callable[[], Dict]] = None,
          aggregate: Optional[bool] = None,
          period_s: Optional[float] = None,
          namespace: Optional[str] = None) -> FleetMember:
    """Join the fleet plane: start this rank's publisher (and, on the
    elected rank — rank 0 unless ``aggregate`` overrides — the
    aggregator). Starting the publisher enables the registry: joining
    the fleet pane is opting into recording, the TelemetryServer
    contract."""
    metrics.enable()
    ident = local_identity()
    pub = MetricsPublisher(store, identity=ident, period_s=period_s,
                           health_fn=health_fn,
                           namespace=namespace).start()
    agg = None
    if aggregate is None:
        aggregate = ident.rank == 0
    if aggregate:
        agg = FleetAggregator(store, period_s=period_s,
                              namespace=namespace).start()
    return FleetMember(pub, agg)


def start_from_env(health_fn: Optional[Callable[[], Dict]] = None) \
        -> Optional[FleetMember]:
    """The ``PADDLE_FLEET_STORE=host:port`` opt-in (the launcher's
    ``--fleet_store`` exports it): connect a TCPStore client and join
    the plane. Unset/empty -> None; garbage is swallowed observably
    (a bad knob must not take the replica down)."""
    raw = os.environ.get("PADDLE_FLEET_STORE", "").strip()
    if not raw:
        return None
    host, _, port_s = raw.rpartition(":")
    try:
        port = int(port_s)
        if not host:
            raise ValueError(raw)
    except ValueError:
        monitor.record_swallowed(
            "fleet.store_addr",
            ValueError(f"PADDLE_FLEET_STORE={raw!r}"))
        return None
    from .store import TCPStore
    try:
        store = TCPStore(host, port, timeout=30.0)
        return start(store, health_fn=health_fn)
    except Exception as e:
        monitor.record_swallowed("fleet.store_connect", e)
        return None
