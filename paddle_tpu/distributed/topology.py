"""Hybrid-parallel topology -> jax device Mesh.

Reference analog: CommunicateTopology / HybridCommunicateGroup
(python/paddle/distributed/fleet/base/topology.py:26,50,136) builds the
4-D process grid [dp, pp, sharding, mp] and carves NCCL sub-groups per
axis. TPU-native: the grid IS a jax.sharding.Mesh with named axes; XLA
emits the right ICI/DCN collectives from shardings, so "sub-groups" are
just axis names. Axis order follows the scaling-book recipe: put the
highest-traffic axis (mp/tp) innermost so it rides ICI neighbors; dp/pp
outermost so their collectives tolerate DCN (the ProcessGroupHeter
hierarchy, ProcessGroupHeter.h:128-134, falls out of this ordering for
free on multi-slice).

Axes: dp (data), sharding (ZeRO), pp (pipeline), sp (sequence/context —
NEW capability, absent in the reference per SURVEY §5), ep (expert), mp
(tensor). Degenerate axes (degree 1) are kept in the mesh so specs are
uniform.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# canonical axis order, outermost (DCN-tolerant) -> innermost (ICI-hungry)
AXIS_ORDER = ("dp", "sharding", "pp", "sp", "ep", "mp")


class HybridCommunicateGroup:
    """Builds and owns the device mesh for hybrid parallelism."""

    def __init__(self, dp_degree: int = 1, mp_degree: int = 1,
                 pp_degree: int = 1, sharding_degree: int = 1,
                 sp_degree: int = 1, ep_degree: int = 1,
                 devices: Optional[Sequence] = None):
        devices = list(devices if devices is not None else jax.devices())
        degrees = {"dp": dp_degree, "sharding": sharding_degree,
                   "pp": pp_degree, "sp": sp_degree, "ep": ep_degree,
                   "mp": mp_degree}
        total = int(np.prod(list(degrees.values())))
        if total == 0:
            raise ValueError("degrees must be positive")
        if total != len(devices):
            rest = int(np.prod([degrees[a] for a in AXIS_ORDER
                                if a != "dp"]))
            if degrees["dp"] in (0, 1) and len(devices) % rest == 0:
                # dp left at default: infer it to fill the device count
                degrees["dp"] = len(devices) // rest
                total = len(devices)
            else:
                # an explicitly requested layout that doesn't fit is an
                # error, never silently overridden (paddle raises too)
                raise ValueError(
                    f"degree product {total} != {len(devices)} devices "
                    f"(degrees={degrees}); adjust hybrid_configs")
        self.degrees: Dict[str, int] = degrees
        shape = [degrees[a] for a in AXIS_ORDER]
        self.mesh = Mesh(np.array(devices).reshape(shape), AXIS_ORDER)

    # --- paddle-parity accessors (fleet/base/topology.py API) -------------
    def get_data_parallel_world_size(self) -> int:
        return self.degrees["dp"]

    def get_model_parallel_world_size(self) -> int:
        return self.degrees["mp"]

    def get_pipe_parallel_world_size(self) -> int:
        return self.degrees["pp"]

    def get_sharding_parallel_world_size(self) -> int:
        return self.degrees["sharding"]

    def get_sequence_parallel_world_size(self) -> int:
        return self.degrees["sp"]

    def get_expert_parallel_world_size(self) -> int:
        return self.degrees["ep"]

    def topology(self):
        return self.degrees

    @property
    def nranks(self) -> int:
        return int(np.prod(list(self.degrees.values())))

    def axis_names(self) -> List[str]:
        return list(AXIS_ORDER)

    def active_axes(self) -> List[str]:
        return [a for a in AXIS_ORDER if self.degrees[a] > 1]

    def __repr__(self):
        return f"HybridCommunicateGroup({self.degrees})"


_GLOBAL_HCG: Optional[HybridCommunicateGroup] = None


def set_hybrid_communicate_group(hcg: HybridCommunicateGroup):
    global _GLOBAL_HCG
    _GLOBAL_HCG = hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _GLOBAL_HCG


def get_mesh() -> Optional[Mesh]:
    return _GLOBAL_HCG.mesh if _GLOBAL_HCG is not None else None


def create_mesh(axes: Dict[str, int],
                devices: Optional[Sequence] = None) -> Mesh:
    """Free-form mesh builder for advanced users (jax-style)."""
    devices = list(devices if devices is not None else jax.devices())
    names = tuple(axes.keys())
    shape = [axes[n] for n in names]
    if int(np.prod(shape)) != len(devices):
        raise ValueError(f"mesh shape {shape} != {len(devices)} devices")
    return Mesh(np.array(devices).reshape(shape), names)
