"""Hybrid-parallel topology -> jax device Mesh.

Reference analog: CommunicateTopology / HybridCommunicateGroup
(python/paddle/distributed/fleet/base/topology.py:26,50,136) builds the
4-D process grid [dp, pp, sharding, mp] and carves NCCL sub-groups per
axis. TPU-native: the grid IS a jax.sharding.Mesh with named axes; XLA
emits the right ICI/DCN collectives from shardings, so "sub-groups" are
just axis names. Axis order follows the scaling-book recipe: put the
highest-traffic axis (mp/tp) innermost so it rides ICI neighbors; dp/pp
outermost so their collectives tolerate DCN (the ProcessGroupHeter
hierarchy, ProcessGroupHeter.h:128-134, falls out of this ordering for
free on multi-slice).

Axes: dp (data), sharding (ZeRO), pp (pipeline), sp (sequence/context —
NEW capability, absent in the reference per SURVEY §5), ep (expert), mp
(tensor). Degenerate axes (degree 1) are kept in the mesh so specs are
uniform.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# canonical axis order, outermost (DCN-tolerant) -> innermost (ICI-hungry)
AXIS_ORDER = ("dp", "sharding", "pp", "sp", "ep", "mp")


def _slice_groups(devices) -> List[List]:
    """Group devices by TPU slice (multi-slice pods expose
    `slice_index` on each device; anything else is one group)."""
    groups: Dict = {}
    for d in devices:
        key = getattr(d, "slice_index", None)
        groups.setdefault(key if key is not None else 0, []).append(d)
    return [groups[k] for k in sorted(groups)]


def create_hybrid_device_mesh(degrees: Dict[str, int],
                              devices: Optional[Sequence] = None,
                              slices: Optional[Sequence[Sequence]] = None,
                              dcn_axis: str = "dp") -> Mesh:
    """DCN-aware mesh: `dcn_axis` (dp by default) is the ONLY axis that
    crosses slice boundaries; every other axis lives inside one slice so
    its collectives ride ICI. This is the explicit analog of the
    reference's hierarchical ProcessGroupHeter (inner NCCL ring per node
    + outer Gloo ring across nodes, ProcessGroupHeter.h:128-134): here
    the inner ring is an ICI slice and the outer ring is DCN.

    `slices` overrides slice discovery (testing / virtual meshes); the
    default groups by each device's `slice_index`.
    """
    devices = list(devices if devices is not None else jax.devices())
    groups = [list(g) for g in slices] if slices is not None \
        else _slice_groups(devices)
    n_slices = len(groups)
    names = [a for a in AXIS_ORDER if a in degrees]
    for a in degrees:
        if a not in AXIS_ORDER:
            raise ValueError(f"unknown mesh axis {a!r} (of {AXIS_ORDER})")
    shape = [degrees[a] for a in names]
    total = int(np.prod(shape))
    if total != len(devices):
        raise ValueError(
            f"degree product {total} != {len(devices)} devices")
    if n_slices == 1:
        return Mesh(np.array(devices).reshape(shape), tuple(names))
    sizes = {len(g) for g in groups}
    if len(sizes) != 1:
        raise ValueError(f"unequal slice sizes {sorted(sizes)}; "
                         "a hybrid mesh needs homogeneous slices")
    dcn_degree = degrees.get(dcn_axis, 1)
    if dcn_degree % n_slices != 0:
        raise ValueError(
            f"{dcn_axis} degree {dcn_degree} must be a multiple of the "
            f"slice count {n_slices} — only {dcn_axis!r} may span DCN; "
            "raise it or fold the other axes into one slice")
    per_slice_dcn = dcn_degree // n_slices
    inner = [degrees[a] for a in names if a != dcn_axis]
    per_slice = per_slice_dcn * int(np.prod(inner)) if inner \
        else per_slice_dcn
    if per_slice != len(groups[0]):
        raise ValueError(
            f"per-slice layout {per_slice} != slice size "
            f"{len(groups[0])} (degrees={degrees}, slices={n_slices})")
    # slice-major along the DCN axis: rows [s*per_dcn, (s+1)*per_dcn)
    # of `dcn_axis` come wholly from slice s, so each non-dcn
    # hyperplane is intra-slice and only dcn-axis collectives cross DCN
    dcn_pos = names.index(dcn_axis)
    blocks = []
    for g in groups:
        block_shape = list(shape)
        block_shape[dcn_pos] = per_slice_dcn
        blocks.append(np.array(g).reshape(block_shape))
    arr = np.concatenate(blocks, axis=dcn_pos)
    return Mesh(arr, tuple(names))


class HybridCommunicateGroup:
    """Builds and owns the device mesh for hybrid parallelism."""

    def __init__(self, dp_degree: int = 1, mp_degree: int = 1,
                 pp_degree: int = 1, sharding_degree: int = 1,
                 sp_degree: int = 1, ep_degree: int = 1,
                 devices: Optional[Sequence] = None,
                 slices: Optional[Sequence[Sequence]] = None):
        devices = list(devices if devices is not None else jax.devices())
        degrees = {"dp": dp_degree, "sharding": sharding_degree,
                   "pp": pp_degree, "sp": sp_degree, "ep": ep_degree,
                   "mp": mp_degree}
        total = int(np.prod(list(degrees.values())))
        if total == 0:
            raise ValueError("degrees must be positive")
        if total != len(devices):
            rest = int(np.prod([degrees[a] for a in AXIS_ORDER
                                if a != "dp"]))
            if degrees["dp"] in (0, 1) and len(devices) % rest == 0:
                # dp left at default: infer it to fill the device count
                degrees["dp"] = len(devices) // rest
                total = len(devices)
            else:
                # an explicitly requested layout that doesn't fit is an
                # error, never silently overridden (paddle raises too)
                raise ValueError(
                    f"degree product {total} != {len(devices)} devices "
                    f"(degrees={degrees}); adjust hybrid_configs")
        self.degrees: Dict[str, int] = degrees
        self.mesh = create_hybrid_device_mesh(
            dict(degrees), devices=devices, slices=slices)

    # --- paddle-parity accessors (fleet/base/topology.py API) -------------
    def get_data_parallel_world_size(self) -> int:
        return self.degrees["dp"]

    def get_model_parallel_world_size(self) -> int:
        return self.degrees["mp"]

    def get_pipe_parallel_world_size(self) -> int:
        return self.degrees["pp"]

    def get_sharding_parallel_world_size(self) -> int:
        return self.degrees["sharding"]

    def get_sequence_parallel_world_size(self) -> int:
        return self.degrees["sp"]

    def get_expert_parallel_world_size(self) -> int:
        return self.degrees["ep"]

    def topology(self):
        return self.degrees

    @property
    def nranks(self) -> int:
        return int(np.prod(list(self.degrees.values())))

    def axis_names(self) -> List[str]:
        return list(AXIS_ORDER)

    def active_axes(self) -> List[str]:
        return [a for a in AXIS_ORDER if self.degrees[a] > 1]

    def __repr__(self):
        return f"HybridCommunicateGroup({self.degrees})"


_GLOBAL_HCG: Optional[HybridCommunicateGroup] = None


def set_hybrid_communicate_group(hcg: HybridCommunicateGroup):
    global _GLOBAL_HCG
    _GLOBAL_HCG = hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _GLOBAL_HCG


def get_mesh() -> Optional[Mesh]:
    return _GLOBAL_HCG.mesh if _GLOBAL_HCG is not None else None


def create_mesh(axes: Dict[str, int],
                devices: Optional[Sequence] = None) -> Mesh:
    """Free-form mesh builder for advanced users (jax-style)."""
    devices = list(devices if devices is not None else jax.devices())
    names = tuple(axes.keys())
    shape = [axes[n] for n in names]
    if int(np.prod(shape)) != len(devices):
        raise ValueError(f"mesh shape {shape} != {len(devices)} devices")
    return Mesh(np.array(devices).reshape(shape), names)
