"""Elastic training manager.

Reference analog: ElasticManager
(python/paddle/distributed/fleet/elastic/manager.py:128) — ranks
register in etcd, the manager watches membership, rewrites the endpoint
env and restarts workers on scale events within [min_np, max_np]
(exit codes 101/102, manager.py:32-33).

TPU-native: membership lives in the launcher's TCPStore (no etcd in the
stack); a scale event means the pod/slice re-formed, so the restarted
job simply resumes from the latest checkpoint — XLA collectives are
re-compiled for the new mesh, there are no endpoint lists to patch.
"""
from __future__ import annotations

import os
import time
from typing import Callable, List, Optional

from ..core import monitor
from .store import TCPStore

ELASTIC_EXIT_CODE = 101
ELASTIC_SCALE_CODE = 102
_PREFIX = "__elastic"


class ElasticManager:
    def __init__(self, store: TCPStore, job_id: str, np_range,
                 host: Optional[str] = None,
                 heartbeat_interval: float = 2.0,
                 heartbeat_timeout: float = 10.0):
        """``np_range`` is (min_np, max_np) — the tolerated node count,
        like the reference's `--np 2:4` syntax."""
        self.store = store
        self.job_id = job_id
        self.min_np, self.max_np = np_range
        self.host = host or f"{os.uname().nodename}-{os.getpid()}"
        self.hb_interval = heartbeat_interval
        self.hb_timeout = heartbeat_timeout
        self._stop = False

    # ---------------------------------------------------------- membership
    def _key(self, host: str) -> str:
        return f"{_PREFIX}/{self.job_id}/nodes/{host}"

    def register(self) -> None:
        # server-clock stamps: cross-host wall clocks may be skewed by
        # more than heartbeat_timeout, so liveness must be judged on one
        # clock — the store server's
        self.store.set_timestamp(self._key(self.host))

    def deregister(self) -> None:
        try:
            self.store.delete(self._key(self.host))
        except (TimeoutError, RuntimeError, OSError) as e:
            # best-effort by design (the job is going down anyway), but
            # never silent: a flaky store at teardown is a signal
            monitor.record_swallowed("elastic.deregister", e)

    def heartbeat(self) -> None:
        self.store.set_timestamp(self._key(self.host))

    def hosts(self) -> List[str]:
        prefix = f"{_PREFIX}/{self.job_id}/nodes/"
        now = self.store.now()
        alive = []
        for k in self.store.keys(prefix):
            try:
                ts = float(self.store.get(k, timeout=1.0))
            except (TimeoutError, RuntimeError):
                continue
            if now - ts <= self.hb_timeout:
                alive.append(k[len(prefix):])
        return sorted(alive)

    # --------------------------------------------------------------- watch
    def watch(self, on_scale: Callable[[List[str]], None],
              poll: float = 0.5,
              max_events: Optional[int] = None) -> None:
        """Heartbeat + watch membership; call ``on_scale(hosts)`` when
        the alive set changes while within [min_np, max_np]. The caller
        typically restarts the training process with exit code 101 so
        the launcher's Controller relaunches against the new mesh."""
        known = self.hosts()
        events = 0
        last_hb = 0.0
        while not self._stop:
            now = time.monotonic()
            if now - last_hb >= self.hb_interval:
                self.heartbeat()
                last_hb = now
            cur = self.hosts()
            if cur != known:
                # track membership even while outside [min_np, max_np]:
                # a dip below min_np followed by the same host rejoining
                # must still fire once the set is viable again
                known = cur
                if self.min_np <= len(cur) <= self.max_np:
                    on_scale(cur)
                    events += 1
                    if max_events is not None and events >= max_events:
                        return
            time.sleep(poll)

    def stop(self) -> None:
        self._stop = True
