"""paddle.distributed.rpc analog — simple worker-to-worker RPC.

Reference: paddle/fluid/distributed/rpc/ (brpc services) +
python/paddle/distributed/rpc/rpc.py (init_rpc / rpc_sync / rpc_async /
shutdown over WorkerInfo). Here: stdlib TCP servers, endpoint discovery
through the rendezvous TCPStore, pickled callables — host-side control
plane only (tensor traffic belongs to XLA collectives, not RPC).
"""
from __future__ import annotations

import hmac
import hashlib
import pickle
import socket
import socketserver
import struct
import threading
import traceback
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass
from typing import Dict, List, Optional

from .store import TCPStore, _recv_exact, free_port


# RPC is a host-side control plane; cap frames so an unauthenticated
# peer can't force multi-GiB buffering before the HMAC check rejects it
_MAX_FRAME = 64 << 20


def _send_auth(sock: socket.socket, obj, key: bytes,
               nonce: bytes, direction: bytes) -> None:
    """Frame: u32 length | 32-byte HMAC-SHA256(nonce|dir|payload) |
    payload. The server-chosen per-connection nonce makes captured
    frames worthless on a new connection (no replay), and the
    direction byte stops reflecting a request back as a response."""
    payload = pickle.dumps(obj)
    if len(payload) > _MAX_FRAME:
        raise ValueError(
            f"rpc payload of {len(payload)} bytes exceeds the "
            f"{_MAX_FRAME}-byte frame limit — ship bulk tensors via "
            "collectives, not rpc")
    tag = hmac.new(key, nonce + direction + payload,
                   hashlib.sha256).digest()
    sock.sendall(struct.pack("!I", len(payload)) + tag + payload)


def _recv_auth(sock: socket.socket, key: bytes,
               nonce: bytes, direction: bytes):
    """Verify the HMAC before unpickling — frames from peers that do not
    hold the job's shared secret never reach pickle.loads."""
    (n,) = struct.unpack("!I", _recv_exact(sock, 4))
    if n > _MAX_FRAME:
        raise ConnectionError("rpc frame exceeds size limit")
    tag = _recv_exact(sock, 32)
    payload = _recv_exact(sock, n)
    want = hmac.new(key, nonce + direction + payload,
                    hashlib.sha256).digest()
    if not hmac.compare_digest(tag, want):
        raise ConnectionError("rpc frame failed HMAC authentication")
    return pickle.loads(payload)

# process-global like the reference (rpc state must be visible from any
# thread — remote handlers doing nested rpc run on server threads)
_RPC_STATE: Dict[str, object] = {}


def _host_ip(peer_host: str = "8.8.8.8") -> str:
    """The address other hosts can reach this process at: the source IP
    of a (connectionless) route toward the store/master."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect((peer_host, 1))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


@dataclass(frozen=True)
class WorkerInfo:
    name: str
    rank: int
    ip: str
    port: int


class _RpcServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class _RpcHandler(socketserver.BaseRequestHandler):
    def handle(self):
        import secrets
        key = self.server.auth_key  # type: ignore[attr-defined]
        try:
            nonce = secrets.token_bytes(16)
            self.request.sendall(nonce)
            fn, args, kwargs = _recv_auth(self.request, key, nonce, b"q")
            try:
                result = fn(*args, **kwargs)
                _send_auth(self.request, ("ok", result), key, nonce, b"p")
            except Exception:
                _send_auth(self.request,
                           ("error", traceback.format_exc()),
                           key, nonce, b"p")
        except (ConnectionError, OSError, pickle.PickleError,
                struct.error):
            return


class _Rpc:
    def __init__(self, name: str, rank: int, world_size: int,
                 store: TCPStore):
        self.name, self.rank, self.world_size = name, rank, world_size
        self.store = store
        # Shared job secret: PADDLE_RPC_SECRET env if the launcher set
        # one (never touches the wire), else rank 0 generates one and
        # publishes it through the store for the duration of init only
        # (the store rides the launch-time trusted rendezvous network;
        # rank 0 deletes the key right after the init barrier). Every
        # RPC frame is HMAC-authenticated with it before unpickling.
        import os as _os
        import secrets as _secrets
        env_secret = _os.environ.get("PADDLE_RPC_SECRET")
        if rank == 0:
            if env_secret:
                self.auth_key = env_secret.encode()
                store.set("__rpc/secret", b"__ENV__")
            else:
                self.auth_key = _secrets.token_bytes(32)
                store.set("__rpc/secret", self.auth_key)
        else:
            published = store.get("__rpc/secret")
            if published == b"__ENV__":
                if not env_secret:
                    raise RuntimeError(
                        "rank 0 was launched with PADDLE_RPC_SECRET "
                        "but this rank's environment lacks it — export "
                        "the same secret on every host")
                self.auth_key = env_secret.encode()
            else:
                if env_secret and env_secret.encode() != published:
                    raise RuntimeError(
                        "this rank has PADDLE_RPC_SECRET set but rank "
                        "0 does not — export the same secret on every "
                        "host (or on none)")
                self.auth_key = published
        # bind all interfaces, advertise the cross-host-reachable address
        # (route toward the master/store host)
        self.server = _RpcServer(("0.0.0.0", 0), _RpcHandler)
        self.server.auth_key = self.auth_key  # type: ignore[attr-defined]
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()
        self.pool = ThreadPoolExecutor(max_workers=8)
        ip = "127.0.0.1" if store.host in ("127.0.0.1", "localhost") \
            else _host_ip(store.host)
        info = WorkerInfo(name, rank, ip, self.port)
        store.set(f"__rpc/worker/{name}", info)
        store.set(f"__rpc/rank/{rank}", name)
        store.barrier("rpc_init", world_size)
        if rank == 0:
            # narrow the secret's exposure window to init only
            try:
                store.delete("__rpc/secret")
            except Exception:
                pass
        self.workers: Dict[str, WorkerInfo] = {}
        for r in range(world_size):
            wname = store.get(f"__rpc/rank/{r}")
            self.workers[wname] = store.get(f"__rpc/worker/{wname}")

    def call(self, to: str, fn, args, kwargs, timeout: float):
        info = self.workers[to]
        with socket.create_connection((info.ip, info.port),
                                      timeout=timeout) as s:
            nonce = _recv_exact(s, 16)
            _send_auth(s, (fn, args, kwargs), self.auth_key, nonce, b"q")
            status, val = _recv_auth(s, self.auth_key, nonce, b"p")
        if status == "error":
            raise RuntimeError(f"rpc to {to!r} failed:\n{val}")
        return val

    def shutdown(self):
        self.store.barrier("rpc_shutdown", self.world_size)
        self.server.shutdown()
        self.server.server_close()
        self.pool.shutdown(wait=False)


def init_rpc(name: str, rank: Optional[int] = None,
             world_size: Optional[int] = None,
             master_endpoint: Optional[str] = None,
             store: Optional[TCPStore] = None) -> None:
    import os
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0")) \
        if rank is None else rank
    world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1")) \
        if world_size is None else world_size
    if store is None:
        # NOTE: PADDLE_MASTER is where init_parallel_env binds the jax
        # coordination service — the rpc store must NOT reuse that port
        # (EADDRINUSE on rank 0). Default to master port + 1, override
        # with PADDLE_RPC_MASTER / master_endpoint.
        ep = master_endpoint or os.environ.get("PADDLE_RPC_MASTER")
        if ep is None:
            base = os.environ.get("PADDLE_MASTER")
            if base:
                host, port = base.rsplit(":", 1)
                ep = f"{host}:{int(port) + 1}"
            else:
                ep = f"127.0.0.1:{free_port()}"
        host, port = ep.rsplit(":", 1)
        store = TCPStore(host, int(port), is_master=(rank == 0))
    _RPC_STATE["rpc"] = _Rpc(name, rank, world_size, store)


def _rpc() -> _Rpc:
    rpc = _RPC_STATE.get("rpc")
    if rpc is None:
        raise RuntimeError("call paddle_tpu.distributed.rpc.init_rpc first")
    return rpc


def rpc_sync(to: str, fn, args=(), kwargs=None, timeout: float = 60.0):
    return _rpc().call(to, fn, args, kwargs or {}, timeout)


def rpc_async(to: str, fn, args=(), kwargs=None,
              timeout: float = 60.0) -> Future:
    rpc = _rpc()
    return rpc.pool.submit(rpc.call, to, fn, args, kwargs or {}, timeout)


def get_worker_info(name: Optional[str] = None) -> WorkerInfo:
    rpc = _rpc()
    if name is None:
        return rpc.workers[rpc.name]
    return rpc.workers[name]


def get_all_worker_infos() -> List[WorkerInfo]:
    return list(_rpc().workers.values())


def get_current_worker_info() -> WorkerInfo:
    return get_worker_info()


def shutdown() -> None:
    rpc = _RPC_STATE.pop("rpc", None)
    if rpc is not None:
        rpc.shutdown()
