"""paddle.distributed.spawn analog — fork/spawn-based in-script launch.

Reference: python/paddle/distributed/spawn.py:482 — start `nprocs`
python processes running `func(*args)` with the parallel env prepared,
as the no-CLI alternative to `paddle.distributed.launch`.
"""
from __future__ import annotations

import multiprocessing as mp
import os
import traceback
from typing import Optional, Sequence

from .env_contract import build_rank_env
from .store import free_port


def _worker(func, args, rank, nprocs, master, backend, err_q):
    os.environ.update(build_rank_env(rank, nprocs, rank, master))
    if backend == "cpu":
        # virtual-CPU testing path: one CPU device per process
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        func(*args)
    except Exception:
        err_q.put((rank, traceback.format_exc()))
        raise


class SpawnContext:
    def __init__(self, procs, err_q):
        self.processes = procs
        self._err_q = err_q

    def join(self, timeout: Optional[float] = None) -> bool:
        """Wait for all children; if any child fails while siblings are
        still blocked (e.g. on the rendezvous), terminate the siblings
        so the failure surfaces instead of hanging (reference spawn.py
        does the same)."""
        import time as _time
        deadline = None if timeout is None else _time.monotonic() + timeout
        while True:
            codes = [p.exitcode for p in self.processes]
            if any(c not in (None, 0) for c in codes):
                for p in self.processes:
                    if p.is_alive():
                        p.terminate()
                for p in self.processes:
                    p.join(10.0)
                break
            if all(c == 0 for c in codes):
                break
            if deadline is not None and _time.monotonic() >= deadline:
                return False
            _time.sleep(0.05)
        bad = [p for p in self.processes if p.exitcode != 0]
        if bad:
            msg = ""
            while not self._err_q.empty():
                rank, tb = self._err_q.get_nowait()
                msg += f"\n----- rank {rank} -----\n{tb}"
            raise RuntimeError(
                f"{len(bad)} spawned process(es) failed "
                f"(exitcodes {[p.exitcode for p in bad]}){msg}")
        return True


def spawn(func, args: Sequence = (), nprocs: int = 1, join: bool = True,
          master: Optional[str] = None,
          backend: Optional[str] = None) -> SpawnContext:
    """Run ``func(*args)`` in ``nprocs`` fresh processes with the
    parallel env set. Uses the 'spawn' start method so each child gets
    its own un-initialized jax backend."""
    master = master or f"127.0.0.1:{free_port()}"
    ctx = mp.get_context("spawn")
    err_q = ctx.Queue()
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_worker,
                        args=(func, args, rank, nprocs, master,
                              backend, err_q),
                        daemon=False)
        p.start()
        procs.append(p)
    sc = SpawnContext(procs, err_q)
    if join:
        sc.join()
    return sc
