"""Gradient clipping (≈ python/paddle/fluid/clip.py: ClipGradByValue,
ClipGradByNorm, ClipGradByGlobalNorm). Clips operate on lists of raw arrays
so they work both eagerly and inside jitted train steps. The TP-aware
variant (global norm psum over model-parallel axis) lives in
distributed/fleet — see HybridParallelClipGrad analog."""
from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp


class ClipGradBase:
    def __call__(self, grads: List[jax.Array]) -> List[jax.Array]:
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def __call__(self, grads):
        return [jnp.clip(g, self.min, self.max) for g in grads]


class ClipGradByNorm(ClipGradBase):
    """Per-tensor L2 clip."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, grads):
        out = []
        for g in grads:
            norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12),
                                1.0)
            out.append((g.astype(jnp.float32) * scale).astype(g.dtype))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Global L2 clip across all grads (the default for LLM training)."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)

    def global_norm(self, grads):
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads)
        return jnp.sqrt(sq)

    def __call__(self, grads):
        gnorm = self.global_norm(grads)
        scale = jnp.minimum(self.clip_norm / jnp.maximum(gnorm, 1e-12), 1.0)
        return [(g.astype(jnp.float32) * scale).astype(g.dtype)
                for g in grads]
