from . import lr  # noqa: F401
from .grad_clip import (ClipGradByGlobalNorm, ClipGradByNorm,  # noqa: F401
                        ClipGradByValue)
from .optimizer import Optimizer  # noqa: F401
from .optimizers import (SGD, Adagrad, Adam, Adamax, AdamW, Lamb,  # noqa: F401
                         Momentum, RMSProp)
