"""Optimizer base.

Reference analog: python/paddle/optimizer/optimizer.py:101 (`class
Optimizer`) — parameter groups, LR scheduler integration, grad clip,
`step`/`clear_grad`, state_dict. TPU-first difference: every optimizer
defines ONE pure update rule `_update(param, grad, state, lr) ->
(new_param, new_state)`; `step()` applies it eagerly to `.grad`s (dygraph
UX), while `apply_gradients()` applies it functionally over pytrees inside
a jitted train step (the perf path — one fused XLA program, which is what
the reference's fused Adam kernels approximate by hand:
phi/kernels/gpu/adamw_kernel.cu)."""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..core.tensor import Parameter, Tensor
from .lr import LRScheduler

_UID_COUNTER = iter(range(1, 1 << 62))


def opt_key(p) -> int:
    """Stable per-Parameter state key. `id(p)` would alias if a
    Parameter is garbage-collected and a new one lands at the same
    address (VERDICT r1 weak #5); a monotonically-assigned uid stored
    on the tensor never reuses."""
    uid = getattr(p, "_uid", None)
    if uid is None:
        uid = next(_UID_COUNTER)
        p._uid = uid
    return uid


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 multi_precision: bool = False):
        self._parameter_list = list(parameters) if parameters is not None \
            else None
        self._lr = learning_rate
        self._grad_clip = grad_clip
        self._weight_decay = weight_decay if not isinstance(weight_decay,
                                                            (int, float)) \
            else float(weight_decay)
        self.multi_precision = multi_precision
        # state: opt_key(param) -> dict of jax arrays; + global step count
        self._state: Dict[int, Dict[str, Any]] = {}
        self._step_count = 0

    # ------------------------------------------------------------- LR
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return float(self._lr())
        return float(self._lr)

    def set_lr(self, value: float):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("can't set_lr when using an LRScheduler")
        self._lr = float(value)

    @property
    def _learning_rate(self):
        return self._lr

    # ------------------------------------------------------------- rule
    def _init_state(self, param_shape, param_dtype) -> Dict[str, Any]:
        return {}

    def init_state_for(self, param_value) -> Dict[str, Any]:
        """State for one param, with value-dependent slots (fp32 master
        weights) materialized eagerly so the state pytree structure is
        stable across steps (a lazily-filled None would retrigger jit
        compilation on step 2)."""
        arr = param_value.data if isinstance(param_value, Tensor) \
            else param_value
        st = self._init_state(arr.shape, arr.dtype)
        if "master" in st and st["master"] is None:
            st["master"] = arr.astype(jnp.float32)
        return st

    def _update(self, p, g, state: Dict[str, Any], lr, step):
        """Pure update rule on raw arrays. Returns (new_p, new_state)."""
        raise NotImplementedError

    def _decay_coeff(self) -> float:
        """L2-style decay folded into the update (AdamW overrides to apply
        decoupled decay; plain L2 regularization adds to grad)."""
        return 0.0

    # ------------------------------------------------------------- dygraph
    def step(self):
        if self._parameter_list is None:
            raise RuntimeError("Optimizer was constructed without "
                               "parameters; use apply_gradients instead")
        params = [p for p in self._parameter_list
                  if isinstance(p, Parameter) and p.trainable]
        grads = [p.grad for p in params]
        live = [(p, g) for p, g in zip(params, grads) if g is not None]
        if not live:
            return
        if self._grad_clip is not None:
            clipped = self._grad_clip([g.data for _, g in live])
            live = [(p, Tensor(g)) for (p, _), g in zip(live, clipped)]
        lr = self.get_lr()
        self._step_count += 1
        for p, g in live:
            garr = g.data.astype(p.data.dtype) if g.data.dtype != p.data.dtype \
                else g.data
            garr = self._apply_decay(garr, p.data,
                                     getattr(p, "regularizer", None))
            sid = opt_key(p)
            if sid not in self._state:
                self._state[sid] = self._init_state(p.data.shape,
                                                    p.data.dtype)
            new_p, new_state = self._update(p.data, garr, self._state[sid],
                                            lr, self._step_count)
            p._replace_data(new_p)
            self._state[sid] = new_state
        if isinstance(self._lr, LRScheduler) and self._lr._step_each_iter:
            self._lr.step()

    def _decoupled_decay(self) -> bool:
        return False

    def _apply_decay(self, garr, parr, reg=None):
        """Fold weight decay into the gradient: a per-parameter
        regularizer (ParamAttr(regularizer=...)) takes precedence over
        the optimizer-level weight_decay, matching the reference; a
        float coeff is classic L2-style coupled decay (skipped by
        decoupled optimizers, i.e. AdamW); L1Decay/L2Decay objects
        (paddle_tpu.regularizer) are applied as grad terms the way the
        reference's regularizer appends them."""
        if reg is not None:
            return reg(garr, parr)
        wd = self._weight_decay
        if callable(wd) and not isinstance(wd, float):
            return wd(garr, parr)
        if isinstance(wd, float) and wd and not self._decoupled_decay():
            return garr + wd * parr
        return garr

    def _param_regularizers(self, leaves):
        """Per-leaf regularizer list for the functional update path.
        When every leaf is one of the optimizer's own Tensor objects the
        match is by identity — immune to params trees whose flatten
        order differs from _parameter_list (dict-keyed trees, reordered
        lists). Raw-array leaves fall back to positional alignment,
        which REQUIRES the tree to flatten in _parameter_list order;
        a count mismatch raises rather than silently training the
        jitted path differently from eager opt.step()."""
        plist = self._parameter_list
        if plist is None:
            return None
        by_id = {id(p): getattr(p, "regularizer", None) for p in plist}
        if not any(r is not None for r in by_id.values()):
            return None
        if all(isinstance(p, Tensor) and id(p) in by_id for p in leaves):
            return [by_id[id(p)] for p in leaves]
        if len(plist) != len(leaves):
            raise ValueError(
                f"per-parameter regularizers are set but the functional "
                f"update received {len(leaves)} params vs the optimizer's "
                f"{len(plist)} — construct the optimizer with the same "
                f"parameter list the train step uses (e.g. "
                f"model.parameters()) so they can be matched")
        return [getattr(p, "regularizer", None) for p in plist]

    def clear_grad(self):
        if self._parameter_list is not None:
            for p in self._parameter_list:
                p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        # static mode: append backward + update ops to the current Program
        # (≈ Optimizer.minimize appending ops via append_backward +
        # _append_optimize_op in python/paddle/optimizer/optimizer.py)
        from ..static.program import (Variable, append_backward,
                                      append_optimizer, in_static_build)
        if in_static_build() and isinstance(loss, Variable):
            prog = loss._static_program
            plist = parameters if parameters is not None else \
                self._parameter_list
            names = None
            if plist is not None:
                # map eager Parameter objects to their captured var names
                names = []
                for p in plist:
                    if isinstance(p, str):
                        names.append(p)
                    else:
                        n = prog._param_ids.get(id(p))
                        if n is not None:
                            names.append(n)
                names = names or None
            params_grads = append_backward(loss, parameter_list=names,
                                           no_grad_set=no_grad_set)
            append_optimizer(self, params_grads)
            return None, params_grads
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    # ------------------------------------------------------------- functional
    def init_state_tree(self, params_tree):
        """Build the optimizer state pytree for a params pytree (functional
        path; shapes mirror params)."""
        return jax.tree_util.tree_map(
            lambda p: self._init_state(jnp.shape(p), jnp.asarray(p).dtype
                                       if not hasattr(p, "dtype") else p.dtype),
            params_tree,
            is_leaf=lambda x: isinstance(x, (jax.Array, Tensor)))

    def apply_gradients(self, params_tree, grads_tree, state_tree,
                        lr=None, step=None):
        """Pure functional update: returns (new_params, new_state). Safe to
        call inside jit; `lr`/`step` may be traced scalars."""
        lr = self.get_lr() if lr is None else lr
        step = (self._step_count + 1) if step is None else step
        if self._grad_clip is not None:
            leaves, treedef = jax.tree_util.tree_flatten(grads_tree)
            leaves = self._grad_clip(leaves)
            grads_tree = jax.tree_util.tree_unflatten(treedef, leaves)

        p_leaves, p_def = jax.tree_util.tree_flatten(
            params_tree, is_leaf=lambda x: isinstance(x, Tensor))
        g_leaves = jax.tree_util.tree_leaves(
            grads_tree, is_leaf=lambda x: isinstance(x, Tensor))
        s_leaves = jax.tree_util.tree_leaves(
            state_tree, is_leaf=lambda x: isinstance(x, dict))
        regs = self._param_regularizers(p_leaves)
        new_p, new_s = [], []
        for i, (p, g, s) in enumerate(zip(p_leaves, g_leaves, s_leaves)):
            parr = p.data if isinstance(p, Tensor) else p
            garr = g.data if isinstance(g, Tensor) else g
            if garr.dtype != parr.dtype:
                garr = garr.astype(parr.dtype)
            garr = self._apply_decay(garr, parr,
                                     regs[i] if regs else None)
            np_, ns_ = self._update(parr, garr, s, lr, step)
            new_p.append(np_)
            new_s.append(ns_)
        return (jax.tree_util.tree_unflatten(p_def, new_p),
                jax.tree_util.tree_unflatten(p_def, new_s))

    # ------------------------------------------------------------- state io
    def state_dict(self) -> Dict[str, Any]:
        sd: Dict[str, Any] = {"_step_count": self._step_count}
        if self._parameter_list is not None:
            import numpy as np
            for i, p in enumerate(self._parameter_list):
                st = self._state.get(opt_key(p))
                if st:
                    sd[f"param_{i}"] = {k: np.asarray(v)
                                        for k, v in st.items()}
        if isinstance(self._lr, LRScheduler):
            sd["LR_Scheduler"] = self._lr.state_dict()
        return sd

    def set_state_dict(self, state_dict: Dict[str, Any]):
        self._step_count = int(state_dict.get("_step_count", 0))
        if self._parameter_list is not None:
            for i, p in enumerate(self._parameter_list):
                key = f"param_{i}"
                if key in state_dict:
                    self._state[opt_key(p)] = {
                        k: jnp.asarray(v)
                        for k, v in state_dict[key].items()}
        if isinstance(self._lr, LRScheduler) and "LR_Scheduler" in state_dict:
            self._lr.set_state_dict(state_dict["LR_Scheduler"])
