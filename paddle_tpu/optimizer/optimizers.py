"""Concrete optimizers (≈ python/paddle/optimizer/{sgd,momentum,adam,adamw,
lamb,...}.py; fused GPU kernels phi/kernels/gpu/{adam,adamw,lamb}_kernel.cu).
Each is one pure `_update` rule; XLA fuses the whole step."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .optimizer import Optimizer


def _zeros(shape, dtype):
    """Moment-buffer zeros built on HOST and device_put: a relaunch
    initializes dozens of these, and ``jnp.zeros`` compiles one tiny
    broadcast program per distinct shape (~150ms of XLA across a
    test-tiny AdamW state on a cold jit cache — measured on the
    ISSUE-9 warm-restart path); device_put of a host buffer skips XLA
    entirely. Under tracing (eval_shape / audit) the constant stays
    abstract — numerics unchanged."""
    return jax.device_put(np.zeros(shape, np.dtype(dtype)))


def _full(shape, value, dtype):
    return jax.device_put(np.full(shape, value, np.dtype(dtype)))


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision=multi_precision)

    def _init_state(self, shape, dtype):
        if self.multi_precision and jnp.dtype(dtype) in (
                jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16)):
            return {"master": None}  # filled lazily from the param
        return {}

    def _update(self, p, g, state, lr, step):
        if "master" in state:
            master = state["master"] if state["master"] is not None \
                else p.astype(jnp.float32)
            master = master - lr * g.astype(jnp.float32)
            return master.astype(p.dtype), {"master": master}
        return p - lr * g, state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None, multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision=multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_state(self, shape, dtype):
        st = {"velocity": _zeros(shape, jnp.float32)}
        if self.multi_precision and jnp.dtype(dtype) in (
                jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16)):
            st["master"] = None  # filled lazily from the param
        return st

    def _update(self, p, g, state, lr, step):
        v = self._momentum * state["velocity"] + g.astype(jnp.float32)
        upd = lr * ((g.astype(jnp.float32) + self._momentum * v)
                    if self._nesterov else v)
        new_state = {"velocity": v}
        if "master" in state:
            master = state["master"] if state["master"] is not None \
                else p.astype(jnp.float32)
            master = master - upd
            new_state["master"] = master
            return master.astype(p.dtype), new_state
        return (p.astype(jnp.float32) - upd).astype(p.dtype), new_state


class Adagrad(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _init_state(self, shape, dtype):
        return {"moment": _full(shape, self._init_acc, dtype)}

    def _update(self, p, g, state, lr, step):
        m = state["moment"] + jnp.square(g)
        return p - lr * g / (jnp.sqrt(m) + self._epsilon), {"moment": m}


class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, rho=0.95, epsilon=1e-6,
                 momentum=0.0, centered=False, parameters=None,
                 weight_decay=None, grad_clip=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_state(self, shape, dtype):
        st = {"mean_square": _zeros(shape, dtype),
              "momentum": _zeros(shape, dtype)}
        if self._centered:
            st["mean_grad"] = _zeros(shape, dtype)
        return st

    def _update(self, p, g, state, lr, step):
        ms = self._rho * state["mean_square"] + (1 - self._rho) * jnp.square(g)
        new = {"mean_square": ms}
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * g
            denom = jnp.sqrt(ms - jnp.square(mg) + self._epsilon)
            new["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * state["momentum"] + lr * g / denom
        new["momentum"] = mom
        return p - mom, new


class _AdamBase(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision=multi_precision)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _init_state(self, shape, dtype):
        # master weights: keep moments (and fp32 master param when the param
        # itself is low precision) in fp32 — the reference's multi_precision
        # path (phi/kernels/gpu/adamw_kernel.cu master-weight arguments)
        mdtype = jnp.float32
        st = {"moment1": _zeros(shape, mdtype),
              "moment2": _zeros(shape, mdtype)}
        if self.multi_precision and jnp.dtype(dtype) in (
                jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16)):
            st["master"] = None  # filled lazily from the param on first step
        return st

    def _adam_m_v(self, g, state, step):
        g32 = g.astype(jnp.float32)
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * jnp.square(g32)
        mhat = m / (1 - self._beta1 ** step)
        vhat = v / (1 - self._beta2 ** step)
        return m, v, mhat, vhat


class Adam(_AdamBase):
    def _update(self, p, g, state, lr, step):
        m, v, mhat, vhat = self._adam_m_v(g, state, step)
        new_state = {"moment1": m, "moment2": v}
        if "master" in state:
            master = state["master"] if state["master"] is not None \
                else p.astype(jnp.float32)
            master = master - lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
            new_state["master"] = master
            return master.astype(p.dtype), new_state
        upd = lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        return (p.astype(jnp.float32) - upd).astype(p.dtype), new_state


class AdamW(_AdamBase):
    """Decoupled weight decay (Loshchilov & Hutter), ≈ paddle.optimizer.AdamW
    (python/paddle/optimizer/adamw.py; decay applied multiplicatively to the
    param before the adam update, coeff default 0.01)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 apply_decay_param_fun=None, lr_ratio=None, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         float(weight_decay), grad_clip,
                         multi_precision=multi_precision)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decoupled_decay(self):
        return True

    def _update(self, p, g, state, lr, step):
        m, v, mhat, vhat = self._adam_m_v(g, state, step)
        new_state = {"moment1": m, "moment2": v}
        wd = self._weight_decay or 0.0
        if "master" in state:
            master = state["master"] if state["master"] is not None \
                else p.astype(jnp.float32)
            master = master * (1.0 - lr * wd)
            master = master - lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
            new_state["master"] = master
            return master.astype(p.dtype), new_state
        p32 = p.astype(jnp.float32)
        p32 = p32 * (1.0 - lr * wd)
        p32 = p32 - lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        return p32.astype(p.dtype), new_state


class Adamax(_AdamBase):
    def _init_state(self, shape, dtype):
        return {"moment": _zeros(shape, jnp.float32),
                "inf_norm": _zeros(shape, jnp.float32)}

    def _update(self, p, g, state, lr, step):
        g32 = g.astype(jnp.float32)
        m = self._beta1 * state["moment"] + (1 - self._beta1) * g32
        u = jnp.maximum(self._beta2 * state["inf_norm"], jnp.abs(g32))
        upd = lr / (1 - self._beta1 ** step) * m / (u + self._epsilon)
        return (p.astype(jnp.float32) - upd).astype(p.dtype), \
            {"moment": m, "inf_norm": u}


class Lamb(_AdamBase):
    """Layer-wise adaptive moments (≈ paddle.optimizer.Lamb,
    phi/kernels/gpu/lamb_kernel.cu)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip)
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _update(self, p, g, state, lr, step):
        m, v, mhat, vhat = self._adam_m_v(g, state, step)
        p32 = p.astype(jnp.float32)
        r = mhat / (jnp.sqrt(vhat) + self._epsilon) + self._lamb_wd * p32
        w_norm = jnp.linalg.norm(p32)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return (p32 - lr * trust * r).astype(p.dtype), \
            {"moment1": m, "moment2": v}
