"""paddle.sparse.nn analog (≈ python/paddle/sparse/nn/) — layer-style
wrappers over sparse functional ops."""
from __future__ import annotations

from . import unary

__all__ = ["ReLU", "Softmax"]


class ReLU:
    def __call__(self, x):
        return unary.relu(x)


class Softmax:
    """Row-wise softmax over stored values (csr rows; reference
    sparse/nn/functional/activation.py softmax)."""

    def __init__(self, axis: int = -1):
        if axis != -1:
            raise ValueError("sparse softmax supports axis=-1 only")

    def __call__(self, x):
        import jax.numpy as jnp
        from jax.experimental import sparse as jsparse
        from .creation import SparseCsrTensor
        dense = x._mat.todense()
        # softmax over non-zero entries per row, zeros stay zero
        mask = dense != 0
        neg_inf = jnp.where(mask, dense, -jnp.inf)
        sm = jnp.exp(neg_inf - neg_inf.max(-1, keepdims=True))
        sm = jnp.where(mask, sm, 0)
        sm = sm / jnp.clip(sm.sum(-1, keepdims=True), 1e-30, None)
        coo = jsparse.BCOO.fromdense(sm)
        if isinstance(x, SparseCsrTensor):
            return SparseCsrTensor(jsparse.BCSR.from_bcoo(coo))
        from .creation import SparseCooTensor
        return SparseCooTensor(coo)
