"""paddle.sparse.nn analog (≈ python/paddle/sparse/nn/) — layer-style
wrappers over sparse functional ops.

r5 adds the 3-D sparse layer family (reference
python/paddle/sparse/nn/layer/conv.py:133 Conv3D, :268 SubmConv3D,
norm.py:23 BatchNorm, pooling.py:19 MaxPool3D): convolutions run as
dense MXU matmuls per kernel offset over gathered active sites (see
nn_functional), BatchNorm normalizes the [nnz, C] value rows with the
dense BatchNorm1D machinery — the reference's own formulation.
"""
from __future__ import annotations

import math

from . import nn_functional as functional  # noqa: F401  (sparse.nn.functional)
from . import unary
from .creation import SparseCooTensor
from ..nn import BatchNorm1D as _BatchNorm1D, Layer as _Layer

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax", "Conv3D",
           "SubmConv3D", "BatchNorm", "SyncBatchNorm", "MaxPool3D",
           "functional"]


class ReLU:
    def __call__(self, x):
        return unary.relu(x)


class ReLU6:
    def __call__(self, x):
        return functional.relu6(x)


class LeakyReLU:
    def __init__(self, negative_slope=0.01):
        self._slope = negative_slope

    def __call__(self, x):
        return functional.leaky_relu(x, self._slope)


class Softmax:
    """Row-wise softmax over stored values (csr rows; reference
    sparse/nn/functional/activation.py softmax)."""

    def __init__(self, axis: int = -1):
        if axis != -1:
            raise ValueError("sparse softmax supports axis=-1 only")

    def __call__(self, x):
        import jax.numpy as jnp
        from jax.experimental import sparse as jsparse
        from .creation import SparseCsrTensor
        dense = x._mat.todense()
        # softmax over non-zero entries per row, zeros stay zero
        mask = dense != 0
        neg_inf = jnp.where(mask, dense, -jnp.inf)
        sm = jnp.exp(neg_inf - neg_inf.max(-1, keepdims=True))
        sm = jnp.where(mask, sm, 0)
        sm = sm / jnp.clip(sm.sum(-1, keepdims=True), 1e-30, None)
        coo = jsparse.BCOO.fromdense(sm)
        if isinstance(x, SparseCsrTensor):
            return SparseCsrTensor(jsparse.BCSR.from_bcoo(coo))
        return SparseCooTensor(coo)


class _Conv3D(_Layer):
    """Shared sparse Conv3D/SubmConv3D body: a real framework Layer, so
    state_dict/named_parameters/optimizers and weight_attr/bias_attr
    behave exactly like the dense convs. Weight layout
    [kd, kh, kw, C_in, C_out] (the reference's NDHWC layout,
    sparse/nn/layer/conv.py:97)."""

    _subm = False

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 key=None):
        super().__init__()
        if padding_mode != "zeros":
            raise ValueError("only padding_mode='zeros' is supported "
                             "(the reference has the same restriction)")
        if groups != 1:
            raise ValueError("only groups=1 is supported")
        if data_format != "NDHWC":
            raise ValueError("only NDHWC is supported")
        from ..nn import initializer as I
        ks = functional._triple(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = ks
        fan_in = in_channels * ks[0] * ks[1] * ks[2]
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            ks + (in_channels, out_channels), attr=weight_attr,
            default_initializer=I.Uniform(-bound, bound))
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        fn = functional.subm_conv3d if self._subm else functional.conv3d
        return fn(x, self.weight, self.bias, stride=self._stride,
                  padding=self._padding, dilation=self._dilation)


class Conv3D(_Conv3D):
    """Sparse 3-D convolution layer (reference
    python/paddle/sparse/nn/layer/conv.py:133)."""
    _subm = False


class SubmConv3D(_Conv3D):
    """Submanifold sparse 3-D convolution layer — output sites equal
    input sites (reference python/paddle/sparse/nn/layer/conv.py:268)."""
    _subm = True


class BatchNorm(_BatchNorm1D):
    """Sparse BatchNorm: a real BatchNorm1D over the [nnz, C] value
    rows, index set unchanged — the reference's own formulation
    (python/paddle/sparse/nn/layer/norm.py:23 calls the dense
    functional on values). Subclassing the dense layer means
    state_dict, running stats, and train/eval behave identically."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None,
                 data_format="NDHWC", use_global_stats=None, name=None):
        if data_format != "NDHWC":
            raise ValueError("sparse BatchNorm supports NDHWC only")
        super().__init__(num_features, momentum=momentum,
                         epsilon=epsilon, weight_attr=weight_attr,
                         bias_attr=bias_attr,
                         use_global_stats=use_global_stats)

    def forward(self, x):
        from jax.experimental import sparse as jsparse
        out_vals = super().forward(x.values())
        mat = x._mat
        new = jsparse.BCOO(
            (out_vals._data, mat.indices), shape=mat.shape,
            indices_sorted=bool(mat.indices_sorted),
            unique_indices=bool(mat.unique_indices))
        return SparseCooTensor(new)


class SyncBatchNorm(BatchNorm):
    """Cross-replica sparse BatchNorm (reference norm.py:231). Under
    GSPMD the value rows are sharded along nnz; the dense batch-norm
    reduction compiles to a global psum over the mesh, so the single
    implementation serves both — this alias exists for API parity."""


class MaxPool3D:
    """Sparse 3-D max pooling layer (reference
    python/paddle/sparse/nn/layer/pooling.py:19)."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 ceil_mode=False, return_mask=False,
                 data_format="NDHWC", name=None):
        if ceil_mode or return_mask:
            raise ValueError("ceil_mode/return_mask are not supported")
        self._ks, self._st, self._pd = kernel_size, stride, padding
        if data_format != "NDHWC":
            raise ValueError("sparse MaxPool3D supports NDHWC only")

    def __call__(self, x):
        return functional.max_pool3d(x, self._ks, stride=self._st,
                                     padding=self._pd)

    forward = __call__
