"""paddle.sparse analog — COO/CSR sparse tensors.

Reference: python/paddle/sparse/ (sparse_coo_tensor/sparse_csr_tensor
creation, unary/binary ops, matmul/masked_matmul, coalesce, nn.ReLU)
backed by phi sparse kernels (paddle/phi/kernels/sparse/,
paddle/phi/core/sparse_coo_tensor.h). TPU-native: jax.experimental.sparse
BCOO/BCSR carry (indices, values) through XLA; TPU kernels densify for
compute-heavy ops (the MXU has no native gather-scatter sparsity), so
sparse here is a memory/IO format with correct semantics, not a FLOP
saver — same trade the reference makes on non-cuSPARSE backends.
"""
from . import nn  # noqa: F401
from .binary import (add, addmm, divide, masked_matmul, matmul, mv,  # noqa: F401
                     multiply, subtract)
from .creation import (SparseCooTensor, SparseCsrTensor,  # noqa: F401
                       sparse_coo_tensor, sparse_csr_tensor)
from .unary import (abs, asin, asinh, atan, atanh, log1p, reshape, transpose,  # noqa: F401
                    cast, coalesce, deg2rad, expm1,
                    is_same_shape, neg, pow, rad2deg, relu, sin, sinh,
                    sqrt, square, tan, tanh)
