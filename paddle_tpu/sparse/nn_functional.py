"""paddle.sparse.nn.functional analog — sparse 3-D conv / pooling.

Reference: python/paddle/sparse/nn/functional/conv.py (conv3d:31,
subm_conv3d:130) and pooling.py (max_pool3d:20), backed by the phi
sparse conv kernels (paddle/phi/kernels/sparse/conv_kernel.h). The
reference gathers rulebook pairs on GPU; the TPU-native formulation
here is the same math expressed as dense MXU work per kernel offset:

    for each of the K^3 kernel offsets:
        map every OUTPUT site to its contributing INPUT site
        (sorted-key binary search over the flattened coordinates),
        gather those value rows -> [n_out, C_in],
        one dense matmul with W[offset] -> accumulate [n_out, C_out].

Index structure (which sites exist, who contributes where) is computed
on the host in numpy — it is data-layout, not math, and stays constant
under autodiff; the value path is pure jnp, so gradients w.r.t. input
values / weight / bias flow through jax.grad. Output index sets are
data-dependent (except submanifold conv), so these ops are eager-only —
the same constraint the reference's dynamic rulebook has.

Layout: NDHWC only (the reference's only supported layout), indices
[nnz, 4] = (n, d, h, w) with dense trailing channels.
"""
from __future__ import annotations

from typing import Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from .creation import SparseCooTensor

__all__ = ["conv3d", "subm_conv3d", "max_pool3d", "relu", "relu6",
           "leaky_relu", "softmax", "attention"]


def _triple(v) -> tuple:
    if isinstance(v, (list, tuple)):
        assert len(v) == 3, f"expected 3 elements, got {v}"
        return tuple(int(x) for x in v)
    return (int(v),) * 3


def _flat(idx: np.ndarray, dims: Sequence[int]) -> np.ndarray:
    """Flatten (n, d, h, w) integer coords to one sortable key."""
    n, d, h, w = idx[:, 0], idx[:, 1], idx[:, 2], idx[:, 3]
    return ((n.astype(np.int64) * dims[0] + d) * dims[1] + h) \
        * dims[2] + w


def _out_dim(size, k, s, p, dil) -> int:
    return (size + 2 * p - dil * (k - 1) - 1) // s + 1


def _check_coo(x, name):
    if not isinstance(x, SparseCooTensor):
        raise TypeError(f"{name} expects a SparseCooTensor, got "
                        f"{type(x).__name__}")
    if len(x.shape) != 5:
        raise ValueError(f"{name} expects a 5-D NDHWC sparse input, "
                         f"got shape {x.shape}")


def _sorted_index(in_idx: np.ndarray, in_dims):
    """Sort the input coordinate keys ONCE per op call (hoisted out of
    the K^3 offset loop — re-sorting per offset multiplies host setup
    cost 27x for a 3-cubed kernel)."""
    keys = _flat(in_idx, in_dims)
    order = np.argsort(keys, kind="stable")
    return keys[order], order


def _gather_rows(sorted_keys, order, in_dims, query: np.ndarray):
    """For each query coord row, the input row index holding it, and a
    found mask (binary search over the pre-sorted flattened keys)."""
    skeys = sorted_keys
    qkeys = _flat(query, in_dims)
    pos = np.searchsorted(skeys, qkeys)
    pos_c = np.minimum(pos, len(skeys) - 1) if len(skeys) else pos * 0
    found = (len(skeys) > 0) & (skeys[pos_c] == qkeys)
    rows = order[pos_c] if len(skeys) else pos_c
    return rows, found


def _conv_out_sites(in_idx, in_dims, out_dims, ks, st, pd, dl):
    """Standard sparse conv output site set: every out site whose
    receptive field touches >= 1 input site (union of shifted inputs)."""
    cands = []
    for kd in range(ks[0]):
        for kh in range(ks[1]):
            for kw in range(ks[2]):
                # i = o*s - p + k*dil  =>  o = (i + p - k*dil) / s
                num = in_idx[:, 1:4] + np.array(pd) \
                    - np.array((kd, kh, kw)) * np.array(dl)
                ok = (num % np.array(st) == 0).all(1)
                o = num // np.array(st)
                ok &= (o >= 0).all(1) & (o < np.array(out_dims)).all(1)
                if ok.any():
                    cands.append(np.concatenate(
                        [in_idx[ok, :1], o[ok]], axis=1))
    if not cands:
        return np.zeros((0, 4), np.int32)
    allc = np.concatenate(cands, axis=0)
    keys = _flat(allc, out_dims)
    _, first = np.unique(keys, return_index=True)
    return allc[first]  # unique() sorts keys -> rows in row-major order


def _sparse_conv3d(x, weight, bias, stride, padding, dilation, subm,
                   name):
    _check_coo(x, name)
    mat = x._mat
    wv = weight._data if hasattr(weight, "_data") else jnp.asarray(weight)
    if wv.ndim != 5:
        raise ValueError(f"{name} weight must be [kd, kh, kw, C_in, "
                         f"C_out], got shape {wv.shape}")
    N, D, H, W, C = mat.shape
    ks = tuple(int(s) for s in wv.shape[:3])
    cin, cout = int(wv.shape[3]), int(wv.shape[4])
    if cin != C:
        raise ValueError(f"{name}: weight C_in {cin} != input C {C}")
    st, pd, dl = _triple(stride), _triple(padding), _triple(dilation)
    in_idx = np.asarray(mat.indices)
    vals = mat.data  # [nnz, C] — jnp, stays differentiable
    in_dims = (D, H, W)
    if subm:
        if st != (1, 1, 1):
            raise ValueError("subm_conv3d requires stride 1 (the output "
                             "index set equals the input's)")
        out_dims, out_idx = in_dims, in_idx
    else:
        out_dims = tuple(_out_dim(s, k, t, p, d) for s, k, t, p, d
                         in zip((D, H, W), ks, st, pd, dl))
        out_idx = _conv_out_sites(in_idx, in_dims, out_dims,
                                  ks, st, pd, dl)
    n_out = len(out_idx)
    skeys, korder = _sorted_index(in_idx, in_dims)
    acc = jnp.zeros((n_out, cout), vals.dtype)
    for kd in range(ks[0]):
        for kh in range(ks[1]):
            for kw in range(ks[2]):
                src = out_idx.copy()
                src[:, 1:4] = out_idx[:, 1:4] * np.array(st) \
                    - np.array(pd) + np.array((kd, kh, kw)) * np.array(dl)
                inb = ((src[:, 1:4] >= 0).all(1)
                       & (src[:, 1:4] < np.array(in_dims)).all(1))
                src_c = np.where(inb[:, None], src, 0)
                rows, found = _gather_rows(skeys, korder, in_dims, src_c)
                found = found & inb
                if not found.any():
                    continue
                g = jnp.take(vals, jnp.asarray(rows), axis=0) \
                    * jnp.asarray(found[:, None], vals.dtype)
                acc = acc + g @ wv[kd, kh, kw].astype(vals.dtype)
    if bias is not None:
        bv = bias._data if hasattr(bias, "_data") else jnp.asarray(bias)
        acc = acc + bv.astype(acc.dtype)
    flags = dict(indices_sorted=bool(mat.indices_sorted),
                 unique_indices=bool(mat.unique_indices)) if subm else \
        dict(indices_sorted=True, unique_indices=True)
    out = jsparse.BCOO((acc, jnp.asarray(out_idx.astype(np.int32))),
                       shape=(N,) + tuple(out_dims) + (cout,), **flags)
    return SparseCooTensor(out)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NDHWC", name=None):
    """Sparse 3-D convolution over a SparseCooTensor [N, D, H, W, C].
    Reference: python/paddle/sparse/nn/functional/conv.py:31."""
    if groups != 1:
        raise ValueError("sparse conv3d supports groups=1 only "
                         "(the reference has the same restriction)")
    if data_format != "NDHWC":
        raise ValueError("sparse conv3d supports NDHWC only")
    return _sparse_conv3d(x, weight, bias, stride, padding, dilation,
                          False, "conv3d")


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """Submanifold sparse conv: the output index set IS the input's —
    no dilation of the active site set through depth, the property that
    keeps sparse 3-D backbones sparse. Reference:
    python/paddle/sparse/nn/functional/conv.py:130."""
    if groups != 1:
        raise ValueError("sparse subm_conv3d supports groups=1 only")
    if data_format != "NDHWC":
        raise ValueError("sparse subm_conv3d supports NDHWC only")
    return _sparse_conv3d(x, weight, bias, stride, padding, dilation,
                          True, "subm_conv3d")


def max_pool3d(x, kernel_size, stride=None, padding=0,
               data_format="NDHWC", name=None):
    """Sparse 3-D max pooling: output sites are the conv-style site
    union; each pools the max over PRESENT inputs in its window (absent
    sites do not contribute zeros — reference
    python/paddle/sparse/nn/functional/pooling.py:20 semantics)."""
    _check_coo(x, "max_pool3d")
    if data_format != "NDHWC":
        raise ValueError("sparse max_pool3d supports NDHWC only")
    mat = x._mat
    N, D, H, W, C = mat.shape
    ks = _triple(kernel_size)
    st = _triple(stride) if stride is not None else ks
    pd = _triple(padding)
    dl = (1, 1, 1)
    in_idx = np.asarray(mat.indices)
    vals = mat.data
    in_dims = (D, H, W)
    out_dims = tuple(_out_dim(s, k, t, p, 1) for s, k, t, p
                     in zip((D, H, W), ks, st, pd))
    out_idx = _conv_out_sites(in_idx, in_dims, out_dims, ks, st, pd,
                              dl)
    n_out = len(out_idx)
    skeys, korder = _sorted_index(in_idx, in_dims)
    # identity element per dtype: -inf only exists for floats; integer
    # values would silently cast (or raise) against a float fill
    if jnp.issubdtype(vals.dtype, jnp.floating):
        neg = jnp.asarray(-jnp.inf, vals.dtype)
    elif jnp.issubdtype(vals.dtype, jnp.integer):
        neg = jnp.asarray(jnp.iinfo(vals.dtype).min, vals.dtype)
    else:
        raise ValueError(
            f"sparse max_pool3d: unsupported values dtype {vals.dtype}")
    acc = jnp.full((n_out, C), neg)
    for kd in range(ks[0]):
        for kh in range(ks[1]):
            for kw in range(ks[2]):
                src = out_idx.copy()
                src[:, 1:4] = out_idx[:, 1:4] * np.array(st) \
                    - np.array(pd) + np.array((kd, kh, kw))
                inb = ((src[:, 1:4] >= 0).all(1)
                       & (src[:, 1:4] < np.array(in_dims)).all(1))
                src_c = np.where(inb[:, None], src, 0)
                rows, found = _gather_rows(skeys, korder, in_dims, src_c)
                found = found & inb
                if not found.any():
                    continue
                g = jnp.take(vals, jnp.asarray(rows), axis=0)
                g = jnp.where(jnp.asarray(found[:, None]), g, neg)
                acc = jnp.maximum(acc, g)
    out = jsparse.BCOO((acc, jnp.asarray(out_idx.astype(np.int32))),
                       shape=(N,) + tuple(out_dims) + (C,),
                       indices_sorted=True, unique_indices=True)
    return SparseCooTensor(out)


def relu(x, name=None):
    """Zero-preserving ReLU over stored values (reference
    sparse/nn/functional/activation.py:22)."""
    from . import unary
    return unary.relu(x)


def relu6(x, name=None):
    """min(max(v, 0), 6) over stored values (activation.py:60)."""
    from .unary import _map_values
    return _map_values(x, lambda v: jnp.clip(v, 0.0, 6.0))


def leaky_relu(x, negative_slope=0.01, name=None):
    """Leaky ReLU over stored values (activation.py:98)."""
    from .unary import _map_values
    return _map_values(
        x, lambda v: jnp.where(v >= 0, v, negative_slope * v))


def softmax(x, axis=-1, name=None):
    """Row-wise softmax over stored entries (activation.py:136)."""
    from .nn import Softmax
    return Softmax(axis)(x)


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse-mask scaled-dot-product attention (reference
    sparse/nn/functional/transformer.py:24): scores are computed ONLY
    at the CSR mask's stored positions, softmax-normalized per row,
    then applied to V. Dense q/k/v [B, H, S, D]; sparse_mask a
    SparseCsrTensor with batch*head stacked rows ([B*H*S] row space)."""
    import jax
    q = query._data if hasattr(query, "_data") else jnp.asarray(query)
    k = key._data if hasattr(key, "_data") else jnp.asarray(key)
    v = value._data if hasattr(value, "_data") else jnp.asarray(value)
    b, h, s, d = q.shape
    scale = 1.0 / float(np.sqrt(d))
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    mask_dense = sparse_mask.to_dense()
    md = mask_dense._data if hasattr(mask_dense, "_data") \
        else jnp.asarray(mask_dense)
    md = md.reshape(b, h, s, s)
    keep = md != 0
    if key_padding_mask is not None:
        kp = key_padding_mask._data if hasattr(key_padding_mask, "_data") \
            else jnp.asarray(key_padding_mask)
        keep = keep & (kp[:, None, None, :] != 0)
    if attn_mask is not None:
        am = attn_mask._data if hasattr(attn_mask, "_data") \
            else jnp.asarray(attn_mask)
        keep = keep & (am[None, None] != 0 if am.ndim == 2 else am != 0)
    scores = jnp.where(keep, scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    p = jnp.where(jnp.isnan(p), 0.0, p)  # fully-masked rows
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    from ..core.tensor import Tensor
    return Tensor(out)
