"""Sparse unary ops (≈ python/paddle/sparse/unary.py; phi kernels
paddle/phi/kernels/sparse/unary_kernel.h). Zero-preserving ops apply to
the stored values only — nnz structure is unchanged."""
from __future__ import annotations

import jax.numpy as jnp

from .creation import SparseCooTensor, SparseCsrTensor, _SparseBase

__all__ = ["abs", "asin", "asinh", "atan", "atanh", "cast",
           "coalesce", "deg2rad", "expm1", "is_same_shape", "log1p",
           "neg", "pow", "rad2deg", "relu", "reshape", "sin", "sinh",
           "sqrt", "square", "tan", "tanh", "transpose"]


def _map_values(x: _SparseBase, fn) -> _SparseBase:
    mat = x._mat
    if hasattr(mat, "indptr"):  # BCSR
        new = type(mat)((fn(mat.data), mat.indices, mat.indptr),
                        shape=mat.shape)
    else:  # BCOO
        new = type(mat)((fn(mat.data), mat.indices), shape=mat.shape)
    return type(x)(new)


def relu(x):
    return _map_values(x, lambda v: jnp.maximum(v, 0))


def abs(x):  # noqa: A001
    return _map_values(x, jnp.abs)


def neg(x):
    return _map_values(x, jnp.negative)


def sin(x):
    return _map_values(x, jnp.sin)


def sinh(x):
    return _map_values(x, jnp.sinh)


def tan(x):
    return _map_values(x, jnp.tan)


def tanh(x):
    return _map_values(x, jnp.tanh)


def sqrt(x):
    return _map_values(x, jnp.sqrt)


def square(x):
    return _map_values(x, jnp.square)


def expm1(x):
    return _map_values(x, jnp.expm1)


def deg2rad(x):
    return _map_values(x, jnp.deg2rad)


def rad2deg(x):
    return _map_values(x, jnp.rad2deg)


def pow(x, factor):  # noqa: A001
    return _map_values(x, lambda v: jnp.power(v, factor))


def cast(x, index_dtype=None, value_dtype=None):
    out = x
    if value_dtype is not None:
        out = _map_values(out, lambda v: v.astype(jnp.dtype(value_dtype)))
    if index_dtype is not None:
        mat = out._mat
        idt = jnp.dtype(index_dtype)
        if hasattr(mat, "indptr"):  # BCSR
            new = type(mat)((mat.data, mat.indices.astype(idt),
                             mat.indptr.astype(idt)), shape=mat.shape)
        else:
            new = type(mat)((mat.data, mat.indices.astype(idt)),
                            shape=mat.shape)
        out = type(out)(new)
    return out


def coalesce(x: SparseCooTensor) -> SparseCooTensor:
    return x.coalesce()


def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


# round-2: remaining elementwise surface (reference python/paddle/
# sparse/unary.py) — value-map ops preserve the sparsity pattern
def asin(x):
    return _map_values(x, jnp.arcsin)


def asinh(x):
    return _map_values(x, jnp.arcsinh)


def atan(x):
    return _map_values(x, jnp.arctan)


def atanh(x):
    return _map_values(x, jnp.arctanh)


def log1p(x):
    return _map_values(x, jnp.log1p)


def reshape(x, shape):
    """Sparse reshape via densify/re-sparsify (the reference's sparse
    reshape kernel reindexes; COO on XLA round-trips through dense,
    acceptable for the API surface)."""
    import jax.experimental.sparse as jsparse
    from .creation import SparseCooTensor
    dense = x._mat.todense().reshape(tuple(int(s) for s in shape))
    return SparseCooTensor(jsparse.BCOO.fromdense(dense))


def transpose(x, perm):
    import jax.experimental.sparse as jsparse
    from .creation import SparseCooTensor
    dense = jnp.transpose(x._mat.todense(), tuple(perm))
    return SparseCooTensor(jsparse.BCOO.fromdense(dense))
