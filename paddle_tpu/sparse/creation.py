"""Sparse tensor creation (≈ python/paddle/sparse/creation.py;
phi/core/sparse_coo_tensor.h:1, sparse_csr_tensor.h:1)."""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor

__all__ = ["SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
           "sparse_csr_tensor"]


def _raw(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x)


class _SparseBase:
    """Shared surface of Coo/Csr wrappers over jax BCOO/BCSR."""

    def __init__(self, mat):
        self._mat = mat

    @property
    def shape(self):
        return list(self._mat.shape)

    @property
    def dtype(self):
        return self._mat.dtype

    @property
    def nnz(self) -> int:
        return int(self._mat.nse)

    def values(self) -> Tensor:
        return Tensor(self._mat.data)

    def to_dense(self) -> Tensor:
        return Tensor(self._mat.todense())

    def numpy(self):
        return np.asarray(self._mat.todense())

    def astype(self, dtype):
        return type(self)(self._mat.astype(jnp.dtype(dtype)))

    def __repr__(self):
        return (f"{type(self).__name__}(shape={self.shape}, "
                f"nnz={self.nnz}, dtype={self.dtype})")


class SparseCooTensor(_SparseBase):
    def indices(self) -> Tensor:
        # paddle stores [sparse_dim, nnz]; BCOO stores [nnz, sparse_dim]
        return Tensor(self._mat.indices.T)

    def is_coalesced(self) -> bool:
        return bool(self._mat.unique_indices)

    def coalesce(self) -> "SparseCooTensor":
        return SparseCooTensor(
            self._mat.sum_duplicates(remove_zeros=False))

    def to_sparse_csr(self) -> "SparseCsrTensor":
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(
            self._mat.sum_duplicates(remove_zeros=False)))


class SparseCsrTensor(_SparseBase):
    def crows(self) -> Tensor:
        return Tensor(self._mat.indptr)

    def cols(self) -> Tensor:
        return Tensor(self._mat.indices)

    def to_sparse_coo(self, sparse_dim: Optional[int] = None) \
            -> "SparseCooTensor":
        return SparseCooTensor(self._mat.to_bcoo())


def sparse_coo_tensor(indices, values, shape: Optional[Sequence[int]] = None,
                      dtype=None, place=None,
                      stop_gradient: bool = True) -> SparseCooTensor:
    """indices: [sparse_dim, nnz] (reference layout); values: [nnz, ...]."""
    idx = _raw(indices).astype(jnp.int32)
    vals = _raw(values)
    if dtype is not None:
        vals = vals.astype(jnp.dtype(dtype) if isinstance(dtype, str)
                           else dtype)
    if idx.ndim != 2:
        raise ValueError(f"indices must be [sparse_dim, nnz], "
                         f"got shape {idx.shape}")
    if shape is None:
        shape = tuple(int(m) + 1 for m in np.asarray(idx.max(axis=1))) \
            + tuple(vals.shape[1:])
    mat = jsparse.BCOO((vals, idx.T), shape=tuple(int(s) for s in shape))
    return SparseCooTensor(mat)


def sparse_csr_tensor(crows, cols, values,
                      shape: Optional[Sequence[int]] = None,
                      dtype=None, place=None,
                      stop_gradient: bool = True) -> SparseCsrTensor:
    indptr = _raw(crows).astype(jnp.int32)
    indices = _raw(cols).astype(jnp.int32)
    vals = _raw(values)
    if dtype is not None:
        vals = vals.astype(jnp.dtype(dtype) if isinstance(dtype, str)
                           else dtype)
    if shape is None:
        raise ValueError("sparse_csr_tensor requires an explicit shape")
    mat = jsparse.BCSR((vals, indices, indptr),
                       shape=tuple(int(s) for s in shape))
    return SparseCsrTensor(mat)
