"""Sparse binary ops and matmul (≈ python/paddle/sparse/binary.py;
phi/kernels/sparse/{elementwise,matmul}_kernel.h). Elementwise ops on
two sparse operands run through BCOO addition / dense fallback; matmul
contracts sparse x dense on the MXU (jax sparse lowers to
gather-matmul)."""
from __future__ import annotations

import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor
from .creation import (SparseCooTensor, SparseCsrTensor, _SparseBase,
                       _raw)

__all__ = ["add", "subtract", "multiply", "divide", "matmul",
           "masked_matmul"]


def _coo(x: _SparseBase) -> jsparse.BCOO:
    mat = x._mat
    return mat.to_bcoo() if isinstance(mat, jsparse.BCSR) else mat


def _rewrap(x_like: _SparseBase, coo: jsparse.BCOO):
    if isinstance(x_like, SparseCsrTensor):
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(
            coo.sum_duplicates(remove_zeros=False)))
    return SparseCooTensor(coo)


def add(x: _SparseBase, y: _SparseBase):
    out = _coo(x) + _coo(y)
    return _rewrap(x, out.sum_duplicates(remove_zeros=False))


def subtract(x: _SparseBase, y: _SparseBase):
    yc = _coo(y)
    out = _coo(x) + jsparse.BCOO((-yc.data, yc.indices), shape=yc.shape)
    return _rewrap(x, out.sum_duplicates(remove_zeros=False))


def multiply(x: _SparseBase, y):
    """Elementwise; sparse*sparse densifies the intersection (same
    semantics as the reference's elementwise_mul on coo)."""
    if isinstance(y, _SparseBase):
        dense = _coo(x).todense() * _coo(y).todense()
    else:
        dense = _coo(x).todense() * _raw(y)
    return _rewrap(x, jsparse.BCOO.fromdense(dense))


def divide(x: _SparseBase, y):
    if isinstance(y, _SparseBase):
        dense = _coo(x).todense() / _coo(y).todense()
    else:
        dense = _coo(x).todense() / _raw(y)
    return _rewrap(x, jsparse.BCOO.fromdense(dense))


def matmul(x, y):
    """sparse @ dense -> dense Tensor (reference: sparse.matmul)."""
    if isinstance(x, _SparseBase):
        out = _coo(x) @ _raw(y)
        return Tensor(out)
    if isinstance(y, _SparseBase):
        return Tensor(_raw(x) @ _coo(y))
    raise TypeError("sparse.matmul needs at least one sparse operand")


def masked_matmul(x, y, mask: _SparseBase):
    """(dense x dense) sampled at mask's sparsity pattern
    (reference: sparse.masked_matmul, cusparse SDDMM analog)."""
    xd, yd = _raw(x), _raw(y)
    coo = _coo(mask)
    rows = coo.indices[:, 0]
    cols = coo.indices[:, 1]
    # compute only the sampled dot products: nnz x K gather then reduce
    vals = (xd[rows, :] * yd[:, cols].T).sum(-1)
    out = jsparse.BCOO((vals, coo.indices), shape=coo.shape)
    return _rewrap(mask, out)


def mv(x, vec):
    """Sparse matrix x dense vector (reference sparse mv kernel)."""
    from ..core.tensor import Tensor
    v = vec.data if isinstance(vec, Tensor) else vec
    return Tensor(x._mat @ v)


def addmm(input, x, y, beta=1.0, alpha=1.0):
    """beta * input + alpha * (x @ y), x sparse (reference sparse
    addmm)."""
    from ..core.tensor import Tensor
    inp = input.data if isinstance(input, Tensor) else input
    yv = y.data if isinstance(y, Tensor) else y
    return Tensor(beta * inp + alpha * (x._mat @ yv))
