"""paddle.linalg namespace (≈ python/paddle/linalg.py re-exporting
tensor/linalg.py) — decompositions and solvers lower to XLA's native
linalg (QR/SVD/Cholesky run on the MXU where shapes allow)."""
from .ops.linalg import (cholesky, cholesky_solve, cond, cov,  # noqa: F401
                         corrcoef, cross, det, eig, eigh, eigvals,
                         eigvalsh, inv, lstsq, lu, lu_unpack,
                         matrix_power, matrix_rank, multi_dot, norm,
                         pinv, qr, slogdet, solve, svd,
                         triangular_solve)

inverse = inv
