"""paddle.linalg namespace (≈ python/paddle/linalg.py re-exporting
tensor/linalg.py) — decompositions and solvers lower to XLA's native
linalg (QR/SVD/Cholesky run on the MXU where shapes allow)."""
from .ops.linalg import (cholesky, cholesky_solve, cov,  # noqa: F401
                         corrcoef, cross, det, eig, eigh, eigvalsh,
                         inv, lstsq, lu, matrix_power, matrix_rank,
                         multi_dot, norm, pinv, qr, slogdet, solve,
                         svd, triangular_solve)

inverse = inv
