"""Token sampling for the jitted decode step.

All transforms are pure jnp over [batch, vocab] fp32 logits with the
sampling hyperparameters closed over as PYTHON values — they select the
trace, so a `generate()` call compiles exactly one decode program per
(shape, config) and never branches on device. Reference analog:
PaddleNLP's TopKProcess/TopPProcess logits processors; the reference
repo's own surface is paddle.tensor.search.top_p_sampling.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

_MASKED = -1e10  # large-negative, not -inf: keeps softmax/categorical exact


def apply_temperature(logits, temperature: float):
    if temperature == 1.0:
        return logits
    return logits / max(float(temperature), 1e-6)


def apply_top_k(logits, k: int):
    """Keep the k highest logits per row, mask the rest."""
    k = min(int(k), logits.shape[-1])
    vals = jax.lax.top_k(logits, k)[0]
    thresh = vals[..., -1:]
    return jnp.where(logits >= thresh, logits, _MASKED)


def apply_top_p(logits, p: float):
    """Nucleus filtering: keep the smallest set of tokens whose
    cumulative probability reaches ``p`` (the top token always
    survives)."""
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    # token j is kept while the mass strictly BEFORE it is < p; pin the
    # top token explicitly so p <= 0 degrades to greedy, not to an
    # all-masked row (which would sample UNIFORMLY over the vocab)
    keep = (jnp.cumsum(probs, axis=-1) - probs) < float(p)
    keep = keep.at[..., 0].set(True)
    thresh = jnp.min(jnp.where(keep, sorted_desc, jnp.inf), axis=-1,
                     keepdims=True)
    return jnp.where(logits >= thresh, logits, _MASKED)


def sample(logits, key=None, *, do_sample: bool = False,
           temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0):
    """[batch, vocab] logits -> [batch] int32 token ids.

    do_sample=False (or temperature == 0) is greedy argmax; otherwise
    temperature, then top-k (when > 0), then top-p (when < 1) filter
    the distribution and ``jax.random.categorical`` draws from it."""
    logits = logits.astype(jnp.float32)
    if not do_sample or float(temperature) == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if key is None:
        raise ValueError("sampling (do_sample=True) needs a PRNG key")
    logits = apply_temperature(logits, temperature)
    if top_k and top_k > 0:
        logits = apply_top_k(logits, top_k)
    if top_p is not None and float(top_p) < 1.0:
        logits = apply_top_p(logits, top_p)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)
