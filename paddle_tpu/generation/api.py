"""Autoregressive generation: jitted (prefill, decode) pair + host loop.

The contract that makes serving-grade decoding possible on TPU:

- exactly TWO compiled programs per (shape, config): one prefill over
  the padded prompt, one single-token decode step. The host loop then
  issues ONE device dispatch per generated token with no per-token
  retrace (gated by ``jit.retraces{cause=new_shape}`` ≈ 0) and no
  per-token host sync — tokens accumulate on device and transfer once
  at the end (eos polling, when enabled, reads one tiny bool every
  ``_EOS_CHECK_EVERY`` steps).
- sampling (greedy/temperature/top-k/top-p) runs INSIDE the decode
  program; the ``GenerationConfig`` is a static jit argument, so the
  sampler never branches on device.
- the KV cache is donated to the decode step on TPU, so each token's
  cache update is an in-place HBM write, not a copy of
  [layers, batch, max_len, heads, head_dim].

Networks plug in via the cache protocol (models/gpt.py wiring):
``forward(input_ids, use_cache=True, prompt_len=..., cache_max_len=N)``
returns (next-token logits, filled cache) for prefill, and
``forward(input_ids, cache=cache)`` returns (logits, cache) for decode.

Reference analog: the reference ships this layer as
paddle/fluid/inference + the fused-multi-transformer decode ops (~90k
LoC); AOT-compiled jax executables + donated buffers make it this file.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import monitor
from ..core.tensor import Tensor
from .kv_cache import KVCache, resolve_cache_dtype
from .sampling import sample

__all__ = ["GenerationConfig", "GenerationSession", "generate"]

_EOS_CHECK_EVERY = 16  # decode steps between host reads of `finished`


@dataclasses.dataclass(frozen=True)
class GenerationConfig:
    """Static sampling/stopping configuration (hashable: it is a jit
    static argument — a new config compiles a new decode program)."""
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    pad_token_id: Optional[int] = None

    @property
    def pad_value(self) -> int:
        if self.pad_token_id is not None:
            return int(self.pad_token_id)
        return int(self.eos_token_id) if self.eos_token_id is not None \
            else 0


def _round_up(n: int, mult: int = 128) -> int:
    return -(-int(n) // mult) * mult


def _sample_cfg(cfg: GenerationConfig) -> dict:
    return dict(do_sample=cfg.do_sample, temperature=cfg.temperature,
                top_k=cfg.top_k, top_p=cfg.top_p)


def _expect_logits_cache(out):
    """The cache protocol returns exactly (logits, cache). Fail with a
    readable error instead of an opaque unpack inside the trace —
    encoder-style cached forwards (e.g. ErnieModel's incremental
    encoding, which returns (seq, pooled, cache)) are not generative
    LMs."""
    if not (isinstance(out, (tuple, list)) and len(out) == 2):
        got = (f"a {len(out)}-tuple" if isinstance(out, (tuple, list))
               else type(out).__name__)
        raise TypeError(
            "generate(): the network's cached forward must return "
            f"(logits, cache), got {got}; use a generative LM head "
            "(e.g. models.gpt.GPTForCausalLM — ErnieModel's "
            "incremental encoding is an encoder protocol, not "
            "a decoder)")
    return out


class GenerationSession:
    """The jitted (prefill, decode) pair for one network.

    Built once per network and reused across ``generate()`` calls, so
    jax's jit cache carries warm executables between requests.
    ``aot_compile`` additionally stores ahead-of-time compiled
    executables for fixed shapes (the Predictor's serving mode) —
    persisted through ``executable_store`` (default: the process
    ``jit.compile_cache`` store, when enabled) so a relaunched process
    loads them instead of recompiling."""

    def __init__(self, network, executable_store=None, cache_dtype=None):
        from ..jit.api import _RetraceTracker, _unwrap, functional_call
        network.eval()
        self.network = network
        self.executable_store = executable_store
        #: low-bit KV-cache mode (None = full width, "int8" = quantized
        #: pages with fused in-kernel dequant); baked into the prefill
        #: program, so a session serves exactly one cache dtype
        self.cache_dtype = resolve_cache_dtype(cache_dtype) \
            if cache_dtype is not None else None
        cache_kw = {} if self.cache_dtype is None \
            else {"cache_dtype": self.cache_dtype}
        self._names = list(network.state_dict().keys())
        # one tracker per jitted fn: prefill and decode each classify
        # their first compile as cause=first, and any later miss on the
        # same fn as the true cause (the gate: new_shape stays 0)
        self._prefill_tracker = _RetraceTracker()
        self._decode_tracker = _RetraceTracker()
        self._compiled = {}  # (kind, shape key) -> AOT executable
        self._spec_sessions = {}  # (SpeculativeConfig, draft id) -> sess
        names = self._names

        def prefill_fn(state_vals, ids, prompt_len, key, cfg, cache_len):
            out = functional_call(
                network, dict(zip(names, state_vals)), Tensor(ids),
                use_cache=True, prompt_len=prompt_len,
                cache_max_len=cache_len, **cache_kw)
            logits, cache = _expect_logits_cache(out)
            logits = _unwrap(logits)[:, -1].astype(jnp.float32)  # [B, V]
            k0, k1 = jax.random.split(key)
            tok = sample(logits, k0, **_sample_cfg(cfg))
            if cfg.eos_token_id is not None:
                finished = tok == cfg.eos_token_id
            else:
                finished = jnp.zeros(tok.shape, bool)
            return tok, cache, k1, finished

        def decode_fn(state_vals, tok, cache, key, finished, cfg):
            out = functional_call(
                network, dict(zip(names, state_vals)), Tensor(tok[:, None]),
                cache=cache)
            logits, cache = _expect_logits_cache(out)
            logits = _unwrap(logits)[:, -1].astype(jnp.float32)
            k0, k1 = jax.random.split(key)
            nxt = sample(logits, k0, **_sample_cfg(cfg))
            # rows that finished on an earlier step emit padding
            emitted = jnp.where(finished, jnp.int32(cfg.pad_value), nxt)
            if cfg.eos_token_id is not None:
                finished = finished | (nxt == cfg.eos_token_id)
            return nxt, emitted, cache, k1, finished

        # donate the cache on TPU only: CPU/GPU donation is a no-op
        # that warns once per program
        donate = (2,) if jax.default_backend() == "tpu" else ()
        self._decode_donate = donate
        self._prefill_fn = prefill_fn
        self._decode_fn = decode_fn
        self._prefill_jit = jax.jit(prefill_fn, static_argnums=(4, 5))
        self._decode_jit = jax.jit(decode_fn, static_argnums=(5,),
                                   donate_argnums=donate)

    # ------------------------------------------------------------- state
    def state_values(self):
        """Fresh parameter/buffer arrays (Tensors are mutated in place
        by optimizers, so ._data is re-read per call; a changed key SET
        means the session must be rebuilt)."""
        state = self.network.state_dict()
        if list(state.keys()) != self._names:
            raise RuntimeError("network structure changed under the "
                               "generation session; rebuild it")
        return tuple(t._data for t in state.values())

    # ----------------------------------------------------------- calling
    def _ensure_eval(self):
        # a fit() loop flips the network back to train mode every batch;
        # a retrace here (new shape) would then BAKE active dropout into
        # the prefill/decode program — force eval before every dispatch
        # (attribute check only on the hot path)
        if self.network.training:
            self.network.eval()

    def prefill(self, state_vals, ids, prompt_len, key, cfg, cache_len):
        self._ensure_eval()
        exe = self._compiled.get(("prefill", ids.shape, cache_len, cfg))
        if exe is not None:
            return exe(state_vals, ids, prompt_len, key)
        pre = self._prefill_tracker.pre(self._prefill_jit)
        out = self._prefill_jit(state_vals, ids, prompt_len, key, cfg,
                                cache_len)
        self._prefill_tracker.observe(self._prefill_jit,
                                      (ids.shape, cache_len, str(cfg)),
                                      pre)
        return out

    def decode(self, state_vals, tok, cache, key, finished, cfg):
        self._ensure_eval()
        exe = self._compiled.get(
            ("decode", tok.shape, cache.max_len, cfg))
        if exe is not None:
            return exe(state_vals, tok, cache, key, finished)
        pre = self._decode_tracker.pre(self._decode_jit)
        out = self._decode_jit(state_vals, tok, cache, key, finished, cfg)
        self._decode_tracker.observe(self._decode_jit,
                                     (tok.shape, cache.max_len,
                                      str(cfg)), pre)
        return out

    # -------------------------------------------------------- speculative
    def speculative(self, spec, draft_network=None):
        """The cached :class:`speculative.SpeculativeSession` (the
        jitted draft+verify program pair) for one SpeculativeConfig —
        built once, reused across ``generate(speculative=...)`` calls
        so the pair's executables stay warm like prefill/decode."""
        from .speculative import SpeculativeSession
        key = (spec, id(draft_network))
        sess = self._spec_sessions.get(key)
        if sess is None:
            sess = SpeculativeSession(self, spec,
                                      draft_network=draft_network)
            self._spec_sessions[key] = sess
        return sess

    # ------------------------------------------------------------- audit
    def audit(self, batch: int, prompt_len: int, cache_len: int,
              cfg: Optional[GenerationConfig] = None, *,
              speculative=None, draft_network=None, max_new: int = 32,
              **audit_kw):
        """Static audit of the (prefill, decode) pair for one padded
        shape (analysis.audit over abstract operands — nothing
        executes). Decode is audited with the TPU donation INTENT (the
        KV cache donated) even on CPU, where the session deliberately
        skips donation: the audit gates the program we serve, not the
        test backend. Returns ``(prefill_report, decode_report)``; the
        tier-1 gate asserts zero ERROR findings on both and full
        donation coverage of the cache in decode. With ``speculative=``
        set (a SpeculativeConfig or mode string) the tuple grows to
        ``(prefill, decode, spec_draft, spec_verify)`` — the draft and
        single-dispatch verify programs audited under the same
        contract, verify with every state lane donated."""
        from ..analysis import audit as _audit
        # same contract as every dispatch path: a mid-fit audit must
        # trace the EVAL program (train-mode dropout would otherwise be
        # baked into the traced jaxpr, and the report would describe a
        # program that is never served)
        self._ensure_eval()
        cfg = cfg if cfg is not None else GenerationConfig()
        # a caller-supplied name= prefixes the pair (the sibling audit
        # entry points honor name overrides; here one call yields TWO
        # reports, so the override becomes their common prefix)
        base = audit_kw.pop("name", "generation")
        # decode donation defaults to the TPU intent; donate=() audits
        # the undonated variant the session dispatches on CPU backends
        decode_donate = audit_kw.pop("donate", (2,))
        sds = jax.ShapeDtypeStruct
        state = tuple(sds(tuple(v.shape), v.dtype)
                      for v in self.state_values())
        ids = sds((batch, prompt_len), jnp.int32)
        plen = sds((batch,), jnp.int32)
        key = sds((2,), jnp.uint32)
        prefill_report = _audit(
            self._prefill_fn, state, ids, plen, key, cfg, cache_len,
            static_argnums=(4, 5), name=f"{base}.prefill", **audit_kw)
        # decode operand avals come straight from the prefill audit's
        # own trace (report.out_shape) — no second prefill trace
        _, cache_aval, _, fin = prefill_report.out_shape
        tok = sds((batch,), jnp.int32)
        decode_report = _audit(
            self._decode_fn, state, tok, cache_aval, key, fin, cfg,
            static_argnums=(5,), donate=decode_donate,
            name=f"{base}.decode", **audit_kw)
        if speculative is None:
            return prefill_report, decode_report
        from .speculative import as_spec_config
        spec = as_spec_config(speculative, draft_network)
        draft_report, verify_report = self.speculative(
            spec, draft_network).audit(
            batch, prompt_len, cache_len, max_new, cfg,
            name=f"{base}.spec", **audit_kw)
        return (prefill_report, decode_report, draft_report,
                verify_report)

    # --------------------------------------------------------------- aot
    def aot_compile(self, batch: int, prompt_len: int, cache_len: int,
                    cfg: GenerationConfig, decode: bool = True):
        """Ahead-of-time compile the (prefill, decode) pair for one
        fixed padded shape (serving: compile at startup, zero retraces
        under live traffic). Compiled executables are called WITHOUT
        the static args — they are baked in. With an executable store
        active (``self.executable_store`` or the process default) the
        pair is loaded from disk when a relaunch already compiled it —
        zero XLA work, and on a manifest hit zero TRACE work, on the
        warm path. ``decode=False`` builds the prefill only (the
        speculative draft model's admission path — its decode program
        is never dispatched)."""
        from ..jit import compile_cache
        store = self.executable_store
        sds = jax.ShapeDtypeStruct
        state = tuple(sds(v.shape, v.dtype) for v in self.state_values())
        ids = sds((batch, prompt_len), jnp.int32)
        plen = sds((batch,), jnp.int32)
        key = sds((2,), jnp.uint32)
        base_sig = compile_cache.network_signature(self.network)

        def sig_for(kind):
            if base_sig is None:
                return None   # no sound traceless key: traced path
            sig = dict(base_sig)
            sig.update(program=(kind, batch, prompt_len, cache_len),
                       generation=repr(cfg),
                       kv_cache=self.cache_dtype,
                       operands=compile_cache.aval_signature(state))
            return sig

        pexe = compile_cache.build_or_load(
            sig_for("generation.prefill"),
            lambda: self._prefill_jit.lower(state, ids, plen, key, cfg,
                                            cache_len),
            store=store, extra=dict(kind="generation.prefill",
                                    donation=()),
            label=f"generation.prefill.b{batch}s{prompt_len}")
        self._compiled[("prefill", (batch, prompt_len), cache_len,
                        cfg)] = pexe
        if not decode:
            return pexe, None

        def lower_decode():
            # decode avals come from the prefill's own outputs (an
            # abstract trace — only paid when the manifest misses)
            _, cache_aval, _, fin = jax.eval_shape(
                lambda s, i, p, k: self._prefill_fn(s, i, p, k, cfg,
                                                    cache_len),
                state, ids, plen, key)
            tok = sds((batch,), jnp.int32)
            return self._decode_jit.lower(state, tok, cache_aval, key,
                                          fin, cfg)

        dexe = compile_cache.build_or_load(
            sig_for("generation.decode"), lower_decode,
            store=store, extra=dict(kind="generation.decode",
                                    donation=self._decode_donate),
            label=f"generation.decode.b{batch}c{cache_len}")
        self._compiled[("decode", (batch,), cache_len, cfg)] = dexe
        return pexe, dexe


def _as_int_ids(input_ids) -> np.ndarray:
    ids = input_ids
    if isinstance(ids, Tensor):
        ids = np.asarray(ids._data)  # lint: host-sync-ok (pre-dispatch input prep)
    ids = np.asarray(ids)  # lint: host-sync-ok (pre-dispatch input prep)
    if ids.ndim == 1:
        ids = ids[None, :]
    if ids.ndim != 2:
        raise ValueError(f"input_ids must be [batch, seq], got "
                         f"shape {ids.shape}")
    return ids.astype(np.int32)


def _session_for(network, cache_dtype=None) -> GenerationSession:
    sess = getattr(network, "_generation_session", None)
    if sess is None or sess.network is not network or \
            list(network.state_dict().keys()) != sess._names or \
            getattr(sess, "cache_dtype", None) != cache_dtype:
        sess = GenerationSession(network, cache_dtype=cache_dtype)
        object.__setattr__(network, "_generation_session", sess)
    return sess


def generate(network, input_ids, max_new_tokens: int = 32, *,
             do_sample: bool = False, temperature: float = 1.0,
             top_k: int = 0, top_p: float = 1.0,
             eos_token_id: Optional[int] = None,
             pad_token_id: Optional[int] = None,
             prompt_len=None, cache_max_len: Optional[int] = None,
             seed: Optional[int] = None,
             session: Optional[GenerationSession] = None,
             live_rows: Optional[int] = None,
             speculative=None, draft_model=None,
             kv_cache_dtype=None) -> Tensor:
    """Generate ``max_new_tokens`` tokens after ``input_ids``.

    input_ids: [batch, seq] int prompt (right-padded for ragged
    batches; pass per-row true lengths via ``prompt_len``). Returns the
    GENERATED ids only, [batch, max_new_tokens] int32; with
    ``eos_token_id`` set, positions after a row's first eos hold
    ``pad_token_id`` (default: the eos id).

    Exactly one prefill dispatch plus one decode dispatch per token;
    two compiles per (shape, sampling config). Raises up front when
    prompt + new tokens would exceed the model's
    ``max_position_embeddings`` (a wrapped/clipped position gather
    would silently corrupt the distribution otherwise).

    ``seed=None`` with ``do_sample=True`` draws fresh entropy from the
    framework RNG (``paddle.seed`` pins it) — repeated calls sample
    DIFFERENT continuations; pass an explicit ``seed`` for a
    reproducible draw. ``live_rows`` marks how many leading batch rows
    are real requests (the Predictor's fixed-batch padding rows are
    not) — the ``gen.tokens`` metric counts only live rows, and only
    up to each row's first eos.

    ``speculative`` turns on speculative decoding: ``"ngram"`` (the
    model-free prompt-lookup drafter), ``"draft"`` (with
    ``draft_model=`` a small LM sharing the vocabulary), or a
    :class:`speculative.SpeculativeConfig` for the draft-k / n-gram
    knobs. One target dispatch then verifies up to ``k + 1`` tokens
    per row; greedy outputs are bitwise-identical to the sequential
    path, sampling matches distributionally. The KV ring (and the
    position table) must carry ``k`` extra slack beyond
    prompt + max_new_tokens for the last verify window's unaccepted
    overhang — validated here, never discovered as ring corruption.

    ``kv_cache_dtype="int8"`` (or ``PADDLE_KV_CACHE_DTYPE=int8``)
    quantizes the KV cache: values write int8 with per-(position,
    head) bf16 scales, the decode kernel dequantizes in-register —
    half the HBM streamed per decode step, output logits within a
    small calibrated bound of the full-width cache (eos positions
    parity-gated on test-tiny in tier-1).
    """
    ids = _as_int_ids(input_ids)
    b, s = ids.shape
    max_new_tokens = int(max_new_tokens)
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, "
                         f"got {max_new_tokens}")
    if prompt_len is None:
        plen = np.full((b,), s, np.int32)
    else:
        plen = np.asarray(  # lint: host-sync-ok (pre-dispatch input prep)
            prompt_len._data if isinstance(prompt_len, Tensor)
            else prompt_len).astype(np.int32).reshape(-1)
        if plen.shape != (b,):
            raise ValueError(f"prompt_len must be [batch]={b}, got "
                             f"shape {plen.shape}")
        if (plen < 1).any() or (plen > s).any():
            raise ValueError("prompt_len entries must be in [1, "
                             f"{s}], got {plen.tolist()}")

    from .speculative import as_spec_config
    spec = as_spec_config(speculative, draft_model)
    # the speculative verify window writes (and embeds positions for)
    # up to k unaccepted draft tokens past the last real token: both
    # the position table and the KV ring need that slack
    overhang = spec.k if spec is not None else 0

    # out-of-range decode positions fail HERE, not as a silent clipped
    # position-embedding gather deep in the model
    cfg_obj = getattr(network, "cfg", None)
    max_pos = getattr(cfg_obj, "max_position_embeddings", None)
    total = int(plen.max()) + max_new_tokens
    if max_pos is not None and total + overhang > int(max_pos):
        raise ValueError(
            f"generate(): prompt ({int(plen.max())} tokens) + "
            f"max_new_tokens ({max_new_tokens})"
            + (f" + speculative window overhang ({overhang})"
               if overhang else "")
            + f" = {total + overhang} exceeds the "
            f"model's max_position_embeddings ({int(max_pos)}); shorten "
            "the prompt, lower max_new_tokens, or build the model with "
            "a larger max_position_embeddings")
    # the draft model walks the same positions (its cache stays
    # aligned with the target's): a smaller draft position table would
    # otherwise clip its gathers silently — garbage proposals and a
    # mysteriously low accept rate instead of an error
    if spec is not None and spec.mode == "draft":
        d_max = getattr(getattr(draft_model, "cfg", None),
                        "max_position_embeddings", None)
        if d_max is not None and total + overhang > int(d_max):
            raise ValueError(
                f"generate(): prompt + max_new_tokens + speculative "
                f"overhang = {total + overhang} exceeds the DRAFT "
                f"model's max_position_embeddings ({int(d_max)}); the "
                "draft model must cover the same positions as the "
                "target (build it with a larger "
                "max_position_embeddings)")

    cache_len = int(cache_max_len) if cache_max_len is not None \
        else _round_up(s + max_new_tokens + overhang)
    if cache_len < s + max_new_tokens + overhang:
        raise ValueError(
            f"cache_max_len {cache_len} < prompt {s} + max_new_tokens "
            f"{max_new_tokens}"
            + (f" + speculative verify-window overhang {overhang} (the "
               "last window's unaccepted draft tokens still write "
               "their KV before rollback)" if overhang else "")
            + "; the ring cache would wrap and overwrite the oldest "
            "context")

    cfg = GenerationConfig(do_sample=do_sample, temperature=temperature,
                           top_k=top_k, top_p=top_p,
                           eos_token_id=eos_token_id,
                           pad_token_id=pad_token_id)
    cache_dtype = resolve_cache_dtype(kv_cache_dtype)
    if session is not None:
        if kv_cache_dtype is not None and \
                session.cache_dtype != kv_cache_dtype:
            raise ValueError(
                f"generate(): session serves kv_cache_dtype="
                f"{session.cache_dtype!r} but {kv_cache_dtype!r} was "
                "requested; build a session with the matching "
                "cache_dtype")
        sess = session
    else:
        sess = _session_for(network, cache_dtype)
    state_vals = sess.state_values()
    if seed is not None:
        key = jax.random.PRNGKey(int(seed))
    elif cfg.do_sample:
        # fresh entropy per call: repeated unseeded sampling must not
        # replay one fixed key stream (paddle.seed pins the source)
        from ..core import random as _random
        key = _random.next_key()
    else:
        key = jax.random.PRNGKey(0)  # greedy: key is never consumed

    if spec is not None:
        from .speculative import decode_loop
        return decode_loop(network, sess, state_vals, ids, plen, cfg,
                           spec, draft_model, cache_len, max_new_tokens,
                           key, live_rows)

    tok, cache, key, finished = sess.prefill(
        state_vals, jnp.asarray(ids), jnp.asarray(plen), key, cfg,
        cache_len)
    if monitor.enabled:
        monitor.record_generation(prefill_steps=1)
    outs = [tok]
    n_done = 1
    for i in range(max_new_tokens - 1):
        tok, emitted, cache, key, finished = sess.decode(
            state_vals, tok, cache, key, finished, cfg)
        outs.append(emitted)
        n_done += 1
        if monitor.enabled:
            monitor.record_generation(decode_steps=1)
        # eos early-exit: one tiny host read every K steps (never per
        # token — that would drain the dispatch queue)
        if cfg.eos_token_id is not None and \
                (i + 1) % _EOS_CHECK_EVERY == 0 and \
                bool(jnp.all(finished)):  # lint: host-sync-ok (every-K poll)
            break
    result = jnp.stack(outs, axis=1)                 # [B, n_done]
    if monitor.enabled:
        # real generated tokens only: live rows, each counted up to its
        # first eos (padding-row and post-eos emissions are not
        # throughput). One [live, n_done] host read at call end — the
        # caller is about to transfer the result anyway.
        live = b if live_rows is None else min(int(live_rows), b)
        arr = np.asarray(result[:live])  # lint: host-sync-ok (one end-of-call read)
        if cfg.eos_token_id is not None:
            hit = arr == cfg.eos_token_id
            per_row = np.where(hit.any(1), hit.argmax(1) + 1, n_done)
            tokens = int(per_row.sum())
        else:
            tokens = live * n_done
        monitor.record_generation(tokens=tokens)
        monitor.record_cache_occupancy(
            (int(plen.max()) + n_done) / cache_len)
        if getattr(cache, "k_scale", None) is not None:
            # quantized cache: HBM the int8 storage saved vs the wide
            # dtype the activations carry (host arithmetic from
            # shapes), plus this call's int8 saturation count (one
            # scalar read, beside the result transfer above)
            wdt = np.dtype(state_vals[0].dtype)
            # name check: np.issubdtype(bfloat16, floating) is False,
            # and bf16 params are the standard TPU config — falling to
            # the 4-byte default would overstate savings 3x
            wide = wdt.itemsize if (np.issubdtype(wdt, np.floating)
                                    or wdt.name == "bfloat16") else 4
            saved = 2 * cache.k.size * (wide - 1) \
                - 2 * cache.k_scale.size * 2
            clips = int(np.asarray(cache.clips))  # lint: host-sync-ok (one end-of-call read)
            monitor.record_kv_quant(bytes_saved=max(0, saved),
                                    scale_clips=clips)
    if n_done < max_new_tokens:                      # early eos exit
        result = jnp.concatenate(
            [result, jnp.full((b, max_new_tokens - n_done),
                              cfg.pad_value, jnp.int32)], axis=1)
    return Tensor(result)
