"""Ring KV cache for incremental decoding.

One donated on-device pytree holds every layer's cached keys/values:

    k, v:   [num_layers, batch, max_len, num_heads, head_dim]
    kv_len: [batch] int32 — valid entries per row (ragged batches)

``update(layer, k, v, pos)`` is pure-functional (returns a new KVCache
whose buffers alias the old ones under XLA donation), so the SAME code
path jit-compiles for prefill (write the whole padded prompt at pos 0)
and decode (write 1..8 new rows at each row's ``kv_len``). Write
positions wrap modulo ``max_len`` (ring semantics); ``generate()``
validates lengths up front so a live cache never actually wraps — the
wrap exists so an out-of-contract write corrupts the oldest entries
instead of faulting.

Sharding: ``partition_spec()`` places batch on the (dp, sharding) mesh
axes and heads on mp — the same layout the models' qkv activations
carry under ``DistributedTrainStep`` — so hybrid-mesh models decode
without resharding. ``shard(mesh)`` trims the spec to the axes the mesh
actually has.

Quantized mode (``cache_dtype="int8"``, ROADMAP item 4): at long
context decode is bandwidth-bound on STREAMING the cache, so
:class:`QuantKVCache` stores K/V as int8 with a bfloat16 scale per
(position, head) in small sidecar arrays — half the HBM bytes per
decode step (and double the rows a fixed pool holds, compounding with
the paged cache). ``update`` quantizes IN-TRACE at write time (absmax
over head_dim per appended token), and the decode kernels dequantize
in-register: the K scale folds into the score-tile columns and the V
scale into the softmax weights, so a wide cache is never materialized
anywhere. A tiny ``clips`` counter rides the pytree recording values
that saturated the int8 range (the bf16 scale rounding can clip a
token's absmax element by <=0.4%) — drained into
``gen.cache.quant.scale_clips``.

Reference analog: the fused-multi-transformer decode ops' CacheKV
tensors (paddle/fluid/operators/fused/fused_multi_transformer_op.cu);
here the cache is a plain pytree the compiled step updates in place via
buffer donation.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

#: cache dtypes ``KVCache.create(cache_dtype=)`` accepts (None = the
#: activation dtype, the full-width mode)
CACHE_DTYPES = (None, "int8")


def _raw(x):
    from ..core.tensor import Tensor
    return x._data if isinstance(x, Tensor) else x


def validate_cache_dtype(value):
    """Reject anything outside CACHE_DTYPES with the one shared error
    (config knobs, cache constructors, and the resolver all call this
    — one rule, one message)."""
    if value not in CACHE_DTYPES:
        raise ValueError(
            f"kv_cache_dtype {value!r}: one of "
            f"{[d for d in CACHE_DTYPES if d]} or None (full width)")
    return value


def resolve_cache_dtype(explicit=None):
    """The effective KV-cache dtype: an explicit value wins (and is
    validated — a typo'd config raises, never silently serves wide),
    else ``PADDLE_KV_CACHE_DTYPE``; garbage in the env is recorded via
    ``record_swallowed`` and falls back to full width (same contract as
    PADDLE_KV_PAGE_SIZE)."""
    if explicit is not None:
        return validate_cache_dtype(explicit)
    env = os.environ.get("PADDLE_KV_CACHE_DTYPE", "").strip().lower()
    if not env or env in ("auto", "none", "off", "wide", "float"):
        return None
    if env in CACHE_DTYPES:
        return env
    from ..core import monitor
    monitor.record_swallowed(
        "generation.kv_cache_dtype",
        ValueError(f"PADDLE_KV_CACHE_DTYPE={env!r}"))
    return None


def quantize_kv(x):
    """Quantize fresh K or V values ``[..., heads, head_dim]`` to int8
    with one bfloat16 scale per (..., head): ``scale = absmax/127``
    (bf16-rounded — half the sidecar HBM of fp32, and the rounding
    error is an order below the int8 step), ``q = round(x / scale)``
    clipped to the int8 range. Returns ``(q int8, scale bf16, clips)``
    where ``clips`` counts values that saturated past +-127 BEFORE the
    clip — structurally 0 under round-to-nearest absmax scales (the
    worst-case ratio is 127 * (1 + 2^-9) < 127.5), so a nonzero count
    is the alarm that a future scale scheme (calibrated, EMA,
    coarser-grained) actually saturates."""
    xf = _raw(x).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)               # [..., heads]
    scale = (jnp.maximum(absmax, 1e-6) / 127.0).astype(jnp.bfloat16)
    q = jnp.round(xf / scale.astype(jnp.float32)[..., None])
    clips = jnp.sum((jnp.abs(q) > 127.0).astype(jnp.int32))
    q = jnp.clip(q, -127.0, 127.0).astype(jnp.int8)
    return q, scale, clips


def _axis_trimmer(mesh):
    """Trim partition-spec axes to the names ``mesh`` actually has."""
    names = set(mesh.axis_names)

    def trim(axes):
        if isinstance(axes, tuple):
            kept = tuple(a for a in axes if a in names)
            return kept if kept else None
        return axes if axes in names else None

    return trim


@jax.tree_util.register_pytree_node_class
class KVCache:
    """Per-layer K/V ring cache with per-row valid lengths."""

    __slots__ = ("k", "v", "kv_len")

    def __init__(self, k, v, kv_len):
        self.k = k
        self.v = v
        self.kv_len = kv_len

    # ------------------------------------------------------------ pytree
    def tree_flatten(self):
        return (self.k, self.v, self.kv_len), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # ------------------------------------------------------------- shape
    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    @property
    def batch(self) -> int:
        return self.k.shape[1]

    @property
    def max_len(self) -> int:
        return self.k.shape[2]

    @property
    def dtype(self):
        return self.k.dtype

    @property
    def cache_dtype(self):
        """The declared low-bit storage mode (None = full width)."""
        return None

    # ---------------------------------------------------------- creation
    @classmethod
    def create(cls, num_layers: int, batch: int, max_len: int,
               num_heads: int, head_dim: int, dtype=jnp.float32,
               mesh=None, cache_dtype=None) -> "KVCache":
        shape = (num_layers, batch, max_len, num_heads, head_dim)
        if validate_cache_dtype(cache_dtype) is not None:
            sshape = (num_layers, batch, max_len, num_heads)
            cache = QuantKVCache(
                jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
                jnp.zeros((batch,), jnp.int32),
                jnp.zeros(sshape, jnp.bfloat16),
                jnp.zeros(sshape, jnp.bfloat16),
                jnp.zeros((), jnp.int32))
        else:
            cache = cls(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype),
                        jnp.zeros((batch,), jnp.int32))
        return cache.shard(mesh) if mesh is not None else cache

    @staticmethod
    def partition_spec() -> P:
        """[layers, batch, max_len, heads, head_dim]: batch over
        (dp, sharding), heads over mp — the models' qkv layout."""
        return P(None, ("dp", "sharding"), None, "mp", None)

    def shard(self, mesh) -> "KVCache":
        """Place the cache on ``mesh`` (spec trimmed to the axes the
        mesh has). Works both eagerly (device_put) and inside a trace
        (sharding constraint)."""
        trim = _axis_trimmer(mesh)
        spec = P(*(trim(ax) for ax in self.partition_spec()))
        kv_sh = NamedSharding(mesh, spec)
        len_sh = NamedSharding(mesh, P(trim(("dp", "sharding"))))
        place = jax.lax.with_sharding_constraint \
            if isinstance(self.k, jax.core.Tracer) else jax.device_put
        return KVCache(place(self.k, kv_sh), place(self.v, kv_sh),
                       place(self.kv_len, len_sh))

    # ------------------------------------------------------------ update
    def update(self, layer: int, k_new, v_new, pos) -> "KVCache":
        """Write ``k_new``/``v_new`` ([batch, s, heads, head_dim]) into
        ``layer`` at per-row start position ``pos`` ([batch] int32 or a
        scalar), wrapping modulo max_len. Does NOT advance ``kv_len`` —
        every layer of one forward writes at the same positions; the
        model advances the length once via ``with_kv_len``."""
        k_new, v_new = _raw(k_new), _raw(v_new)
        pos = jnp.asarray(_raw(pos), jnp.int32)
        if pos.ndim == 0:
            pos = jnp.broadcast_to(pos, (k_new.shape[0],))
        steps = jnp.arange(k_new.shape[1], dtype=jnp.int32)

        def write(buf, new, p):  # [T, H, D], [S, H, D], scalar
            # scatter, not dynamic_update_slice: each target slot wraps
            # modulo max_len independently (true ring semantics; a
            # slice write would CLAMP at the end instead)
            idx = (p + steps) % buf.shape[0]
            return buf.at[idx].set(new.astype(buf.dtype))

        k_l = jax.vmap(write)(self.k[layer], k_new, pos)
        v_l = jax.vmap(write)(self.v[layer], v_new, pos)
        return KVCache(self.k.at[layer].set(k_l),
                       self.v.at[layer].set(v_l), self.kv_len)

    def positions(self, s: int):
        """Absolute positions of ``s`` appended tokens per row
        ([batch, s] int32: ``kv_len[r] .. kv_len[r]+s-1``) — the decode
        position-embedding offsets."""
        return self.kv_len[:, None] + \
            jnp.arange(s, dtype=jnp.int32)[None, :]

    # -------------------------------------------------------- slot reuse
    def reset_rows(self, rows) -> "KVCache":
        """Free batch rows for reuse: zero ``kv_len`` at ``rows`` (one
        row index, an int array of rows, or a [batch] bool mask)
        without touching the K/V buffers or the pytree structure — the
        serving scheduler calls this (jit-compiled, cache donated) when
        a slot's request terminates, so slot turnover never rebuilds or
        reallocates the cache. Stale K/V beyond a reset row's kv_len is
        invisible (attention masks by kv_len) and the next
        prefill-into-slot overwrites it; after a reset the ring write
        position wraps back to 0 for that row."""
        rows = jnp.asarray(_raw(rows))
        if rows.dtype == jnp.bool_:
            kv_len = jnp.where(rows, 0, self.kv_len)
        else:
            kv_len = self.kv_len.at[rows].set(0)
        return KVCache(self.k, self.v, kv_len)

    def copy_row_from(self, src: "KVCache", src_row, dst_row) -> "KVCache":
        """Slot admission: overwrite row ``dst_row`` of this cache with
        row ``src_row`` of ``src`` — K, V, and kv_len — leaving every
        other row untouched. ``src`` must share layers/max_len/heads/
        head_dim (typically a batch-1 prefill cache being installed
        into a freed slot of the shared decode cache). Row indices may
        be traced scalars, so ONE compiled program serves every slot."""
        src_row = jnp.asarray(_raw(src_row), jnp.int32)
        dst_row = jnp.asarray(_raw(dst_row), jnp.int32)
        return KVCache(
            self.k.at[:, dst_row].set(src.k[:, src_row].astype(self.k.dtype)),
            self.v.at[:, dst_row].set(src.v[:, src_row].astype(self.v.dtype)),
            self.kv_len.at[dst_row].set(src.kv_len[src_row]))

    def with_kv_len(self, kv_len) -> "KVCache":
        kv_len = jnp.asarray(_raw(kv_len), jnp.int32)
        if kv_len.ndim == 0:
            kv_len = jnp.broadcast_to(kv_len, (self.batch,))
        return KVCache(self.k, self.v, kv_len)

    # --------------------------------------------------------- telemetry
    def occupancy(self) -> float:
        """Host-side fraction of the cache in use (max over rows) — the
        gen.cache_occupancy gauge. Syncs kv_len (a [batch] int32 — a
        few bytes) to host."""
        import numpy as np
        top = np.max(np.asarray(self.kv_len))  # lint: host-sync-ok (tiny read)
        return float(top) / self.max_len  # lint: host-sync-ok (host scalar)

    def __repr__(self):
        return (f"KVCache(layers={self.num_layers}, batch={self.batch}, "
                f"max_len={self.max_len}, dtype={self.k.dtype})")


@jax.tree_util.register_pytree_node_class
class QuantKVCache(KVCache):
    """Int8 ring cache: K/V stored int8 with per-(position, head) bf16
    scales in sidecar arrays ``k_scale``/``v_scale``
    ([layers, batch, max_len, heads]) plus a scalar ``clips`` int32
    counting int8 saturations. Same protocol as :class:`KVCache` —
    ``update`` quantizes in-trace at write time, and the decode kernels
    read the scale rows beside ``kv_len`` to dequantize in-register
    (``kernels.flash_attention_decode(k_scale=, v_scale=)``). Scales
    are per written position, so an append-only update never needs to
    requantize earlier entries (a coarser running-absmax scale would),
    and a row/page copy moves values + scales verbatim — admission
    installs and COW privatizations stay bitwise."""

    __slots__ = ("k_scale", "v_scale", "clips")

    def __init__(self, k, v, kv_len, k_scale, v_scale, clips):
        super().__init__(k, v, kv_len)
        self.k_scale = k_scale
        self.v_scale = v_scale
        self.clips = clips

    # ------------------------------------------------------------ pytree
    def tree_flatten(self):
        return (self.k, self.v, self.kv_len, self.k_scale, self.v_scale,
                self.clips), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def cache_dtype(self):
        return "int8"

    def shard(self, mesh) -> "QuantKVCache":
        trim = _axis_trimmer(mesh)
        spec = P(*(trim(ax) for ax in self.partition_spec()))
        kv_sh = NamedSharding(mesh, spec)
        # scales: [layers, batch, max_len, heads] — same layout minus
        # the head_dim axis
        sc_sh = NamedSharding(mesh, P(*(trim(ax) for ax in
                                        self.partition_spec()[:-1])))
        len_sh = NamedSharding(mesh, P(trim(("dp", "sharding"))))
        rep_sh = NamedSharding(mesh, P())
        place = jax.lax.with_sharding_constraint \
            if isinstance(self.k, jax.core.Tracer) else jax.device_put
        return QuantKVCache(
            place(self.k, kv_sh), place(self.v, kv_sh),
            place(self.kv_len, len_sh), place(self.k_scale, sc_sh),
            place(self.v_scale, sc_sh), place(self.clips, rep_sh))

    # ------------------------------------------------------------ update
    def update(self, layer: int, k_new, v_new, pos) -> "QuantKVCache":
        """Quantize the fresh k/v (absmax per appended token x head) and
        ring-write int8 values + bf16 scales at ``pos``; saturated
        values bump ``clips``. Same contract as the wide cache."""
        k_new, v_new = _raw(k_new), _raw(v_new)
        pos = jnp.asarray(_raw(pos), jnp.int32)
        if pos.ndim == 0:
            pos = jnp.broadcast_to(pos, (k_new.shape[0],))
        steps = jnp.arange(k_new.shape[1], dtype=jnp.int32)
        kq, ks, kc = quantize_kv(k_new)
        vq, vs, vc = quantize_kv(v_new)

        def write(buf, new, p):  # [T, ...], [S, ...], scalar
            idx = (p + steps) % buf.shape[0]
            return buf.at[idx].set(new.astype(buf.dtype))

        k_l = jax.vmap(write)(self.k[layer], kq, pos)
        v_l = jax.vmap(write)(self.v[layer], vq, pos)
        ks_l = jax.vmap(write)(self.k_scale[layer], ks, pos)
        vs_l = jax.vmap(write)(self.v_scale[layer], vs, pos)
        return QuantKVCache(
            self.k.at[layer].set(k_l), self.v.at[layer].set(v_l),
            self.kv_len, self.k_scale.at[layer].set(ks_l),
            self.v_scale.at[layer].set(vs_l), self.clips + kc + vc)

    # -------------------------------------------------------- slot reuse
    def reset_rows(self, rows) -> "QuantKVCache":
        base = KVCache.reset_rows(self, rows)
        return QuantKVCache(self.k, self.v, base.kv_len, self.k_scale,
                            self.v_scale, self.clips)

    def copy_row_from(self, src: "QuantKVCache", src_row,
                      dst_row) -> "QuantKVCache":
        """Slot admission: int8 values AND their scales copy verbatim —
        no requantization, so an installed row decodes bitwise-equal to
        its batch-1 prefill. ``src.clips`` (the prefill's saturation
        count) folds into this cache's counter."""
        src_row = jnp.asarray(_raw(src_row), jnp.int32)
        dst_row = jnp.asarray(_raw(dst_row), jnp.int32)
        return QuantKVCache(
            self.k.at[:, dst_row].set(src.k[:, src_row]),
            self.v.at[:, dst_row].set(src.v[:, src_row]),
            self.kv_len.at[dst_row].set(src.kv_len[src_row]),
            self.k_scale.at[:, dst_row].set(src.k_scale[:, src_row]),
            self.v_scale.at[:, dst_row].set(src.v_scale[:, src_row]),
            self.clips + src.clips)

    def with_kv_len(self, kv_len) -> "QuantKVCache":
        kv_len = jnp.asarray(_raw(kv_len), jnp.int32)
        if kv_len.ndim == 0:
            kv_len = jnp.broadcast_to(kv_len, (self.batch,))
        return QuantKVCache(self.k, self.v, kv_len, self.k_scale,
                            self.v_scale, self.clips)

    def __repr__(self):
        return (f"QuantKVCache(layers={self.num_layers}, "
                f"batch={self.batch}, max_len={self.max_len}, "
                f"dtype=int8+bf16-scales)")
