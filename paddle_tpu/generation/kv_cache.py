"""Ring KV cache for incremental decoding.

One donated on-device pytree holds every layer's cached keys/values:

    k, v:   [num_layers, batch, max_len, num_heads, head_dim]
    kv_len: [batch] int32 — valid entries per row (ragged batches)

``update(layer, k, v, pos)`` is pure-functional (returns a new KVCache
whose buffers alias the old ones under XLA donation), so the SAME code
path jit-compiles for prefill (write the whole padded prompt at pos 0)
and decode (write 1..8 new rows at each row's ``kv_len``). Write
positions wrap modulo ``max_len`` (ring semantics); ``generate()``
validates lengths up front so a live cache never actually wraps — the
wrap exists so an out-of-contract write corrupts the oldest entries
instead of faulting.

Sharding: ``partition_spec()`` places batch on the (dp, sharding) mesh
axes and heads on mp — the same layout the models' qkv activations
carry under ``DistributedTrainStep`` — so hybrid-mesh models decode
without resharding. ``shard(mesh)`` trims the spec to the axes the mesh
actually has.

Reference analog: the fused-multi-transformer decode ops' CacheKV
tensors (paddle/fluid/operators/fused/fused_multi_transformer_op.cu);
here the cache is a plain pytree the compiled step updates in place via
buffer donation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


def _raw(x):
    from ..core.tensor import Tensor
    return x._data if isinstance(x, Tensor) else x


@jax.tree_util.register_pytree_node_class
class KVCache:
    """Per-layer K/V ring cache with per-row valid lengths."""

    __slots__ = ("k", "v", "kv_len")

    def __init__(self, k, v, kv_len):
        self.k = k
        self.v = v
        self.kv_len = kv_len

    # ------------------------------------------------------------ pytree
    def tree_flatten(self):
        return (self.k, self.v, self.kv_len), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # ------------------------------------------------------------- shape
    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    @property
    def batch(self) -> int:
        return self.k.shape[1]

    @property
    def max_len(self) -> int:
        return self.k.shape[2]

    @property
    def dtype(self):
        return self.k.dtype

    # ---------------------------------------------------------- creation
    @classmethod
    def create(cls, num_layers: int, batch: int, max_len: int,
               num_heads: int, head_dim: int, dtype=jnp.float32,
               mesh=None) -> "KVCache":
        shape = (num_layers, batch, max_len, num_heads, head_dim)
        cache = cls(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype),
                    jnp.zeros((batch,), jnp.int32))
        return cache.shard(mesh) if mesh is not None else cache

    @staticmethod
    def partition_spec() -> P:
        """[layers, batch, max_len, heads, head_dim]: batch over
        (dp, sharding), heads over mp — the models' qkv layout."""
        return P(None, ("dp", "sharding"), None, "mp", None)

    def shard(self, mesh) -> "KVCache":
        """Place the cache on ``mesh`` (spec trimmed to the axes the
        mesh has). Works both eagerly (device_put) and inside a trace
        (sharding constraint)."""
        names = set(mesh.axis_names)

        def trim(axes):
            if isinstance(axes, tuple):
                kept = tuple(a for a in axes if a in names)
                return kept if kept else None
            return axes if axes in names else None

        spec = P(*(trim(ax) for ax in self.partition_spec()))
        kv_sh = NamedSharding(mesh, spec)
        len_sh = NamedSharding(mesh, P(trim(("dp", "sharding"))))
        place = jax.lax.with_sharding_constraint \
            if isinstance(self.k, jax.core.Tracer) else jax.device_put
        return KVCache(place(self.k, kv_sh), place(self.v, kv_sh),
                       place(self.kv_len, len_sh))

    # ------------------------------------------------------------ update
    def update(self, layer: int, k_new, v_new, pos) -> "KVCache":
        """Write ``k_new``/``v_new`` ([batch, s, heads, head_dim]) into
        ``layer`` at per-row start position ``pos`` ([batch] int32 or a
        scalar), wrapping modulo max_len. Does NOT advance ``kv_len`` —
        every layer of one forward writes at the same positions; the
        model advances the length once via ``with_kv_len``."""
        k_new, v_new = _raw(k_new), _raw(v_new)
        pos = jnp.asarray(_raw(pos), jnp.int32)
        if pos.ndim == 0:
            pos = jnp.broadcast_to(pos, (k_new.shape[0],))
        steps = jnp.arange(k_new.shape[1], dtype=jnp.int32)

        def write(buf, new, p):  # [T, H, D], [S, H, D], scalar
            # scatter, not dynamic_update_slice: each target slot wraps
            # modulo max_len independently (true ring semantics; a
            # slice write would CLAMP at the end instead)
            idx = (p + steps) % buf.shape[0]
            return buf.at[idx].set(new.astype(buf.dtype))

        k_l = jax.vmap(write)(self.k[layer], k_new, pos)
        v_l = jax.vmap(write)(self.v[layer], v_new, pos)
        return KVCache(self.k.at[layer].set(k_l),
                       self.v.at[layer].set(v_l), self.kv_len)

    def positions(self, s: int):
        """Absolute positions of ``s`` appended tokens per row
        ([batch, s] int32: ``kv_len[r] .. kv_len[r]+s-1``) — the decode
        position-embedding offsets."""
        return self.kv_len[:, None] + \
            jnp.arange(s, dtype=jnp.int32)[None, :]

    # -------------------------------------------------------- slot reuse
    def reset_rows(self, rows) -> "KVCache":
        """Free batch rows for reuse: zero ``kv_len`` at ``rows`` (one
        row index, an int array of rows, or a [batch] bool mask)
        without touching the K/V buffers or the pytree structure — the
        serving scheduler calls this (jit-compiled, cache donated) when
        a slot's request terminates, so slot turnover never rebuilds or
        reallocates the cache. Stale K/V beyond a reset row's kv_len is
        invisible (attention masks by kv_len) and the next
        prefill-into-slot overwrites it; after a reset the ring write
        position wraps back to 0 for that row."""
        rows = jnp.asarray(_raw(rows))
        if rows.dtype == jnp.bool_:
            kv_len = jnp.where(rows, 0, self.kv_len)
        else:
            kv_len = self.kv_len.at[rows].set(0)
        return KVCache(self.k, self.v, kv_len)

    def copy_row_from(self, src: "KVCache", src_row, dst_row) -> "KVCache":
        """Slot admission: overwrite row ``dst_row`` of this cache with
        row ``src_row`` of ``src`` — K, V, and kv_len — leaving every
        other row untouched. ``src`` must share layers/max_len/heads/
        head_dim (typically a batch-1 prefill cache being installed
        into a freed slot of the shared decode cache). Row indices may
        be traced scalars, so ONE compiled program serves every slot."""
        src_row = jnp.asarray(_raw(src_row), jnp.int32)
        dst_row = jnp.asarray(_raw(dst_row), jnp.int32)
        return KVCache(
            self.k.at[:, dst_row].set(src.k[:, src_row].astype(self.k.dtype)),
            self.v.at[:, dst_row].set(src.v[:, src_row].astype(self.v.dtype)),
            self.kv_len.at[dst_row].set(src.kv_len[src_row]))

    def with_kv_len(self, kv_len) -> "KVCache":
        kv_len = jnp.asarray(_raw(kv_len), jnp.int32)
        if kv_len.ndim == 0:
            kv_len = jnp.broadcast_to(kv_len, (self.batch,))
        return KVCache(self.k, self.v, kv_len)

    # --------------------------------------------------------- telemetry
    def occupancy(self) -> float:
        """Host-side fraction of the cache in use (max over rows) — the
        gen.cache_occupancy gauge. Syncs kv_len (a [batch] int32 — a
        few bytes) to host."""
        import numpy as np
        top = np.max(np.asarray(self.kv_len))  # lint: host-sync-ok (tiny read)
        return float(top) / self.max_len  # lint: host-sync-ok (host scalar)

    def __repr__(self):
        return (f"KVCache(layers={self.num_layers}, batch={self.batch}, "
                f"max_len={self.max_len}, dtype={self.k.dtype})")
