"""Speculative decoding: draft-propose + single-dispatch verify.

Sequential decode pays one target-model dispatch per token — the
latency-bound regime serving lives in at low batch. Speculation breaks
the serialization: a cheap DRAFTER proposes K tokens, then ONE target
forward over the K+1-token window (the ragged q-len 1..8 shape
``kernels.flash_attention_decode`` already supports) verifies them all,
and per-row accept lengths decide how many tokens each row really
emitted (1..K+1 per dispatch). Two drafters share the machinery:

- **self-speculative / prompt-lookup** (``mode="ngram"``): find the
  most recent earlier occurrence of the last ``ngram`` tokens in the
  row's own token buffer (prompt + everything emitted, resident on
  device) and propose its continuation. Pure jnp, no second model —
  every deployment benefits; it shines on the input-grounded repetition
  real traffic is full of (summarization, code edit, RAG).
- **draft model** (``mode="draft"``): a small LM sharing the target's
  vocab and the exact ``KVCache`` layout proposes K tokens greedily
  (one jitted program unrolls the K+1 tiny steps — the extra step
  writes the last draft token's KV so both caches stay position-aligned
  under full acceptance).

Acceptance is exact, never approximate:

- **greedy**: accept draft tokens while they equal the target argmax;
  emit the accepted prefix plus the target's own token at the first
  mismatch. The emitted stream is BITWISE the sequential greedy stream
  — the tier-1 gate asserts it on session and engine paths.
- **temperature > 0**: rejection sampling against the target's
  FILTERED distribution (temperature/top-k/top-p, the same transforms
  ``sampling.sample`` applies). Both drafters propose deterministically
  (a point-mass draft distribution), so token ``d`` is accepted with
  probability ``p_target(d)`` and a rejection resamples from the
  residual with ``d`` masked out — the emitted marginal equals
  sequential sampling exactly (tested distributionally).

KV-cache rollback is free: the verify forward writes all K+1 positions,
then per-row ``kv_len`` is rolled back to ``base + emit_n`` — entries
past ``kv_len`` are invisible to attention and overwritten by the next
window. The ring must carry ``spec.k`` slack beyond prompt+max_new for
the last window's unaccepted overhang; ``generate()`` and the engine
validate that bound up front (the clamp satellite).

Reference analog: the reference's inference layer amortizes decode
dispatch overhead with fused multi-token ops; speculative verify is the
same amortization expressed as one ragged-window program.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..core import monitor
from ..core.tensor import Tensor

__all__ = ["SpeculativeConfig", "SpeculativeSession", "ngram_propose",
           "spec_accept"]


@dataclasses.dataclass(frozen=True)
class SpeculativeConfig:
    """Static speculation knobs (hashable: a jit static argument —
    a new config compiles a new draft/verify pair).

    mode: ``"ngram"`` (self-speculative prompt lookup, no second model)
    or ``"draft"`` (a draft LM passed separately).
    k: draft tokens proposed per window; the verify window is ``k + 1``
    query rows and must fit the decode kernel's sublane tile.
    ngram: suffix length the prompt-lookup drafter matches on."""
    mode: str = "ngram"
    k: int = 4
    ngram: int = 3

    def __post_init__(self):
        from ..kernels.flash_attention import MAX_DECODE_QLEN
        if self.mode not in ("ngram", "draft"):
            raise ValueError(
                f"speculative mode {self.mode!r}: one of 'ngram' "
                "(self-speculative prompt lookup) or 'draft' (draft "
                "model)")
        if self.k < 1:
            raise ValueError(f"speculative draft_k must be >= 1, "
                             f"got {self.k}")
        if self.k + 1 > MAX_DECODE_QLEN:
            # the q-len guard at the API boundary: fail here with the
            # limit's name instead of letting an oversized window fall
            # through the decode kernel's padding paths
            raise ValueError(
                f"speculative draft_k={self.k}: the verify window "
                f"k+1={self.k + 1} exceeds flash_attention_decode's "
                f"MAX_DECODE_QLEN ({MAX_DECODE_QLEN}, the 8-row fp32 "
                f"sublane tile); use draft_k <= {MAX_DECODE_QLEN - 1}")
        if self.ngram < 1:
            raise ValueError(f"speculative ngram must be >= 1, "
                             f"got {self.ngram}")


def as_spec_config(speculative, draft_model=None):
    """Coerce the user-facing ``speculative=`` argument (None | mode
    string | SpeculativeConfig) and cross-check the draft model."""
    if speculative is None or speculative is False:
        return None
    if isinstance(speculative, str):
        speculative = SpeculativeConfig(mode=speculative)
    if not isinstance(speculative, SpeculativeConfig):
        raise TypeError(
            "speculative= takes 'ngram', 'draft', or a "
            f"SpeculativeConfig; got {type(speculative).__name__}")
    if speculative.mode == "draft" and draft_model is None:
        raise ValueError(
            "speculative='draft' needs draft_model= (a generative LM "
            "sharing the target's vocabulary); use "
            "speculative='ngram' for model-free self-speculation")
    if speculative.mode == "ngram" and draft_model is not None:
        raise ValueError(
            "draft_model= given but speculative mode is 'ngram'; pass "
            "speculative='draft' to use it")
    return speculative


# ------------------------------------------------------------- drafters

def ngram_propose(tok_buf, tok_len, *, k: int, n: int):
    """Prompt-lookup proposal, pure jnp with static shapes.

    tok_buf: [B, L] int32 — each row's full token history (prompt +
    every emitted token, INCLUDING the pending one the next window
    feeds). tok_len: [B] int32 valid lengths. Finds the most recent
    p < len - n with ``buf[p:p+n] == buf[len-n:len]`` and proposes the
    k tokens following the match (clamped to known tokens); rows with
    no match (or history shorter than n+1) propose their last token
    repeated — verification keeps correctness either way, a bad draft
    only costs accept rate."""
    b, L = tok_buf.shape
    ctx_idx = jnp.clip(tok_len[:, None] - n + jnp.arange(n)[None, :],
                       0, L - 1)
    ctx = jnp.take_along_axis(tok_buf, ctx_idx, axis=1)        # [B, n]
    # candidate windows buf[p:p+n] for every p, as [B, L-n+1, n]
    win = jnp.stack([tok_buf[:, i:L - n + 1 + i] for i in range(n)],
                    axis=-1)
    eq = jnp.all(win == ctx[:, None, :], axis=-1)              # [B, P]
    p = jnp.arange(L - n + 1, dtype=jnp.int32)[None, :]
    valid = (p < tok_len[:, None] - n) & (tok_len[:, None] >= n + 1)
    best = jnp.max(jnp.where(eq & valid, p, -1), axis=1)       # [B]
    last = jnp.take_along_axis(
        tok_buf, jnp.maximum(tok_len - 1, 0)[:, None], axis=1)[:, 0]
    cont_idx = best[:, None] + n + jnp.arange(k, dtype=jnp.int32)[None, :]
    cont = jnp.take_along_axis(tok_buf, jnp.clip(cont_idx, 0, L - 1),
                               axis=1)
    ok = (best[:, None] >= 0) & (cont_idx < tok_len[:, None])
    return jnp.where(ok, cont, last[:, None]).astype(jnp.int32)


# ----------------------------------------------------------- acceptance

def spec_accept(logits, draft, key, cfg):
    """Accept/reject K deterministic draft tokens against the target's
    K+1 logits. logits: [B, K+1, V] fp32 (position j predicts the token
    AFTER window input j); draft: [B, K] int32. Returns
    ``(emitted [B, K+1], n_accept [B])`` — emitted[j] is draft[j] for
    j < n_accept, the target's own correction/bonus token at
    j == n_accept, garbage beyond (masked by the caller's emit count).

    Greedy (cfg.do_sample False or temperature 0): accept while
    draft == argmax — the emitted stream is bitwise the sequential
    greedy stream. Sampling: rejection sampling against the FILTERED
    target distribution (temperature/top-k/top-p, exactly
    ``sampling.sample``'s transforms); the drafters are deterministic
    (point-mass q), so accept-with-prob-p(d) + residual-resample
    reproduces the sequential sampling marginal exactly."""
    from .sampling import apply_temperature, apply_top_k, apply_top_p
    b, kp1, v = logits.shape
    k = kp1 - 1
    pos = jnp.arange(kp1, dtype=jnp.int32)[None, :]
    dpad = jnp.concatenate([draft, draft[:, -1:]], axis=1)     # [B, K+1]
    if not cfg.do_sample or float(cfg.temperature) == 0.0:  # lint: host-sync-ok (static config coercion)
        tgt = jnp.argmax(logits, axis=-1).astype(jnp.int32)    # [B, K+1]
        match = (draft == tgt[:, :k]).astype(jnp.int32)
        n_accept = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
        corr = jnp.take_along_axis(tgt, n_accept[:, None], axis=1)
        emitted = jnp.where(pos == n_accept[:, None], corr, dpad)
        return emitted.astype(jnp.int32), n_accept
    f = apply_temperature(logits, cfg.temperature)
    if cfg.top_k and cfg.top_k > 0:
        f = apply_top_k(f, cfg.top_k)
    if cfg.top_p is not None and float(cfg.top_p) < 1.0:  # lint: host-sync-ok (static config coercion)
        f = apply_top_p(f, cfg.top_p)
    probs = jax.nn.softmax(f, axis=-1)                         # [B,K+1,V]
    p_draft = jnp.take_along_axis(probs[:, :k], draft[..., None],
                                  axis=-1)[..., 0]             # [B, K]
    ku, kr = jax.random.split(key)
    accept = (jax.random.uniform(ku, (b, k)) < p_draft).astype(jnp.int32)
    n_accept = jnp.sum(jnp.cumprod(accept, axis=1), axis=1)    # 0..K
    # distribution at the stop position: residual (draft token masked,
    # renormalized by categorical) on a rejection, the plain filtered
    # distribution for the bonus token on full acceptance
    p_stop = jnp.take_along_axis(
        probs, n_accept[:, None, None],
        axis=1)[:, 0]                                          # [B, V]
    d_stop = jnp.take_along_axis(dpad, n_accept[:, None], axis=1)[:, 0]
    masked = p_stop * (jnp.arange(v)[None, :] != d_stop[:, None])
    resid = jnp.where((n_accept == k)[:, None], p_stop, masked)
    corr = jax.random.categorical(
        kr, jnp.log(jnp.maximum(resid, 1e-38)), axis=-1).astype(jnp.int32)
    emitted = jnp.where(pos == n_accept[:, None], corr[:, None], dpad)
    return emitted.astype(jnp.int32), n_accept


def acceptance_bookkeeping(emitted, n_accept, finished, done, budget,
                           eos_token_id):
    """Clamp a window's acceptance into per-row emit counts.

    done/budget: [B] int32 tokens already emitted / per-row cap. The
    clamps are the overshoot guard: a row can never emit past its
    budget (``emit_n <= budget - done``) nor past its first eos inside
    the window. Returns ``(emit_n, new_finished)``; callers advance
    ``done``/``kv_len``/buffers by ``emit_n``."""
    kp1 = emitted.shape[1]
    avail = jnp.maximum(budget - done, 0)
    emit_n = jnp.minimum(n_accept + 1, avail)
    emit_n = jnp.where(finished, 0, emit_n)
    j = jnp.arange(kp1, dtype=jnp.int32)[None, :]
    if eos_token_id is not None:
        is_eos = (emitted == jnp.int32(eos_token_id)) & \
            (j < emit_n[:, None])
        eos_hit = jnp.any(is_eos, axis=1)
        first = jnp.argmax(is_eos, axis=1).astype(jnp.int32)
        emit_n = jnp.where(eos_hit, jnp.minimum(emit_n, first + 1),
                           emit_n)
    else:
        eos_hit = jnp.zeros(finished.shape, bool)
    new_finished = finished | eos_hit | (done + emit_n >= budget)
    return emit_n, new_finished


def scatter_window(buf, start, vals, emit_n):
    """Write ``vals[:, :emit_n]`` into ``buf`` at per-row offsets
    ``start`` (masked lanes routed out of bounds and dropped, so a
    clamped row never writes anywhere)."""
    b, c = buf.shape
    j = jnp.arange(vals.shape[1], dtype=jnp.int32)[None, :]
    idx = jnp.where(j < emit_n[:, None], start[:, None] + j, c)
    rows = jnp.arange(b, dtype=jnp.int32)[:, None]
    return buf.at[rows, idx].set(vals, mode="drop")


def window_advance(tok, emitted, emit_n):
    """Next pending token: the window's last emitted token (the row's
    old pending token when the row emitted nothing)."""
    last = jnp.take_along_axis(
        emitted, jnp.maximum(emit_n - 1, 0)[:, None], axis=1)[:, 0]
    return jnp.where(emit_n > 0, last, tok).astype(jnp.int32)


def apply_verify_window(logits, draft, key, cfg, spec, tok, cache,
                        finished, done, budget, out_buf, tok_buf,
                        tok_len, proposed, accepted, *,
                        pin_finished_kv=False):
    """The one acceptance/bookkeeping core behind every verify program
    (the session's verify_fn AND the engine's fused slot step): accept
    the window, clamp emissions (budget/eos/finished), scatter into the
    output and token-history buffers, advance the pending token and
    counters, and roll the cache back to the accepted window.
    ``pin_finished_kv`` is the engine's idle-lane contract (finished
    slots hold kv_len 0 so they never wrap the ring while parked).
    Returns ``(tok, cache, finished, done, out_buf, tok_buf, tok_len,
    proposed, accepted)`` — all advanced."""
    emitted, n_accept = spec_accept(logits, draft, key, cfg)
    emit_n, new_finished = acceptance_bookkeeping(
        emitted, n_accept, finished, done, budget, cfg.eos_token_id)
    out_buf = scatter_window(out_buf, done, emitted, emit_n)
    tok_buf = scatter_window(tok_buf, tok_len, emitted, emit_n)
    live = (~finished).astype(jnp.int32)
    proposed = proposed + jnp.int32(spec.k) * jnp.sum(live)
    # clamped-away acceptances count as NOT accepted (they were wasted
    # proposals); the correction/bonus token is never a draft token
    accepted = accepted + jnp.sum(jnp.minimum(n_accept, emit_n) * live)
    tok = window_advance(tok, emitted, emit_n)
    # rollback: the forward wrote (and advanced past) all K+1 window
    # positions; keep only the accepted inputs
    base = cache.kv_len - jnp.int32(spec.k + 1)
    new_len = base + emit_n
    if pin_finished_kv:
        new_len = jnp.where(new_finished, 0, new_len)
    cache = cache.with_kv_len(new_len)
    return (tok, cache, new_finished, done + emit_n, out_buf, tok_buf,
            tok_len + emit_n, proposed, accepted)


# -------------------------------------------------------------- session

class SpeculativeSession:
    """The jitted (draft, verify) program pair over one target network
    (and, in draft mode, one draft network). Built once per
    (GenerationSession, SpeculativeConfig, draft network) and cached on
    the generation session, so jax's jit cache carries warm executables
    across ``generate(speculative=...)`` calls; ``aot_compile`` is the
    Predictor's bucket path (compile at startup, zero retraces under
    traffic, executables persisted through the ``jit.compile_cache``
    store)."""

    def __init__(self, session, spec: SpeculativeConfig,
                 draft_network=None):
        from ..jit.api import _RetraceTracker, _unwrap, functional_call
        from .api import GenerationSession, _expect_logits_cache
        self.session = session
        self.spec = spec
        self.draft_network = draft_network
        network = session.network
        names = session._names
        self._draft_tracker = _RetraceTracker()
        self._verify_tracker = _RetraceTracker()
        self._compiled = {}

        if spec.mode == "draft":
            if draft_network is None:
                raise ValueError("speculative mode 'draft' needs a "
                                 "draft network")
            draft_network.eval()
            # the draft model's own (prefill, decode) session: prefill
            # fills the draft KV cache at generate() start; its decode
            # program is unused (the draft loop below replaces it)
            self._draft_session = GenerationSession(
                draft_network, executable_store=session.executable_store)
            dnames = self._draft_session._names

            def draft_fn(dvals, tok, dcache, sync_len, spec):
                # re-anchor the draft cache at the target's accepted
                # length (the post-rollback kv_len travels as data), so
                # one program serves every acceptance outcome
                dcache = dcache.with_kv_len(sync_len)
                drafts = []
                t = tok
                # k proposals + one extra step that only writes the
                # last draft token's KV: under full acceptance the next
                # window's rollback needs base + k + 1 entries in BOTH
                # caches (the k+1'th greedy token is discarded)
                for _ in range(spec.k + 1):
                    out = functional_call(
                        draft_network, dict(zip(dnames, dvals)),
                        Tensor(t[:, None]), cache=dcache)
                    logits, dcache = _expect_logits_cache(out)
                    t = jnp.argmax(
                        _unwrap(logits)[:, -1].astype(jnp.float32),
                        axis=-1).astype(jnp.int32)
                    drafts.append(t)
                return jnp.stack(drafts[:spec.k], axis=1), dcache
        else:
            self._draft_session = None

            def draft_fn(tok_buf, tok_len, spec):
                return ngram_propose(tok_buf, tok_len, k=spec.k,
                                     n=spec.ngram)

        def verify_fn(state_vals, tok, draft, cache, key, finished,
                      done, budget, out_buf, tok_buf, tok_len, proposed,
                      accepted, cfg, spec):
            window = jnp.concatenate([tok[:, None], draft], axis=1)
            out = functional_call(network, dict(zip(names, state_vals)),
                                  Tensor(window), cache=cache)
            logits, cache = _expect_logits_cache(out)
            logits = _unwrap(logits).astype(jnp.float32)  # [B, K+1, V]
            k0, k1 = jax.random.split(key)
            (tok, cache, finished, done, out_buf, tok_buf, tok_len,
             proposed, accepted) = apply_verify_window(
                logits, draft, k0, cfg, spec, tok, cache, finished,
                done, budget, out_buf, tok_buf, tok_len, proposed,
                accepted)
            return (tok, cache, k1, finished, done, out_buf, tok_buf,
                    tok_len, proposed, accepted)

        self._draft_fn, self._verify_fn = draft_fn, verify_fn
        tpu = jax.default_backend() == "tpu"
        # donation intent (TPU only; CPU/GPU donation is a warn-only
        # no-op): every state-carrying lane of the verify step — cache,
        # pending token, key, flags, counters, and both token buffers —
        # updates in place across windows. audit() gates this intent.
        self._verify_donate = (1, 3, 4, 5, 6, 8, 9, 10, 11, 12) \
            if tpu else ()
        self._draft_donate = ((2,) if tpu else ()) \
            if spec.mode == "draft" else ()
        self._draft_jit = jax.jit(
            draft_fn,
            static_argnums=(4,) if spec.mode == "draft" else (2,),
            donate_argnums=self._draft_donate)
        self._verify_jit = jax.jit(verify_fn, static_argnums=(13, 14),
                                   donate_argnums=self._verify_donate)

    # ----------------------------------------------------------- calling
    def registered_buf_width(self, batch: int, cache_len: int, cfg,
                             min_width: int) -> int:
        """The smallest AOT-registered verify out-buffer width that can
        hold ``min_width`` tokens (or ``min_width`` itself when nothing
        matching is registered). The verify executable is shape-keyed
        on the out buffer, so a caller asking for FEWER tokens than the
        compiled budget (``Predictor.generate(max_new_tokens=...)``)
        must decode into the compiled width — budget travels as a lane,
        the program never depends on it — instead of missing every warm
        executable and re-compiling under traffic."""
        widths = [k[2][1] for k in self._compiled
                  if k[0] == "verify" and k[1] == (batch,)
                  and k[3] == cache_len and k[4] == cfg
                  and k[2][1] >= min_width]
        return min(widths) if widths else min_width

    def _draft_key(self, args):
        # ngram dispatches (tok_buf, tok_len); draft mode dispatches
        # (draft_state, tok, draft_cache, sync_len) — the shape-bearing
        # arg differs, the key shape is what AOT registered
        return ("draft", args[0].shape if self.spec.mode == "ngram"
                else args[1].shape)

    def draft(self, *args):
        """One draft dispatch: ``(tok_buf, tok_len)`` in ngram mode,
        ``(draft_state, tok, draft_cache, sync_len)`` in draft mode."""
        self.session._ensure_eval()
        exe = self._compiled.get(self._draft_key(args))
        if exe is not None:
            return exe(*args)
        pre = self._draft_tracker.pre(self._draft_jit)
        out = self._draft_jit(*args, self.spec)
        self._draft_tracker.observe(
            self._draft_jit,
            tuple(getattr(a, "shape", None) for a in args), pre)
        return out

    def verify(self, state_vals, tok, draft, cache, key, finished, done,
               budget, out_buf, tok_buf, tok_len, proposed, accepted,
               cfg):
        self.session._ensure_eval()
        ckey = ("verify", tok.shape, out_buf.shape, cache.max_len, cfg)
        exe = self._compiled.get(ckey)
        if exe is not None:
            return exe(state_vals, tok, draft, cache, key, finished,
                       done, budget, out_buf, tok_buf, tok_len,
                       proposed, accepted)
        pre = self._verify_tracker.pre(self._verify_jit)
        out = self._verify_jit(state_vals, tok, draft, cache, key,
                               finished, done, budget, out_buf, tok_buf,
                               tok_len, proposed, accepted, cfg,
                               self.spec)
        self._verify_tracker.observe(self._verify_jit, ckey[1:], pre)
        return out

    # --------------------------------------------------------------- aot
    def aot_compile(self, batch: int, prompt_len: int, cache_len: int,
                    max_new: int, cfg):
        """AOT-compile the (draft, verify) pair for one fixed padded
        shape — the Predictor's serving mode, persisted through the
        executable store under the new ``generation.spec_draft`` /
        ``generation.spec_verify`` program kinds. Draft mode also
        AOT-compiles the draft model's own prefill bucket so admission
        never traces under traffic."""
        from ..jit import compile_cache
        sess = self.session
        store = sess.executable_store
        spec, k = self.spec, self.spec.k
        sds = jax.ShapeDtypeStruct
        state = tuple(sds(tuple(v.shape), v.dtype)
                      for v in sess.state_values())
        tok = sds((batch,), jnp.int32)
        draft_a = sds((batch, k), jnp.int32)
        key = sds((2,), jnp.uint32)
        flags = sds((batch,), jnp.bool_)
        lane = sds((batch,), jnp.int32)
        out_buf = sds((batch, int(max_new)), jnp.int32)
        tok_buf = sds((batch, int(cache_len)), jnp.int32)
        scalar = sds((), jnp.int32)
        base_sig = compile_cache.network_signature(sess.network)

        def sig_for(kind):
            if base_sig is None:
                return None
            sig = dict(base_sig)
            sig.update(program=(kind, batch, prompt_len, cache_len,
                                max_new),
                       generation=repr(cfg), speculative=repr(spec),
                       operands=compile_cache.aval_signature(state))
            return sig

        # the cache aval comes from the base prefill's abstract trace
        ids = sds((batch, prompt_len), jnp.int32)
        plen = sds((batch,), jnp.int32)
        _, cache_a, _, _ = jax.eval_shape(
            lambda s, i, p, kk: sess._prefill_fn(s, i, p, kk, cfg,
                                                 cache_len),
            state, ids, plen, key)

        if spec.mode == "draft":
            # draft admission path: the draft model's own prefill
            # bucket only (its decode program is never dispatched —
            # the unrolled draft program below replaces it)
            self._draft_session.aot_compile(batch, prompt_len,
                                            cache_len, cfg,
                                            decode=False)
            dstate = tuple(sds(tuple(v.shape), v.dtype)
                           for v in self._draft_session.state_values())
            _, dcache_a, _, _ = jax.eval_shape(
                lambda s, i, p, kk: self._draft_session._prefill_fn(
                    s, i, p, kk, cfg, cache_len),
                dstate, ids, plen, key)
            dexe = compile_cache.build_or_load(
                sig_for("generation.spec_draft"),
                lambda: self._draft_jit.lower(dstate, tok, dcache_a,
                                              lane, spec),
                store=store,
                extra=dict(kind="generation.spec_draft",
                           donation=self._draft_donate),
                label=f"generation.spec_draft.b{batch}k{k}")
            self._compiled[("draft", tok.shape)] = dexe
        else:
            dexe = compile_cache.build_or_load(
                sig_for("generation.spec_draft"),
                lambda: self._draft_jit.lower(tok_buf, lane, spec),
                store=store,
                extra=dict(kind="generation.spec_draft", donation=()),
                label=f"generation.spec_draft.b{batch}k{k}")
            self._compiled[("draft", tok_buf.shape)] = dexe

        vexe = compile_cache.build_or_load(
            sig_for("generation.spec_verify"),
            lambda: self._verify_jit.lower(
                state, tok, draft_a, cache_a, key, flags, lane, lane,
                out_buf, tok_buf, lane, scalar, scalar, cfg, spec),
            store=store,
            extra=dict(kind="generation.spec_verify",
                       donation=self._verify_donate),
            label=f"generation.spec_verify.b{batch}w{k + 1}")
        self._compiled[("verify", tok.shape, out_buf.shape, cache_len,
                        cfg)] = vexe
        return dexe, vexe

    # ------------------------------------------------------------- audit
    def audit(self, batch: int, prompt_len: int, cache_len: int,
              max_new: int, cfg, **audit_kw):
        """Static audit of the (draft, verify) pair for one padded
        shape (nothing executes). Verify is audited with the TPU
        donation INTENT — the KV cache, token buffers, and every lane
        donated — even on CPU; the tier-1 gate asserts zero ERROR
        findings on both and full donation coverage on verify."""
        from ..analysis import audit as _audit
        self.session._ensure_eval()
        base = audit_kw.pop("name", "generation.spec")
        verify_donate = audit_kw.pop(
            "donate", (1, 3, 4, 5, 6, 8, 9, 10, 11, 12))
        draft_donate = audit_kw.pop("draft_donate", (2,))
        spec, k = self.spec, self.spec.k
        sds = jax.ShapeDtypeStruct
        state = tuple(sds(tuple(v.shape), v.dtype)
                      for v in self.session.state_values())
        tok = sds((batch,), jnp.int32)
        draft_a = sds((batch, k), jnp.int32)
        key = sds((2,), jnp.uint32)
        flags = sds((batch,), jnp.bool_)
        lane = sds((batch,), jnp.int32)
        out_buf = sds((batch, int(max_new)), jnp.int32)
        tok_buf = sds((batch, int(cache_len)), jnp.int32)
        scalar = sds((), jnp.int32)
        ids = sds((batch, prompt_len), jnp.int32)
        _, cache_a, _, _ = jax.eval_shape(
            lambda s, i, p, kk: self.session._prefill_fn(
                s, i, p, kk, cfg, cache_len),
            state, ids, lane, key)
        if spec.mode == "draft":
            dstate = tuple(sds(tuple(v.shape), v.dtype)
                           for v in self._draft_session.state_values())
            _, dcache_a, _, _ = jax.eval_shape(
                lambda s, i, p, kk: self._draft_session._prefill_fn(
                    s, i, p, kk, cfg, cache_len),
                dstate, ids, lane, key)
            draft_report = _audit(
                self._draft_fn, dstate, tok, dcache_a, lane, spec,
                static_argnums=(4,), donate=draft_donate,
                name=f"{base}.draft", **audit_kw)
        else:
            draft_report = _audit(
                self._draft_fn, tok_buf, lane, spec,
                static_argnums=(2,), name=f"{base}.draft",
                **audit_kw)
        verify_report = _audit(
            self._verify_fn, state, tok, draft_a, cache_a, key, flags,
            lane, lane, out_buf, tok_buf, lane, scalar, scalar, cfg,
            spec, static_argnums=(13, 14), donate=verify_donate,
            name=f"{base}.verify", **audit_kw)
        return draft_report, verify_report


# ----------------------------------------------------------- host loop

def decode_loop(network, session, state_vals, ids, plen, cfg, spec,
                draft_model, cache_len, max_new_tokens, key, live_rows,
                poll_every: int = 4):
    """The speculative ``generate()`` host loop: one base prefill, then
    draft+verify window dispatches until every row finishes (eos or
    budget). Rows advance RAGGEDLY — per-row emit counts live on
    device; the host polls one tiny bool every ``poll_every`` windows
    (never per window). Returns the [B, max_new_tokens] int32 result
    with post-eos padding, identical in contract (and, under greedy,
    bitwise) to the sequential path."""
    spec_sess = session.speculative(spec, draft_model)
    b = ids.shape[0]
    tok, cache, key, finished = session.prefill(
        state_vals, jnp.asarray(ids), jnp.asarray(plen), key, cfg,
        cache_len)
    if monitor.enabled:
        monitor.record_generation(prefill_steps=1)

    dstate = dcache = None
    if spec.mode == "draft":
        dsess = spec_sess._draft_session
        dstate = dsess.state_values()
        _, dcache, _, _ = dsess.prefill(
            dstate, jnp.asarray(ids), jnp.asarray(plen), key, cfg,
            cache_len)
        if monitor.enabled:
            monitor.record_generation(prefill_steps=1)

    pad = jnp.int32(cfg.pad_value)
    # decode into the compiled out-buffer width when one is registered
    # (the Predictor's smaller-than-budget max_new_tokens path): budget
    # is a lane, so rows still stop at max_new_tokens and the result is
    # sliced back — but every dispatch stays on a warm executable
    width = spec_sess.registered_buf_width(b, cache_len, cfg,
                                           max_new_tokens)
    out_buf = jnp.full((b, width), pad, jnp.int32).at[:, 0].set(tok)
    # token history for the drafter: padded prompt + the pending token
    hist = np.full((b, cache_len), int(cfg.pad_value), np.int32)
    hist[:, :ids.shape[1]] = ids
    tok_buf = jnp.asarray(hist).at[jnp.arange(b), jnp.asarray(plen)] \
        .set(tok)
    tok_len = jnp.asarray(plen, jnp.int32) + 1
    done = jnp.ones((b,), jnp.int32)
    budget = jnp.full((b,), max_new_tokens, jnp.int32)
    finished = finished | (done >= budget)
    proposed = accepted = jnp.zeros((), jnp.int32)

    for w in range(max_new_tokens - 1):
        if spec.mode == "draft":
            draft, dcache = spec_sess.draft(dstate, tok, dcache,
                                            cache.kv_len)
        else:
            draft = spec_sess.draft(tok_buf, tok_len)
        (tok, cache, key, finished, done, out_buf, tok_buf, tok_len,
         proposed, accepted) = spec_sess.verify(
            state_vals, tok, draft, cache, key, finished, done, budget,
            out_buf, tok_buf, tok_len, proposed, accepted, cfg)
        if monitor.enabled:
            monitor.record_generation(decode_steps=1)
        # ragged progress: one tiny bool read every poll_every windows
        # (never per window — that would drain the dispatch queue);
        # every live row emits >= 1 token per window, so the loop also
        # terminates unpolled after max_new_tokens - 1 windows
        if (w + 1) % poll_every == 0 and \
                bool(jnp.all(finished)):  # lint: host-sync-ok (every-K-window poll)
            break

    result = out_buf[:, :max_new_tokens] if width > max_new_tokens \
        else out_buf
    if monitor.enabled:
        live = b if live_rows is None else min(int(live_rows), b)
        np_prop = int(proposed)  # lint: host-sync-ok (end-of-call counter read)
        np_acc = int(accepted)  # lint: host-sync-ok (end-of-call counter read)
        monitor.record_speculative(np_prop, np_acc)
        arr = np.asarray(result[:live])  # lint: host-sync-ok (one end-of-call read)
        done_h = np.asarray(done)  # lint: host-sync-ok (same end-of-call read)
        if cfg.eos_token_id is not None:
            hit = arr == cfg.eos_token_id
            per_row = np.where(hit.any(1), hit.argmax(1) + 1,
                               max_new_tokens)
            tokens = int(per_row.sum())
        else:
            tokens = int(done_h[:live].sum())
        monitor.record_generation(tokens=tokens)
        # occupancy from tokens ACTUALLY emitted (same contract as the
        # sequential path's n_done) — an early-eos batch must not read
        # as a full ring
        plen_h = np.asarray(plen)  # lint: host-sync-ok (host-side plen)
        monitor.record_cache_occupancy(
            int(np.max(plen_h + done_h)) / cache_len)
    return Tensor(result)
