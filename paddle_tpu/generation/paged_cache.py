"""Block-table paged KV cache with shared-prefix reuse.

The dense ring cache (``kv_cache.KVCache``) charges every slot
``max_len`` HBM whether its request uses 40 tokens or 4000, and N
concurrent requests sharing a system prompt each store their own copy
of its K/V. This module replaces the per-slot rows with a POOL of
fixed-size pages plus per-row page tables — the PagedAttention design
(Kwon et al., SOSP '23) with Hydragen-style shared-prefix reuse —
built natively on the decode kernel's index-map indirection (the same
mechanism its GQA head mapping already uses):

- **PagedKVCache** (device pytree): ``k, v`` pools of shape
  ``[layers, n_pages, page_size, heads, head_dim]``, a per-row int32
  ``page_table [batch, pages_per_row]``, and the familiar per-row
  ``kv_len``. ``update``/``install_row``/``reset_rows`` are
  pure-functional (donated in the engine's compiled programs, same as
  the dense cache) and every write resolves its destination page
  through the table in-trace — the page ids are DATA, so one compiled
  program serves every allocation layout.
- **Page 0 is the reserved null page**: masked install positions,
  out-of-table positions, and idle engine lanes (``kv_len == 0``, the
  finished-slot contract) all route their writes there. Nothing ever
  reads it unmasked — this is what makes a parked slot with a stale
  table harmless while its pages are already re-owned by another row.
- **PageAllocator** (host): free-list allocation, per-page refcounts,
  and a prompt-prefix registry hashed at page granularity — an
  admission whose leading full pages hash-match a registered prompt
  REFERENCES those pages (prefill once, reference-count many) instead
  of storing a private copy. A prompt diverging INSIDE a shared page
  (its tail is a partial page of a fully-matched prefix) gets a
  private copy-on-write page at admission — the only moment a write
  could land on shared content, because full prompt pages are never
  written after install and decode writes always start at the row's
  own ``kv_len``. Registered pages with refcount 0 stay cached for
  future prefix hits and are reclaimed LRU when allocation runs dry.

Host syncs: the allocator runs entirely on host metadata (page ids,
token hashes) — it never touches device arrays; the only device reads
on this path stay the engine's existing poll-cadence lane reads.

Reference analog: the reference's serving layer keeps contiguous
CacheKV tensors per request (fused_multi_transformer); vLLM proved the
block-table form is what survives real traffic. Here the table rides
the same BlockSpec/SMEM machinery as the per-row ``kv_len``.
"""
from __future__ import annotations

import collections
import hashlib
import threading
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .kv_cache import KVCache, _raw, quantize_kv, validate_cache_dtype

__all__ = ["PagedKVCache", "QuantPagedKVCache", "PageAllocator",
           "AdmissionPlan"]


@jax.tree_util.register_pytree_node_class
class PagedKVCache:
    """Paged K/V pool + per-row page tables + per-row valid lengths.

    Implements the decode half of the KV-cache protocol (``update``,
    ``positions``, ``with_kv_len``, ``reset_rows``, ``kv_len``) so the
    model stack and the speculative verify core drive it unchanged;
    prefill stays on the dense batch-1 row cache, which
    ``install_row`` then scatters into the pool through the table.
    """

    __slots__ = ("k", "v", "page_table", "kv_len")

    def __init__(self, k, v, page_table, kv_len):
        self.k = k
        self.v = v
        self.page_table = page_table
        self.kv_len = kv_len

    # ------------------------------------------------------------ pytree
    def tree_flatten(self):
        return (self.k, self.v, self.page_table, self.kv_len), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # ------------------------------------------------------------- shape
    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    @property
    def n_pages(self) -> int:
        return self.k.shape[1]

    @property
    def page_size(self) -> int:
        return self.k.shape[2]

    @property
    def batch(self) -> int:
        return self.page_table.shape[0]

    @property
    def pages_per_row(self) -> int:
        return self.page_table.shape[1]

    @property
    def max_len(self) -> int:
        """Logical per-row capacity (the dense cache's ``max_len``)."""
        return self.pages_per_row * self.page_size

    @property
    def dtype(self):
        return self.k.dtype

    @property
    def cache_dtype(self):
        """The declared low-bit storage mode (None = full width)."""
        return None

    # ---------------------------------------------------------- creation
    @classmethod
    def create(cls, num_layers: int, batch: int, n_pages: int,
               page_size: int, pages_per_row: int, num_heads: int,
               head_dim: int, dtype=jnp.float32,
               cache_dtype=None) -> "PagedKVCache":
        shape = (num_layers, n_pages, page_size, num_heads, head_dim)
        if validate_cache_dtype(cache_dtype) is not None:
            sshape = (num_layers, n_pages, page_size, num_heads)
            return QuantPagedKVCache(
                jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
                jnp.zeros((batch, pages_per_row), jnp.int32),
                jnp.zeros((batch,), jnp.int32),
                jnp.zeros(sshape, jnp.bfloat16),
                jnp.zeros(sshape, jnp.bfloat16),
                jnp.zeros((), jnp.int32))
        return cls(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype),
                   jnp.zeros((batch, pages_per_row), jnp.int32),
                   jnp.zeros((batch,), jnp.int32))

    # ------------------------------------------------------------ update
    def _write_pages(self, pos):
        """(page, offset) destinations for per-row write positions
        ``pos`` ([batch, s] int32): resolve through the table, routing
        idle lanes (row write base 0 — the engine pins finished slots'
        kv_len to 0) and out-of-table positions to the null page 0."""
        slot = pos // self.page_size
        page = jnp.take_along_axis(
            self.page_table,
            jnp.minimum(slot, self.pages_per_row - 1), axis=1)
        dead = (pos[:, 0:1] == 0) | (slot >= self.pages_per_row)
        return jnp.where(dead, 0, page), pos % self.page_size

    def update(self, layer: int, k_new, v_new, pos) -> "PagedKVCache":
        """Write ``k_new``/``v_new`` ([batch, s, heads, head_dim]) into
        ``layer`` at per-row start position ``pos`` through the page
        table. Decode-path contract: a live row's ``pos`` (its
        ``kv_len``) is >= 1 (it holds at least its prompt), so a row
        writing at position 0 is an idle engine lane and lands on the
        null page. Does NOT advance ``kv_len`` (same contract as the
        dense cache: the model advances it once per forward)."""
        k_new, v_new = _raw(k_new), _raw(v_new)
        pos = jnp.asarray(_raw(pos), jnp.int32)
        if pos.ndim == 0:
            pos = jnp.broadcast_to(pos, (k_new.shape[0],))
        b, s = k_new.shape[0], k_new.shape[1]
        positions = pos[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
        page, off = self._write_pages(positions)          # [b, s] each
        page_f, off_f = page.reshape(-1), off.reshape(-1)

        def write(buf, new):
            flat = new.reshape((b * s,) + new.shape[2:]).astype(buf.dtype)
            return buf.at[layer, page_f, off_f].set(flat)

        return PagedKVCache(write(self.k, k_new), write(self.v, v_new),
                            self.page_table, self.kv_len)

    def install_row(self, src: KVCache, slot, table_row,
                    start) -> "PagedKVCache":
        """Slot admission: scatter the batch-1 dense prefill cache
        ``src`` into the pool pages named by ``table_row``
        ([pages_per_row] int32), install the table row and ``kv_len``
        at ``slot``. Positions below ``start`` are covered by shared
        prefix pages and are NOT written (the whole point); positions
        at/past ``src.kv_len[0]`` route to the null page. ``slot``,
        ``table_row`` and ``start`` are traced data — ONE compiled
        program serves every slot and every allocation layout."""
        slot = jnp.asarray(_raw(slot), jnp.int32)
        table_row = jnp.asarray(_raw(table_row), jnp.int32)
        start = jnp.asarray(_raw(start), jnp.int32)
        length = src.kv_len[0]
        t = src.max_len
        pos = jnp.arange(t, dtype=jnp.int32)
        page_slot = pos // self.page_size
        page = table_row[jnp.minimum(page_slot, self.pages_per_row - 1)]
        valid = (pos >= start) & (pos < length) & \
            (page_slot < self.pages_per_row)
        page = jnp.where(valid, page, 0)
        off = pos % self.page_size

        def write(buf, row):  # row: [layers, t, heads, head_dim]
            return buf.at[:, page, off].set(row.astype(buf.dtype))

        return PagedKVCache(
            write(self.k, src.k[:, 0]), write(self.v, src.v[:, 0]),
            self.page_table.at[slot].set(table_row),
            self.kv_len.at[slot].set(length))

    def install_span(self, src: KVCache, table_row,
                     start) -> "PagedKVCache":
        """Chunked-prefill incremental install: scatter positions
        ``[start, src.kv_len[0])`` of the batch-1 dense chunk cache
        ``src`` into the pool pages named by ``table_row`` WITHOUT
        installing the table row or ``kv_len`` — the slot stays parked
        (kv_len 0, null table) so decode steps keep routing its lane's
        writes to the null page until the final chunk's admission
        installs the pointers atomically. The same program runs after
        every non-final chunk; the admission-time :meth:`install_row`
        then writes only the final span (``start`` = last chunk
        boundary)."""
        table_row = jnp.asarray(_raw(table_row), jnp.int32)
        start = jnp.asarray(_raw(start), jnp.int32)
        length = src.kv_len[0]
        t = src.max_len
        pos = jnp.arange(t, dtype=jnp.int32)
        page_slot = pos // self.page_size
        page = table_row[jnp.minimum(page_slot, self.pages_per_row - 1)]
        valid = (pos >= start) & (pos < length) & \
            (page_slot < self.pages_per_row)
        page = jnp.where(valid, page, 0)
        off = pos % self.page_size

        def write(buf, row):  # row: [layers, t, heads, head_dim]
            return buf.at[:, page, off].set(row.astype(buf.dtype))

        return PagedKVCache(
            write(self.k, src.k[:, 0]), write(self.v, src.v[:, 0]),
            self.page_table, self.kv_len)

    def positions(self, s: int):
        """Absolute positions of ``s`` appended tokens per row — the
        decode position-embedding offsets (dense-cache contract)."""
        return self.kv_len[:, None] + \
            jnp.arange(s, dtype=jnp.int32)[None, :]

    # -------------------------------------------------------- slot reuse
    def reset_rows(self, rows) -> "PagedKVCache":
        """Free rows for reuse: zero ``kv_len`` AND null the page-table
        row (one row index, an int array, or a [batch] bool mask). The
        HOST allocator owns returning the pages themselves to the free
        list — this program only severs the row's pointers so a stale
        lane can never write through them once the pages are
        re-owned."""
        rows = jnp.asarray(_raw(rows))
        if rows.dtype == jnp.bool_:
            kv_len = jnp.where(rows, 0, self.kv_len)
            table = jnp.where(rows[:, None], 0, self.page_table)
        else:
            kv_len = self.kv_len.at[rows].set(0)
            table = self.page_table.at[rows].set(0)
        return PagedKVCache(self.k, self.v, table, kv_len)

    def with_kv_len(self, kv_len) -> "PagedKVCache":
        kv_len = jnp.asarray(_raw(kv_len), jnp.int32)
        if kv_len.ndim == 0:
            kv_len = jnp.broadcast_to(kv_len, (self.batch,))
        return PagedKVCache(self.k, self.v, self.page_table, kv_len)

    # --------------------------------------------------------- telemetry
    def occupancy(self) -> float:
        """Host-side fraction of the LOGICAL per-row capacity in use
        (max over rows) — the gen.cache_occupancy gauge; page-level
        occupancy is the allocator's (host-only) page_occupancy."""
        top = np.max(np.asarray(self.kv_len))  # lint: host-sync-ok (tiny read)
        return float(top) / self.max_len  # lint: host-sync-ok (host scalar)

    def __repr__(self):
        return (f"PagedKVCache(layers={self.num_layers}, "
                f"batch={self.batch}, pages={self.n_pages}x"
                f"{self.page_size}, per_row={self.pages_per_row}, "
                f"dtype={self.k.dtype})")


@jax.tree_util.register_pytree_node_class
class QuantPagedKVCache(PagedKVCache):
    """Int8 page pool: K/V pages stored int8 with per-(slot, head) bf16
    scales in sidecar pools ``k_scale``/``v_scale``
    ([layers, n_pages, page_size, heads]) plus the scalar ``clips``
    saturation counter. The scales live IN the page (one row per
    position), so everything the allocator does at page granularity —
    shared-prefix referencing, COW privatization, LRU reclaim — carries
    the scales with the values for free: a referenced shared page
    dequantizes identically for every sharer, and a COW private copy
    rewrites values + scales together at install."""

    __slots__ = ("k_scale", "v_scale", "clips")

    def __init__(self, k, v, page_table, kv_len, k_scale, v_scale,
                 clips):
        super().__init__(k, v, page_table, kv_len)
        self.k_scale = k_scale
        self.v_scale = v_scale
        self.clips = clips

    # ------------------------------------------------------------ pytree
    def tree_flatten(self):
        return (self.k, self.v, self.page_table, self.kv_len,
                self.k_scale, self.v_scale, self.clips), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def cache_dtype(self):
        return "int8"

    # ------------------------------------------------------------ update
    def update(self, layer: int, k_new, v_new, pos) -> "QuantPagedKVCache":
        """Quantize the fresh k/v per (token, head) and write int8
        values + bf16 scales through the page table — same null-page
        routing for idle/out-of-table positions as the wide pool."""
        k_new, v_new = _raw(k_new), _raw(v_new)
        pos = jnp.asarray(_raw(pos), jnp.int32)
        if pos.ndim == 0:
            pos = jnp.broadcast_to(pos, (k_new.shape[0],))
        b, s = k_new.shape[0], k_new.shape[1]
        positions = pos[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
        page, off = self._write_pages(positions)
        page_f, off_f = page.reshape(-1), off.reshape(-1)
        kq, ks, kc = quantize_kv(k_new)
        vq, vs, vc = quantize_kv(v_new)

        def write(buf, new):
            flat = new.reshape((b * s,) + new.shape[2:]).astype(buf.dtype)
            return buf.at[layer, page_f, off_f].set(flat)

        return QuantPagedKVCache(
            write(self.k, kq), write(self.v, vq), self.page_table,
            self.kv_len, write(self.k_scale, ks), write(self.v_scale, vs),
            self.clips + kc + vc)

    def install_row(self, src, slot, table_row,
                    start) -> "QuantPagedKVCache":
        """Slot admission from a batch-1 :class:`QuantKVCache` prefill
        row: int8 values AND scales scatter verbatim through the table
        (no requantization — the installed pages decode bitwise-equal
        to the dense row), positions below ``start`` stay covered by
        the shared prefix pages, masked positions route to null."""
        slot = jnp.asarray(_raw(slot), jnp.int32)
        table_row = jnp.asarray(_raw(table_row), jnp.int32)
        start = jnp.asarray(_raw(start), jnp.int32)
        length = src.kv_len[0]
        t = src.max_len
        pos = jnp.arange(t, dtype=jnp.int32)
        page_slot = pos // self.page_size
        page = table_row[jnp.minimum(page_slot, self.pages_per_row - 1)]
        valid = (pos >= start) & (pos < length) & \
            (page_slot < self.pages_per_row)
        page = jnp.where(valid, page, 0)
        off = pos % self.page_size

        def write(buf, row):  # row: [layers, t, ...]
            return buf.at[:, page, off].set(row.astype(buf.dtype))

        return QuantPagedKVCache(
            write(self.k, src.k[:, 0]), write(self.v, src.v[:, 0]),
            self.page_table.at[slot].set(table_row),
            self.kv_len.at[slot].set(length),
            write(self.k_scale, src.k_scale[:, 0]),
            write(self.v_scale, src.v_scale[:, 0]),
            self.clips + src.clips)

    def install_span(self, src, table_row,
                     start) -> "QuantPagedKVCache":
        """Chunked-prefill incremental install from a batch-1
        :class:`QuantKVCache` chunk row: int8 values + scales scatter
        for ``[start, src.kv_len[0])`` only, table row and ``kv_len``
        untouched (see the wide-pool docstring). ``clips`` is NOT
        accumulated here — the admission-time ``install_row`` adds the
        source cache's counter once; adding it per span would
        multiply-count every earlier chunk's clips."""
        table_row = jnp.asarray(_raw(table_row), jnp.int32)
        start = jnp.asarray(_raw(start), jnp.int32)
        length = src.kv_len[0]
        t = src.max_len
        pos = jnp.arange(t, dtype=jnp.int32)
        page_slot = pos // self.page_size
        page = table_row[jnp.minimum(page_slot, self.pages_per_row - 1)]
        valid = (pos >= start) & (pos < length) & \
            (page_slot < self.pages_per_row)
        page = jnp.where(valid, page, 0)
        off = pos % self.page_size

        def write(buf, row):  # row: [layers, t, ...]
            return buf.at[:, page, off].set(row.astype(buf.dtype))

        return QuantPagedKVCache(
            write(self.k, src.k[:, 0]), write(self.v, src.v[:, 0]),
            self.page_table, self.kv_len,
            write(self.k_scale, src.k_scale[:, 0]),
            write(self.v_scale, src.v_scale[:, 0]),
            self.clips)

    # -------------------------------------------------------- slot reuse
    def reset_rows(self, rows) -> "QuantPagedKVCache":
        base = PagedKVCache.reset_rows(self, rows)
        return QuantPagedKVCache(self.k, self.v, base.page_table,
                                 base.kv_len, self.k_scale, self.v_scale,
                                 self.clips)

    def with_kv_len(self, kv_len) -> "QuantPagedKVCache":
        kv_len = jnp.asarray(_raw(kv_len), jnp.int32)
        if kv_len.ndim == 0:
            kv_len = jnp.broadcast_to(kv_len, (self.batch,))
        return QuantPagedKVCache(self.k, self.v, self.page_table, kv_len,
                                 self.k_scale, self.v_scale, self.clips)

    def __repr__(self):
        return (f"QuantPagedKVCache(layers={self.num_layers}, "
                f"batch={self.batch}, pages={self.n_pages}x"
                f"{self.page_size}, per_row={self.pages_per_row}, "
                f"dtype=int8+bf16-scales)")


class AdmissionPlan:
    """One admission's page plan (host-only): the shared prefix pages to
    reference, how many private pages to allocate, and whether the
    divergence point sits inside a shared page (copy-on-write)."""

    __slots__ = ("shared_pages", "shared_len", "n_private", "cow",
                 "total_pages", "keys")

    def __init__(self, shared_pages, shared_len, n_private, cow,
                 total_pages, keys):
        self.shared_pages = shared_pages    # List[int]
        self.shared_len = shared_len        # tokens covered by sharing
        self.n_private = n_private          # pages to allocate
        self.cow = cow                      # divergence inside a shared
        #                                     page -> private copy made
        self.total_pages = total_pages
        self.keys = keys                    # full-page registry keys


class PageAllocator:
    """Host-side page bookkeeping: free list, refcounts, and the
    prompt-prefix registry. Page 0 is reserved (the null page) and is
    never allocated. All state is host ints — no device arrays, no
    syncs; the engine calls ``plan``/``commit`` at admission,
    ``register`` after install, and ``free_row`` at completion or
    eviction. ``assert_conserved`` is the drain-time invariant: every
    page is exactly one of {null, free, referenced, cached}."""

    def __init__(self, n_pages: int, page_size: int):
        if n_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is the "
                             "reserved null page)")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        # leaf lock: the scheduler mutates under the engine's pump
        # lock while the telemetry HTTP thread reads free_pages() for
        # /readyz — an unguarded registry iteration there would raise
        # mid-scrape exactly when the router signal matters
        self._lock = threading.Lock()
        self._free: List[int] = list(range(n_pages - 1, 0, -1))
        self._ref: Dict[int, int] = {}          # page -> live row refs
        # prefix registry: full-page key -> page id (insertion order is
        # the LRU order; re-registration moves to the back)
        self._prefix: "collections.OrderedDict[bytes, int]" = \
            collections.OrderedDict()
        self._page_key: Dict[int, bytes] = {}   # page -> its registry key
        self.stats = dict(pages_allocated=0, pages_freed=0,
                          prefix_hits=0, shared_pages=0, cow_copies=0,
                          reclaimed=0)
        # bumped on every state mutation: the engine caches a blocked
        # queue head's failed plan against this, so a saturated pool is
        # re-planned only when something actually changed (a free, a
        # reclaim, a registration) instead of on every pump iteration
        self.version = 0

    # ---------------------------------------------------------- hashing
    def _page_keys(self, ids: np.ndarray) -> List[bytes]:
        """Chained per-page digests of the prompt's FULL pages: key i
        commits to every token in pages 0..i, so a match at page i
        implies the whole prefix matches."""
        keys, h = [], hashlib.blake2b(digest_size=16)
        ps = self.page_size
        for i in range(len(ids) // ps):
            h.update(np.ascontiguousarray(
                ids[i * ps:(i + 1) * ps]).tobytes())
            keys.append(h.digest())
        return keys

    # ------------------------------------------------------- accounting
    def free_pages(self) -> int:
        """Pages allocatable right now: the free list plus cached
        (registered, refcount-0) pages the reclaimer may take.
        Thread-safe: the telemetry thread calls this mid-traffic."""
        with self._lock:
            return len(self._free) + sum(
                1 for p in self._prefix.values() if not self._ref.get(p))

    def used_pages(self) -> int:
        return len(self._ref)

    def page_occupancy(self) -> float:
        """Referenced pages / allocatable universe (excludes null)."""
        return len(self._ref) / max(1, self.n_pages - 1)

    # -------------------------------------------------------- admission
    def plan(self, ids: np.ndarray, extra_tokens: int) -> AdmissionPlan:
        """Plan one admission: prompt ``ids`` plus ``extra_tokens`` of
        decode budget (incl. any speculative overhang). Pure read —
        commits nothing."""
        ids = np.asarray(ids, np.int32).reshape(-1)  # lint: host-sync-ok (host token ids)
        ps = self.page_size
        plen = int(ids.size)
        total = -(-(plen + int(extra_tokens)) // ps)
        keys = self._page_keys(ids)
        shared: List[int] = []
        with self._lock:
            for key in keys:
                page = self._prefix.get(key)
                if page is None:
                    break
                shared.append(page)
        shared_len = len(shared) * ps
        # copy-on-write: the prompt diverges INSIDE a page whose prefix
        # is shared (its full pages all matched and a partial tail
        # remains) — the install privatizes that page's content
        cow = bool(shared) and shared_len == (plen // ps) * ps \
            and plen % ps != 0
        return AdmissionPlan(shared, shared_len, total - len(shared),
                             cow, total, keys)

    def commit(self, plan: AdmissionPlan) -> Optional[List[int]]:
        """Acquire the plan's pages: reference the shared prefix pages
        and allocate the private ones (reclaiming cached prefix pages
        LRU if the free list runs dry). Returns the row's full page
        list (shared + private, position order), or None when the pool
        cannot cover it — the caller leaves the request queued."""
        with self._lock:
            if plan.n_private > len(self._free):
                self._reclaim(plan.n_private - len(self._free),
                              protect=set(plan.shared_pages))
            if plan.n_private > len(self._free):
                return None
            private = [self._free.pop() for _ in range(plan.n_private)]
            for p in private:
                self._ref[p] = 1
            for p in plan.shared_pages:
                self._ref[p] = self._ref.get(p, 0) + 1
            self.version += 1
            self.stats["pages_allocated"] += len(private)
            if plan.shared_pages:
                self.stats["prefix_hits"] += 1
                self.stats["shared_pages"] += len(plan.shared_pages)
            if plan.cow:
                self.stats["cow_copies"] += 1
            return plan.shared_pages + private

    def register(self, plan: AdmissionPlan, pages: List[int]):
        """Register the admitted prompt's FULL pages for future prefix
        hits (key i -> pages[i]). Safe because full prompt pages are
        never written after install: decode appends at the row's
        kv_len, past the last full prompt page's content. Re-registering
        a shared page refreshes its LRU position."""
        with self._lock:
            for i, key in enumerate(plan.keys):
                old = self._prefix.pop(key, None)
                if old is not None and old != pages[i]:
                    # the key was re-installed onto a different page
                    # while the old one still exists (it was referenced
                    # when this admission planned around it): drop the
                    # old binding
                    self._page_key.pop(old, None)
                    self._maybe_release(old)
                self._prefix[key] = pages[i]
                self._page_key[pages[i]] = key
            if plan.keys:
                self.version += 1

    def free_row(self, pages: List[int]):
        """Release one row's page references (completion/eviction).
        Unreferenced unregistered pages return to the free list;
        unreferenced REGISTERED pages stay cached for future prefix
        hits until reclaimed."""
        with self._lock:
            for p in pages:
                n = self._ref.get(p, 0) - 1
                if n > 0:
                    self._ref[p] = n
                else:
                    self._ref.pop(p, None)
                    self._maybe_release(p)
            self.version += 1

    def _maybe_release(self, page: int):  # lint: lock-discipline-ok (caller holds self._lock)
        if page in self._ref or page in self._page_key:
            return
        self._free.append(page)
        self.stats["pages_freed"] += 1

    def _reclaim(self, need: int, protect=frozenset()):  # lint: lock-discipline-ok (caller holds self._lock)
        """Evict cached (refcount-0, registered) prefix pages LRU-first
        until ``need`` pages were freed or nothing reclaimable is
        left. Caller holds self._lock."""
        for key in list(self._prefix):
            if need <= 0:
                break
            page = self._prefix[key]
            if self._ref.get(page) or page in protect:
                continue
            del self._prefix[key]
            del self._page_key[page]
            self._free.append(page)
            self.version += 1
            self.stats["pages_freed"] += 1
            self.stats["reclaimed"] += 1
            need -= 1

    def drop_registry(self):
        """Forget every cached prefix (refcount-0 registered pages go
        back to the free list) — test/diagnostic hook."""
        with self._lock:
            self._reclaim(len(self._prefix))
            # still-referenced registered pages lose their registry entry
            for key in list(self._prefix):
                page = self._prefix.pop(key)
                self._page_key.pop(page, None)
            self.version += 1

    # ------------------------------------------------------ invariants
    def assert_conserved(self):
        """Every page is exactly one of {null, free, referenced,
        cached}: no leaks, no double frees. The chaos drain gate."""
        with self._lock:
            return self._assert_conserved_locked()

    def _assert_conserved_locked(self):
        free = set(self._free)
        if len(free) != len(self._free):
            raise AssertionError("double-freed page(s): free list has "
                                 "duplicates")
        refd = set(self._ref)
        cached = {p for p in self._page_key if p not in refd}
        if free & refd or free & cached:
            raise AssertionError(
                f"page in two states: free∩ref={sorted(free & refd)} "
                f"free∩cached={sorted(free & cached)}")
        if 0 in free or 0 in refd or 0 in cached:
            raise AssertionError("reserved null page 0 was allocated")
        total = 1 + len(free) + len(refd) + len(cached)
        if total != self.n_pages:
            raise AssertionError(
                f"page leak: null+free({len(free)})+referenced"
                f"({len(refd)})+cached({len(cached)}) = {total} != "
                f"pool {self.n_pages}")

    def __repr__(self):
        return (f"PageAllocator(pages={self.n_pages}x{self.page_size}, "
                f"free={len(self._free)}, used={len(self._ref)}, "
                f"cached={len(self._prefix)})")
