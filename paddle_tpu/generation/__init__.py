"""Autoregressive generation subsystem: ring KV cache, decode-shaped
flash attention, sampling, and the jitted (prefill, decode) pair behind
``Model.generate()`` / ``inference.Predictor``'s generation mode.

See docs/architecture.md "Generation & KV cache".
"""
from .api import GenerationConfig, GenerationSession, generate  # noqa: F401
from .kv_cache import (KVCache, QuantKVCache,  # noqa: F401
                       quantize_kv, resolve_cache_dtype)
from .paged_cache import (AdmissionPlan, PageAllocator,  # noqa: F401
                          PagedKVCache, QuantPagedKVCache)
from .sampling import (apply_temperature, apply_top_k,  # noqa: F401
                       apply_top_p, sample)
from .speculative import (SpeculativeConfig,  # noqa: F401
                          SpeculativeSession, ngram_propose, spec_accept)

__all__ = [
    "GenerationConfig", "GenerationSession", "generate", "KVCache",
    "QuantKVCache", "quantize_kv", "resolve_cache_dtype",
    "PagedKVCache", "QuantPagedKVCache", "PageAllocator",
    "AdmissionPlan",
    "sample", "apply_temperature", "apply_top_k", "apply_top_p",
    "SpeculativeConfig", "SpeculativeSession", "ngram_propose",
    "spec_accept",
]
