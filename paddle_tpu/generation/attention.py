"""Shared cached-attention step for model wiring.

GPT and ERNIE attention layers run the identical cache choreography —
write the fresh k/v into the ring at each row's ``kv_len``, then either
attend the cached prefix through the decode flash kernel (decode) or
run ordinary self-attention over the fresh window (prefill). One
implementation here so a fix (GQA cache heads, sharded creation, mask
semantics) can never silently diverge between models; only the
``causal`` flag differs.
"""
from __future__ import annotations


def cached_attention(q, k, v, cache, layer_idx, *, decode: bool,
                     causal: bool, attn_mask=None):
    """Write ``k``/``v`` ([b, s, heads, head_dim] Tensors) into
    ``cache`` at layer ``layer_idx`` and attend. Returns (out, cache);
    ``out`` is [b, s, heads, head_dim]. Decode reads the cached prefix
    via ``kernels.flash_attention_decode`` with per-row ragged masking
    at ``kv_len + s``; prefill is plain self-attention over the fresh
    window (``causal`` per model family, ``attn_mask`` honored)."""
    from ..core.tensor import dispatch
    from ..nn import functional as F
    cache = cache.update(layer_idx, k, v, cache.kv_len)
    if decode:
        s = q.shape[1]
        mask_len = cache.kv_len + s  # includes the new rows
        # int8 cache (QuantKVCache/QuantPagedKVCache): the layer's
        # scale sidecars ride as two extra operands — dequant fuses
        # in-register, the wide cache is never materialized
        scales = () if getattr(cache, "k_scale", None) is None else \
            (cache.k_scale[layer_idx], cache.v_scale[layer_idx])
        if getattr(cache, "page_table", None) is not None:
            # paged cache: attend the pooled pages through the row's
            # page table (index-map indirection on TPU, gather+mask
            # off it — bitwise-equal either way)
            from ..kernels.flash_attention import \
                flash_attention_decode_paged
            out = dispatch(
                "flash_attention_decode_paged",
                lambda q_, kp, vp, pt, kl, *sc:
                    flash_attention_decode_paged(
                        q_, kp, vp, pt, kl,
                        **(dict(k_scale=sc[0], v_scale=sc[1])
                           if sc else {})),
                (q, cache.k[layer_idx], cache.v[layer_idx],
                 cache.page_table, mask_len) + scales, {},
                differentiable=False)
            return out, cache
        from ..kernels.flash_attention import (
            MAX_DECODE_QLEN, flash_attention_chunk,
            flash_attention_decode)
        if s > MAX_DECODE_QLEN:
            # chunk-prefill window (serving's chunked admission): a
            # C-token slice of a long prompt attends the cache written
            # by the earlier chunks — decode-shaped ragged masking,
            # q-tiled kernel (dense cache only; the engine's chunk
            # side-cache is never paged)
            out = dispatch(
                "flash_attention_chunk",
                lambda q_, kc, vc, kl, *sc: flash_attention_chunk(
                    q_, kc, vc, kl,
                    **(dict(k_scale=sc[0], v_scale=sc[1])
                       if sc else {})),
                (q, cache.k[layer_idx], cache.v[layer_idx], mask_len)
                + scales, {}, differentiable=False)
            return out, cache
        out = dispatch(
            "flash_attention_decode",
            lambda q_, kc, vc, kl, *sc: flash_attention_decode(
                q_, kc, vc, kl,
                **(dict(k_scale=sc[0], v_scale=sc[1]) if sc else {})),
            (q, cache.k[layer_idx], cache.v[layer_idx], mask_len)
            + scales, {}, differentiable=False)
    else:
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=causal,
            dropout_p=0.0, training=False)
    return out, cache
