from .api import (TrainStep, functional_call, grad, jit, to_static,  # noqa: F401
                  value_and_grad)
from .save_load import load, save  # noqa: F401
