from .api import (ProgramTranslator, TracedLayer, TrainStep,  # noqa: F401
                  TranslatedLayer, functional_call, grad, jit,
                  not_to_static, set_code_level, set_verbosity,
                  to_static,
                  value_and_grad)
from .save_load import load, save  # noqa: F401
