from .api import (ProgramTranslator, TracedLayer, TrainStep,  # noqa: F401
                  TranslatedLayer, functional_call, grad, jit,
                  not_to_static, set_code_level, set_verbosity,
                  to_static,
                  value_and_grad)
from .compile_cache import (ExecutableStore, compile_or_load,  # noqa: F401
                            default_store, enable_compile_cache,
                            set_default_store)
from .save_load import load, save  # noqa: F401
