"""Graph capture: the @to_static analog.

Reference analog: paddle.jit @to_static rewrites Python AST into a static
ProgramDesc (python/paddle/fluid/dygraph/dygraph_to_static/
program_translator.py) executed by run_program. On TPU there is no AST
surgery: Layer code is already pure jax underneath (the tape skips
recording for Tracers), so capture == `jax.jit` over a functionalized
view of (parameters, buffers, inputs). Compile caching is jax's; the
whole train step compiles to ONE XLA program — the design goal the
reference's InterpreterCore + fused kernels approximate.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from ..core import flight_recorder as _flight_recorder
from ..core import monitor
from ..core.tensor import Parameter, Tensor, no_grad
from ..optimizer.optimizer import opt_key as _opt_key
from ..nn.layer import Layer


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


class _RetraceTracker:
    """Classifies jax.jit cache misses into the metrics registry:
    first | new_shape | new_dtype | new_structure | donation_miss (the
    signature was seen but the jit cache still grew — donation or
    weak-type mismatch). Zero work unless the monitor is enabled."""

    # cap remembered signatures: under pathological dynamic shapes the
    # classifier degrades gracefully (oldest evicted) instead of scanning
    # and retaining an unbounded history
    MAX_SEEN = 256

    def __init__(self):
        import collections
        self._seen = collections.deque(maxlen=self.MAX_SEEN)
        self._seen_set = set()

    @staticmethod
    def _signature(trees):
        """(treedef, ((shape, dtype), ...)) — treedef included because
        it is part of jax's jit cache key (same leaves under a different
        container nesting still retrace)."""
        leaves, treedef = jax.tree_util.tree_flatten(trees)
        sig = []
        for v in leaves:
            if hasattr(v, "shape") and hasattr(v, "dtype"):
                sig.append((tuple(v.shape), str(v.dtype)))
            else:
                sig.append((type(v).__name__, ""))
        return (str(treedef), tuple(sig))

    def _classify(self, sig) -> str:
        if not self._seen:
            return "first"
        tdef, leaves = sig
        if any(s_leaves == leaves and s_tdef != tdef
               for s_tdef, s_leaves in self._seen):
            return "new_structure"
        same_len = [s_leaves for _, s_leaves in self._seen
                    if len(s_leaves) == len(leaves)]
        if not same_len:
            return "new_structure"
        shapes = tuple(s for s, _ in leaves)
        dtypes = tuple(d for _, d in leaves)
        for s in same_len:
            if tuple(d for _, d in s) == dtypes:
                return "new_shape"
        for s in same_len:
            if tuple(sh for sh, _ in s) == shapes:
                return "new_dtype"
        return "new_structure"

    @staticmethod
    def _cache_of(jitted):
        try:
            return jitted._cache_size()
        except Exception:
            return None

    def pre(self, jitted):
        """Call BEFORE the jitted call: cache size going in, or None
        when neither the monitor nor the flight recorder is on
        (observe() will no-op)."""
        if not (monitor.enabled or _flight_recorder.enabled):
            return None
        return self._cache_of(jitted)

    def observe(self, jitted, trees, pre_cache):
        """Call AFTER the jitted call with pre()'s value. A retrace is
        counted only when the compiled cache actually grew during this
        call, so enabling the monitor against a warmed function never
        reports phantom compiles; without cache introspection the
        signature novelty is the (over-approximate) fallback. Runs for
        the flight recorder too — a post-mortem must show what
        compiled even when the metrics registry was never enabled
        (monitor.record_retrace feeds both streams)."""
        if not (monitor.enabled or _flight_recorder.enabled):
            return
        cache = self._cache_of(jitted)
        known = cache is not None and pre_cache is not None
        compiled = known and cache > pre_cache
        if not monitor.enabled and known and not compiled:
            # flight-recorder-only mode: nothing compiled this call, so
            # skip the per-leaf signature walk — the black box only
            # needs the (rare) compile events, not a hot-path tax
            return
        sig = self._signature(trees)
        if sig in self._seen_set:
            if compiled:
                monitor.record_retrace("donation_miss")
            return
        if compiled or not known:
            monitor.record_retrace(self._classify(sig))
        if len(self._seen) == self.MAX_SEEN:
            self._seen_set.discard(self._seen[0])  # deque evicts it
        self._seen_set.add(sig)
        self._seen.append(sig)


def _wrap(x):
    return Tensor(x) if isinstance(x, jax.Array) else x


def functional_call(layer: Layer, params_and_buffers: Dict[str, Any],
                    *args, **kwargs):
    """Run `layer` with parameter/buffer values taken from the dict
    (name -> array/Tensor), without mutating the layer. The bridge between
    the stateful Layer API and jax transforms (≈ torch.func.functional_call;
    no reference analog — Paddle's static bridge is dy2static)."""
    state = layer.state_dict()
    saved = {name: t._data for name, t in state.items()}
    try:
        for name, value in params_and_buffers.items():
            if name in state:
                state[name]._data = _unwrap(value)
        with no_grad():
            out = layer(*args, **kwargs)
        return out
    finally:
        for name, t in state.items():
            t._data = saved[name]


def to_static(function=None, input_spec=None, full_graph=True, backend=None,
              donate_params: bool = False, static_argnums=()):
    """Decorator: compile a function or Layer.forward with jax.jit.
    Tensor args are passed as traced arrays; outputs come back as Tensors.
    For a Layer, parameters/buffers are captured as traced constants
    re-read on every call (so `opt.step()` updates are seen) but donate
    nothing; use TrainStep for the fused, donated training path."""

    def deco(fn):
        is_layer = isinstance(fn, Layer)
        target = fn.forward if is_layer else fn
        if getattr(target, "__jit_not_to_static__", False):
            return fn  # @not_to_static: stay eager
        # dy2static pass: tensor-dependent if/while become
        # lax.cond/while_loop before jax.jit traces the function
        if not is_layer:
            from .dy2static import convert_to_static
            target = convert_to_static(target)

        @functools.partial(jax.jit, static_argnums=static_argnums)
        def jitted(state_vals, arg_vals, kw_vals):
            if is_layer:
                names = jitted._state_names
                out = functional_call(fn, dict(zip(names, state_vals)),
                                      *arg_vals, **kw_vals)
            else:
                with no_grad():
                    out = target(*arg_vals, **kw_vals)
            return jax.tree_util.tree_map(_unwrap, out,
                                          is_leaf=lambda x: isinstance(x, Tensor))

        jitted._state_names = None
        tracker = _RetraceTracker()

        @functools.wraps(target)
        def wrapper(*args, **kwargs):
            if not ProgramTranslator.enable_to_static:
                # global kill-switch: run the ORIGINAL eagerly so
                # breakpoints/prints work (reference
                # ProgramTranslator.enable(False) semantics)
                return fn(*args, **kwargs) if is_layer else \
                    (fn(*args, **kwargs))
            if is_layer:
                state = fn.state_dict()
                jitted._state_names = list(state.keys())
                state_vals = tuple(t._data for t in state.values())
            else:
                state_vals = ()
            arg_vals = jax.tree_util.tree_map(
                _unwrap, args, is_leaf=lambda x: isinstance(x, Tensor))
            kw_vals = jax.tree_util.tree_map(
                _unwrap, kwargs, is_leaf=lambda x: isinstance(x, Tensor))
            pre_cache = tracker.pre(jitted)
            out = jitted(state_vals, arg_vals, kw_vals)
            tracker.observe(jitted, (state_vals, arg_vals, kw_vals),
                            pre_cache)
            return jax.tree_util.tree_map(_wrap, out)

        wrapper.__wrapped_layer__ = fn if is_layer else None
        wrapper._jitted = jitted
        return wrapper

    if function is not None:
        return deco(function)
    return deco


jit = to_static  # alias


def grad(*fargs, **fkwargs):
    """Dual-personality `paddle.grad`:

    - grad(fn, argnums=0, has_aux=False) -> functional transform
      (jax.grad with Tensor marshalling), the jit-compatible autodiff.
    - grad(outputs, inputs, grad_outputs=None, retain_graph=None,
      create_graph=False, only_inputs=True, allow_unused=False,
      no_grad_vars=None) -> reference dygraph API
      (python/paddle/fluid/dygraph/base.py grad()): tape-based grads of
      output Tensors w.r.t. input Tensors, incl. create_graph=True for
      double grad. Delegates to autograd.backward_engine.tensor_grad.
    """
    if fargs and callable(fargs[0]) and not isinstance(fargs[0], Tensor):
        return _functional_grad(*fargs, **fkwargs)
    from ..autograd.backward_engine import tensor_grad
    return tensor_grad(*fargs, **fkwargs)


def _functional_grad(fn: Callable, argnums=0, has_aux: bool = False):
    def wrapped(*args, **kwargs):
        def pure(*raw_args):
            targs = jax.tree_util.tree_map(_wrap, raw_args)
            out = fn(*targs, **kwargs)
            return jax.tree_util.tree_map(
                _unwrap, out, is_leaf=lambda x: isinstance(x, Tensor))

        raw = jax.tree_util.tree_map(
            _unwrap, args, is_leaf=lambda x: isinstance(x, Tensor))
        g = jax.grad(pure, argnums=argnums, has_aux=has_aux)(*raw)
        return jax.tree_util.tree_map(_wrap, g)

    return wrapped


def value_and_grad(fn: Callable, argnums=0, has_aux: bool = False):
    def wrapped(*args, **kwargs):
        def pure(*raw_args):
            targs = jax.tree_util.tree_map(_wrap, raw_args)
            out = fn(*targs, **kwargs)
            return jax.tree_util.tree_map(
                _unwrap, out, is_leaf=lambda x: isinstance(x, Tensor))

        raw = jax.tree_util.tree_map(
            _unwrap, args, is_leaf=lambda x: isinstance(x, Tensor))
        v, g = jax.value_and_grad(pure, argnums=argnums,
                                  has_aux=has_aux)(*raw)
        return (jax.tree_util.tree_map(_wrap, v),
                jax.tree_util.tree_map(_wrap, g))

    return wrapped


class TrainStep:
    """Fused, donated training step: (params, opt_state, batch) -> (loss,
    params', opt_state') as ONE compiled XLA program.

    This is the TPU answer to the reference's per-op dygraph loop + fused
    optimizer kernels + Reducer overlap: forward, backward, (clip), update
    all fuse under XLA, with parameter buffers donated so updates are
    in-place in HBM.

    Usage:
        step = TrainStep(model, opt, loss_fn)
        for batch in loader:
            loss = step(batch_inputs, labels)   # updates model in place
    Sharding: pass in_shardings/mesh via `sharding` (see distributed.fleet).
    """

    def __init__(self, model: Layer, optimizer, loss_fn: Callable,
                 donate: bool = True, sharding=None,
                 offload_opt_state: bool = False,
                 skip_nonfinite: bool = False, recompute=None):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self._sharding = sharding
        # recompute: a fleet.utils.RecomputeConfig (or policy name) —
        # the whole forward becomes a jax.checkpoint region under the
        # config's policy, trading backward FLOPs for activation HBM
        # without touching the model definition
        if recompute is not None:
            from ..distributed.fleet.utils.recompute import _as_config
            recompute = _as_config(recompute)
        self._recompute = recompute
        # skip_nonfinite: the in-jit half of the resilience layer's
        # anomaly guard — a non-finite loss keeps params/opt state
        # unchanged (the jnp.where select fuses away; same pattern as
        # GradScaler's found_inf skip), the poisoned loss still returns
        # for the host-side AnomalyGuard to count.
        self._skip_nonfinite = skip_nonfinite
        # offload_opt_state: park optimizer moments in host memory
        # (pinned_host) between steps — HBM relief for big-batch /
        # long-seq configs at the cost of PCIe streaming per step (the
        # reference's sharding offload, group_sharded_storage.py).
        # Falls back silently where the backend lacks memory kinds.
        self._offload = offload_opt_state
        self._host_shardings = None

        self._param_names = [n for n, _ in model.named_parameters()]
        # the Parameter objects themselves: cached so the hot loop does
        # not re-walk the module tree (names + containers) every step
        self._params_cache = [p for _, p in model.named_parameters()]
        self._opt_state_tree = None

        def step_fn(param_vals, opt_state, lr, step_no, *batch):
            params = dict(zip(self._param_names, param_vals))

            def loss_of(pvals):
                pdict = dict(zip(self._param_names, pvals))
                out = functional_call(self.model, pdict, *batch[:-1])
                loss = self.loss_fn(
                    out, jax.tree_util.tree_map(_wrap, batch[-1]))
                return _unwrap(loss)

            if self._recompute is not None and self._recompute.enabled:
                loss_of = self._recompute.wrap(loss_of)
            loss, grads = jax.value_and_grad(loss_of)(list(param_vals))
            new_params, new_state = self.optimizer.apply_gradients(
                list(param_vals), grads, opt_state, lr=lr, step=step_no)
            if self._skip_nonfinite:
                import jax.numpy as jnp
                ok = jnp.isfinite(loss)
                new_params = [jnp.where(ok, n, o)
                              for n, o in zip(new_params, param_vals)]
                new_state = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(ok, n, o),
                    new_state, opt_state)
            return loss, new_params, new_state

        donate_argnums = (0, 1) if donate else ()
        self._step_fn = step_fn
        self._donate_argnums = donate_argnums
        self._jitted = jax.jit(step_fn, donate_argnums=donate_argnums)
        self._tracker = _RetraceTracker()
        self._warm_store = None   # enable_warm_start() opt-in
        self._warm_exe = None
        # the warm/AOT path bakes donation only where the backend
        # implements it: a serialized executable REPLAYS its
        # input_output_aliases on load, and deserialized-on-CPU
        # aliasing double-frees the donated buffers (heap corruption)
        # where the live jit path merely drops the request with a
        # warning. audit() keeps gating the donation INTENT.
        self._aot_donate = donate_argnums \
            if jax.default_backend() == "tpu" else ()
        self._aot_jitted = self._jitted \
            if self._aot_donate == donate_argnums \
            else jax.jit(step_fn, donate_argnums=self._aot_donate)

    def enable_warm_start(self, store=None):
        """Opt-in executable persistence for the fused step — the
        ``Model.fit(resume=True)`` warm path. The first call lowers the
        step and loads a serialized executable from ``store`` (default:
        the ``jit.compile_cache`` process store), so a relaunched
        trainer reaches its first step in load time, not compile time;
        a cold store compiles once and persists for the next relaunch.
        Dispatch falls back to the regular jit path the moment the
        operand signature drifts from the warmed executable.

        No-op under ``offload_opt_state``: the offload path re-jits a
        ``device_put``-wrapped program in ``_setup_offload``, and
        persisting the resident-state variant would silently disable
        the offload (and its HBM relief) on relaunch."""
        if self._offload:
            return self
        from . import compile_cache
        self._warm_store = store if store is not None \
            else compile_cache.default_store()
        return self

    def _warm_signature(self, args):
        """Structural identity of the fused step WITHOUT tracing it
        (the store's traceless manifest key): model code + config,
        loss/optimizer code and their baked scalar constants, the
        recompute/skip flags, and the full operand aval tree. None —
        forcing the always-correct traced path — when any piece has no
        deterministic description (REPL lambdas, address-bearing
        reprs, opaque closure cells)."""
        from . import compile_cache
        sig = compile_cache.network_signature(self.model)
        loss_sig = compile_cache.callable_signature(self.loss_fn)
        opt_src = compile_cache.source_hash(type(self.optimizer))
        flags = repr((self._skip_nonfinite, self._offload,
                      self._recompute))
        if sig is None or loss_sig is None or opt_src is None \
                or "0x" in flags:
            return None
        sig.update(
            program=("TrainStep",), loss=loss_sig,
            opt=(type(self.optimizer).__qualname__, opt_src,
                 compile_cache.scalar_signature(self.optimizer)),
            flags=flags,
            operands=compile_cache.aval_signature(args))
        return sig

    def _setup_offload(self):
        """Re-jit with the opt state parked in pinned host memory: the
        step transfers moments host->HBM, updates, and writes them back
        host-side, so they are never HBM-resident between steps."""
        leaves = jax.tree_util.tree_leaves(self._opt_state_tree)
        dev = next(iter(leaves[0].devices())) if leaves \
            else jax.devices()[0]
        if dev.platform != "tpu":
            self._offload = False  # only TPU has a distinct host space
            return
        try:
            host = jax.sharding.SingleDeviceSharding(
                dev, memory_kind="pinned_host")
            devmem = jax.sharding.SingleDeviceSharding(
                dev, memory_kind="device")
            state_sh = jax.tree_util.tree_map(
                lambda _: host, self._opt_state_tree)
            inner = self._step_fn

            def offload_step(param_vals, opt_state, lr, step_no, *batch):
                opt_dev = jax.tree_util.tree_map(
                    lambda a: jax.device_put(a, devmem), opt_state)
                loss, new_params, new_state = inner(
                    param_vals, opt_dev, lr, step_no, *batch)
                new_host = jax.tree_util.tree_map(
                    lambda a: jax.device_put(a, host), new_state)
                return loss, new_params, new_host

            self._jitted = jax.jit(
                offload_step, donate_argnums=self._donate_argnums)
            self._opt_state_tree = jax.device_put(
                self._opt_state_tree, state_sh)
            self._host_shardings = state_sh
        except Exception:
            # backend without memory-kind support: resident-state path
            self._jitted = jax.jit(
                self._step_fn, donate_argnums=self._donate_argnums)
            self._offload = False

    def __call__(self, *batch):
        params = self._params_cache
        if self._opt_state_tree is None:
            # seed from the optimizer's own state when present (e.g. a
            # restored checkpoint via opt.set_state_dict) so resume works
            self._opt_state_tree = [
                self.optimizer._state.get(_opt_key(p))
                or self.optimizer.init_state_for(p) for p in params]
            if self._offload:
                self._setup_offload()
        lr = self.optimizer.get_lr()
        self.optimizer._step_count += 1
        raw_batch = tuple(
            jax.tree_util.tree_map(
                _unwrap, b, is_leaf=lambda t: isinstance(t, Tensor))
            for b in batch)
        args = ([p._data for p in params], self._opt_state_tree,
                np.float32(lr), np.int32(self.optimizer._step_count),
                *raw_batch)
        if self._warm_store is not None and self._warm_exe is None:
            from . import compile_cache
            self._warm_exe = compile_cache.build_or_load(
                self._warm_signature(args),
                lambda: self._aot_jitted.lower(*args),
                store=self._warm_store,
                extra=dict(kind="TrainStep",
                           donation=self._aot_donate),
                label="train_step")
            self._warm_store = None  # warmed once; drift falls back
        if self._warm_exe is not None:
            try:
                loss, new_vals, self._opt_state_tree = \
                    self._warm_exe(*args)
            except (TypeError, ValueError) as e:
                # operand signature drifted from the warmed executable
                # (input validation fails BEFORE execution — no donated
                # buffer was consumed): permanent fallback to jit
                monitor.record_swallowed("jit.compile_cache.warm_step",
                                         e)
                self._warm_exe = None
        if self._warm_exe is None:
            pre_cache = self._tracker.pre(self._jitted)
            loss, new_vals, self._opt_state_tree = self._jitted(*args)
            if monitor.enabled or _flight_recorder.enabled:
                # donated args keep their aval metadata
                self._tracker.observe(
                    self._jitted, (args[0], raw_batch), pre_cache)
        for p, v in zip(params, new_vals):
            p._data = v
        # mirror the functional state back so optimizer.state_dict()
        # checkpoints the live accumulators
        for p, st in zip(params, self._opt_state_tree):
            self.optimizer._state[_opt_key(p)] = st
        from ..optimizer.lr import LRScheduler
        if isinstance(self.optimizer._lr, LRScheduler) and \
                self.optimizer._lr._step_each_iter:
            self.optimizer._lr.step()
        return _wrap(loss)

    def audit(self, *batch, **audit_kw):
        """Static audit of the fused training step (analysis.audit):
        traces step_fn on abstract operands — nothing executes, no
        buffer is allocated — and runs the detector passes (donation
        misses, host callbacks, dtype leaks, baked consts, collective
        accounting). The tier-1 gate asserts zero ERROR findings and
        full donation coverage of params + optimizer state."""
        from ..analysis import abstractify, audit as _audit
        params = self._params_cache
        p_avals = [jax.ShapeDtypeStruct(tuple(p._data.shape),
                                        p._data.dtype) for p in params]
        if self._opt_state_tree is not None:
            opt_avals = abstractify(self._opt_state_tree)
        else:
            opt_avals = [jax.eval_shape(self.optimizer.init_state_for,
                                        p._data) for p in params]
        raw_batch = tuple(
            jax.tree_util.tree_map(
                _unwrap, b, is_leaf=lambda t: isinstance(t, Tensor))
            for b in batch)
        audit_kw.setdefault("name", "TrainStep.step_fn")
        return _audit(
            self._step_fn, p_avals, opt_avals,
            jax.ShapeDtypeStruct((), np.float32),
            jax.ShapeDtypeStruct((), np.int32), *abstractify(raw_batch),
            donate=self._donate_argnums, **audit_kw)

    def cost_analysis(self, *batch):
        """XLA's cost model for the compiled step on these inputs
        (['flops'], bytes accessed, ...) — bench.py derives MFU from it
        instead of hand-maintained per-model formulas (the reference's
        op cost-model table, cost_model/static_op_benchmark.json, is a
        measured equivalent)."""
        params = self._params_cache
        if self._opt_state_tree is None:
            self._opt_state_tree = [
                self.optimizer._state.get(_opt_key(p))
                or self.optimizer.init_state_for(p) for p in params]
            if self._offload:
                # keep offload active even when cost_analysis seeds the
                # state before the first real step
                self._setup_offload()
        raw_batch = tuple(
            jax.tree_util.tree_map(
                _unwrap, b, is_leaf=lambda t: isinstance(t, Tensor))
            for b in batch)
        lowered = self._jitted.lower(
            [p._data for p in params], self._opt_state_tree,
            np.float32(self.optimizer.get_lr()),
            np.int32(self.optimizer._step_count + 1), *raw_batch)
        return lowered.compile().cost_analysis()


def not_to_static(fn=None):
    """Mark a function to stay un-converted under @to_static (reference
    jit/api.py not_to_static)."""
    def deco(f):
        f.__jit_not_to_static__ = True
        return f

    return deco(fn) if fn is not None else deco


def set_code_level(level: int = 100, also_to_stdout: bool = False):
    """dy2static transformed-code logging (reference
    dygraph_to_static/logging_utils.set_code_level)."""
    import logging
    logging.getLogger("paddle_tpu.jit").setLevel(
        logging.DEBUG if level > 0 else logging.WARNING)


def set_verbosity(level: int = 0, also_to_stdout: bool = False):
    """dy2static verbosity (reference logging_utils.set_verbosity)."""
    set_code_level(level, also_to_stdout)


class ProgramTranslator:
    """Singleton toggling dy2static conversion globally (reference
    dygraph_to_static/program_translator.py ProgramTranslator). Here
    conversion happens in to_static itself; the toggle makes
    @to_static fall back to eager when disabled."""

    _instance = None
    enable_to_static = True

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, enable_to_static: bool):
        type(self).enable_to_static = bool(enable_to_static)


class TracedLayer:
    """Trace a dygraph Layer into a compiled callable + saved artifact
    (reference fluid/dygraph/jit.py TracedLayer over the legacy
    tracer; here: to_static capture + jit save)."""

    def __init__(self, layer: Layer, inputs):
        self._layer = layer
        self._compiled = to_static(layer)
        self._example = inputs

    @staticmethod
    def trace(layer: Layer, inputs):
        traced = TracedLayer(layer, inputs)
        return traced(*inputs), traced

    def __call__(self, *inputs):
        return self._compiled(*inputs)

    def save_inference_model(self, path, feed=None, fetch=None):
        from .save_load import save as jit_save
        jit_save(self._layer, path, input_spec=list(self._example))


def TranslatedLayer(path):
    """Load a saved program as a callable layer-like object (reference
    jit/translated_layer.py TranslatedLayer; here the jit.load result
    plays that role directly)."""
    from .save_load import load as jit_load
    return jit_load(path)
