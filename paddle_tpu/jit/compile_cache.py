"""Executable persistence: the warm-restart layer (ROADMAP item 4).

Every relaunch in this stack is BY DESIGN — preemption-safe training
exits and resumes, the serving engine AOT-warms one prefill executable
per bucket plus the decode/admit/free trio on every process start — and
each relaunch used to re-pay tens of seconds of XLA work. This module
makes a relaunched process warm-start in seconds, two layers deep:

1. **The process-global jax persistent compilation cache.**
   ``enable_compile_cache(dir)`` (or ``PADDLE_COMPILE_CACHE_DIR``)
   points jax's own HLO->binary disk cache at ``dir``. The jax cache
   dir is process-global state: it is set ONCE here and never silently
   re-pointed — a second caller naming a different dir gets a warning
   and the original dir (predictor B must not hijack predictor A's
   cache). This module is the only place allowed to touch
   ``jax_compilation_cache_dir`` (lint rule ``compile-cache-dir``).

2. **The executable store above it.** jax's cache keys on internals
   and still re-runs part of the compile pipeline on a hit; the
   :class:`ExecutableStore` instead persists whole compiled
   executables (``jax.experimental.serialize_executable``) keyed by
   (StableHLO fingerprint, mesh/sharding signature, donation
   signature, jax/jaxlib version, backend platform + device kind +
   device count). A hit deserializes straight to a callable
   ``jax.stages.Compiled`` — zero XLA compiles — in ~tens of
   milliseconds. Every AOT path threads through
   :func:`compile_or_load`: ``GenerationSession.aot_compile``, the
   ``ServingEngine.warmup()`` program set, the Predictor's per-bucket
   build, and the ``TrainStep``/``DistributedTrainStep`` opt-in warm
   path behind ``Model.fit(resume=True)``.

3. **The traceless manifest.** Even a store hit still pays the jax
   TRACE to produce the StableHLO the key hashes — and on relaunch,
   tracing every program costs nearly as much as compiling small ones.
   So the store keeps a second, derived level: ``.ref`` manifest
   entries mapping a *structural program signature* — framework + model
   **source hashes**, parameter/operand structure, generation/serving
   config reprs, donation, mesh, versions, backend — to the HLO key of
   the executable it produced. A warm relaunch resolves the signature,
   reads the ref, and deserializes the executable with ZERO traces and
   zero compiles; any doubt (no deterministic signature, missing ref,
   ref pointing at a dropped entry) falls back to the traced path,
   which is always correct and rewrites the ref.
   ``PADDLE_COMPILE_CACHE_VERIFY=1`` is the paranoid mode: the trace
   runs anyway and a ref whose stored key disagrees with the real
   fingerprint is recorded as ``misses{cause=stale_ref}`` and replaced
   — CI can prove the manifest honest.

Durability follows the CheckpointManager commit-marker idiom: entries
are written to a temp file and atomically renamed (a torn write is
never visible under the final name), carry a sha256 checksum, and a
corrupt/truncated/version-skewed entry is NEVER fatal — the load
falls back to a fresh compile, records
``jit.compile_cache.misses{cause=corrupt}``, removes the bad entry,
and rewrites a good one.

Reference analog: the reference ships this layer as serialized
inference programs in ``paddle/fluid/inference`` (PAPER.md §1) —
``save_optimized_model`` + the NaiveExecutor loading pre-analyzed
program descs; here the serialized artifact is the XLA executable
itself.
"""
from __future__ import annotations

import hashlib
import os
import pickle
import threading
import time
import warnings
from typing import Any, Dict, List, Optional

import jax

from ..core import monitor

__all__ = [
    "ExecutableStore",
    "aval_signature",
    "build_or_load",
    "cache_key",
    "callable_signature",
    "compile_or_load",
    "default_store",
    "enable_compile_cache",
    "network_signature",
    "scalar_signature",
    "set_default_store",
    "source_hash",
]

#: executable-entry file layout: MAGIC + 64 hex sha256(payload) + payload
_MAGIC = b"PDTPU-EXE1\n"
#: manifest-entry layout: REF_MAGIC + 64 hex chars (the executable key)
_REF_MAGIC = b"PDTPU-REF1\n"
ENTRY_SUFFIX = ".pdexe"
REF_SUFFIX = ".ref"

_lock = threading.RLock()
_CACHE_DIR: Optional[str] = None
_DEFAULT_STORE: Optional["ExecutableStore"] = None


# --------------------------------------------------- process-global cache

def enable_compile_cache(path: str,
                         min_compile_time_secs: float = 0.0
                         ) -> "ExecutableStore":
    """Point jax's persistent compilation cache at ``path`` and anchor
    the process-default :class:`ExecutableStore` at
    ``path/executables``. Returns the store.

    The jax cache dir is process-global; it is set once and a later
    call naming a DIFFERENT path warns and keeps the original (the
    same conflict semantics the inference predictor always had —
    ``Config.enable_compile_cache`` delegates here)."""
    global _CACHE_DIR, _DEFAULT_STORE
    with _lock:
        if _CACHE_DIR is None:
            os.makedirs(path, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", path)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              float(min_compile_time_secs))
            _CACHE_DIR = path
            _DEFAULT_STORE = ExecutableStore(
                os.path.join(path, "executables"))
        elif os.path.abspath(path) != os.path.abspath(_CACHE_DIR):
            warnings.warn(
                f"compile cache already at {_CACHE_DIR!r}; the jax "
                f"cache dir is process-global, ignoring {path!r}")
        return _DEFAULT_STORE


def cache_dir() -> Optional[str]:
    """The process-global persistent-cache dir (None until enabled)."""
    return _CACHE_DIR


def default_store() -> Optional["ExecutableStore"]:
    """The process-default executable store: the one
    :func:`enable_compile_cache` anchored, else auto-enabled from
    ``PADDLE_COMPILE_CACHE_DIR`` on first ask, else None (AOT paths
    then compile directly, persisting nothing)."""
    with _lock:
        if _DEFAULT_STORE is None:
            env = os.environ.get("PADDLE_COMPILE_CACHE_DIR", "").strip()
            if env:
                return enable_compile_cache(env)
        return _DEFAULT_STORE


def set_default_store(store: Optional["ExecutableStore"]
                      ) -> Optional["ExecutableStore"]:
    """Swap the process-default store (embedding apps, tests). Returns
    the previous default. Does NOT touch the jax persistent-cache dir —
    that stays set-once."""
    global _DEFAULT_STORE
    with _lock:
        prev, _DEFAULT_STORE = _DEFAULT_STORE, store
        return prev


# --------------------------------------------------------------- cache key

def backend_signature() -> Dict[str, Any]:
    """The environment half of the cache key: an executable is only
    loadable into the runtime flavor that produced it."""
    import jaxlib
    dev = jax.devices()[0]
    return dict(
        jax_version=jax.__version__,
        jaxlib_version=jaxlib.__version__,
        backend=dev.platform,
        device_kind=getattr(dev, "device_kind", ""),
        n_devices=jax.device_count(),
    )


def cache_key(hlo_fingerprint: str, *, extra: Optional[dict] = None,
              **overrides) -> str:
    """Derive the store key for one program. ``hlo_fingerprint`` is the
    sha256 of the lowered StableHLO text (shapes, dtypes, shardings and
    the sampling/config constants are all in there); ``extra`` carries
    the caller-declared components the HLO text cannot be trusted to
    encode on every backend — donation signature, mesh axes, program
    kind. ``overrides`` replace :func:`backend_signature` fields
    (tests prove a changed jaxlib/backend string MISSES).

    Changing ANY component must change the key: a stale hit that
    silently serves the wrong program is the failure mode this
    derivation exists to make impossible."""
    parts = backend_signature()
    parts.update(overrides)
    parts["hlo"] = str(hlo_fingerprint)
    if extra:
        parts["extra"] = tuple(sorted(
            (str(k), str(v)) for k, v in extra.items()))
    canon = repr(tuple(sorted((k, str(v)) for k, v in parts.items())))
    return hashlib.sha256(canon.encode("utf-8")).hexdigest()


def fingerprint_lowered(lowered) -> str:
    """sha256 of the lowered module's StableHLO text — deterministic
    across fresh traces of the same program."""
    return hashlib.sha256(lowered.as_text().encode("utf-8")).hexdigest()


# ----------------------------------------------- structural signatures
#
# The traceless manifest needs a deterministic description of "the
# program this call site would trace" WITHOUT tracing it. Program
# identity = code that builds the trace + operand structure + static
# config; the helpers below hash exactly that, and return None whenever
# no deterministic description exists — callers then use the traced
# path, which is always correct.

#: framework source whose edits can change traced program STRUCTURE; a
#: manifest written by different source must never resolve. The bias is
#: deliberately broad — every .py under these trees joins the salt, so
#: an edited layer/op/kernel/optimizer costs one extra cold compile
#: after the edit instead of ever risking a stale traceless hit.
_SALT_DIRS = (
    "nn", "ops", "kernels", "optimizer", "generation", "amp",
    "distributed/fleet",
)
_SALT_FILES = (
    "jit/api.py",
    "serving/engine.py",
    "inference/precision.py",
    "core/tensor.py",
)
_framework_salt_cache: Optional[str] = None


def _framework_salt() -> str:
    global _framework_salt_cache
    if _framework_salt_cache is None:
        import paddle_tpu
        root = os.path.dirname(os.path.abspath(paddle_tpu.__file__))
        h = hashlib.sha256(
            str(getattr(paddle_tpu, "__version__", "")).encode())

        def feed(path, rel):
            try:
                with open(path, "rb") as f:
                    h.update(rel.encode())
                    h.update(hashlib.sha256(f.read()).digest())
            except OSError:
                h.update(b"missing:" + rel.encode())

        for rel in _SALT_FILES:
            feed(os.path.join(root, rel), rel)
        for d in _SALT_DIRS:
            base = os.path.join(root, d)
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames.sort()
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        p = os.path.join(dirpath, name)
                        feed(p, os.path.relpath(p, root))
        _framework_salt_cache = h.hexdigest()
    return _framework_salt_cache


def source_hash(obj) -> Optional[str]:
    """sha256 of the object's source (class, function, lambda-in-file);
    None when no source is reachable (REPL lambdas, builtins) — the
    caller must then fall back to the traced path."""
    import inspect
    try:
        src = inspect.getsource(obj)
    except (OSError, TypeError):
        return None
    return hashlib.sha256(src.encode("utf-8")).hexdigest()


def aval_signature(tree) -> tuple:
    """(treedef, ((shape, dtype), ...)) of a pytree of arrays /
    ShapeDtypeStructs — the operand-structure half of a program
    signature, readable without any device work."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    sig = []
    for x in leaves:
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            sig.append((tuple(x.shape), str(x.dtype)))
        else:
            sig.append((repr(x),))
    return (str(treedef), tuple(sig))


def network_signature(network) -> Optional[dict]:
    """Structural identity of a live Layer without tracing it: class
    qualname + the SOURCE hash of its whole defining module (the trunk
    classes and helpers a model file executes live next to the class —
    hashing only the class block would miss them) + its config
    dataclass (or an address-free repr) + parameter/buffer structure +
    the framework salt (every nn/ops/kernels/optimizer source file).
    None when any piece is non-deterministic (e.g. a repr carrying
    object addresses) — then there is no sound traceless key and the
    traced path must be used."""
    import sys
    cls = type(network)
    mod_file = getattr(sys.modules.get(cls.__module__), "__file__",
                       None)
    cls_src = None
    if mod_file is not None:
        try:
            with open(mod_file, "rb") as f:
                cls_src = hashlib.sha256(f.read()).hexdigest()
        except OSError:
            cls_src = None
    if cls_src is None:
        cls_src = source_hash(cls)   # REPL/zip: class block only
    if cls_src is None:
        return None
    sig = dict(cls=f"{cls.__module__}.{cls.__qualname__}",
               cls_src=cls_src, salt=_framework_salt())
    cfg = getattr(network, "cfg", None)
    desc = repr(cfg) if cfg is not None else repr(network)
    if "0x" in desc:   # id()-bearing repr: not stable across processes
        return None
    sig["net"] = desc
    try:
        state = network.state_dict()
        sig["state"] = tuple(
            (name, tuple(t.shape), str(t.dtype))
            for name, t in state.items())
    except Exception:
        return None
    return sig


def scalar_signature(obj) -> tuple:
    """The plain-scalar attributes of an object, sorted — the baked
    trace-time constants an optimizer/config instance contributes to a
    program (betas, eps, weight decay, ...)."""
    out = []
    try:
        attrs = vars(obj)
    except TypeError:
        return ()
    for k in sorted(attrs):
        v = attrs[k]
        if isinstance(v, (int, float, bool, str, bytes)) or v is None:
            out.append((k, repr(v)))
    return tuple(out)


def callable_signature(fn, _depth: int = 0) -> Optional[tuple]:
    """Source hash of a callable PLUS the identifiable values it closes
    over (scalars are baked into the trace as constants; closed-over
    callables/Layers recurse). None when anything in the closure cannot
    be identified deterministically — then no traceless key exists."""
    src = source_hash(fn)
    if src is None or _depth > 4:
        return None
    parts = []
    for cell in getattr(fn, "__closure__", None) or ():
        try:
            v = cell.cell_contents
        except ValueError:
            return None
        if isinstance(v, (int, float, bool, str, bytes)) or v is None:
            parts.append(repr(v))
        elif hasattr(v, "state_dict"):
            ns = network_signature(v)
            if ns is None:
                return None
            parts.append(tuple(sorted(
                (k, str(x)) for k, x in ns.items())))
        elif callable(v):
            inner = callable_signature(v, _depth + 1)
            if inner is None:
                return None
            parts.append(inner)
        else:
            return None   # unidentifiable baked operand
    return (src, tuple(parts))


def _signature_key(signature: dict, extra: Optional[dict]) -> str:
    canon = repr(tuple(sorted(
        (str(k), str(v)) for k, v in signature.items())))
    return cache_key("ref:" + hashlib.sha256(
        canon.encode("utf-8")).hexdigest(), extra=extra)


def _verify_mode() -> bool:
    return os.environ.get("PADDLE_COMPILE_CACHE_VERIFY",
                          "").strip().lower() in ("1", "true", "on")


# ------------------------------------------------------------------- store

class ExecutableStore:
    """Directory of serialized compiled executables, one file per key.

    ::

        store = ExecutableStore("/ckpt/compile_cache/executables")
        exe = store.get_or_compile(jitted.lower(*avals),
                                   extra=dict(kind="decode",
                                              donation=(2,)))

    Writes are atomic (temp file + ``os.replace`` — the commit-marker
    idiom collapsed to a single-file rename), loads are
    corruption-tolerant (checksum + magic; any failure removes the bad
    entry and returns None so the caller recompiles), and every
    hit/miss/byte flows into the ``jit.compile_cache.*`` metrics family
    as well as the instance-local ``stats`` dict (readable without the
    monitor enabled — bench reads it)."""

    def __init__(self, root: str):
        self.root = str(root)
        self.stats = dict(hits=0, misses=0, saves=0,
                          bytes_loaded=0, bytes_saved=0)

    # ------------------------------------------------------------ layout
    def path_for(self, key: str) -> str:
        return os.path.join(self.root, key + ENTRY_SUFFIX)

    def entries(self) -> List[str]:
        """Sorted entry paths (deterministic handle for fault
        injection)."""
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        return sorted(os.path.join(self.root, n) for n in names
                      if n.endswith(ENTRY_SUFFIX))

    def __len__(self) -> int:
        return len(self.entries())

    def key_for(self, lowered, *, extra: Optional[dict] = None,
                **overrides) -> str:
        return cache_key(fingerprint_lowered(lowered), extra=extra,
                         **overrides)

    # -------------------------------------------------------------- load
    def load(self, key: str, label: str = "") -> Optional[Any]:
        """A ``jax.stages.Compiled`` for ``key``, or None (absent or
        corrupt — corrupt entries are deleted and recorded as
        ``misses{cause=corrupt}`` so the next save rewrites a good
        one)."""
        path = self.path_for(key)
        t0 = time.perf_counter()
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            self._miss("absent")
            return None
        try:
            if not blob.startswith(_MAGIC):
                raise ValueError("bad magic")
            digest = blob[len(_MAGIC):len(_MAGIC) + 64]
            payload = blob[len(_MAGIC) + 64:]
            if hashlib.sha256(payload).hexdigest().encode() != digest:
                raise ValueError("checksum mismatch (torn/corrupt entry)")
            from jax.experimental import serialize_executable as _se
            serialized, in_tree, out_tree = pickle.loads(payload)
            exe = _se.deserialize_and_load(serialized, in_tree, out_tree)
        except Exception as e:
            # a bad entry must never crash a relaunch: recompile instead
            # (and drop the entry so the fresh compile rewrites it)
            self._miss("corrupt")
            monitor.record_swallowed(f"jit.compile_cache.load[{label}]", e)
            try:
                os.remove(path)
            except OSError:
                pass
            return None
        load_ms = (time.perf_counter() - t0) * 1e3
        self.stats["hits"] += 1
        self.stats["bytes_loaded"] += len(blob)
        monitor.record_compile_cache_hit(len(blob), load_ms)
        return exe

    def _miss(self, cause: str):
        self.stats["misses"] += 1
        monitor.record_compile_cache_miss(cause)

    # -------------------------------------------------------------- save
    def save(self, key: str, compiled, label: str = "") -> bool:
        """Serialize + atomically commit one executable; False when the
        backend/executable does not support serialization (recorded,
        never raised — persistence is an optimization, not a
        contract)."""
        t0 = time.perf_counter()
        try:
            from jax.experimental import serialize_executable as _se
            serialized, in_tree, out_tree = _se.serialize(compiled)
            payload = pickle.dumps((serialized, in_tree, out_tree),
                                   protocol=pickle.HIGHEST_PROTOCOL)
        except Exception as e:
            monitor.record_swallowed(f"jit.compile_cache.save[{label}]", e)
            return False
        blob = _MAGIC + hashlib.sha256(payload).hexdigest().encode() \
            + payload
        path = self.path_for(key)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            # makedirs inside the guard: an unwritable/uncreatable
            # store root degrades to no-persistence, never to a
            # crashed training/serving step
            os.makedirs(self.root, exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)  # atomic commit: readers see a whole
            #                        entry under the final name, or none
        except OSError as e:
            monitor.record_swallowed(f"jit.compile_cache.save[{label}]", e)
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False
        save_ms = (time.perf_counter() - t0) * 1e3
        self.stats["saves"] += 1
        self.stats["bytes_saved"] += len(blob)
        monitor.record_compile_cache_save(len(blob), save_ms)
        return True

    # ----------------------------------------------------- the manifest
    def _ref_path(self, ref_key: str) -> str:
        return os.path.join(self.root, ref_key + REF_SUFFIX)

    def _read_ref(self, ref_key: str) -> Optional[str]:
        try:
            with open(self._ref_path(ref_key), "rb") as f:
                blob = f.read()
        except OSError:
            return None
        if not blob.startswith(_REF_MAGIC):
            return None
        key = blob[len(_REF_MAGIC):].decode("ascii", "replace").strip()
        if len(key) != 64 or any(c not in "0123456789abcdef"
                                 for c in key):
            return None   # corrupt ref: treated as absent
        return key

    def _write_ref(self, ref_key: str, exe_key: str):
        path = self._ref_path(ref_key)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            os.makedirs(self.root, exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(_REF_MAGIC + exe_key.encode("ascii"))
            os.replace(tmp, path)
        except OSError as e:
            monitor.record_swallowed("jit.compile_cache.ref", e)
            try:
                os.remove(tmp)
            except OSError:
                pass

    # ---------------------------------------------------------- combined
    def get_or_compile(self, lowered, *, extra: Optional[dict] = None,
                       label: str = ""):
        """The traced AOT entry point: key the lowered program, load
        the stored executable on a hit (zero XLA compiles), else
        compile and persist. Always returns a callable ``Compiled``."""
        key = self.key_for(lowered, extra=extra)
        exe = self.load(key, label=label)
        if exe is not None:
            return exe
        exe = lowered.compile()
        self.save(key, exe, label=label)
        return exe

    def get_or_build(self, signature: Optional[dict], lower_fn, *,
                     extra: Optional[dict] = None, label: str = ""):
        """The TRACELESS AOT entry point. ``signature`` structurally
        identifies the program (see :func:`network_signature` /
        :func:`aval_signature`); on a manifest hit the executable
        deserializes with zero traces AND zero compiles — ``lower_fn``
        is never called. Every doubt (``signature`` None, no ref, ref
        pointing at a dropped entry) falls back to
        ``lower_fn() -> get_or_compile`` — always correct — and
        rewrites the ref for the next relaunch. Under
        ``PADDLE_COMPILE_CACHE_VERIFY=1`` the trace runs regardless and
        a lying ref is recorded as ``misses{cause=stale_ref}`` and
        replaced."""
        ref_key = None
        failed_key = None
        if signature is not None:
            ref_key = _signature_key(signature, extra)
            exe_key = self._read_ref(ref_key)
            if exe_key is not None and not _verify_mode():
                exe = self.load(exe_key, label=label)
                if exe is not None:
                    return exe
                # entry vanished/corrupt under the ref (miss recorded
                # by load): re-derive everything through the traced path
                failed_key = exe_key
        lowered = lower_fn()
        true_key = self.key_for(lowered, extra=extra)
        if ref_key is not None and _verify_mode():
            stored = self._read_ref(ref_key)
            if stored is not None and stored != true_key:
                self._miss("stale_ref")
                monitor.record_swallowed(
                    f"jit.compile_cache.stale_ref[{label}]",
                    RuntimeError(f"manifest {ref_key[:12]} pointed at "
                                 f"{stored[:12]}, program is "
                                 f"{true_key[:12]}"))
        # when the ref's target just failed and IS this program's key,
        # skip the second lookup — one corruption must count one miss,
        # not corrupt+absent
        exe = None if true_key == failed_key \
            else self.load(true_key, label=label)
        if exe is None:
            exe = lowered.compile()
            self.save(true_key, exe, label=label)
        if ref_key is not None:
            self._write_ref(ref_key, true_key)
        return exe

    def refs(self) -> List[str]:
        """Sorted manifest-entry paths."""
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        return sorted(os.path.join(self.root, n) for n in names
                      if n.endswith(REF_SUFFIX))

    def clear(self):
        for path in self.entries() + self.refs():
            try:
                os.remove(path)
            except OSError:
                pass

    def __repr__(self):
        return (f"ExecutableStore({self.root!r}, entries={len(self)}, "
                f"stats={self.stats})")


def compile_or_load(lowered, *, store: Optional[ExecutableStore] = None,
                    extra: Optional[dict] = None, label: str = ""):
    """Compile ``lowered`` through ``store`` (default: the
    process-default store; with no store active this is exactly
    ``lowered.compile()``)."""
    store = store if store is not None else default_store()
    if store is None:
        return lowered.compile()
    return store.get_or_compile(lowered, extra=extra, label=label)


def build_or_load(signature: Optional[dict], lower_fn, *,
                  store: Optional[ExecutableStore] = None,
                  extra: Optional[dict] = None, label: str = ""):
    """Traceless variant of :func:`compile_or_load`: on a manifest hit
    ``lower_fn`` is never called (zero traces, zero compiles). With no
    store active this is ``lower_fn().compile()``."""
    store = store if store is not None else default_store()
    if store is None:
        return lower_fn().compile()
    return store.get_or_build(signature, lower_fn, extra=extra,
                              label=label)
