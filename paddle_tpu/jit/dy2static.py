"""dy2static: AST conversion of tensor-dependent Python control flow.

Reference analog: python/paddle/fluid/dygraph/dygraph_to_static/ —
IfElseTransformer/LoopTransformer rewrite user source so `if`/`while`
over Tensors become control-flow OPS (convert_ifelse/convert_while_loop
in convert_operators.py), driven by ProgramTranslator.

TPU-native: the target ops are jax.lax.cond / jax.lax.while_loop, so
converted functions trace into ONE XLA program even when the Python
control flow depends on runtime tensor values. Plain-Python predicates
keep eager if/while semantics — the convert_* helpers dispatch on
whether the predicate is a Tensor/tracer at runtime, exactly like the
reference's convert_ifelse does.

Scope: `if`/`if-else`, `while`, and `for` (over tensors / indexables /
`range`, incl. a traced trip count) are converted, with break/continue
rewritten to carried flags (the reference's loop_transformer.py +
break_continue_transformer.py pair). Both branches / the loop body must
assign compatible (same shape/dtype) values to the variables that live
past the construct; `return` inside a converted construct stays Python.
`for` over non-indexables (generators, zip, dicts) falls back to the
original Python loop at runtime.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Any, Callable, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["convert_ifelse", "convert_while_loop", "convert_to_static",
           "declarative"]

class _UndefType:
    """Sentinel for a carried variable that was unbound at construct
    entry. Any real use replays the NameError the unconverted code
    would have raised (identity checks like `v is _UNDEF` stay safe)."""
    __slots__ = ()

    @staticmethod
    def _raise(*_a, **_k):
        raise NameError(
            "dy2static: variable referenced before assignment inside "
            "a converted construct")

    __bool__ = __float__ = __int__ = __index__ = __len__ = _raise
    __iter__ = __getitem__ = __setitem__ = __call__ = _raise
    __lt__ = __le__ = __gt__ = __ge__ = __eq__ = __ne__ = _raise
    __add__ = __radd__ = __sub__ = __rsub__ = _raise
    __mul__ = __rmul__ = __truediv__ = __rtruediv__ = _raise
    __neg__ = __pos__ = __abs__ = __matmul__ = __rmatmul__ = _raise
    __hash__ = object.__hash__  # defining __eq__ would otherwise kill it

    def __getattr__(self, _name):
        self._raise()

    def __repr__(self):
        return "<dy2static undef>"


_UNDEF = _UndefType()


def _is_traced_pred(pred) -> bool:
    if isinstance(pred, Tensor):
        return isinstance(pred._data, jax.core.Tracer)
    return isinstance(pred, jax.core.Tracer)


def _raw(x):
    return x._data if isinstance(x, Tensor) else x


def _bool(pred) -> bool:
    return bool(_raw(pred))


def convert_ifelse(pred, true_fn: Callable, false_fn: Callable,
                   init_vals: Tuple = ()):
    """Runtime dispatch for a converted `if` (reference
    convert_operators.convert_ifelse). Both fns take the current values
    of the carried variables and return their new tuple."""
    if not _is_traced_pred(pred):
        return true_fn(*init_vals) if _bool(pred) \
            else false_fn(*init_vals)

    # traced: both branches run under lax.cond on RAW leaves. Values
    # stay raw (to_static already feeds the converted function raw
    # tracers); mixing wrapped Tensors back in would leak Tensor
    # objects into jnp indexing inside the trace.
    def run(fn):
        def inner(_):
            outs = fn(*init_vals)
            is_leaf = lambda t: isinstance(t, Tensor) or t is _UNDEF
            if any(o is _UNDEF for o in
                   jax.tree_util.tree_leaves(outs, is_leaf=is_leaf)):
                raise ValueError(
                    "dy2static: a variable carried across a converted "
                    "tensor-`if` is not assigned on every branch; both "
                    "branches must bind the same variables when the "
                    "predicate is traced")
            return jax.tree_util.tree_map(
                _raw, outs, is_leaf=lambda t: isinstance(t, Tensor))
        return inner

    pred_raw = jnp.asarray(_raw(pred)).reshape(())
    return jax.lax.cond(pred_raw.astype(bool), run(true_fn),
                        run(false_fn), operand=None)


def convert_while_loop(cond_fn: Callable, body_fn: Callable,
                       loop_vars: Tuple):
    """Runtime dispatch for a converted `while` (reference
    convert_operators.convert_while_loop). cond_fn/body_fn take and
    return the loop-variable tuple."""
    probe = cond_fn(*loop_vars)
    has_undef = any(v is _UNDEF for v in loop_vars)
    if has_undef or (not _is_traced_pred(probe) and not any(
            isinstance(_raw(v), jax.core.Tracer) for v in loop_vars)):
        if has_undef and _is_traced_pred(probe):
            raise ValueError(
                "dy2static: a converted tensor-`while` carries a "
                "variable that is unbound before the loop; initialise "
                "it (same shape/dtype as inside the body) before the "
                "loop when the condition is traced")
        vars_ = tuple(loop_vars)
        while _bool(cond_fn(*vars_)):
            vars_ = tuple(body_fn(*vars_))
        return vars_

    def cond(raw_vars):
        return jnp.asarray(_raw(cond_fn(*raw_vars))).reshape(()) \
            .astype(bool)

    def body(raw_vars):
        outs = body_fn(*raw_vars)
        return tuple(_raw(o) for o in outs)

    raw = tuple(_raw(v) for v in loop_vars)
    return jax.lax.while_loop(cond, body, raw)


def _any_traced(*vals) -> bool:
    return any(isinstance(_raw(v), jax.core.Tracer) for v in vals)


def convert_logical_and(a, b):
    """Runtime `and` that stays traceable (loop conditions combine the
    user test with the break flag)."""
    if _any_traced(a, b):
        return jnp.logical_and(jnp.asarray(_raw(a)).astype(bool),
                               jnp.asarray(_raw(b)).astype(bool))
    return _bool(a) and _bool(b)


def convert_logical_not(a):
    if _any_traced(a):
        return jnp.logical_not(jnp.asarray(_raw(a)).astype(bool))
    return not _bool(a)


def convert_no_jump(brk, cnt):
    """True while neither break nor continue has fired this iteration."""
    if _any_traced(brk, cnt):
        return jnp.logical_not(jnp.logical_or(
            jnp.asarray(_raw(brk)).astype(bool),
            jnp.asarray(_raw(cnt)).astype(bool)))
    return not (_bool(brk) or _bool(cnt))


def convert_indexable(it) -> bool:
    """Should the for-loop desugar take over this iterable? Only
    Tensor/jax arrays: their trip count is a STATIC shape and indexing
    with a traced counter lowers to dynamic_slice. Python sequences
    (list/tuple/range-object/ndarray) keep the original Python loop —
    it unrolls cleanly under tracing, and indexing them with a traced
    counter would be impossible anyway."""
    return isinstance(it, (Tensor, jax.Array))


def convert_len(it):
    if isinstance(it, Tensor):
        return it.shape[0]
    if isinstance(it, jax.Array):
        return it.shape[0]
    return len(it)


def convert_getitem(it, i):
    if isinstance(it, (list, tuple, range)):
        return it[int(i)] if not _any_traced(i) else it[i]
    return it[i]


def convert_range_len(start, stop, step):
    """Trip count of range(start, stop, step); works for traced
    operands of either step sign (ceil division toward the step's
    direction, clamped at zero)."""
    if _any_traced(start, stop, step):
        s0 = jnp.asarray(_raw(start))
        s1 = jnp.asarray(_raw(stop))
        st = jnp.asarray(_raw(step))
        adj = jnp.where(st > 0, st - 1, st + 1)
        return jnp.maximum(0, (s1 - s0 + adj) // st)
    return len(range(int(_raw(start)), int(_raw(stop)),
                     int(_raw(step))))


# --------------------------------------------------------------- AST pass
class _AssignedNames(ast.NodeVisitor):
    def __init__(self):
        self.names: List[str] = []

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)) and \
                node.id not in self.names:
            self.names.append(node.id)

    def visit_FunctionDef(self, node):  # don't descend into nested defs
        if node.name not in self.names:
            self.names.append(node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass


def _assigned(stmts: Sequence[ast.stmt]) -> List[str]:
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


def _has_jump(stmts: Sequence[ast.stmt]) -> bool:
    """True if a return/break/continue would cross the construct's
    boundary. Nested function bodies (incl. __jst helpers from inner
    conversions) have their own scope and don't count."""

    def walk(node) -> bool:
        if isinstance(node, (ast.Return, ast.Break, ast.Continue)):
            return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return False
        return any(walk(c) for c in ast.iter_child_nodes(node))

    return any(walk(s) for s in stmts)


def _has_return(stmts: Sequence[ast.stmt]) -> bool:
    def walk(node) -> bool:
        if isinstance(node, ast.Return):
            return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return False
        return any(walk(c) for c in ast.iter_child_nodes(node))

    return any(walk(s) for s in stmts)


def _stmt_has_break_continue(node) -> bool:
    """break/continue belonging to THIS loop level (not crossing nested
    loops or function bodies)."""
    if isinstance(node, (ast.Break, ast.Continue)):
        return True
    if isinstance(node, (ast.For, ast.While, ast.FunctionDef,
                         ast.AsyncFunctionDef, ast.Lambda)):
        return False
    return any(_stmt_has_break_continue(c)
               for c in ast.iter_child_nodes(node))


# carried generated variables use the __jstv_ prefix so the loop-var
# collectors keep them (plain __jst_* names are helper FUNCTIONS and
# are filtered out); per-loop numbering keeps nested loops' flags apart


def _carried(names):
    return [n for n in names
            if not n.startswith("__jst") or n.startswith("__jstv")]


class _ControlFlowTransformer(ast.NodeTransformer):
    """Rewrites tensor-convertible `if` and `while` statements into
    convert_ifelse / convert_while_loop calls."""

    def __init__(self):
        super().__init__()
        # depth > 0 ⇒ this construct's statements end up inside a
        # generated __jst_* function whose trailing `return (...)`
        # still loads every carried name — deleting one there would
        # raise UnboundLocalError at the return. Only the outermost
        # level un-binds leftover sentinels; inner levels pass _UNDEF
        # through (it raises NameError on any real use).
        self._depth = 0
        self._uid = 0  # per-construct counter for generated var names

    def _load(self, name):
        return ast.Name(id=name, ctx=ast.Load())

    def _init_val(self, name):
        # locals().get(name, _UNDEF): carried vars may be unbound
        # before the branch (e.g. first assigned inside it)
        return ast.Call(
            func=ast.Attribute(
                value=ast.Call(func=ast.Name(id="locals",
                                             ctx=ast.Load()),
                               args=[], keywords=[]),
                attr="get", ctx=ast.Load()),
            args=[ast.Constant(value=name),
                  ast.Name(id="__jst_undef", ctx=ast.Load())],
            keywords=[])

    def _undef_cleanup(self, names):
        # `if n is __jst_undef: del n` per carried name: a branch/loop
        # that never bound the variable must leave it unbound, so later
        # use raises NameError exactly like the unconverted code
        out = []
        for n in names:
            out.append(ast.If(
                test=ast.Compare(
                    left=self._load(n), ops=[ast.Is()],
                    comparators=[ast.Name(id="__jst_undef",
                                          ctx=ast.Load())]),
                body=[ast.Delete(targets=[
                    ast.Name(id=n, ctx=ast.Del())])],
                orelse=[]))
        return out

    def _branch_fn(self, fname, body, out_names):
        ret = ast.Return(value=ast.Tuple(
            elts=[self._load(n) for n in out_names], ctx=ast.Load()))
        # carried vars come in as parameters so assignments inside the
        # branch never shadow unbound outer locals
        return ast.FunctionDef(
            name=fname,
            args=ast.arguments(
                posonlyargs=[],
                args=[ast.arg(arg=n) for n in out_names],
                kwonlyargs=[], kw_defaults=[], defaults=[]),
            body=list(body) + [ret], decorator_list=[])

    def visit_If(self, node: ast.If):
        self._depth += 1
        try:
            self.generic_visit(node)
        finally:
            self._depth -= 1
        # jumps can't cross a lax.cond boundary — leave those to Python
        if _has_jump(node.body) or _has_jump(node.orelse):
            return node
        out_names = []
        for n in _carried(_assigned(node.body) + _assigned(node.orelse)):
            # __jst_* helper defs from nested conversions are internal;
            # __jstv_* carried flags/counters stay
            if n not in out_names:
                out_names.append(n)
        if not out_names:
            return node  # pure side-effect-free branch: keep python
        true_fn = self._branch_fn("__jst_true", node.body, out_names)
        false_fn = self._branch_fn(
            "__jst_false", node.orelse or [ast.Pass()], out_names)
        call = ast.Call(
            func=ast.Name(id="__jst_convert_ifelse", ctx=ast.Load()),
            args=[node.test, self._load("__jst_true"),
                  self._load("__jst_false"),
                  ast.Tuple(elts=[self._init_val(n)
                                  for n in out_names],
                            ctx=ast.Load())], keywords=[])
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store())
                      for n in out_names], ctx=ast.Store())],
            value=call)
        stmts = [true_fn, false_fn, assign]
        if self._depth == 0:
            stmts += self._undef_cleanup(out_names)
        return stmts

    # ---- break/continue -> carried flags (reference
    # break_continue_transformer.py). Statements after a potential
    # jump point are wrapped in `if __jst_no_jump(brk, cnt):` guards;
    # dead code directly after a bare break/continue is dropped.
    def _rewrite_jumps(self, stmts, brk, cnt):
        out = []
        for i, s in enumerate(stmts):
            if isinstance(s, ast.Break):
                out.append(self._assign_name(brk, ast.Constant(True)))
                return out
            if isinstance(s, ast.Continue):
                out.append(self._assign_name(cnt, ast.Constant(True)))
                return out
            if isinstance(s, ast.If) and _stmt_has_break_continue(s):
                out.append(ast.If(
                    test=s.test,
                    body=self._rewrite_jumps(s.body, brk, cnt),
                    orelse=self._rewrite_jumps(s.orelse, brk, cnt)
                    if s.orelse else []))
                rest = stmts[i + 1:]
                if rest:
                    out.append(ast.If(
                        test=ast.Call(
                            func=self._load("__jst_no_jump"),
                            args=[self._load(brk), self._load(cnt)],
                            keywords=[]),
                        body=self._rewrite_jumps(rest, brk, cnt),
                        orelse=[]))
                return out
            out.append(s)
        return out

    def _assign_name(self, name, value):
        return ast.Assign(
            targets=[ast.Name(id=name, ctx=ast.Store())], value=value)

    def visit_While(self, node: ast.While):
        if _has_return(node.body) or node.orelse:
            self._depth += 1
            try:
                self.generic_visit(node)
            finally:
                self._depth -= 1
            return node
        pre: list = []
        if any(_stmt_has_break_continue(s) for s in node.body):
            self._uid += 1
            brk = f"__jstv_brk{self._uid}"
            cnt = f"__jstv_cnt{self._uid}"
            new_body = [self._assign_name(cnt, ast.Constant(False))] + \
                self._rewrite_jumps(list(node.body), brk, cnt)
            if any(_stmt_has_break_continue(s) for s in new_body):
                # a break/continue under with/try/... survived the
                # rewrite — moving it into a generated function would
                # be a SyntaxError; leave the loop to Python
                self._depth += 1
                try:
                    self.generic_visit(node)
                finally:
                    self._depth -= 1
                return node
            pre = [self._assign_name(brk, ast.Constant(False)),
                   self._assign_name(cnt, ast.Constant(False))]
            node = ast.While(
                test=ast.Call(
                    func=self._load("__jst_and"),
                    args=[ast.Call(func=self._load("__jst_not"),
                                   args=[self._load(brk)], keywords=[]),
                          node.test],
                    keywords=[]),
                body=new_body,
                orelse=[])
        self._depth += 1
        try:
            self.generic_visit(node)
        finally:
            self._depth -= 1
        loop_names = _carried(_assigned(node.body))
        if not loop_names:
            return pre + [node] if pre else node
        args = ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=n) for n in loop_names],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        cond_fn = ast.FunctionDef(
            name="__jst_cond", args=args,
            body=[ast.Return(value=node.test)], decorator_list=[])
        body_fn = ast.FunctionDef(
            name="__jst_body", args=args,
            body=list(node.body) + [ast.Return(value=ast.Tuple(
                elts=[self._load(n) for n in loop_names],
                ctx=ast.Load()))],
            decorator_list=[])
        call = ast.Call(
            func=ast.Name(id="__jst_convert_while", ctx=ast.Load()),
            args=[self._load("__jst_cond"), self._load("__jst_body"),
                  ast.Tuple(elts=[self._init_val(n)
                                  for n in loop_names],
                            ctx=ast.Load())],
            keywords=[])
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store())
                      for n in loop_names], ctx=ast.Store())],
            value=call)
        stmts = pre + [cond_fn, body_fn, assign]
        if self._depth == 0:
            stmts += self._undef_cleanup(loop_names)
        return stmts

    # ---- `for` -> while desugar (reference loop_transformer.py) -------
    def visit_For(self, node: ast.For):
        if node.orelse or _has_return(node.body):
            self._depth += 1
            try:
                self.generic_visit(node)
            finally:
                self._depth -= 1
            return node
        import copy
        orig = copy.deepcopy(node)

        is_range = (isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range"
                    and 1 <= len(node.iter.args) <= 3
                    and not node.iter.keywords)
        self._uid += 1
        u = self._uid
        i_name, n_name, it_name = (f"__jstv_i{u}", f"__jstv_n{u}",
                                   f"__jstv_it{u}")

        def _call(fn, args):
            return ast.Call(func=self._load(fn), args=args, keywords=[])

        if is_range:
            a = node.iter.args
            start = a[0] if len(a) >= 2 else ast.Constant(0)
            stop = a[1] if len(a) >= 2 else a[0]
            step = a[2] if len(a) == 3 else ast.Constant(1)
            s_name, e_name = f"__jstv_start{u}", f"__jstv_step{u}"
            o_name = f"__jstv_stop{u}"
            pre = [
                self._assign_name(s_name, start),
                self._assign_name(o_name, stop),
                self._assign_name(e_name, step),
                self._assign_name(n_name, _call(
                    "__jst_range_len",
                    [self._load(s_name), self._load(o_name),
                     self._load(e_name)])),
            ]
            # traced trip count -> while desugar (ONE lax.while_loop);
            # python-int trip count -> keep the original Python loop,
            # which unrolls under tracing and lets the body index
            # python containers with the concrete counter
            item = ast.BinOp(
                left=self._load(s_name), op=ast.Add(),
                right=ast.BinOp(left=self._load(i_name), op=ast.Mult(),
                                right=self._load(e_name)))
            loop = ast.While(
                test=ast.Compare(left=self._load(i_name), ops=[ast.Lt()],
                                 comparators=[self._load(n_name)]),
                body=[ast.Assign(targets=[node.target], value=item),
                      self._assign_name(i_name, ast.BinOp(
                          left=self._load(i_name), op=ast.Add(),
                          right=ast.Constant(1)))] + list(node.body),
                orelse=[])
            visited = self.visit(loop)
            traced_branch = [
                self._assign_name(i_name, ast.Constant(0)),
                ast.Assign(targets=[copy.deepcopy(node.target)],
                           value=self._load(s_name)),
            ] + (visited if isinstance(visited, list) else [visited])
            self._depth += 1
            try:
                fb_body = []
                for s in orig.body:
                    v = self.visit(s)
                    fb_body.extend(v if isinstance(v, list) else [v])
            finally:
                self._depth -= 1
            eager_for = ast.For(
                target=orig.target,
                iter=ast.Call(func=ast.Name(id="range", ctx=ast.Load()),
                              args=[self._load(s_name),
                                    self._load(o_name),
                                    self._load(e_name)],
                              keywords=[]),
                body=fb_body, orelse=[])
            dispatch_if = ast.If(
                test=ast.Call(
                    func=ast.Name(id="isinstance", ctx=ast.Load()),
                    args=[self._load(n_name),
                          ast.Name(id="int", ctx=ast.Load())],
                    keywords=[]),
                body=[eager_for], orelse=traced_branch)
            return pre + [dispatch_if]

        # generic iterable: runtime dispatch — desugar only when the
        # value is indexable (tensor/array/list/tuple/range); anything
        # else (generator, zip, dict) keeps the original Python loop
        pre = [
            self._assign_name(it_name, node.iter),
            ast.If(
                test=_call("__jst_indexable", [self._load(it_name)]),
                body=[self._assign_name(
                    n_name, _call("__jst_len", [self._load(it_name)]))],
                orelse=[self._assign_name(n_name, ast.Constant(0))]),
            self._assign_name(i_name, ast.Constant(0)),
        ]
        item = _call("__jst_getitem",
                     [self._load(it_name), self._load(i_name)])
        loop = ast.While(
            test=ast.Compare(left=self._load(i_name), ops=[ast.Lt()],
                             comparators=[self._load(n_name)]),
            body=[ast.Assign(targets=[node.target], value=item),
                  self._assign_name(i_name, ast.BinOp(
                      left=self._load(i_name), op=ast.Add(),
                      right=ast.Constant(1)))] + list(node.body),
            orelse=[])
        init_tgt = ast.If(  # typed target init (n is a python int here)
            test=ast.Compare(left=self._load(n_name), ops=[ast.Gt()],
                             comparators=[ast.Constant(0)]),
            body=[ast.Assign(
                targets=[copy.deepcopy(node.target)],
                value=_call("__jst_getitem",
                            [self._load(it_name), ast.Constant(0)]))],
            orelse=[])
        visited = self.visit(loop)
        loop_stmts = visited if isinstance(visited, list) else [visited]
        # visit the fallback body too (nested tensor-ifs still convert)
        self._depth += 1
        try:
            fb_body = []
            for s in orig.body:
                v = self.visit(s)
                fb_body.extend(v if isinstance(v, list) else [v])
        finally:
            self._depth -= 1
        fallback = ast.For(target=orig.target, iter=self._load(it_name),
                           body=fb_body, orelse=[])
        dispatch_if = ast.If(
            test=_call("__jst_indexable", [self._load(it_name)]),
            body=[init_tgt] + loop_stmts, orelse=[fallback])
        return pre + [dispatch_if]


def convert_to_static(fn: Callable) -> Callable:
    """Source-rewrite `fn` so tensor-dependent if/while trace into
    lax.cond/while_loop (the ProgramTranslator.get_func analog).
    Falls back to the original function when source is unavailable."""
    if getattr(fn, "__jit_not_to_static__", False):
        return fn  # @not_to_static opt-out
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return fn
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return fn
    fdef = tree.body[0]
    # only plain named defs convert: a lambda/comprehension source is
    # its ENCLOSING statement — re-exec'ing that would replay arbitrary
    # side effects and never bind fn.__name__
    if not isinstance(fdef, ast.FunctionDef) or \
            fdef.name != fn.__name__:
        return fn
    # drop decorators so re-exec doesn't recurse through @declarative;
    # with MULTIPLE stacked decorators that would silently strip the
    # inner ones — leave such functions unconverted
    if len(fdef.decorator_list) > 1:
        return fn
    fdef.decorator_list = []
    before = ast.dump(tree)
    new_tree = _ControlFlowTransformer().visit(tree)
    ast.fix_missing_locations(new_tree)
    if ast.dump(new_tree) == before:
        return fn  # nothing convertible: keep the original object
    code = compile(new_tree, filename=f"<dy2static {fn.__name__}>",
                   mode="exec")
    glb = dict(fn.__globals__)
    # read-only closures survive as globals in the re-executed source
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                glb[name] = cell.cell_contents
            except ValueError:  # empty cell
                return fn
    glb["__jst_convert_ifelse"] = convert_ifelse
    glb["__jst_convert_while"] = convert_while_loop
    glb["__jst_undef"] = _UNDEF
    glb["__jst_and"] = convert_logical_and
    glb["__jst_not"] = convert_logical_not
    glb["__jst_no_jump"] = convert_no_jump
    glb["__jst_indexable"] = convert_indexable
    glb["__jst_len"] = convert_len
    glb["__jst_getitem"] = convert_getitem
    glb["__jst_range_len"] = convert_range_len
    exec(code, glb)
    out = glb[fn.__name__]
    out = functools.wraps(fn)(out)
    out.__wrapped_original__ = fn
    return out


def declarative(fn: Callable) -> Callable:
    """@declarative: convert control flow, then behave like the plain
    function — combine with paddle.jit.to_static / jax.jit for
    compilation."""
    return convert_to_static(fn)
