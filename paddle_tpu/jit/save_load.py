"""jit.save/load: AOT export of traced functions.

Reference analog: paddle.jit.save -> inference ProgramDesc + params
(python/paddle/fluid/dygraph/jit.py; consumed by AnalysisPredictor).
TPU-native: `jax.export` serializes the StableHLO of the traced function;
params ship as an .npz next to it. Loading returns a callable that runs
the compiled artifact — the serving path without Python model code.
"""
from __future__ import annotations

import json
import os
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, no_grad
from ..nn.layer import Layer
from .api import functional_call


def save(layer, path: str, input_spec: Optional[Sequence] = None):
    """Export `layer` (or a to_static-wrapped function) as
    {path}.stablehlo + {path}.pdiparams.npz + {path}.meta.json."""
    from jax import export as jexport

    if isinstance(layer, Layer):
        state = layer.state_dict()
        names = list(state.keys())
        vals = [t._data for t in state.values()]
        if input_spec is None:
            raise ValueError("jit.save(layer, ...) needs input_spec "
                             "(list of example Tensors or ShapeDtypeStructs)")
        specs = [_to_sds(s) for s in input_spec]

        def fn(state_vals, *inputs):
            out = functional_call(layer, dict(zip(names, state_vals)),
                                  *[Tensor(i) for i in inputs])
            return jax.tree_util.tree_map(
                lambda t: t._data if isinstance(t, Tensor) else t, out,
                is_leaf=lambda x: isinstance(x, Tensor))

        state_specs = [jax.ShapeDtypeStruct(v.shape, v.dtype) for v in vals]
        exported = jexport.export(jax.jit(fn))(state_specs, *specs)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path + ".stablehlo", "wb") as f:
            f.write(exported.serialize())
        np.savez(path + ".pdiparams.npz",
                 **{n: np.asarray(v) for n, v in zip(names, vals)})
        with open(path + ".meta.json", "w") as f:
            json.dump({"param_names": names,
                       "n_inputs": len(specs)}, f)
    else:
        raise TypeError("jit.save expects a Layer")


class LoadedFunction:
    def __init__(self, path: str):
        from jax import export as jexport
        with open(path + ".stablehlo", "rb") as f:
            self._exported = jexport.deserialize(f.read())
        with open(path + ".meta.json") as f:
            self._meta = json.load(f)
        npz = np.load(path + ".pdiparams.npz")
        self._state_vals = [jnp.asarray(npz[n])
                            for n in self._meta["param_names"]]

    def __call__(self, *inputs):
        raw = [i._data if isinstance(i, Tensor) else jnp.asarray(i)
               for i in inputs]
        out = self._exported.call(self._state_vals, *raw)
        return jax.tree_util.tree_map(
            lambda x: Tensor(x) if isinstance(x, jax.Array) else x, out)


def load(path: str) -> LoadedFunction:
    return LoadedFunction(path)


def _to_sds(s):
    if isinstance(s, jax.ShapeDtypeStruct):
        return s
    if isinstance(s, Tensor):
        return jax.ShapeDtypeStruct(tuple(s.shape), s.dtype)
    arr = jnp.asarray(s)
    return jax.ShapeDtypeStruct(arr.shape, arr.dtype)
