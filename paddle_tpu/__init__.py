"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's
capability surface, built on jax/XLA/Pallas/pjit. See SURVEY.md for the
blueprint and per-component reference citations."""
from __future__ import annotations

__version__ = "0.1.0"

from .core import dtype as _dtype_mod
from .core.dtype import (bfloat16, bool_, complex64, complex128,  # noqa: F401
                         float16, float32, float64, int8, int16, int32,
                         int64, uint8)
from .core.device import (device_count, get_device,  # noqa: F401
                          is_compiled_with_tpu, set_device, synchronize)
from .core.flags import get_flags, set_flags  # noqa: F401
from .core.random import (get_state as get_rng_state,  # noqa: F401
                          seed, set_state as set_rng_state)
from .core.tensor import (Parameter, Tensor, enable_grad,  # noqa: F401
                          is_grad_enabled, no_grad, set_grad_enabled,
                          to_tensor)

# ops namespaces -----------------------------------------------------------
from . import ops  # noqa: F401  (installs Tensor methods)
from .ops.creation import (arange, assign, bernoulli, diag,  # noqa: F401
                           diagflat, empty, empty_like, eye, full, full_like,
                           linspace, logspace, meshgrid, multinomial, normal,
                           ones, ones_like, rand, randint, randn, randperm,
                           tril, tril_indices, triu, triu_indices, uniform,
                           zeros, zeros_like)
from .ops.linalg import (bmm, dot, einsum, matmul, mm, mv, t)  # noqa: F401
from .ops.manipulation import (broadcast_to, chunk, concat, expand,  # noqa: F401
                               expand_as, flatten, flip, gather, gather_nd,
                               index_add, index_fill, index_select,
                               masked_fill, masked_select, moveaxis,
                               nonzero, numel, one_hot, put_along_axis,
                               repeat_interleave, reshape, roll,
                               scatter, scatter_nd, scatter_nd_add, split,
                               squeeze, stack, take_along_axis, tile,
                               topk, transpose, unbind, unique, unsqueeze,
                               where)
from .ops.manipulation import (bucketize, diff,  # noqa: F401
                               index_sample, searchsorted, take)
from .ops.math import (addmm, cummax, cummin, diagonal,  # noqa: F401
                       frac, gcd, heaviside, hypot, inner, kron, lcm,
                       lerp, logaddexp, logcumsumexp, nanmean,
                       nanmedian, nansum, outer, trace, vander)
from .ops.math import (abs, add, all, allclose, any, argmax,  # noqa: F401
                       argmin, cast, ceil, clip, cos, cumprod, cumsum,
                       divide, equal, equal_all, exp, floor, floor_divide,
                       isfinite, isinf, isnan, log, logical_and, logical_not,
                       logical_or, logsumexp, max, maximum, mean, median,
                       min, minimum, multiply, pow, prod, remainder, round,
                       rsqrt, scale, sign, sin, sqrt, square, std, subtract,
                       sum, tanh, trunc, var)

# round-2 export-parity wave (VERDICT Missing #3): every op the
# reference exports at paddle.* resolves here too
from .ops.math import (acos, acosh, add_n, amax, amin, angle,  # noqa: F401
                       asin, asinh, atan, atan2, atanh, bitwise_and,
                       bitwise_not, bitwise_or, bitwise_xor, clone, conj,
                       cosh, count_nonzero, deg2rad, digamma, erf, erfinv,
                       expm1, fmax, fmin, frexp, greater_equal,
                       greater_than, imag, increment, isclose, kthvalue,
                       less_equal, less_than, lgamma, log10, log1p, log2,
                       logical_xor, logit, mod, mode, multiplex,
                       nanquantile, neg, not_equal, quantile, rad2deg,
                       real, reciprocal, renorm, sgn, sinh, stanh, tan)
from .ops.math import mod as floor_mod  # noqa: F401
from .ops.manipulation import (diag_embed, fill_diagonal,  # noqa: F401
                               fill_diagonal_tensor)
from .ops.manipulation import (argsort, as_complex, as_real,  # noqa: F401
                               broadcast_shape, broadcast_tensors,
                               complex, crop, index_add_, reshape_,
                               reverse, rot90, scatter_, shape,
                               shard_index, slice, sort, squeeze_,
                               strided_slice, tanh_, unique_consecutive,
                               unsqueeze_, unstack, vsplit)
from .ops.linalg import (bincount, cross, dist, histogram,  # noqa: F401
                         tensordot)
from .ops.creation import (create_parameter, poisson,  # noqa: F401
                           randint_like, standard_normal)
from .framework import (CPUPlace, CUDAPinnedPlace, CUDAPlace,  # noqa: F401
                        DataParallel, LazyGuard, NPUPlace, batch,
                        check_shape, disable_signal_handler, finfo,
                        get_cuda_rng_state, iinfo, is_complex, is_empty,
                        is_floating_point, is_integer, is_tensor, rank,
                        set_cuda_rng_state, set_printoptions, tolist)
from .core.dtype import bool_ as bool  # noqa: F401,A001

get_default_dtype = _dtype_mod.get_default_dtype
set_default_dtype = _dtype_mod.set_default_dtype
dtype = _dtype_mod.convert_dtype  # paddle.dtype('float32') parity

# subsystems ---------------------------------------------------------------
from . import amp  # noqa: F401,E402
from . import autograd  # noqa: F401,E402
from . import device  # noqa: F401,E402
from . import generation  # noqa: F401,E402
from . import io  # noqa: F401,E402
from . import jit  # noqa: F401,E402
from . import metric  # noqa: F401,E402
from . import nn  # noqa: F401,E402
from . import optimizer  # noqa: F401,E402
from . import static  # noqa: F401,E402
from .framework_io import load, save  # noqa: F401,E402
from .nn import ParamAttr  # noqa: F401,E402


# -- mode toggles (paddle.enable_static/disable_static; TPU build is
# dygraph-first — static building happens inside static.program_guard,
# so these track intent for API parity and in_dynamic_mode()) ----------
_static_mode = False


def enable_static():
    global _static_mode
    _static_mode = True


def disable_static():
    global _static_mode
    _static_mode = False


def in_dynamic_mode() -> bool:
    from .core import static_hook
    return not _static_mode and not static_hook.enabled


def get_cudnn_version():
    return None  # no cuDNN in the TPU stack (parity shim)
from .jit.api import grad, value_and_grad  # noqa: F401,E402
from .nn.functional.common import (pixel_shuffle,  # noqa: F401,E402
                                   pixel_unshuffle)

# `paddle.distributed`-style access is heavy: import lazily ---------------
_LAZY = {"audio", "callbacks", "compat", "dataset", "distributed",
         "distribution", "fft",
         "geometric", "hub", "linalg", "reader", "regularizer",
         "sysconfig", "version",
         "models", "vision", "kernels", "hapi", "onnx", "profiler",
         "incubate", "inference", "quantization", "serving", "signal",
         "sparse", "static", "text", "utils"}


_LAZY_ATTRS = {
    "Model": ("paddle_tpu.hapi.model", "Model"),
    "summary": ("paddle_tpu.hapi.model_summary", "summary"),
    "flops": ("paddle_tpu.hapi.model_summary", "flops"),
}


def __getattr__(name):
    import importlib
    if name in _LAZY:
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    if name in _LAZY_ATTRS:
        mod_name, attr = _LAZY_ATTRS[name]
        val = getattr(importlib.import_module(mod_name), attr)
        globals()[name] = val
        return val
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")
