"""Segment reductions (≈ python/paddle/geometric/math.py;
phi/kernels/segment_pool_kernel.h)."""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops.op_registry import op

__all__ = ["segment_sum", "segment_mean", "segment_min", "segment_max"]


def _nseg(segment_ids, num_segments: Optional[int]):
    if num_segments is not None:
        return int(num_segments)
    ids = segment_ids._data if isinstance(segment_ids, Tensor) \
        else jnp.asarray(segment_ids)
    # eager path: concrete max is fine; under jit pass num_segments
    return int(ids.max()) + 1 if ids.size else 0


def _fill_empty(out, ids, num_segments):
    """Paddle fills empty segments with 0 (dtype-preserving); jax's
    segment_min/max leave +/-inf (float) or iinfo extremes (int)."""
    counts = jax.ops.segment_sum(
        jnp.ones(ids.shape[0], dtype=jnp.int32), ids,
        num_segments=num_segments)
    mask = (counts > 0).reshape((num_segments,) + (1,) * (out.ndim - 1))
    return jnp.where(mask, out, jnp.zeros((), dtype=out.dtype))


@op("segment_sum")
def _segment_sum_impl(data, segment_ids, num_segments):
    return jax.ops.segment_sum(data, segment_ids.astype(jnp.int32),
                               num_segments=num_segments)


@op("segment_mean")
def _segment_mean_impl(data, segment_ids, num_segments):
    ids = segment_ids.astype(jnp.int32)
    total = jax.ops.segment_sum(data, ids, num_segments=num_segments)
    # counts over a 1-D ones vector, not a full ones_like(data) scatter
    count = jax.ops.segment_sum(
        jnp.ones(ids.shape[0], dtype=data.dtype), ids,
        num_segments=num_segments)
    count = count.reshape((num_segments,) + (1,) * (data.ndim - 1))
    return total / jnp.maximum(count, 1)


@op("segment_min")
def _segment_min_impl(data, segment_ids, num_segments):
    ids = segment_ids.astype(jnp.int32)
    out = jax.ops.segment_min(data, ids, num_segments=num_segments)
    return _fill_empty(out, ids, num_segments)


@op("segment_max")
def _segment_max_impl(data, segment_ids, num_segments):
    ids = segment_ids.astype(jnp.int32)
    out = jax.ops.segment_max(data, ids, num_segments=num_segments)
    return _fill_empty(out, ids, num_segments)


def segment_sum(data, segment_ids, num_segments: Optional[int] = None):
    return _segment_sum_impl(data, segment_ids,
                             num_segments=_nseg(segment_ids,
                                                num_segments))


def segment_mean(data, segment_ids, num_segments: Optional[int] = None):
    return _segment_mean_impl(data, segment_ids,
                              num_segments=_nseg(segment_ids,
                                                 num_segments))


def segment_min(data, segment_ids, num_segments: Optional[int] = None):
    return _segment_min_impl(data, segment_ids,
                             num_segments=_nseg(segment_ids,
                                                num_segments))


def segment_max(data, segment_ids, num_segments: Optional[int] = None):
    return _segment_max_impl(data, segment_ids,
                             num_segments=_nseg(segment_ids,
                                                num_segments))
