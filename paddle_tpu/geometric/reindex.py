"""Graph reindexing (≈ python/paddle/geometric/reindex.py:24
reindex_graph, :136 reindex_heter_graph, over the phi graph_reindex
kernel).

Host-side numpy by design: reindexing happens in the GNN input
pipeline between neighbor sampling and the device step — it is
integer bookkeeping over dynamic-size id lists, not accelerator math
(the reference's GPU hashtable variant exists to keep the sampler
resident on-device; on TPU the sampler feeds the infeed like every
other data-loading stage)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["reindex_graph", "reindex_heter_graph"]


def _raw_1d(t, name, dtype=None):
    a = np.asarray(t.numpy() if isinstance(t, Tensor) else t)
    a = a.reshape(-1)
    if dtype is not None:
        a = a.astype(dtype)
    return a


def _reindex(x, neighbor_lists):
    """Shared body: build out_nodes (x first, then unseen neighbors in
    first-appearance order across all graphs) and remap each list.
    Fully vectorized — million-edge batches must not be bottlenecked
    by a Python per-element loop in the input pipeline."""
    x = x.astype(np.int64)
    all_ids = np.concatenate([x] + [nb.astype(np.int64)
                                    for nb in neighbor_lists])
    uniq, first = np.unique(all_ids, return_index=True)  # uniq sorted
    order = np.argsort(first, kind="stable")  # first-appearance order
    out_nodes = uniq[order]
    new_index = np.empty(len(uniq), np.int64)
    new_index[order] = np.arange(len(uniq))
    remapped = [new_index[np.searchsorted(uniq, nb.astype(np.int64))]
                for nb in neighbor_lists]
    return remapped, out_nodes


def reindex_graph(x, neighbors, count, value_buffer=None,
                  index_buffer=None, name=None):
    """Reindex sampled neighbors to a dense [0, n) id space: returns
    (reindex_src, reindex_dst, out_nodes) with the input nodes first in
    out_nodes. Reference python/paddle/geometric/reindex.py:24; the
    value/index hashtable buffers are a GPU-kernel affordance and are
    accepted-and-ignored here."""
    xa = _raw_1d(x, "x")
    nb = _raw_1d(neighbors, "neighbors")
    ct = _raw_1d(count, "count", np.int64)
    if ct.sum() != len(nb):
        raise ValueError(
            f"count sums to {int(ct.sum())} but neighbors has "
            f"{len(nb)} entries")
    (src,), out_nodes = _reindex(xa, [nb])
    dst = np.repeat(np.arange(len(xa), dtype=np.int64), ct)
    dt = xa.dtype
    return (Tensor(src.astype(dt)), Tensor(dst.astype(dt)),
            Tensor(out_nodes.astype(dt)))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous variant: neighbors/count are per-edge-type lists
    sharing ONE id space; outputs concatenate the per-type edge lists
    (reference python/paddle/geometric/reindex.py:136)."""
    xa = _raw_1d(x, "x")
    nbs = [_raw_1d(n, "neighbors") for n in neighbors]
    cts = [_raw_1d(c, "count", np.int64) for c in count]
    for nb, ct in zip(nbs, cts):
        if ct.sum() != len(nb):
            raise ValueError("count/neighbors length mismatch")
    remapped, out_nodes = _reindex(xa, nbs)
    srcs = np.concatenate(remapped) if remapped else \
        np.zeros(0, np.int64)
    dsts = np.concatenate([
        np.repeat(np.arange(len(xa), dtype=np.int64), ct)
        for ct in cts]) if cts else np.zeros(0, np.int64)
    dt = xa.dtype
    return (Tensor(srcs.astype(dt)), Tensor(dsts.astype(dt)),
            Tensor(out_nodes.astype(dt)))
