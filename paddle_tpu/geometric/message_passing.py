"""Graph message passing (≈ python/paddle/geometric/message_passing/
send_recv.py send_u_recv/send_ue_recv over the graph_send_recv ops,
paddle/phi/kernels/graph_send_recv_kernel.h)."""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops.op_registry import op
from .math import (_segment_max_impl, _segment_mean_impl,
                   _segment_min_impl, _segment_sum_impl)

__all__ = ["send_u_recv", "send_ue_recv", "send_uv"]

_REDUCERS = {"sum": _segment_sum_impl.raw, "mean": _segment_mean_impl.raw,
             "max": _segment_max_impl.raw, "min": _segment_min_impl.raw}


def _segment_reduce(msgs, dst, pool_type, num_nodes):
    # single source of truth: the registered segment impls from math.py
    return _REDUCERS[pool_type](msgs, dst, num_nodes)


@op("graph_send_u_recv")
def _send_u_recv_impl(x, src, dst, pool_type, out_size):
    msgs = jnp.take(x, src.astype(jnp.int32), axis=0)
    return _segment_reduce(msgs, dst, pool_type, out_size)


@op("graph_send_ue_recv")
def _send_ue_recv_impl(x, e, src, dst, message_op, pool_type, out_size):
    msgs = jnp.take(x, src.astype(jnp.int32), axis=0)
    if message_op == "add":
        msgs = msgs + e
    elif message_op == "mul":
        msgs = msgs * e
    else:
        raise ValueError(f"unknown message_op {message_op!r}")
    return _segment_reduce(msgs, dst, pool_type, out_size)


def _out_size(x, out_size):
    if out_size is not None:
        return int(out_size)
    # default: number of nodes in x (reference uses max(dst)+1 or x rows)
    return int(x.shape[0])


def send_u_recv(x, src_index, dst_index, reduce_op: str = "sum",
                out_size: Optional[int] = None):
    """Gather x[src], reduce onto dst (graph aggregation)."""
    if reduce_op not in _REDUCERS:
        raise ValueError(
            f"reduce_op must be one of {sorted(_REDUCERS)}")
    return _send_u_recv_impl(x, src_index, dst_index,
                             pool_type=reduce_op,
                             out_size=_out_size(x, out_size))


@op("graph_send_uv")
def _send_uv_impl(x, y, src, dst, message_op):
    xs = jnp.take(x, src.astype(jnp.int32), axis=0)
    yd = jnp.take(y, dst.astype(jnp.int32), axis=0)
    if message_op == "add":
        return xs + yd
    if message_op == "sub":
        return xs - yd
    if message_op == "mul":
        return xs * yd
    if message_op == "div":
        return xs / yd
    raise ValueError(f"unknown message_op {message_op!r}")


def send_uv(x, y, src_index, dst_index, message_op: str = "add",
            name=None):
    """Per-edge features from both endpoints: op(x[src], y[dst]) — no
    intermediate [num_edges, ...] gather materialized by the caller
    (reference python/paddle/geometric/message_passing/send_recv.py:387,
    graph_send_uv kernel)."""
    if message_op not in ("add", "sub", "mul", "div"):
        raise ValueError("message_op must be add/sub/mul/div")
    return _send_uv_impl(x, y, src_index, dst_index,
                         message_op=message_op)


def send_ue_recv(x, y, src_index, dst_index, message_op: str = "add",
                 reduce_op: str = "sum",
                 out_size: Optional[int] = None):
    """Like send_u_recv but combines edge features y into the message."""
    if reduce_op not in _REDUCERS:
        raise ValueError(
            f"reduce_op must be one of {sorted(_REDUCERS)}")
    return _send_ue_recv_impl(x, y, src_index, dst_index,
                              message_op=message_op,
                              pool_type=reduce_op,
                              out_size=_out_size(x, out_size))
