"""paddle.geometric analog — graph/message-passing ops.

Reference: python/paddle/geometric/ (segment_sum/mean/max/min in
math.py over phi segment kernels; send_u_recv / send_ue_recv message
passing in message_passing/send_recv.py over graph_send_recv ops).
TPU-native: jax.ops.segment_* — XLA lowers them to sorted scatter
reductions, which is the efficient TPU pattern for GNN aggregation.
"""
from .math import (segment_max, segment_mean, segment_min,  # noqa: F401
                   segment_sum)
from .message_passing import send_u_recv, send_ue_recv, send_uv  # noqa: F401
from .reindex import reindex_graph, reindex_heter_graph  # noqa: F401
from .sampling import sample_neighbors  # noqa: F401
