"""Graph neighbor sampling (≈ python/paddle/geometric/sampling/
neighbors.py:23 sample_neighbors, phi graph_sample_neighbors kernel).

Host-side numpy by design: sampling is input-pipeline work — a
random, dynamic-size selection per node that feeds the device step
(the reference's fisher-yates GPU path exists to keep sampling
on-device next to a GPU trainer; a TPU trainer streams samples through
the infeed like any other data loader stage)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from .reindex import _raw_1d

__all__ = ["sample_neighbors"]


def sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                     eids=None, return_eids=False, perm_buffer=None,
                     name=None):
    """Sample up to `sample_size` neighbors for each input node from a
    CSC graph (row, colptr). Returns (out_neighbors, out_count) and,
    with return_eids=True, the sampled edges' ids. perm_buffer is the
    reference's GPU fisher-yates affordance — accepted and ignored."""
    r = _raw_1d(row, "row")
    cp = _raw_1d(colptr, "colptr")
    nodes = _raw_1d(input_nodes, "input_nodes")
    if return_eids and eids is None:
        raise ValueError("return_eids=True requires eids")
    ea = None
    if eids is not None:
        ea = _raw_1d(eids, "eids")
        if len(ea) != len(r):
            raise ValueError("eids must have one entry per edge")
    # fresh draw per call: fold a split of the global PRNG key into a
    # host seed, so repeated calls sample fresh neighbors while
    # paddle.seed() still makes the SEQUENCE reproducible (a fixed
    # RandomState(get_seed()) would freeze every minibatch's sample)
    import jax as _jax
    from ..core import random as random_mod
    key = random_mod.next_key()
    rng = np.random.RandomState(
        int(_jax.random.randint(key, (), 0, np.iinfo(np.int32).max)))
    out_nb, out_ct, out_eid = [], [], []
    n_nodes = len(cp) - 1
    for n in nodes:
        n = int(n)
        if not 0 <= n < n_nodes:
            raise ValueError(f"node {n} outside [0, {n_nodes})")
        lo, hi = int(cp[n]), int(cp[n + 1])
        deg = hi - lo
        if sample_size < 0 or deg <= sample_size:
            pick = np.arange(lo, hi)
        else:
            pick = lo + rng.choice(deg, sample_size, replace=False)
        out_nb.append(r[pick])
        out_ct.append(len(pick))
        if return_eids:
            out_eid.append(ea[pick])
    nb = np.concatenate(out_nb) if out_nb else np.zeros(0, r.dtype)
    ct = np.asarray(out_ct, np.int32)
    if return_eids:
        ei = np.concatenate(out_eid) if out_eid else np.zeros(0, r.dtype)
        return Tensor(nb), Tensor(ct), Tensor(ei)
    return Tensor(nb), Tensor(ct)
