"""Metrics (≈ paddle.metric: python/paddle/metric/metrics.py). Local
accumulation on host; distributed reduction helper in
distributed/fleet/metrics (allreduce of counters, like
fleet/metrics/metric.py)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def _np(x):
    return np.asarray(x.data if isinstance(x, Tensor) else x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__.lower()


class Accuracy(Metric):
    def __init__(self, topk=(1,), name="acc"):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self._name = name
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label):
        pred = _np(pred)
        label = _np(label)
        maxk = max(self.topk)
        idx = np.argsort(-pred, axis=-1)[..., :maxk]
        if label.ndim == pred.ndim:
            label = label.squeeze(-1)
        correct = (idx == label[..., None])
        return correct

    def update(self, correct):
        correct = _np(correct)
        n = correct.shape[0]
        for i, k in enumerate(self.topk):
            self.total[i] += correct[..., :k].sum()
            self.count[i] += n
        accs = self.total / np.maximum(self.count, 1)
        return accs[0] if len(self.topk) == 1 else accs

    def accumulate(self):
        accs = self.total / np.maximum(self.count, 1)
        return float(accs[0]) if len(self.topk) == 1 else accs.tolist()

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        labels = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        labels = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, num_thresholds=4095, name="auc"):
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels).reshape(-1)
        pos_prob = preds[:, 1] if preds.ndim == 2 else preds.reshape(-1)
        bins = (pos_prob * self.num_thresholds).astype(np.int64)
        bins = np.clip(bins, 0, self.num_thresholds)
        for b, l in zip(bins, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoid over thresholds descending
        pos_cum = np.cumsum(self._stat_pos[::-1])
        neg_cum = np.cumsum(self._stat_neg[::-1])
        tpr = pos_cum / tot_pos
        fpr = neg_cum / tot_neg
        return float(np.trapz(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1):
    """Functional accuracy (paddle.metric.accuracy)."""
    pred = _np(input)
    lbl = _np(label)
    idx = np.argsort(-pred, axis=-1)[..., :k]
    if lbl.ndim == pred.ndim:
        lbl = lbl.squeeze(-1)
    correct = (idx == lbl[..., None]).any(-1)
    return Tensor(np.asarray(correct.mean(), np.float32))
