"""Functional op namespace; also installs method-style aliases on Tensor
(the reference generates Tensor methods in pybind
`eager_method.cc`/`eager_op_function_generator`; here it's a loop)."""
from __future__ import annotations

from . import creation, linalg, manipulation, math
from . import validators  # registers InferMeta-style checks (enforce)
from .op_registry import OPS, get_op, op
from ..core.tensor import Tensor

# ---- method aliases on Tensor ------------------------------------------

_METHOD_SOURCES = {
    math: [
        "add", "subtract", "multiply", "divide", "floor_divide", "remainder",
        "pow", "maximum", "minimum", "abs", "sqrt", "rsqrt", "square", "exp",
        "log", "log2", "log10", "log1p", "sin", "cos", "tan", "tanh", "floor",
        "ceil", "round", "sign", "reciprocal", "erf", "clip", "scale", "cast",
        "cumsum", "cumprod", "sum", "mean", "max", "min", "prod", "std",
        "var", "logsumexp", "all", "any", "argmax", "argmin", "isnan",
        "isinf", "isfinite", "allclose", "equal_all", "trace", "lerp",
        "nan_to_num", "count_nonzero", "median", "clone", "equal",
        "not_equal", "less_than", "less_equal", "greater_than",
        "greater_equal", "logical_and", "logical_or", "logical_not",
    ],
    manipulation: [
        "reshape", "flatten", "squeeze", "unsqueeze", "split", "chunk",
        "transpose", "tile", "expand", "expand_as", "broadcast_to", "flip",
        "roll", "gather", "gather_nd", "scatter", "scatter_nd_add",
        "index_select", "masked_select", "masked_fill", "topk", "sort",
        "argsort", "unbind", "numel", "unique", "repeat_interleave",
        "take_along_axis", "put_along_axis", "moveaxis", "nonzero", "pad",
    ],
    linalg: [
        "matmul", "mm", "bmm", "dot", "mv", "norm", "dist", "cholesky",
        "inverse", "det", "matrix_power", "pinv", "solve", "qr", "svd", "t",
        "trace" if False else "cross",
    ],
    creation: ["tril", "triu", "zeros_like", "ones_like", "full_like"],
}

for module, names in _METHOD_SOURCES.items():
    for name in names:
        fn = getattr(module, name, None)
        if fn is not None and not hasattr(Tensor, name):
            setattr(Tensor, name, fn)


def _astype(self, dtype):
    return math.cast(self, dtype)


Tensor.astype = _astype
Tensor.cast = _astype
