"""Functional op namespace; also installs method-style aliases on Tensor
(the reference generates Tensor methods in pybind
`eager_method.cc`/`eager_op_function_generator`; here it's a loop)."""
from __future__ import annotations

from . import creation, linalg, manipulation, math
from . import validators  # registers InferMeta-style checks (enforce)
from .op_registry import OPS, get_op, op
from ..core.tensor import Tensor

# ---- method aliases on Tensor ------------------------------------------

_METHOD_SOURCES = {
    math: [
        "add", "subtract", "multiply", "divide", "floor_divide", "remainder",
        "pow", "maximum", "minimum", "abs", "sqrt", "rsqrt", "square", "exp",
        "log", "log2", "log10", "log1p", "sin", "cos", "tan", "tanh", "floor",
        "ceil", "round", "sign", "reciprocal", "erf", "clip", "scale", "cast",
        "cumsum", "cumprod", "sum", "mean", "max", "min", "prod", "std",
        "var", "logsumexp", "all", "any", "argmax", "argmin", "isnan",
        "isinf", "isfinite", "allclose", "equal_all", "trace", "lerp",
        "nan_to_num", "count_nonzero", "median", "clone", "equal",
        "not_equal", "less_than", "less_equal", "greater_than",
        "greater_equal", "logical_and", "logical_or", "logical_not",
    ],
    manipulation: [
        "reshape", "flatten", "squeeze", "unsqueeze", "split", "chunk",
        "transpose", "tile", "expand", "expand_as", "broadcast_to", "flip",
        "roll", "gather", "gather_nd", "scatter", "scatter_nd_add",
        "index_select", "masked_select", "masked_fill", "topk", "sort",
        "argsort", "unbind", "numel", "unique", "repeat_interleave",
        "take_along_axis", "put_along_axis", "moveaxis", "nonzero", "pad",
    ],
    linalg: [
        "matmul", "mm", "bmm", "dot", "mv", "norm", "dist", "cholesky",
        "inverse", "det", "matrix_power", "pinv", "solve", "qr", "svd", "t",
        "trace" if False else "cross",
    ],
    creation: ["tril", "triu", "zeros_like", "ones_like", "full_like"],
}

# round-2 completion: install the REST of the reference's
# tensor_method_func surface (python/paddle/tensor/__init__.py) —
# everything already implemented as a function becomes a method
_METHOD_SOURCES[math] += [
    "acos", "acosh", "asin", "asinh", "atan", "atanh", "sinh", "cosh",
    "atan2", "add_n", "addmm", "amax", "amin", "angle", "conj", "real",
    "imag", "deg2rad", "rad2deg", "digamma", "lgamma", "erfinv",
    "expm1", "fmax", "fmin", "frac", "frexp", "gcd", "lcm", "heaviside",
    "increment", "inner", "outer", "isclose", "kron", "kthvalue",
    "logit", "logcumsumexp", "logical_xor", "mod", "mode", "multiplex",
    "nanmean", "nanmedian", "nanquantile", "nansum", "neg", "quantile",
    "sgn", "stanh", "trunc", "diagonal", "cummax", "cummin", "hypot",
    "vander", "renorm",
]
_METHOD_SOURCES[manipulation] += [
    "as_complex", "as_real", "broadcast_shape", "broadcast_tensors",
    "bucketize", "concat", "diff", "index_add", "index_sample",
    "index_fill", "reverse", "rot90", "scatter_nd", "shard_index",
    "slice", "stack", "strided_slice", "take", "unique_consecutive",
    "unstack", "vsplit", "swapaxes", "searchsorted", "where", "one_hot",
    # module-level inplace variants double as methods (single
    # implementation: manipulation.py's _adopt-based functions)
    "reshape_", "squeeze_", "unsqueeze_", "scatter_", "index_add_",
    "tanh_",
]
_METHOD_SOURCES[linalg] += [
    "bincount", "histogram", "cond", "corrcoef", "cov", "eig",
    "eigvals", "eigvalsh", "cholesky_solve", "triangular_solve",
    "lstsq", "lu", "lu_unpack", "multi_dot", "tensordot",
]
_METHOD_SOURCES[math] += [
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
]

for module, names in _METHOD_SOURCES.items():
    for name in names:
        fn = getattr(module, name, None)
        if fn is not None and not hasattr(Tensor, name):
            setattr(Tensor, name, fn)

# framework predicates as methods (reference registers them too)
from .. import framework as _framework  # noqa: E402

for _n in ("is_complex", "is_empty", "is_floating_point", "is_integer",
           "is_tensor", "rank"):
    if not hasattr(Tensor, _n):
        setattr(Tensor, _n, getattr(_framework, _n))


def _astype(self, dtype):
    return math.cast(self, dtype)  # guarded: int/bool targets detach


Tensor.astype = _astype
Tensor.cast = _astype


# ---- trailing-underscore inplace variants -------------------------------
# reference: inplace-version APIs (python/paddle/tensor/*_ with
# monkey_patch); here: run the out-of-place op, adopt value+grad record
# via Tensor._adopt (snapshot-safe)

def _make_inplace(base_name):
    def inplace(self, *args, **kwargs):
        out = getattr(self, base_name)(*args, **kwargs)
        self._adopt(out)
        return self

    inplace.__name__ = base_name + "_"
    return inplace


# generated only where no module-level _ function exists (those are
# installed as methods directly above)
_INPLACE_BASES = [
    "add", "subtract", "ceil", "clip", "exp", "floor", "erfinv",
    "lerp", "reciprocal", "remainder", "round", "rsqrt", "scale",
    "sqrt", "flatten", "put_along_axis",
]
for _b in _INPLACE_BASES:
    if hasattr(Tensor, _b) and not hasattr(Tensor, _b + "_"):
        setattr(Tensor, _b + "_", _make_inplace(_b))


def _uniform_(self, min=-1.0, max=1.0, seed=0):
    """In-place uniform refill (reference Tensor.uniform_)."""
    new = creation.uniform(self.shape, dtype=str(self.dtype),
                           min=min, max=max, seed=seed)
    self._adopt(new.detach())
    return self


def _exponential_(self, lam=1.0):
    """In-place exponential refill: -log(U)/lam."""
    import jax.numpy as jnp
    u = creation.uniform(self.shape, dtype=str(self.dtype),
                         min=1e-7, max=1.0)
    self._adopt(Tensor(-jnp.log(u._data) / lam))
    return self


Tensor.uniform_ = _uniform_
Tensor.exponential_ = _exponential_
Tensor.floor_mod = Tensor.remainder  # reference alias
