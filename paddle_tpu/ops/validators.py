"""InferMeta-style validators for the most-used ops.

Reference: paddle/phi/infermeta/{unary,binary,ternary,multiary}.cc —
every kernel validates operand shapes/dtypes and raises
PADDLE_ENFORCE_* with expected-vs-got messages. Here validators are
registered per op name (core/enforce.py) and run at the dispatch
boundary before the jax impl, so users get an op-named shape message
instead of a raw XLA traceback. Checks read only static shape/dtype —
they are free under tracing (run once at trace time).
"""
from __future__ import annotations

from ..core.enforce import enforce, infer_check

__all__ = []


def _shape(x):
    return tuple(getattr(x, "shape", ()))


def _ndim(x):
    return len(_shape(x))


def _broadcastable(a, b) -> bool:
    for x, y in zip(reversed(a), reversed(b)):
        if x != 1 and y != 1 and x != y:
            return False
    return True


def _check_axis(op, axis, ndim, allow_scalar_like=True):
    lo = -ndim if ndim else -1
    hi = max(ndim - 1, 0)
    enforce(lo <= axis <= hi, op,
            f"axis must be in [{lo}, {hi}] for a {ndim}-d operand, "
            f"got {axis}")


def _binary_broadcast(op):
    @infer_check(op)
    def check(x, y, *a, **k):
        sx, sy = _shape(x), _shape(y)
        enforce(_broadcastable(sx, sy), op,
                f"operands could not be broadcast together: "
                f"x{list(sx)} vs y{list(sy)}")
    return check


for _name in ("add", "subtract", "multiply", "divide", "maximum",
              "minimum", "pow", "remainder", "floor_divide", "atan2",
              "fmax", "fmin", "heaviside", "logaddexp", "hypot"):
    _binary_broadcast(_name)


@infer_check("matmul")
def _matmul(x, y, transpose_x=False, transpose_y=False, *a, **k):
    sx, sy = _shape(x), _shape(y)
    enforce(len(sx) >= 1 and len(sy) >= 1, "matmul",
            f"operands need ndim >= 1, got x{list(sx)} y{list(sy)}")
    if len(sx) >= 2 and len(sy) >= 2:
        kx = sx[-1] if not transpose_x else sx[-2]
        ky = sy[-2] if not transpose_y else sy[-1]
        enforce(kx == ky, "matmul",
                f"inner dims must match: x{list(sx)}"
                f"{'^T' if transpose_x else ''} @ y{list(sy)}"
                f"{'^T' if transpose_y else ''} -> {kx} != {ky}")
        bx, by = sx[:-2], sy[:-2]
        enforce(_broadcastable(bx, by), "matmul",
                f"batch dims not broadcastable: {list(bx)} vs {list(by)}")


@infer_check("bmm")
def _bmm(x, y, *a, **k):
    sx, sy = _shape(x), _shape(y)
    enforce(len(sx) == 3 and len(sy) == 3, "bmm",
            f"bmm needs two 3-d operands, got x{list(sx)} y{list(sy)}")
    enforce(sx[0] == sy[0], "bmm",
            f"batch sizes differ: {sx[0]} vs {sy[0]}")
    enforce(sx[2] == sy[1], "bmm",
            f"inner dims must match: {sx[2]} != {sy[1]}")


@infer_check("mv")
def _mv(x, vec, *a, **k):
    sx, sv = _shape(x), _shape(vec)
    enforce(len(sx) == 2 and len(sv) == 1, "mv",
            f"mv needs (matrix, vector), got x{list(sx)} vec{list(sv)}")
    enforce(sx[1] == sv[0], "mv",
            f"matrix cols {sx[1]} != vector size {sv[0]}")


@infer_check("concat")
def _concat(xs, axis=0, *a, **k):
    if not isinstance(xs, (list, tuple)) or not xs:
        return
    nd = _ndim(xs[0])
    _check_axis("concat", int(axis), nd)
    ax = int(axis) % max(nd, 1)
    base = list(_shape(xs[0]))
    for i, t in enumerate(xs[1:], 1):
        s = list(_shape(t))
        enforce(len(s) == nd, "concat",
                f"input {i} has rank {len(s)}, expected {nd}")
        ok = all(s[d] == base[d] for d in range(nd) if d != ax)
        enforce(ok, "concat",
                f"input {i} shape {s} mismatches input 0 shape {base} "
                f"outside concat axis {ax}")


@infer_check("stack")
def _stack(xs, axis=0, *a, **k):
    if not isinstance(xs, (list, tuple)) or not xs:
        return
    base = _shape(xs[0])
    for i, t in enumerate(xs[1:], 1):
        enforce(_shape(t) == base, "stack",
                f"input {i} shape {list(_shape(t))} != input 0 shape "
                f"{list(base)} (stack needs identical shapes)")


@infer_check("reshape")
def _reshape(x, shape, *a, **k):
    import numpy as np
    tgt = [int(s) for s in (shape.tolist() if hasattr(shape, "tolist")
                            else shape)]
    enforce(tgt.count(-1) <= 1, "reshape",
            f"at most one -1 allowed in target shape, got {tgt}")
    n = int(np.prod(_shape(x))) if _shape(x) else 1
    fixed = int(np.prod([s for s in tgt if s != -1])) if tgt else 1
    if -1 in tgt:
        enforce(fixed != 0 and n % fixed == 0, "reshape",
                f"cannot infer -1: {n} elements not divisible by "
                f"{fixed} (shape {list(_shape(x))} -> {tgt})")
    else:
        enforce(fixed == n, "reshape",
                f"element count mismatch: {list(_shape(x))} has {n} "
                f"elements, target {tgt} has {fixed}")


@infer_check("softmax")
def _softmax(x, axis=-1, *a, **k):
    _check_axis("softmax", int(axis), max(_ndim(x), 1))


@infer_check("log_softmax")
def _log_softmax(x, axis=-1, *a, **k):
    _check_axis("log_softmax", int(axis), max(_ndim(x), 1))


@infer_check("gather")
def _gather(x, index, axis=0, *a, **k):
    _check_axis("gather", int(axis), max(_ndim(x), 1))
    enforce(_ndim(index) <= 2, "gather",
            f"index must be 0/1/2-d, got {_ndim(index)}-d")


@infer_check("index_select")
def _index_select(x, index, axis=0, *a, **k):
    _check_axis("index_select", int(axis), max(_ndim(x), 1))


@infer_check("take_along_axis")
def _take_along_axis(arr, indices, axis, *a, **k):
    _check_axis("take_along_axis", int(axis), max(_ndim(arr), 1))
    enforce(_ndim(indices) == _ndim(arr), "take_along_axis",
            f"indices rank {_ndim(indices)} must equal array rank "
            f"{_ndim(arr)}")


@infer_check("one_hot")
def _one_hot(x, num_classes, *a, **k):
    enforce(int(num_classes) > 0, "one_hot",
            f"num_classes must be positive, got {num_classes}")


@infer_check("topk")
def _topk(x, k=1, axis=-1, *a, **kw):
    nd = max(_ndim(x), 1)
    _check_axis("topk", int(axis), nd)
    dim = _shape(x)[int(axis) % nd] if _shape(x) else 1
    enforce(0 < int(k) <= dim, "topk",
            f"k must be in [1, {dim}] for axis size {dim}, got {k}")


@infer_check("squeeze")
def _squeeze(x, axis=None, *a, **k):
    if axis is None:
        return
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    for ax in axes:
        _check_axis("squeeze", int(ax), max(_ndim(x), 1))


@infer_check("unsqueeze")
def _unsqueeze(x, axis, *a, **k):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    out_rank = _ndim(x) + len(axes)
    for ax in axes:
        enforce(-out_rank <= int(ax) < out_rank, "unsqueeze",
                f"axis {ax} out of range for output rank {out_rank}")


@infer_check("transpose")
def _transpose(x, perm, *a, **k):
    p = [int(v) for v in perm]
    enforce(sorted(p) == list(range(_ndim(x))), "transpose",
            f"perm {p} must be a permutation of 0..{_ndim(x) - 1} "
            f"for a {_ndim(x)}-d operand")


@infer_check("embedding")
def _embedding(x, weight, padding_idx=None, *a, **k):
    enforce(_ndim(weight) == 2, "embedding",
            f"weight must be 2-d [vocab, dim], got {list(_shape(weight))}")


@infer_check("linear")
def _linear(x, weight, bias=None, *a, **k):
    sx, sw = _shape(x), _shape(weight)
    enforce(len(sw) == 2, "linear",
            f"weight must be 2-d [in, out], got {list(sw)}")
    enforce(sx and sx[-1] == sw[0], "linear",
            f"input features {sx[-1] if sx else '?'} != weight rows "
            f"{sw[0]} (x{list(sx)} @ w{list(sw)})")
    if bias is not None:
        sb = _shape(bias)
        enforce(sb in ((sw[1],), ()), "linear",
                f"bias shape {list(sb)} != [{sw[1]}]")


@infer_check("conv2d")
def _conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
            groups=1, data_format="NCHW", *a, **k):
    sx, sw = _shape(x), _shape(weight)
    enforce(len(sx) == 4, "conv2d",
            f"input must be 4-d {data_format}, got {list(sx)}")
    enforce(len(sw) == 4, "conv2d",
            f"weight must be 4-d [out_c, in_c/groups, kh, kw], "
            f"got {list(sw)}")
    ch_axis = 1 if str(data_format).upper().startswith("NC") else -1
    in_c = sx[ch_axis]
    enforce(in_c == sw[1] * groups, "conv2d",
            f"in_channels {in_c} ({data_format}) != weight in_c/groups "
            f"{sw[1]} * groups {groups}")
    enforce(sw[0] % groups == 0, "conv2d",
            f"out_channels {sw[0]} not divisible by groups {groups}")


@infer_check("layer_norm")
def _layer_norm(x, normalized_shape=None, weight=None, bias=None,
                epsilon=1e-5, *a, **k):
    if normalized_shape is None:
        return
    ns = ([int(normalized_shape)]
          if not isinstance(normalized_shape, (list, tuple))
          else [int(v) for v in normalized_shape])
    sx = list(_shape(x))
    enforce(sx[-len(ns):] == ns, "layer_norm",
            f"normalized_shape {ns} must match input trailing dims "
            f"{sx[-len(ns):]} (input {sx})")


@infer_check("cross_entropy")
def _cross_entropy(logits, label, *a, **k):
    sl, sy = _shape(logits), _shape(label)
    enforce(len(sl) >= 1, "cross_entropy",
            f"logits need >=1 dims, got {list(sl)}")
    if len(sy) == len(sl) - 1:
        enforce(sy == sl[:-1], "cross_entropy",
                f"label shape {list(sy)} must equal logits shape minus "
                f"class dim {list(sl[:-1])}")


@infer_check("where")
def _where(cond, x=None, y=None, *a, **k):
    if x is None or y is None:
        return
    enforce(_broadcastable(_shape(x), _shape(y)), "where",
            f"x{list(_shape(x))} and y{list(_shape(y))} not "
            f"broadcastable")
    enforce(_broadcastable(_shape(cond), _shape(x)), "where",
            f"condition{list(_shape(cond))} not broadcastable with "
            f"x{list(_shape(x))}")


@infer_check("expand")
def _expand(x, shape, *a, **k):
    tgt = [int(s) for s in (shape.tolist() if hasattr(shape, "tolist")
                            else shape)]
    sx = _shape(x)
    enforce(len(tgt) >= len(sx), "expand",
            f"target rank {len(tgt)} < input rank {len(sx)}")
    diff = len(tgt) - len(sx)
    for i, s in enumerate(tgt):
        if i < diff or s == -1:
            continue
        enforce(sx[i - diff] in (1, s), "expand",
                f"dim {i}: cannot expand {sx[i - diff]} -> {s} "
                f"(x{list(sx)} -> {tgt})")


@infer_check("tile")
def _tile(x, repeat_times, *a, **k):
    reps = [int(r) for r in (repeat_times.tolist()
                             if hasattr(repeat_times, "tolist")
                             else repeat_times)]
    enforce(all(r > 0 for r in reps), "tile",
            f"repeat_times must be positive, got {reps}")


@infer_check("flatten")
def _flatten(x, start_axis=0, stop_axis=-1, *a, **k):
    nd = max(_ndim(x), 1)
    _check_axis("flatten", int(start_axis), nd)
    _check_axis("flatten", int(stop_axis), nd)
    enforce(int(start_axis) % nd <= int(stop_axis) % nd, "flatten",
            f"start_axis {start_axis} must be <= stop_axis {stop_axis}")


@infer_check("cumsum")
def _cumsum(x, axis=None, *a, **k):
    if axis is not None:
        _check_axis("cumsum", int(axis), max(_ndim(x), 1))


@infer_check("put_along_axis")
def _put_along_axis(arr, indices, values, axis, *a, **k):
    _check_axis("put_along_axis", int(axis), max(_ndim(arr), 1))
    enforce(_ndim(indices) == _ndim(arr), "put_along_axis",
            f"indices rank {_ndim(indices)} must equal array rank "
            f"{_ndim(arr)}")


# ---------------- round-3 breadth: the next failure-magnet families
# (VERDICT r2 Next #7 — slice/pad/gather_nd/scatter/pool/conv-transpose/
# norm; reference paddle/phi/infermeta/unary.cc, binary.cc)

def _is_int(v):
    return isinstance(v, int) and not isinstance(v, bool)


@infer_check("slice")
def _slice(x, axes, starts, ends, *a, **k):
    nd = _ndim(x)
    enforce(len(axes) == len(starts) == len(ends), "slice",
            f"axes/starts/ends must have equal length, got "
            f"{len(axes)}/{len(starts)}/{len(ends)}")
    for ax in axes:
        _check_axis("slice", ax, nd)
    enforce(len(set(a % nd for a in axes)) == len(axes), "slice",
            f"repeated axis in {list(axes)}")


@infer_check("strided_slice")
def _strided_slice(x, axes, starts, ends, strides, *a, **k):
    nd = _ndim(x)
    enforce(len(axes) == len(starts) == len(ends) == len(strides),
            "strided_slice",
            f"axes/starts/ends/strides lengths differ: "
            f"{len(axes)}/{len(starts)}/{len(ends)}/{len(strides)}")
    for ax in axes:
        _check_axis("strided_slice", ax, nd)
    for st in strides:
        enforce(st != 0, "strided_slice", "stride must be non-zero")


@infer_check("pad")
def _pad(x, pad, mode="constant", value=0.0, data_format="NCHW", *a, **k):
    nd = _ndim(x)
    if isinstance(pad, (list, tuple)):
        enforce(len(pad) % 2 == 0, "pad",
                f"pad list must have an even length, got {len(pad)}")
        enforce(len(pad) <= 2 * nd, "pad",
                f"pad list of length {len(pad)} exceeds 2*ndim "
                f"({2 * nd}) for a {nd}-d input")
    enforce(mode in ("constant", "reflect", "replicate", "circular"),
            "pad", f"mode must be one of constant/reflect/replicate/"
            f"circular, got {mode!r}")


@infer_check("gather_nd")
def _gather_nd(x, index, *a, **k):
    sx, si = _shape(x), _shape(index)
    enforce(len(si) >= 1, "gather_nd",
            f"index needs ndim >= 1, got {list(si)}")
    enforce(si[-1] <= len(sx), "gather_nd",
            f"index.shape[-1] ({si[-1]}) must be <= x.ndim "
            f"({len(sx)}) — each index row addresses a prefix of x's "
            f"dims")


@infer_check("scatter")
def _scatter(x, index, updates, *a, **k):
    sx, si, su = _shape(x), _shape(index), _shape(updates)
    enforce(len(si) in (0, 1), "scatter",
            f"index must be 0-d or 1-d, got {list(si)}")
    if not si:  # 0-d index: updates replace one row of x
        enforce(su == sx[1:], "scatter",
                f"with a 0-d index, updates shape {list(su)} must "
                f"match one x row {list(sx[1:])}")
        return
    enforce(len(su) >= 1 and su[0] == si[0], "scatter",
            f"updates.shape[0] ({su[0] if su else '()'}) must equal "
            f"index length ({si[0]})")
    enforce(su[1:] == sx[1:], "scatter",
            f"updates trailing dims {list(su[1:])} must match x "
            f"trailing dims {list(sx[1:])}")


@infer_check("scatter_nd_add")
def _scatter_nd_add(x, index, updates, *a, **k):
    sx, si, su = _shape(x), _shape(index), _shape(updates)
    enforce(len(si) >= 1, "scatter_nd_add",
            f"index needs ndim >= 1, got {list(si)}")
    enforce(si[-1] <= len(sx), "scatter_nd_add",
            f"index.shape[-1] ({si[-1]}) must be <= x.ndim ({len(sx)})")
    expect = si[:-1] + sx[si[-1]:]
    enforce(su == expect, "scatter_nd_add",
            f"updates shape {list(su)} must be "
            f"index.shape[:-1] + x.shape[index.shape[-1]:] "
            f"= {list(expect)}")


def _conv_check(op, nsp):
    @infer_check(op)
    def check(x, weight, bias=None, stride=1, padding=0, dilation=1,
              groups=1, data_format=None, *a, **k):
        sx, sw = _shape(x), _shape(weight)
        enforce(len(sx) == nsp + 2, op,
                f"input must be {nsp + 2}-d, got {list(sx)}")
        enforce(len(sw) == nsp + 2, op,
                f"weight must be {nsp + 2}-d, got {list(sw)}")
        cf = bool(data_format) and str(data_format).endswith("C")
        cin = sx[-1] if cf else sx[1]
        enforce(cin == sw[1] * groups, op,
                f"input channels ({cin}) must equal "
                f"weight.shape[1] * groups ({sw[1]} * {groups})")
        enforce(sw[0] % groups == 0, op,
                f"out channels ({sw[0]}) must divide by groups "
                f"({groups})")
    return check


for _n, _d in (("conv1d", 1), ("conv3d", 3)):
    _conv_check(_n, _d)


def _conv_transpose_check(op, nsp):
    @infer_check(op)
    def check(x, weight, bias=None, stride=1, padding=0,
              output_padding=0, groups=1, dilation=1, data_format=None,
              *a, **k):
        sx, sw = _shape(x), _shape(weight)
        enforce(len(sx) == nsp + 2, op,
                f"input must be {nsp + 2}-d, got {list(sx)}")
        enforce(len(sw) == nsp + 2, op,
                f"weight must be {nsp + 2}-d "
                f"[in, out//groups, *k], got {list(sw)}")
        cf = bool(data_format) and str(data_format).endswith("C")
        cin = sx[-1] if cf else sx[1]
        enforce(cin == sw[0], op,
                f"input channels ({cin}) must equal weight.shape[0] "
                f"({sw[0]}) — transpose weights are [in, out//groups, "
                f"*k]")
    return check


for _n, _d in (("conv1d_transpose", 1), ("conv2d_transpose", 2),
               ("conv3d_transpose", 3)):
    _conv_transpose_check(_n, _d)


def _pool_check(op, nsp):
    @infer_check(op)
    def check(x, kernel_size=None, *a, **k):
        sx = _shape(x)
        enforce(len(sx) == nsp + 2, op,
                f"input must be {nsp + 2}-d "
                f"(N, C + {nsp} spatial dims), got {list(sx)}")
        if isinstance(kernel_size, (list, tuple)):
            enforce(len(kernel_size) == nsp, op,
                    f"kernel_size needs {nsp} entries, got "
                    f"{list(kernel_size)}")
    return check


for _n, _d in (("max_pool1d", 1), ("max_pool2d", 2), ("max_pool3d", 3),
               ("avg_pool1d", 1), ("avg_pool2d", 2), ("avg_pool3d", 3),
               ("adaptive_avg_pool1d", 1), ("adaptive_avg_pool2d", 2),
               ("adaptive_avg_pool3d", 3), ("adaptive_max_pool1d", 1),
               ("adaptive_max_pool2d", 2), ("adaptive_max_pool3d", 3)):
    _pool_check(_n, _d)


@infer_check("batch_norm_train")
def _bn_train(x, weight=None, bias=None, epsilon=1e-5,
              data_format="NCHW", **kw):
    _bn_shapes("batch_norm_train", x, weight, bias, data_format)


@infer_check("batch_norm_infer")
def _bn_infer(x, running_mean=None, running_var=None, weight=None,
              bias=None, epsilon=1e-5, data_format="NCHW", **kw):
    _bn_shapes("batch_norm_infer", x, weight, bias, data_format)


@infer_check("instance_norm")
def _in_check(x, weight=None, bias=None, epsilon=1e-5, **kw):
    _bn_shapes("instance_norm", x, weight, bias, "NCHW")


def _bn_shapes(op, x, weight, bias, data_format):
    sx = _shape(x)
    enforce(len(sx) >= 2, op,
            f"input needs ndim >= 2 (N, C, ...), got {list(sx)}")
    c = sx[-1] if str(data_format).endswith("C") else sx[1]
    for nm, p in (("weight", weight), ("bias", bias)):
        if p is not None:
            enforce(_shape(p) == (c,), op,
                    f"{nm} must have shape [{c}] (the channel "
                    f"count), got {list(_shape(p))}")


@infer_check("group_norm")
def _group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5,
                data_format="NCHW", *a, **k):
    sx = _shape(x)
    enforce(len(sx) >= 2, "group_norm",
            f"input needs ndim >= 2, got {list(sx)}")
    c = sx[-1] if str(data_format).endswith("C") else sx[1]
    enforce(c % num_groups == 0, "group_norm",
            f"channels ({c}) must divide by num_groups ({num_groups})")


@infer_check("rms_norm")
def _rms_norm(x, weight=None, *a, **k):
    if weight is not None:
        sx, sw = _shape(x), _shape(weight)
        enforce(sx[-len(sw):] == sw, "rms_norm",
                f"weight shape {list(sw)} must match trailing input "
                f"dims of {list(sx)}")


@infer_check("local_response_norm")
def _lrn(x, size, *a, **k):
    enforce(_ndim(x) in (3, 4, 5), "local_response_norm",
            f"input must be 3/4/5-d, got {_ndim(x)}-d")
    enforce(size > 0, "local_response_norm",
            f"size must be positive, got {size}")


@infer_check("interpolate")
def _interpolate(x, size=None, scale_factor=None, mode="nearest",
                 *a, **k):
    enforce(size is not None or scale_factor is not None, "interpolate",
            "one of size= or scale_factor= is required")
    enforce(size is None or scale_factor is None, "interpolate",
            "size= and scale_factor= are mutually exclusive")
    enforce(_ndim(x) in (3, 4, 5), "interpolate",
            f"input must be 3/4/5-d, got {_ndim(x)}-d")


@infer_check("grid_sample")
def _grid_sample(x, grid, *a, **k):
    sx, sg = _shape(x), _shape(grid)
    enforce(len(sx) == 4 and len(sg) == 4, "grid_sample",
            f"x and grid must be 4-d, got x{list(sx)} grid{list(sg)}")
    enforce(sx[0] == sg[0], "grid_sample",
            f"batch sizes differ: x {sx[0]} vs grid {sg[0]}")
    enforce(sg[-1] == 2, "grid_sample",
            f"grid last dim must be 2 (x, y), got {sg[-1]}")


def _pixel_check(op):
    @infer_check(op)
    def check(x, factor, data_format="NCHW", *a, **k):
        sx = _shape(x)
        enforce(len(sx) == 4, op,
                f"input must be 4-d, got {list(sx)}")
        c = sx[-1] if str(data_format).endswith("C") else sx[1]
        if op == "pixel_shuffle":
            enforce(c % (factor * factor) == 0, op,
                    f"channels ({c}) must divide by upscale_factor^2 "
                    f"({factor}^2)")
        else:
            h = sx[1] if str(data_format).endswith("C") else sx[2]
            w = sx[2] if str(data_format).endswith("C") else sx[3]
            enforce(h % factor == 0 and w % factor == 0, op,
                    f"spatial dims ({h}x{w}) must divide by "
                    f"downscale_factor ({factor})")
    return check


for _n in ("pixel_shuffle", "pixel_unshuffle"):
    _pixel_check(_n)


@infer_check("unfold")
def _unfold(x, kernel_sizes, *a, **k):
    enforce(_ndim(x) == 4, "unfold",
            f"input must be 4-d [N, C, H, W], got {_ndim(x)}-d")


@infer_check("roll")
def _roll(x, shifts, axis=None, *a, **k):
    if axis is not None:
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        for ax in axes:
            _check_axis("roll", ax, _ndim(x))
        ns = len(shifts) if isinstance(shifts, (list, tuple)) else 1
        enforce(ns == len(axes), "roll",
                f"shifts ({ns}) and axis ({len(axes)}) counts differ")


@infer_check("cross")
def _cross(x, y, axis=9, *a, **k):
    sx, sy = _shape(x), _shape(y)
    enforce(sx == sy, "cross",
            f"operands must have identical shapes, got x{list(sx)} "
            f"y{list(sy)}")
    if axis == 9:  # paddle sentinel: first dim of size 3
        enforce(3 in sx, "cross",
                f"no dimension of size 3 in shape {list(sx)}")
    else:
        _check_axis("cross", axis, len(sx))
        enforce(sx[axis] == 3, "cross",
                f"dim {axis} must have size 3, got {sx[axis]}")


@infer_check("dot")
def _dot(x, y, *a, **k):
    sx, sy = _shape(x), _shape(y)
    enforce(len(sx) in (1, 2) and sx == sy, "dot",
            f"dot needs two equal-shape 1-d or 2-d operands, got "
            f"x{list(sx)} y{list(sy)}")


@infer_check("index_sample")
def _index_sample(x, index, *a, **k):
    sx, si = _shape(x), _shape(index)
    enforce(len(sx) == 2 and len(si) == 2, "index_sample",
            f"x and index must be 2-d, got x{list(sx)} index{list(si)}")
    enforce(sx[0] == si[0], "index_sample",
            f"batch dims differ: x {sx[0]} vs index {si[0]}")


@infer_check("repeat_interleave")
def _repeat_interleave(x, repeats, axis=None, *a, **k):
    if axis is not None:
        _check_axis("repeat_interleave", axis, _ndim(x))
    if not _is_int(repeats):
        sr = _shape(repeats)
        enforce(len(sr) == 1, "repeat_interleave",
                f"repeats tensor must be 1-d, got {list(sr)}")


@infer_check("kthvalue")
def _kthvalue(x, k=None, axis=-1, keepdim=False, **kw):
    _check_axis("kthvalue", axis, _ndim(x))
    n = _shape(x)[axis]
    if k is not None:
        enforce(1 <= k <= n, "kthvalue",
                f"k must be in [1, {n}] for axis of size {n}, got {k}")


@infer_check("renorm")
def _renorm(x, p, axis, max_norm, *a, **k):
    _check_axis("renorm", axis, _ndim(x))
    enforce(p > 0, "renorm", f"p must be positive, got {p}")


@infer_check("searchsorted")
def _searchsorted(sorted_sequence, values, *a, **k):
    ss, sv = _shape(sorted_sequence), _shape(values)
    if len(ss) > 1:
        enforce(ss[:-1] == sv[:len(ss) - 1], "searchsorted",
                f"leading dims of sorted_sequence {list(ss)} must "
                f"match values {list(sv)}")


@infer_check("diagonal")
def _diagonal(x, offset=0, axis1=0, axis2=1, *a, **k):
    nd = _ndim(x)
    enforce(nd >= 2, "diagonal", f"input needs ndim >= 2, got {nd}")
    _check_axis("diagonal", axis1, nd)
    _check_axis("diagonal", axis2, nd)
    enforce(axis1 % nd != axis2 % nd, "diagonal",
            f"axis1 and axis2 must differ, both resolve to "
            f"{axis1 % nd}")


@infer_check("diag_embed")
def _diag_embed(x, offset=0, dim1=-2, dim2=-1, *a, **k):
    nd = _ndim(x) + 1
    enforce((dim1 % nd) != (dim2 % nd), "diag_embed",
            f"dim1 and dim2 must differ, both resolve to {dim1 % nd}")


@infer_check("temporal_shift")
def _temporal_shift(x, seg_num, *a, **k):
    sx = _shape(x)
    enforce(len(sx) == 4, "temporal_shift",
            f"input must be 4-d, got {list(sx)}")
    enforce(sx[0] % seg_num == 0, "temporal_shift",
            f"batch ({sx[0]}) must divide by seg_num ({seg_num})")


@infer_check("multi_dot")
def _multi_dot(xs, *a, **k):
    enforce(isinstance(xs, (list, tuple)) and len(xs) >= 2, "multi_dot",
            "multi_dot needs a list of >= 2 matrices")
    for i in range(len(xs) - 1):
        a_, b_ = _shape(xs[i]), _shape(xs[i + 1])
        ka = a_[-1]
        kb = b_[0] if len(b_) >= 1 else None
        enforce(ka == kb, "multi_dot",
                f"matrices {i} and {i + 1} have incompatible inner "
                f"dims: {list(a_)} @ {list(b_)}")
