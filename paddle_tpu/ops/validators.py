"""InferMeta-style validators for the most-used ops.

Reference: paddle/phi/infermeta/{unary,binary,ternary,multiary}.cc —
every kernel validates operand shapes/dtypes and raises
PADDLE_ENFORCE_* with expected-vs-got messages. Here validators are
registered per op name (core/enforce.py) and run at the dispatch
boundary before the jax impl, so users get an op-named shape message
instead of a raw XLA traceback. Checks read only static shape/dtype —
they are free under tracing (run once at trace time).
"""
from __future__ import annotations

from ..core.enforce import enforce, infer_check

__all__ = []


def _shape(x):
    return tuple(getattr(x, "shape", ()))


def _ndim(x):
    return len(_shape(x))


def _broadcastable(a, b) -> bool:
    for x, y in zip(reversed(a), reversed(b)):
        if x != 1 and y != 1 and x != y:
            return False
    return True


def _check_axis(op, axis, ndim, allow_scalar_like=True):
    lo = -ndim if ndim else -1
    hi = max(ndim - 1, 0)
    enforce(lo <= axis <= hi, op,
            f"axis must be in [{lo}, {hi}] for a {ndim}-d operand, "
            f"got {axis}")


def _binary_broadcast(op):
    @infer_check(op)
    def check(x, y, *a, **k):
        sx, sy = _shape(x), _shape(y)
        enforce(_broadcastable(sx, sy), op,
                f"operands could not be broadcast together: "
                f"x{list(sx)} vs y{list(sy)}")
    return check


for _name in ("add", "subtract", "multiply", "divide", "maximum",
              "minimum", "pow", "remainder", "floor_divide", "atan2",
              "fmax", "fmin", "heaviside", "logaddexp", "hypot"):
    _binary_broadcast(_name)


@infer_check("matmul")
def _matmul(x, y, transpose_x=False, transpose_y=False, *a, **k):
    sx, sy = _shape(x), _shape(y)
    enforce(len(sx) >= 1 and len(sy) >= 1, "matmul",
            f"operands need ndim >= 1, got x{list(sx)} y{list(sy)}")
    if len(sx) >= 2 and len(sy) >= 2:
        kx = sx[-1] if not transpose_x else sx[-2]
        ky = sy[-2] if not transpose_y else sy[-1]
        enforce(kx == ky, "matmul",
                f"inner dims must match: x{list(sx)}"
                f"{'^T' if transpose_x else ''} @ y{list(sy)}"
                f"{'^T' if transpose_y else ''} -> {kx} != {ky}")
        bx, by = sx[:-2], sy[:-2]
        enforce(_broadcastable(bx, by), "matmul",
                f"batch dims not broadcastable: {list(bx)} vs {list(by)}")


@infer_check("bmm")
def _bmm(x, y, *a, **k):
    sx, sy = _shape(x), _shape(y)
    enforce(len(sx) == 3 and len(sy) == 3, "bmm",
            f"bmm needs two 3-d operands, got x{list(sx)} y{list(sy)}")
    enforce(sx[0] == sy[0], "bmm",
            f"batch sizes differ: {sx[0]} vs {sy[0]}")
    enforce(sx[2] == sy[1], "bmm",
            f"inner dims must match: {sx[2]} != {sy[1]}")


@infer_check("mv")
def _mv(x, vec, *a, **k):
    sx, sv = _shape(x), _shape(vec)
    enforce(len(sx) == 2 and len(sv) == 1, "mv",
            f"mv needs (matrix, vector), got x{list(sx)} vec{list(sv)}")
    enforce(sx[1] == sv[0], "mv",
            f"matrix cols {sx[1]} != vector size {sv[0]}")


@infer_check("concat")
def _concat(xs, axis=0, *a, **k):
    if not isinstance(xs, (list, tuple)) or not xs:
        return
    nd = _ndim(xs[0])
    _check_axis("concat", int(axis), nd)
    ax = int(axis) % max(nd, 1)
    base = list(_shape(xs[0]))
    for i, t in enumerate(xs[1:], 1):
        s = list(_shape(t))
        enforce(len(s) == nd, "concat",
                f"input {i} has rank {len(s)}, expected {nd}")
        ok = all(s[d] == base[d] for d in range(nd) if d != ax)
        enforce(ok, "concat",
                f"input {i} shape {s} mismatches input 0 shape {base} "
                f"outside concat axis {ax}")


@infer_check("stack")
def _stack(xs, axis=0, *a, **k):
    if not isinstance(xs, (list, tuple)) or not xs:
        return
    base = _shape(xs[0])
    for i, t in enumerate(xs[1:], 1):
        enforce(_shape(t) == base, "stack",
                f"input {i} shape {list(_shape(t))} != input 0 shape "
                f"{list(base)} (stack needs identical shapes)")


@infer_check("reshape")
def _reshape(x, shape, *a, **k):
    import numpy as np
    tgt = [int(s) for s in (shape.tolist() if hasattr(shape, "tolist")
                            else shape)]
    enforce(tgt.count(-1) <= 1, "reshape",
            f"at most one -1 allowed in target shape, got {tgt}")
    n = int(np.prod(_shape(x))) if _shape(x) else 1
    fixed = int(np.prod([s for s in tgt if s != -1])) if tgt else 1
    if -1 in tgt:
        enforce(fixed != 0 and n % fixed == 0, "reshape",
                f"cannot infer -1: {n} elements not divisible by "
                f"{fixed} (shape {list(_shape(x))} -> {tgt})")
    else:
        enforce(fixed == n, "reshape",
                f"element count mismatch: {list(_shape(x))} has {n} "
                f"elements, target {tgt} has {fixed}")


@infer_check("softmax")
def _softmax(x, axis=-1, *a, **k):
    _check_axis("softmax", int(axis), max(_ndim(x), 1))


@infer_check("log_softmax")
def _log_softmax(x, axis=-1, *a, **k):
    _check_axis("log_softmax", int(axis), max(_ndim(x), 1))


@infer_check("gather")
def _gather(x, index, axis=0, *a, **k):
    _check_axis("gather", int(axis), max(_ndim(x), 1))
    enforce(_ndim(index) <= 2, "gather",
            f"index must be 0/1/2-d, got {_ndim(index)}-d")


@infer_check("index_select")
def _index_select(x, index, axis=0, *a, **k):
    _check_axis("index_select", int(axis), max(_ndim(x), 1))


@infer_check("take_along_axis")
def _take_along_axis(arr, indices, axis, *a, **k):
    _check_axis("take_along_axis", int(axis), max(_ndim(arr), 1))
    enforce(_ndim(indices) == _ndim(arr), "take_along_axis",
            f"indices rank {_ndim(indices)} must equal array rank "
            f"{_ndim(arr)}")


@infer_check("one_hot")
def _one_hot(x, num_classes, *a, **k):
    enforce(int(num_classes) > 0, "one_hot",
            f"num_classes must be positive, got {num_classes}")


@infer_check("topk")
def _topk(x, k=1, axis=-1, *a, **kw):
    nd = max(_ndim(x), 1)
    _check_axis("topk", int(axis), nd)
    dim = _shape(x)[int(axis) % nd] if _shape(x) else 1
    enforce(0 < int(k) <= dim, "topk",
            f"k must be in [1, {dim}] for axis size {dim}, got {k}")


@infer_check("squeeze")
def _squeeze(x, axis=None, *a, **k):
    if axis is None:
        return
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    for ax in axes:
        _check_axis("squeeze", int(ax), max(_ndim(x), 1))


@infer_check("unsqueeze")
def _unsqueeze(x, axis, *a, **k):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    out_rank = _ndim(x) + len(axes)
    for ax in axes:
        enforce(-out_rank <= int(ax) < out_rank, "unsqueeze",
                f"axis {ax} out of range for output rank {out_rank}")


@infer_check("transpose")
def _transpose(x, perm, *a, **k):
    p = [int(v) for v in perm]
    enforce(sorted(p) == list(range(_ndim(x))), "transpose",
            f"perm {p} must be a permutation of 0..{_ndim(x) - 1} "
            f"for a {_ndim(x)}-d operand")


@infer_check("embedding")
def _embedding(x, weight, padding_idx=None, *a, **k):
    enforce(_ndim(weight) == 2, "embedding",
            f"weight must be 2-d [vocab, dim], got {list(_shape(weight))}")


@infer_check("linear")
def _linear(x, weight, bias=None, *a, **k):
    sx, sw = _shape(x), _shape(weight)
    enforce(len(sw) == 2, "linear",
            f"weight must be 2-d [in, out], got {list(sw)}")
    enforce(sx and sx[-1] == sw[0], "linear",
            f"input features {sx[-1] if sx else '?'} != weight rows "
            f"{sw[0]} (x{list(sx)} @ w{list(sw)})")
    if bias is not None:
        sb = _shape(bias)
        enforce(sb in ((sw[1],), ()), "linear",
                f"bias shape {list(sb)} != [{sw[1]}]")


@infer_check("conv2d")
def _conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
            groups=1, data_format="NCHW", *a, **k):
    sx, sw = _shape(x), _shape(weight)
    enforce(len(sx) == 4, "conv2d",
            f"input must be 4-d {data_format}, got {list(sx)}")
    enforce(len(sw) == 4, "conv2d",
            f"weight must be 4-d [out_c, in_c/groups, kh, kw], "
            f"got {list(sw)}")
    ch_axis = 1 if str(data_format).upper().startswith("NC") else -1
    in_c = sx[ch_axis]
    enforce(in_c == sw[1] * groups, "conv2d",
            f"in_channels {in_c} ({data_format}) != weight in_c/groups "
            f"{sw[1]} * groups {groups}")
    enforce(sw[0] % groups == 0, "conv2d",
            f"out_channels {sw[0]} not divisible by groups {groups}")


@infer_check("layer_norm")
def _layer_norm(x, normalized_shape=None, weight=None, bias=None,
                epsilon=1e-5, *a, **k):
    if normalized_shape is None:
        return
    ns = ([int(normalized_shape)]
          if not isinstance(normalized_shape, (list, tuple))
          else [int(v) for v in normalized_shape])
    sx = list(_shape(x))
    enforce(sx[-len(ns):] == ns, "layer_norm",
            f"normalized_shape {ns} must match input trailing dims "
            f"{sx[-len(ns):]} (input {sx})")


@infer_check("cross_entropy")
def _cross_entropy(logits, label, *a, **k):
    sl, sy = _shape(logits), _shape(label)
    enforce(len(sl) >= 1, "cross_entropy",
            f"logits need >=1 dims, got {list(sl)}")
    if len(sy) == len(sl) - 1:
        enforce(sy == sl[:-1], "cross_entropy",
                f"label shape {list(sy)} must equal logits shape minus "
                f"class dim {list(sl[:-1])}")


@infer_check("where")
def _where(cond, x=None, y=None, *a, **k):
    if x is None or y is None:
        return
    enforce(_broadcastable(_shape(x), _shape(y)), "where",
            f"x{list(_shape(x))} and y{list(_shape(y))} not "
            f"broadcastable")
    enforce(_broadcastable(_shape(cond), _shape(x)), "where",
            f"condition{list(_shape(cond))} not broadcastable with "
            f"x{list(_shape(x))}")


@infer_check("expand")
def _expand(x, shape, *a, **k):
    tgt = [int(s) for s in (shape.tolist() if hasattr(shape, "tolist")
                            else shape)]
    sx = _shape(x)
    enforce(len(tgt) >= len(sx), "expand",
            f"target rank {len(tgt)} < input rank {len(sx)}")
    diff = len(tgt) - len(sx)
    for i, s in enumerate(tgt):
        if i < diff or s == -1:
            continue
        enforce(sx[i - diff] in (1, s), "expand",
                f"dim {i}: cannot expand {sx[i - diff]} -> {s} "
                f"(x{list(sx)} -> {tgt})")


@infer_check("tile")
def _tile(x, repeat_times, *a, **k):
    reps = [int(r) for r in (repeat_times.tolist()
                             if hasattr(repeat_times, "tolist")
                             else repeat_times)]
    enforce(all(r > 0 for r in reps), "tile",
            f"repeat_times must be positive, got {reps}")


@infer_check("flatten")
def _flatten(x, start_axis=0, stop_axis=-1, *a, **k):
    nd = max(_ndim(x), 1)
    _check_axis("flatten", int(start_axis), nd)
    _check_axis("flatten", int(stop_axis), nd)
    enforce(int(start_axis) % nd <= int(stop_axis) % nd, "flatten",
            f"start_axis {start_axis} must be <= stop_axis {stop_axis}")


@infer_check("cumsum")
def _cumsum(x, axis=None, *a, **k):
    if axis is not None:
        _check_axis("cumsum", int(axis), max(_ndim(x), 1))


@infer_check("put_along_axis")
def _put_along_axis(arr, indices, values, axis, *a, **k):
    _check_axis("put_along_axis", int(axis), max(_ndim(arr), 1))
    enforce(_ndim(indices) == _ndim(arr), "put_along_axis",
            f"indices rank {_ndim(indices)} must equal array rank "
            f"{_ndim(arr)}")
